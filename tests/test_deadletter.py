"""Dead-letter queue + torn-journal recovery (ISSUE 1 satellites).

Malformed events must not just vanish behind a ``bad_lines`` counter:
with ``jax.deadletter.enabled`` the raw rejects land on a
``<topic>-deadletter`` journal, replayable after a parser fix.  And a
journal holding a crashed writer's NUL-torn page must be consumable in
``skip_corrupt`` mode with clean resumption on the far side.
"""

import random

from streambench_tpu.config import default_config, BenchmarkConfig
from streambench_tpu.datagen import gen
from streambench_tpu.encode.encoder import EventEncoder
from streambench_tpu.encode.native_encoder import make_encoder
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker, JournalReader, JournalWriter
from streambench_tpu.io.redis_schema import as_redis

MAPPING = {"ad-1": "camp-1", "ad-2": "camp-1", "ad-3": "camp-2"}


def ev(ad="ad-1", t=1_000_000):
    return (f'{{"user_id": "u1", "page_id": "p1", "ad_id": "{ad}", '
            f'"ad_type": "banner", "event_type": "view", '
            f'"event_time": "{t}", "ip_address": "1.2.3.4"}}').encode()


def test_deadletter_config_key_parses():
    cfg = BenchmarkConfig.from_mapping({"jax.deadletter.enabled": "true"})
    assert cfg.jax_deadletter_enabled
    assert not default_config().jax_deadletter_enabled


def test_encoder_deadletters_rejects(tmp_path):
    """Both encoder paths shunt every ``bad_lines`` reject to the sink,
    raw; parseable lines never land there."""
    for i, enc in enumerate((EventEncoder(MAPPING), make_encoder(MAPPING))):
        broker = FileBroker(str(tmp_path / f"b-{i}-{type(enc).__name__}"))
        dlq = broker.writer("test1-deadletter")
        enc.set_deadletter(dlq)
        bad1, bad2 = b"not json at all", b'{"user_id": "u", "trunc'
        enc.encode([ev(), bad1, ev("ad-2"), bad2], 8)
        dlq.close()
        assert enc.bad_lines == 2 and enc.dlq_lines == 2
        got = list(broker.read_all("test1-deadletter"))
        assert got == [bad1, bad2]


def test_deadletter_off_by_default_only_counts():
    enc = EventEncoder(MAPPING)
    enc.encode([ev(), b"garbage"], 4)
    assert enc.bad_lines == 1 and enc.dlq_lines == 0


def test_deadletter_tbl_path(tmp_path):
    broker = FileBroker(str(tmp_path / "b"))
    dlq = broker.writer("t-deadletter")
    enc = EventEncoder(MAPPING)
    enc.set_deadletter(dlq)
    enc.encode_tbl([b"u|p|ad-1|banner|view|1000000",
                    b"too|few", b"u|p|ad-1|banner|view|notanint"], 4)
    dlq.close()
    assert enc.bad_lines == 2 and enc.dlq_lines == 2
    assert list(broker.read_all("t-deadletter")) == [
        b"too|few", b"u|p|ad-1|banner|view|notanint"]


def test_run_stats_surface_dlq_and_bad_lines(tmp_path):
    """End-to-end: a topic salted with garbage -> RunStats.faults carries
    dlq_lines/bad_lines and the DLQ journal holds exactly the garbage."""
    cfg = default_config(jax_batch_size=64)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=500,
                 rng=random.Random(3), workdir=str(tmp_path))
    garbage = [b"}{ not an event", b'{"user_id": "u"']
    with broker.writer(cfg.kafka_topic) as w:
        w.append_many(garbage)
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    dlq = broker.writer(f"{cfg.kafka_topic}-deadletter")
    eng.encoder.set_deadletter(dlq)
    st = StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    eng.close()
    dlq.close()
    assert st.events == 500
    assert st.faults.get("bad_lines") == 2
    assert st.faults.get("dlq_lines") == 2
    assert list(broker.read_all(f"{cfg.kafka_topic}-deadletter")) == garbage


# ----------------------------------------------------------------------
# torn-tail / skip_corrupt recovery
# ----------------------------------------------------------------------

def test_skip_corrupt_consumes_torn_record(tmp_path):
    """A NUL-torn record (crashed writer's page) is consumed-not-
    delivered; offsets stay byte-exact so resumption is clean."""
    path = str(tmp_path / "t.jsonl")
    good = [b"rec-%d" % i for i in range(6)]
    with open(path, "wb") as f:
        f.write(b"".join(l + b"\n" for l in good[:3]))
        f.write(b"rec-\x00\x00\x00\x00torn\n")      # the torn page
        f.write(b"".join(l + b"\n" for l in good[3:]))

    r = JournalReader(path, skip_corrupt=True)
    assert r.poll(100) == good
    assert r.corrupt_records == 1
    import os
    assert r.offset == os.path.getsize(path)

    # resumption across the torn region: seek back before it and re-poll
    # (the skipped record occupies one of the 4 requested slots — a
    # short return, which every poll caller already tolerates)
    r.seek(0)
    assert r.poll(4) == good[:3]
    assert r.poll(100) == good[3:]

    # default mode still delivers the raw torn record (opt-in policy)
    r2 = JournalReader(path)
    assert len(r2.poll(100)) == 7


def test_skip_corrupt_block_mode(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with open(path, "wb") as f:
        f.write(b"aaaa\n\x00\x00\x00\x00\nbbbb\n")
    r = JournalReader(path, skip_corrupt=True)
    assert r.poll_block() == b"aaaa\nbbbb\n"
    assert r.corrupt_records == 1
    import os
    assert r.offset == os.path.getsize(path)


def test_torn_journal_engine_resumes_cleanly(tmp_path):
    """A topic torn mid-file: the engine (skip_corrupt reader) counts
    every intact event and the oracle diff shows only the torn loss."""
    cfg = default_config(jax_batch_size=64)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=400,
                 rng=random.Random(5), workdir=str(tmp_path))
    # tear the middle of the topic: NUL out one record's bytes in place
    topic = broker.topic_path(cfg.kafka_topic)
    with open(topic, "r+b") as f:
        data = f.read()
        third = data.index(b"\n", data.index(b"\n", data.index(b"\n") + 1)
                           + 1) + 1
        end = data.index(b"\n", third)
        f.seek(third)
        f.write(b"\x00" * (end - third))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    reader = broker.reader(cfg.kafka_topic, skip_corrupt=True)
    st = StreamRunner(eng, reader).run_catchup()
    eng.close()
    assert st.events == 399                       # one record torn away
    assert st.faults.get("journal_corrupt_skipped") == 1
    assert reader.corrupt_records == 1
