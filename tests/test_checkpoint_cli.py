"""Round-3 checkpoint coverage the r02 verdict demanded: the CLI-level
kill -9 + resume path (``--checkpointDir`` with a SKETCH engine — the
gates are gone) and multi-partition checkpoints (per-partition offset
vector).  Reference resume semantics: Kafka committed offsets,
``AdvertisingTopologyNative.java:92``.
"""

import os
import random
import signal
import socket
import subprocess
import sys
import time

from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.config import default_config, write_local_conf
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis, read_seen_counts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_multi_partition_checkpoint_resume(tmp_path):
    """Crash + resume over a 3-partition topic: the snapshot carries the
    per-partition offsets vector and replays only unconsumed tails."""
    cfg = default_config(jax_batch_size=256, kafka_partitions=3)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=9000, partitions=3,
                 rng=random.Random(5), workdir=str(tmp_path))
    assert len(broker.partitions(cfg.kafka_topic)) == 3
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    ckpt = Checkpointer(str(tmp_path / "ckpt"))

    eng1 = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner1 = StreamRunner(eng1, broker.multi_reader(cfg.kafka_topic),
                           checkpointer=ckpt)
    runner1.run_catchup(max_events=4000)
    snap = ckpt.load()
    assert isinstance(snap.offset, list) and len(snap.offset) == 3
    del eng1, runner1  # crash

    eng2 = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner2 = StreamRunner(eng2, broker.multi_reader(cfg.kafka_topic),
                           checkpointer=ckpt)
    assert runner2.resume()
    assert runner2._reader_position() == snap.offset
    runner2.run_catchup()
    eng2.close()

    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _engine_cmd(conf, wd, ckpt_dir):
    return [sys.executable, "-m", "streambench_tpu.engine",
            "--confPath", conf, "--workdir", wd,
            "--brokerDir", os.path.join(wd, "broker"),
            "--engine", "hll", "--checkpointDir", ckpt_dir,
            "--catchup", "--idleTimeout", "1"]


def test_cli_kill9_resume_hll_oracle_exact(tmp_path):
    """ENGINE=hll + --checkpointDir: SIGKILL the engine process mid-run,
    restart it, and the final distinct-user estimates must equal an
    uninterrupted run's (HLL register folds are idempotent, so
    at-least-once replay is exact here)."""
    wd = str(tmp_path)
    port = _free_port()
    conf = os.path.join(wd, "conf.yaml")
    write_local_conf(conf, {
        "redis.host": "127.0.0.1", "redis.port": port,
        "kafka.topic": "ad-events",
        "jax.batch.size": 256,          # slow the catchup enough to kill
        "jax.flush.interval.ms": 200,   # frequent flush -> frequent ckpt
    })
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONUNBUFFERED": "1"}

    redis_proc = subprocess.Popen(
        [sys.executable, "-m", "streambench_tpu.io.fakeredis",
         "--port", str(port)], cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        from streambench_tpu.io.resp import RespClient
        deadline = time.monotonic() + 30
        while True:
            try:
                with RespClient("127.0.0.1", port, timeout_s=1.0) as c:
                    if c.ping() == "PONG":
                        break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        cfg = default_config()
        broker = FileBroker(os.path.join(wd, "broker"))
        with RespClient("127.0.0.1", port) as seed_rc:
            gen.do_setup(as_redis(seed_rc) if not hasattr(seed_rc, "execute")
                         else seed_rc, cfg, broker=broker,
                         events_num=60_000, rng=random.Random(11),
                         workdir=wd, topic="ad-events")

        ckpt_dir = os.path.join(wd, "ckpt")
        p = subprocess.Popen(_engine_cmd(conf, wd, ckpt_dir), cwd=REPO,
                             env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        # kill -9 as soon as the first checkpoint lands
        deadline = time.monotonic() + 120
        killed = False
        while time.monotonic() < deadline:
            if any(n.startswith("ckpt-") for n in
                   os.listdir(ckpt_dir)) if os.path.isdir(ckpt_dir) \
                    else False:
                os.kill(p.pid, signal.SIGKILL)
                killed = True
                break
            if p.poll() is not None:
                break  # finished before we could kill: fall through
            time.sleep(0.01)
        p.wait(timeout=60)
        out1 = p.stdout.read().decode("utf-8", "replace")

        # restart to completion (resumes from the checkpoint if killed)
        p2 = subprocess.run(_engine_cmd(conf, wd, ckpt_dir), cwd=REPO,
                            env=env, capture_output=True, text=True,
                            timeout=300)
        assert p2.returncode == 0, p2.stderr[-800:]
        if killed:
            assert "resumed from checkpoint" in p2.stdout, (
                out1[-400:], p2.stdout[-400:])

        # read what the CLI run wrote
        from streambench_tpu.io.resp import RespClient
        with RespClient("127.0.0.1", port) as rc:
            got = read_seen_counts(rc)
    finally:
        redis_proc.terminate()
        redis_proc.wait(timeout=10)

    # golden: one uninterrupted in-process HLL run over the same journal
    from streambench_tpu.engine.sketches import HLLDistinctEngine

    mapping = gen.load_ad_mapping_file(
        os.path.join(wd, gen.AD_TO_CAMPAIGN_FILE))
    cfg2 = default_config(jax_batch_size=256, kafka_topic="ad-events")
    rr = as_redis(FakeRedisStore())
    from streambench_tpu.io.redis_schema import seed_campaigns
    seed_campaigns(rr, sorted(set(mapping.values())))
    eng = HLLDistinctEngine(cfg2, mapping, redis=rr)
    runner = StreamRunner(eng, broker.reader("ad-events"))
    runner.run_catchup()
    eng.close()
    want = read_seen_counts(rr)

    got = {c: per for c, per in got.items() if per}
    want = {c: per for c, per in want.items() if per}
    assert got == want and len(want) > 0
