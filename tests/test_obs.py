"""Live telemetry subsystem (ISSUE 2 tentpole): registry instruments,
log-bucketed streaming histograms, the metrics.jsonl sampler, the
Prometheus endpoint, the report/diff CLI, supervisor annotations — and
the tier-1 CLI smoke test: a brief engine run with ``jax.metrics.*``
set must journal well-formed snapshots whose final cumulative counters
agree with the exit RunStats JSON line, and serve one good scrape."""

import json
import math
import os
import random
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from streambench_tpu.chaos.plan import EngineCrash
from streambench_tpu.chaos.supervisor import Supervisor
from streambench_tpu.config import default_config, write_local_conf
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker, JournalReader
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.metrics import FaultCounters
from streambench_tpu.obs import (
    MetricsRegistry,
    MetricsSampler,
    MetricsServer,
    StreamingHistogram,
    engine_collector,
)
from streambench_tpu.obs.report import (
    load_records,
    render_diff,
    render_report,
    summarize,
)
from streambench_tpu.trace import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# registry + histogram
def test_counter_monotonic_and_gauge():
    reg = MetricsRegistry()
    c = reg.counter("streambench_events_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    c.set_total(100)
    c.set_total(50)       # lower total ignored: counters are monotonic
    assert c.value == 100
    g = reg.gauge("streambench_backlog_bytes")
    g.set(10)
    g.set(3)              # gauges move both ways
    assert g.value == 3
    # get-or-create: same (name, labels) returns the same instrument
    assert reg.counter("streambench_events_total") is c
    with pytest.raises(ValueError):
        reg.gauge("streambench_events_total")


def test_histogram_observe_is_bucketed_not_stored():
    h = StreamingHistogram("h", lo=1.0, hi=1e6)
    for v in (1, 10, 100, 1000, 10_000):
        h.observe(v)
    # O(1) space: only fixed bucket counts, no sample list anywhere
    assert not any(isinstance(x, list) and len(x) > len(h._counts)
                   for x in vars(h).values())
    assert h.count == 5
    s = h.summary()
    assert s["min"] == 1 and s["max"] == 10_000
    assert s["sum"] == 11_111


def test_histogram_quantiles_within_one_bucket():
    growth = 2 ** 0.25
    h = StreamingHistogram("h", lo=1.0, hi=1e7, growth=growth)
    n = 10_000
    for i in range(1, n + 1):
        h.observe(i)
    p50, p95, p99 = h.quantiles((0.5, 0.95, 0.99))
    # log-bucketing guarantees bounded RELATIVE error: one bucket
    assert 0.5 * n / growth <= p50 <= 0.5 * n * growth
    assert 0.95 * n / growth <= p95 <= n
    assert 0.99 * n / growth <= p99 <= n
    # quantiles clamp to the observed max, never a bucket bound past it
    assert p99 <= n


def test_histogram_edges_and_empty():
    h = StreamingHistogram("h", lo=1.0, hi=100.0)
    assert all(math.isnan(q) for q in h.quantiles((0.5, 0.99)))
    h.observe(0.001)   # below lo -> bucket 0
    h.observe(1e9)     # above hi -> overflow bucket
    assert h.count == 2
    p50, p100 = h.quantiles((0.5, 1.0))
    assert p50 == 1.0          # bucket-0 upper bound
    assert p100 == 1e9         # overflow clamped to observed max


def test_empty_histogram_quantiles_nan_summary_count_only():
    """No samples: quantiles are NaN (never a raise), and summary is
    ``{"count": 0}`` alone — no percentile keys, so the JSON journal
    never carries a non-standard NaN token and a reader can't mistake
    'no samples' for 'zero latency'."""
    h = StreamingHistogram("h")
    qs = h.quantiles((0.0, 0.5, 0.99, 1.0))
    assert len(qs) == 4 and all(math.isnan(q) for q in qs)
    assert h.summary() == {"count": 0}
    # json-safe as-is
    assert json.loads(json.dumps(h.summary())) == {"count": 0}
    # one sample later the full shape comes back
    h.observe(5)
    s = h.summary()
    assert s["count"] == 1 and "p50" in s and "p99" in s


def test_prometheus_rendering_families_and_labels():
    reg = MetricsRegistry()
    reg.counter("streambench_faults_total", "faults",
                labels={"kind": "sink_errors"}).inc(2)
    reg.counter("streambench_faults_total", "faults",
                labels={"kind": "restarts"}).inc(1)
    reg.gauge("streambench_rss_bytes").set(12345)
    h = reg.histogram("streambench_window_latency_ms", lo=1, hi=100)
    h.observe(5)
    text = reg.render_prometheus()
    assert '# TYPE streambench_faults_total counter' in text
    assert 'streambench_faults_total{kind="sink_errors"} 2' in text
    assert 'streambench_faults_total{kind="restarts"} 1' in text
    assert "streambench_rss_bytes 12345" in text
    assert "# TYPE streambench_window_latency_ms histogram" in text
    assert 'streambench_window_latency_ms_bucket{le="+Inf"} 1' in text
    assert "streambench_window_latency_ms_count 1" in text
    # one TYPE header per family, not per labeled child
    assert text.count("# TYPE streambench_faults_total") == 1


# ----------------------------------------------------------------------
# sampler
class _StubEngine:
    """Duck-typed engine surface the collector reads."""

    def __init__(self):
        self.tracer = Tracer()
        self.faults = FaultCounters()
        self.events_processed = 0
        self.windows_written = 0
        self._obs_hist = None

    def telemetry(self):
        return {"events": self.events_processed,
                "windows_written": self.windows_written,
                "watermark_lag_ms": 42,
                "sink_dirty_rows": 0,
                "pending_rows": 0}


def test_collector_reports_ingest_pipeline_telemetry():
    """With a live ingest pipeline on the runner, each snapshot carries
    the stage-health block and mirrors it into streambench_ingest_*
    registry instruments (ISSUE 3 telemetry wiring)."""

    class _StubPipeline:
        def telemetry(self):
            return {"block_queue_depth": 2, "batch_queue_depth": 1,
                    "reader_stalls": 3, "encode_stalls": 0,
                    "encode_starved": 5, "dispatch_starved": 1,
                    "records_read": 100, "records_folded": 90,
                    "read_ms_total": 1.0, "encode_ms_total": 2.0}

    class _StubStats:
        batches = 4
        flushes = 2

    class _StubRunner:
        _pipeline = _StubPipeline()
        stats = _StubStats()

    eng = _StubEngine()
    reg = MetricsRegistry()
    collect = engine_collector(eng, runner=_StubRunner(), registry=reg)
    rec: dict = {}
    collect(rec, 1.0)
    assert rec["ingest"]["block_queue_depth"] == 2
    assert rec["ingest"]["reader_stalls"] == 3
    rendered = reg.render_prometheus()
    assert "streambench_ingest_block_queue_depth 2" in rendered
    assert "streambench_ingest_reader_stalls_total 3" in rendered
    # no pipeline -> no ingest block (the default surface is unchanged)
    class _PlainRunner:
        _pipeline = None
        stats = _StubStats()

    rec2: dict = {}
    engine_collector(_StubEngine(), runner=_PlainRunner(),
                     registry=MetricsRegistry())(rec2, 1.0)
    assert "ingest" not in rec2


def test_sampler_snapshots_deltas_and_final(tmp_path):
    eng = _StubEngine()
    reg = MetricsRegistry()
    hist = reg.histogram("streambench_window_latency_ms")
    eng._obs_hist = hist
    path = str(tmp_path / "metrics.jsonl")
    s = MetricsSampler(path, interval_ms=10, registry=reg)
    s.add_collector(engine_collector(eng, registry=reg))
    s.start()
    eng.events_processed = 1000
    eng.windows_written = 3
    eng.faults.inc("sink_errors")
    with eng.tracer.span("encode"):
        pass
    hist.observe(250)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        recs = [json.loads(l) for l in open(path)] if os.path.exists(path) \
            else []
        if any(r.get("events") == 1000 for r in recs):
            break
        time.sleep(0.01)
    s.annotate("restart", restarts=1)
    s.close(final={"events": 1000, "wall_s": 0.1})
    recs = [json.loads(l) for l in open(path)]
    kinds = [r["kind"] for r in recs]
    assert "snapshot" in kinds and "event" in kinds
    assert kinds[-1] == "final"
    snap = next(r for r in recs if r.get("events") == 1000)
    assert snap["windows_written"] == 3
    assert snap["watermark_lag_ms"] == 42
    assert snap["faults"] == {"sink_errors": 1}
    assert snap["events_per_s"] > 0
    assert snap["latency_ms"]["count"] == 1
    assert snap["latency_ms"]["p50"] >= 200
    # the first snapshot seeing the counters carries them as deltas too
    first = next(r for r in recs if r.get("faults"))
    assert first["fault_deltas"].get("sink_errors") == 1
    ev = next(r for r in recs if r["kind"] == "event")
    assert ev["event"] == "restart" and ev["restarts"] == 1
    final = recs[-1]
    assert final["run_stats"] == {"events": 1000, "wall_s": 0.1}
    assert final["events"] == 1000
    # registry mirrored the same story for a scrape
    assert reg.counter("streambench_events_total").value == 1000
    text = reg.render_prometheus()
    assert 'streambench_faults_total{kind="sink_errors"} 1' in text


def test_sampler_rotates_at_max_bytes(tmp_path):
    """jax.metrics.max.bytes: the journal rotates to metrics.jsonl.1
    instead of growing unboundedly; no file exceeds the cap and no
    record is lost across the rotation."""
    path = str(tmp_path / "metrics.jsonl")
    s = MetricsSampler(path, interval_ms=60_000, max_bytes=512)
    for i in range(40):
        s.annotate("spin", i=i)
    s.close()
    rotated = path + ".1"
    assert os.path.exists(rotated) and s.rotations >= 1
    assert os.path.getsize(rotated) <= 512
    recs = ([json.loads(l) for l in open(rotated)]
            + [json.loads(l) for l in open(path)])
    spins = [r["i"] for r in recs if r.get("event") == "spin"]
    # the newest cap-worth of records survives contiguously, newest last
    assert spins == list(range(spins[0], 40))
    assert recs[-1]["kind"] == "final"


def test_sampler_unbounded_by_default(tmp_path):
    path = str(tmp_path / "m.jsonl")
    s = MetricsSampler(path, interval_ms=60_000)
    for i in range(40):
        s.annotate("spin", i=i)
    s.close()
    assert not os.path.exists(path + ".1") and s.rotations == 0
    assert len([json.loads(l) for l in open(path)]) == 41  # + final


def test_rss_sample_labels_peak_fallback(monkeypatch):
    """The /proc path reports CURRENT rss as ``rss_bytes``; the
    ru_maxrss fallback is PEAK and must say so (``rss_peak_bytes``),
    not masquerade as current."""
    from streambench_tpu.obs import rss_sample
    from streambench_tpu.obs import sampler as sampler_mod

    v, label = rss_sample()
    assert label == "rss_bytes" and v and v > 0   # Linux CI: /proc
    monkeypatch.setattr(sampler_mod.os, "sysconf",
                        lambda *_: (_ for _ in ()).throw(ValueError()))
    v2, label2 = rss_sample()
    assert label2 == "rss_peak_bytes" and v2 and v2 > 0
    # the collector journals under the sample's own label and mirrors
    # the matching gauge only
    eng = _StubEngine()
    reg = MetricsRegistry()
    rec: dict = {}
    engine_collector(eng, registry=reg)(rec, 1.0)
    assert "rss_peak_bytes" in rec and "rss_bytes" not in rec
    assert "streambench_rss_peak_bytes" in reg.render_prometheus()


def test_sampler_no_thread_until_started(tmp_path):
    before = {t.name for t in threading.enumerate()}
    s = MetricsSampler(str(tmp_path / "m.jsonl"), interval_ms=10)
    assert "metrics-sampler" not in {t.name for t in threading.enumerate()
                                     } - before
    s.close()
    assert not any(t.name == "metrics-sampler"
                   for t in threading.enumerate())


def test_journal_backlog_bytes(tmp_path):
    broker = FileBroker(str(tmp_path / "broker"))
    broker.create_topic("t")
    w = broker.writer("t")
    w.append_many([b"x" * 9] * 10)   # 10 lines x 10 bytes
    w.flush()
    r = broker.reader("t")
    assert r.backlog_bytes() == 100
    r.poll(max_records=5)
    assert r.backlog_bytes() == 50
    r.poll()
    assert r.backlog_bytes() == 0
    missing = JournalReader(str(tmp_path / "nope.jsonl"))
    assert missing.backlog_bytes() == 0
    multi = broker.multi_reader("t")   # fresh readers start at offset 0
    assert multi.backlog_bytes() == 100
    multi.poll()
    assert multi.backlog_bytes() == 0


def test_metrics_server_scrape_and_refresh():
    reg = MetricsRegistry()
    reg.counter("streambench_events_total").set_total(7)
    refreshed = []
    srv = MetricsServer(reg, port=0, refresh=lambda: refreshed.append(1))
    try:
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        assert "streambench_events_total 7" in body
        assert refreshed  # pre-scrape refresh ran
        health = urllib.request.urlopen(
            f"http://{srv.host}:{srv.port}/healthz", timeout=10)
        assert health.status == 200
    finally:
        srv.close()


# ----------------------------------------------------------------------
# supervisor annotations
def test_supervisor_annotates_crash_restart_giveup():
    events = []

    class Recorder:
        def annotate(self, event, **fields):
            events.append((event, fields))

    class CrashingRunner:
        checkpointer = None
        crash_points = None

        def resume(self):
            return False

        def _reader_position(self):
            return 10

        def run(self, **kw):
            raise EngineCrash("boom")

    sup = Supervisor(CrashingRunner, max_no_progress_restarts=1,
                     backoff_base_ms=0, sleep=lambda s: None,
                     sampler=Recorder())
    st = sup.run()
    assert st.gave_up
    names = [e for e, _ in events]
    assert names == ["crash", "restart", "crash", "give_up"]
    assert events[0][1]["crash_offset"] == 10


# ----------------------------------------------------------------------
# report CLI
def _write_series(path, rates, faults=None, run_stats=None):
    with open(path, "w") as f:
        for i, rate in enumerate(rates):
            f.write(json.dumps({
                "kind": "snapshot", "seq": i, "ts_ms": 1000 + i * 100,
                "uptime_ms": (i + 1) * 100, "events": (i + 1) * 1000,
                "events_per_s": rate, "windows_written": i,
                "backlog_bytes": 10 * i, "watermark_lag_ms": 5,
                "rss_bytes": 1 << 20,
                "latency_ms": {"count": 4, "p50": 11, "p95": 12,
                               "p99": 13, "min": 10, "max": 14, "sum": 46},
                "stages": {"encode": {"calls": 2, "ms": 1.5}},
                "faults": faults or {}, "fault_deltas": {},
            }) + "\n")
        f.write(json.dumps({
            "kind": "event", "event": "restart", "ts_ms": 2000,
            "uptime_ms": 250, "restarts": 1}) + "\n")
        f.write(json.dumps({
            "kind": "final", "seq": len(rates), "ts_ms": 9000,
            "uptime_ms": (len(rates) + 1) * 100,
            "events": len(rates) * 1000, "events_per_s": 0.0,
            "windows_written": len(rates), "faults": faults or {},
            "fault_deltas": {}, "stages": {},
            "run_stats": run_stats or {"events": len(rates) * 1000},
        }) + "\n")


def test_report_summarize_and_render(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    _write_series(path, [100.0, 200.0, 300.0],
                  faults={"sink_errors": 2})
    # torn tail from a killed run must not poison the report
    with open(path, "a") as f:
        f.write('{"kind": "snapsho')
    s = summarize(load_records(path), path=path)
    assert s["events"] == 3000
    assert s["events_per_s_mean"] == 200.0
    assert s["events_per_s_max"] == 300.0
    assert s["backlog_bytes_max"] == 20
    assert s["latency_ms"]["p99"] == 13
    assert s["faults"] == {"sink_errors": 2}
    assert s["stages"]["encode"]["calls"] == 6
    assert len(s["annotations"]) == 1
    text = render_report(s)
    assert "events/s max" in text and "300.0" in text
    assert "sink_errors" in text and "restart" in text


def test_report_cli_and_diff(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main as obs_main

    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _write_series(a, [100.0, 100.0])
    _write_series(b, [150.0, 250.0], faults={"flush_stalls": 1})
    assert obs_main(["report", a]) == 0
    out = capsys.readouterr().out
    assert "telemetry report" in out
    assert obs_main(["diff", a, b]) == 0
    out = capsys.readouterr().out
    assert "telemetry diff" in out
    assert "+100.0%" in out          # events/s mean 100 -> 200
    assert "fault flush_stalls" in out
    assert obs_main(["report", a, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["events"] == 2000


# ----------------------------------------------------------------------
# engine integration: histogram fed at writeback; CLI smoke test
def test_engine_attach_obs_feeds_live_histogram(tmp_path):
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=4000,
                 rng=random.Random(3), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    reg = MetricsRegistry()
    engine.attach_obs(reg)
    runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
    runner.run_catchup()
    engine.close()
    hist = reg.histogram("streambench_window_latency_ms")
    # every unique written window observed exactly once per writeback
    assert hist.count >= len(engine.window_latency) > 0
    tel = engine.telemetry()
    assert tel["events"] == runner.stats.events
    assert tel["windows_written"] == engine.windows_written
    assert tel["watermark_lag_ms"] is not None


def _read_lines_async(stream, sink):
    for line in iter(stream.readline, ""):
        sink.append(line)


def test_cli_metrics_jsonl_and_prometheus_scrape(tmp_path):
    """The ISSUE's smoke test: engine CLI with jax.metrics.interval.ms
    low journals well-formed snapshots, serves one scrape on an
    ephemeral port, and the final record agrees with the RunStats JSON
    line.  No fixed sleeps: everything is deadline-polled."""
    wd = str(tmp_path)
    conf = os.path.join(wd, "conf.yaml")
    write_local_conf(conf, {
        "redis.host": ":inprocess:",
        "kafka.topic": "ad-events",
        "jax.batch.size": 256,
        "jax.scan.batches": 2,
        "jax.flush.interval.ms": 100,
        "jax.metrics.interval.ms": 25,
        "jax.metrics.port": 0,          # ephemeral, printed at startup
        "jax.obs.lifecycle": True,      # attribution rides the journal
    })
    cfg = default_config()
    broker = FileBroker(os.path.join(wd, "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=20_000, rng=random.Random(17), workdir=wd,
                 topic="ad-events")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONUNBUFFERED": "1"}
    p = subprocess.Popen(
        [sys.executable, "-m", "streambench_tpu.engine",
         "--confPath", conf, "--workdir", wd,
         "--brokerDir", os.path.join(wd, "broker"),
         "--duration", "120"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    lines: list[str] = []
    reader = threading.Thread(target=_read_lines_async,
                              args=(p.stdout, lines), daemon=True)
    reader.start()
    try:
        deadline = time.monotonic() + 180
        url = None
        while time.monotonic() < deadline and url is None:
            for line in list(lines):
                if line.startswith("metrics: ") and "endpoint=" in line:
                    url = line.split("endpoint=", 1)[1].strip()
                    break
            if p.poll() is not None:
                raise AssertionError(
                    f"engine exited early: {''.join(lines)[-800:]}")
            time.sleep(0.01)
        assert url, f"no metrics endpoint line: {''.join(lines)[-800:]}"

        # scrape once (retry until the deadline — the server is up
        # before the line prints, but be tolerant of a slow host)
        body = None
        while time.monotonic() < deadline:
            try:
                body = urllib.request.urlopen(url, timeout=5).read().decode()
                break
            except OSError:
                time.sleep(0.05)
        assert body and "# TYPE streambench_events_total counter" in body
        assert "streambench_window_latency_ms_bucket" in body
        assert "streambench_windows_written_total" in body

        # wait until the journal shows consumed events, then stop
        metrics_path = os.path.join(wd, "metrics.jsonl")
        while time.monotonic() < deadline:
            if os.path.exists(metrics_path):
                recs = [json.loads(l) for l in open(metrics_path)
                        if l.rstrip().endswith("}")]
                if any(r.get("events") for r in recs):
                    break
            time.sleep(0.02)
        p.send_signal(signal.SIGTERM)
        p.wait(timeout=120)
        reader.join(timeout=10)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert p.returncode == 0, "".join(lines)[-800:]

    stats_line = json.loads(
        next(l for l in reversed(lines) if l.startswith("{")))
    recs = [json.loads(l) for l in open(os.path.join(wd, "metrics.jsonl"))]
    snaps = [r for r in recs if r["kind"] == "snapshot"]
    assert snaps, "no snapshot records"
    for r in snaps:  # well-formed: the advertised schema keys exist
        for key in ("seq", "ts_ms", "events", "events_per_s",
                    "windows_written", "backlog_bytes", "stages",
                    "faults", "fault_deltas"):
            assert key in r, (key, r)
    # live latency percentiles appeared once windows were written
    lat = [r for r in recs if r.get("latency_ms")]
    assert lat and all(k in lat[-1]["latency_ms"]
                       for k in ("p50", "p95", "p99"))
    final = recs[-1]
    assert final["kind"] == "final"
    # jax.obs.lifecycle: the final record carries the per-segment
    # attribution, one sample per segment per observed write
    att = final["attribution"]
    assert att["writes_observed"] > 0
    for seg in ("ingest", "encode", "fold", "flush", "sink"):
        assert att["segments"][seg]["count"] == att["writes_observed"]
    assert att["e2e_ms"]["count"] == att["writes_observed"]
    # the time series' last word and the exit stats line agree
    assert final["run_stats"] == stats_line
    assert final["events"] == stats_line["events"]
    assert final["windows_written"] == stats_line["windows_written"]
    # engine-level fault counters agree (RunStats.faults additionally
    # folds encoder/reader counters on top of the engine's)
    for k, v in final["faults"].items():
        assert stats_line["faults"].get(k) == v, (k, v, stats_line)


# ----------------------------------------------------------------------
# real Prometheus histogram exposition (ISSUE 8 satellite): cumulative
# _bucket series + _sum/_count, conformant and compact
def test_histogram_exposition_is_conformant_and_compact():
    import math

    reg = MetricsRegistry()
    h = reg.histogram("streambench_window_segment_ms",
                      "segmented", lo=0.1, hi=1e7,
                      growth=2 ** 0.125, labels={"segment": "ingest"})
    for v in (0.5, 0.5, 3.0, 9_000.0, 5e8):   # 5e8 -> overflow bucket
        h.observe(v)
    lines = h.render()
    buckets = [l for l in lines if "_bucket" in l]
    # sparse: occupied buckets + their lower edges + first + Inf, NOT
    # one line per geometric bucket (~190 at this growth)
    assert 4 <= len(buckets) <= 12, buckets
    # cumulative counts are monotone nondecreasing in bound order
    def bound(line):
        le = line.split('le="')[1].split('"')[0]
        return math.inf if le == "+Inf" else float(le)
    parsed = [(bound(l), int(l.rsplit(" ", 1)[1])) for l in buckets]
    assert parsed == sorted(parsed, key=lambda p: p[0])
    counts = [c for _, c in parsed]
    assert counts == sorted(counts)
    # the +Inf bucket equals _count (the exposition-format invariant)
    assert parsed[-1][0] == math.inf and parsed[-1][1] == 5
    count_line = next(l for l in lines if "_count" in l)
    assert count_line.endswith(" 5")
    sum_line = next(l for l in lines if "_sum" in l)
    assert float(sum_line.rsplit(" ", 1)[1]) == 500009004.0
    # labels ride every series of the family
    assert all('segment="ingest"' in l for l in buckets)
    # every occupied bucket's LOWER edge is also emitted (quantile
    # interpolation keeps one-bucket resolution): each jump in the
    # cumulative series starts from an explicitly emitted bound
    jumps = [i for i in range(1, len(parsed))
             if parsed[i][1] > parsed[i - 1][1]]
    for i in jumps:
        # the preceding emitted bound is the true geometric neighbor:
        # its bound * growth ~= this bound (no gap was skipped)
        lo_b, hi_b = parsed[i - 1][0], parsed[i][0]
        if math.isinf(hi_b):
            continue
        assert hi_b / lo_b == pytest.approx(2 ** 0.125, rel=1e-6)
