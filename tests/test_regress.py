"""Regression comparator (ISSUE 8 tentpole, obs.regress): artifact and
metrics.jsonl normalization, tolerance/direction verdicts, the CLI's
exit codes (the CI gate contract), and rotated-journal stitching."""

import json

import pytest

from streambench_tpu.obs.regress import (
    compare,
    load_artifact,
    normalize_bench,
)


def _bench_doc(evps=2_000_000.0, p99=13_000.0, busy=0.05,
               slo_pass=True):
    return {
        "platform": "cpu",
        "catchup_events_per_s": evps,
        "max_sustained_rate": 100_000,
        "occupancy": {"device_busy_ratio": busy},
        "configs": [{"config": "exact_count",
                     "paced": {"p50_ms": 11_000.0, "p99_ms": p99,
                               "slo": {"pass": slo_pass}}}],
    }


def test_normalize_bench_extracts_comparables():
    n = normalize_bench(_bench_doc(), path="x.json")
    assert n["catchup_events_per_s"] == 2_000_000.0
    assert n["max_sustained_rate"] == 100_000
    assert n["device_busy_ratio"] == 0.05
    assert n["paced_p99_ms"] == 13_000.0
    assert n["slo_pass"] is True


def test_normalize_bench_reach_segments_and_contention():
    """ISSUE 11 regress keys: per-segment p50 scalars (or full
    summaries) + contention ratio out of the bench reach block, all
    with declared 'lower is better' directions."""
    from streambench_tpu.obs.regress import DEFAULT_TOLERANCES

    doc = {"reach": {"qps": 2500.0, "p99_ms": 480.0,
                     "segments": {"queue": 6.6, "batch": 0.06,
                                  "dispatch": {"p50": 0.5, "p99": 1.0},
                                  "reply": 0.2},
                     "contention_ratio": 0.88}}
    n = normalize_bench(doc, path="r.json")
    assert n["reach_segment_queue_ms"] == 6.6
    assert n["reach_segment_dispatch_ms"] == 0.5   # dict -> its p50
    assert n["reach_contention_ratio"] == 0.88
    for key in ("reach_segment_queue_ms", "reach_segment_batch_ms",
                "reach_segment_dispatch_ms", "reach_segment_reply_ms",
                "reach_contention_ratio"):
        assert DEFAULT_TOLERANCES[key][0] == "lower", key
    # direction-aware: a doubled queue segment past tolerance regresses
    b = dict(n)
    b["reach_segment_queue_ms"] = 6.6 * 2.5
    res = compare(n, b)
    rows = {r["metric"]: r["verdict"] for r in res["rows"]}
    assert rows["reach_segment_queue_ms"] == "REGRESSED"


def test_normalize_bench_sliding_ab_keys():
    """ISSUE 12 regress keys: both sliding A/B arms' ev/s out of the
    bench sliding_ab block, direction 'higher is better'."""
    from streambench_tpu.obs.regress import DEFAULT_TOLERANCES

    doc = {"sliding_ab": {"sliding_evps": 230_000.0,
                          "sliding_sliced_evps": 510_000.0,
                          "oracle": "exact"}}
    n = normalize_bench(doc, path="s.json")
    assert n["sliding_evps"] == 230_000.0
    assert n["sliding_sliced_evps"] == 510_000.0
    for key in ("sliding_evps", "sliding_sliced_evps"):
        assert DEFAULT_TOLERANCES[key][0] == "higher", key
    b = dict(n)
    b["sliding_sliced_evps"] = 510_000.0 * 0.2   # collapse past 50% tol
    res = compare(n, b)
    rows = {r["metric"]: r["verdict"] for r in res["rows"]}
    assert rows["sliding_sliced_evps"] == "REGRESSED"
    assert rows["sliding_evps"] == "OK"


def test_compare_directions_and_tolerances():
    a = normalize_bench(_bench_doc())
    # within every (generous) default tolerance
    ok = compare(a, normalize_bench(_bench_doc(evps=1_800_000.0,
                                               p99=14_000.0)))
    assert ok["pass"] and ok["regressions"] == 0
    # throughput collapse: higher-is-better direction
    worse = compare(a, normalize_bench(_bench_doc(evps=500_000.0)))
    assert not worse["pass"]
    row = next(r for r in worse["rows"]
               if r["metric"] == "catchup_events_per_s")
    assert row["verdict"] == "REGRESSED" and row["delta_pct"] == -75.0
    # latency blowout: lower-is-better direction
    slow = compare(a, normalize_bench(_bench_doc(p99=40_000.0)))
    assert not slow["pass"]
    assert any(r["metric"] == "paced_p99_ms"
               and r["verdict"] == "REGRESSED" for r in slow["rows"])
    # big improvement is labeled, not failed
    fast = compare(a, normalize_bench(_bench_doc(evps=9_000_000.0)))
    assert fast["pass"]
    assert any(r["verdict"] == "IMPROVED" for r in fast["rows"])
    # slo flip True -> False is a regression outright
    flipped = compare(a, normalize_bench(_bench_doc(slo_pass=False)))
    assert not flipped["pass"]
    # per-metric tolerance override loosens the gate
    loose = compare(a, normalize_bench(_bench_doc(evps=500_000.0)),
                    tolerances={"catchup_events_per_s": 0.9})
    assert loose["pass"]


def test_missing_metrics_reported_and_optionally_gated():
    a = normalize_bench(_bench_doc())
    b = {"kind": "bench", "path": "b",
         "catchup_events_per_s": 2_000_000.0}
    r = compare(a, b)
    assert r["missing"] > 0 and r["pass"]
    r2 = compare(a, b, strict_missing=True)
    assert not r2["pass"]


def test_load_artifact_detects_metrics_jsonl(tmp_path):
    p = tmp_path / "metrics.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "snapshot", "seq": 0, "ts_ms": 1,
                            "uptime_ms": 1000, "events": 1000,
                            "events_per_s": 1000.0,
                            "windows_written": 3}) + "\n")
        f.write(json.dumps({"kind": "final", "seq": 1, "ts_ms": 2,
                            "uptime_ms": 2000, "events": 5000,
                            "events_per_s": 4000.0,
                            "windows_written": 9,
                            "latency_ms": {"count": 9, "p50": 11_000,
                                           "p95": 12_000,
                                           "p99": 12_500},
                            "run_stats": {
                                "events_per_s": 2500.0,
                                "device_busy_ratio": 0.04,
                                "slo": {"pass": True}}}) + "\n")
    n = load_artifact(str(p))
    assert n["kind"] == "metrics"
    assert n["events_per_s_max"] == 4000.0
    assert n["latency_p99_ms"] == 12_500
    assert n["windows_written"] == 9
    assert n["catchup_events_per_s"] == 2500.0
    assert n["device_busy_ratio"] == 0.04
    assert n["slo_pass"] is True


def test_load_artifact_stitches_rotated_journal(tmp_path):
    """The rotation satellite: metrics.jsonl.1 (the OLDER half) is
    stitched in ahead of metrics.jsonl, so summaries cover the whole
    run, not the post-rotation tail."""
    old = tmp_path / "metrics.jsonl.1"
    new = tmp_path / "metrics.jsonl"
    with open(old, "w") as f:
        for seq in range(5):
            f.write(json.dumps({"kind": "snapshot", "seq": seq,
                                "ts_ms": seq, "uptime_ms": seq * 1000,
                                "events": seq * 100,
                                "events_per_s": 9000.0}) + "\n")
    with open(new, "w") as f:
        f.write(json.dumps({"kind": "final", "seq": 5, "ts_ms": 5,
                            "uptime_ms": 5000, "events": 500,
                            "events_per_s": 10.0,
                            "windows_written": 1}) + "\n")
    from streambench_tpu.obs.report import load_records, summarize

    recs = load_records(str(new))
    assert len(recs) == 6            # both halves, oldest first
    assert recs[0]["seq"] == 0 and recs[-1]["kind"] == "final"
    s = summarize(recs, path=str(new))
    # the pre-rotation rates are part of the summary again
    assert s["events_per_s_max"] == 9000.0
    # stitching is opt-out for callers that want one file only
    assert len(load_records(str(new), stitch_rotated=False)) == 1


def test_cli_exit_codes_and_json(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main as obs_main

    a = tmp_path / "a.json"
    b_ok = tmp_path / "b_ok.json"
    b_bad = tmp_path / "b_bad.json"
    a.write_text(json.dumps(_bench_doc()))
    b_ok.write_text(json.dumps(_bench_doc(evps=1_900_000.0)))
    b_bad.write_text(json.dumps(_bench_doc(evps=100_000.0)))
    assert obs_main(["regress", str(a), str(b_ok)]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out
    assert obs_main(["regress", str(a), str(b_bad)]) == 1
    assert "FAIL" in capsys.readouterr().out
    # advisory mode reports but never gates
    assert obs_main(["regress", str(a), str(b_bad),
                     "--advisory"]) == 0
    capsys.readouterr()
    # --json emits the machine-readable comparison
    assert obs_main(["regress", str(a), str(b_ok), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["pass"] is True and parsed["rows"]
    # tolerance override via CLI
    assert obs_main(["regress", str(a), str(b_bad),
                     "--tol", "catchup_events_per_s=0.99"]) == 0
    capsys.readouterr()
    # malformed tolerance / unusable input -> exit 2
    assert obs_main(["regress", str(a), str(b_ok),
                     "--tol", "nonsense"]) == 2
    capsys.readouterr()
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert obs_main(["regress", str(a), str(empty)]) == 2
    capsys.readouterr()


def test_committed_baseline_loads_when_present():
    """The committed CI baseline must stay parseable by the gate."""
    import os

    from streambench_tpu.obs.regress import _default_baseline

    p = _default_baseline()
    if p is None:
        pytest.skip("no committed baseline in this checkout")
    n = load_artifact(p)
    assert n.get("catchup_events_per_s"), n
    assert os.path.basename(p) == "BASELINE_bench_smoke.json"
