"""Sketch kernels (HLL, count-min, t-digest) vs exact reference models."""

import jax.numpy as jnp
import numpy as np

from streambench_tpu.ops import cms, hll, tdigest


# ---------------------------------------------------------------- HLL
def test_hll_estimate_accuracy_and_merge():
    rng = np.random.default_rng(11)
    C, W, R = 4, 8, 256
    st = hll.init_state(C, W, R)
    join = np.concatenate([np.arange(C, dtype=np.int32)
                           .repeat(3), [-1]]).astype(np.int32)
    n_ads = C * 3
    truth: dict[tuple[int, int], set] = {}
    B = 512
    for _ in range(20):
        ad = rng.integers(0, n_ads, B).astype(np.int32)
        user = rng.integers(0, 5000, B).astype(np.int32)
        et = np.zeros(B, np.int32)  # all views
        tm = rng.integers(70_000, 110_000, B).astype(np.int32)
        valid = np.ones(B, bool)
        st = hll.step(st, join, ad, user, et, tm, valid)
        for a, u, t in zip(ad.tolist(), user.tolist(), tm.tolist()):
            truth.setdefault((join[a], t // 10_000), set()).add(u)
    assert int(st.dropped) == 0
    est = np.asarray(hll.estimate(st.registers))
    wids = np.asarray(st.window_ids)
    rels = []
    for (c, wid), users in truth.items():
        s = wid % W
        if wids[s] != wid:
            continue
        rels.append(abs(est[c, s] - len(users)) / len(users))
    # std error ~ 1.04/sqrt(256) = 6.5%; the raw/linear-counting
    # crossover zone (~2.5R) carries known classic-HLL bias, so bound the
    # mean tightly and the max loosely.
    assert len(rels) >= 4
    assert np.mean(rels) < 0.08, rels
    assert max(rels) < 0.25, rels


def test_hll_flush_frees_closed_slots_only():
    C, W, R = 2, 8, 64
    st = hll.init_state(C, W, R)
    join = np.array([0, 1, -1], np.int32)
    ad = np.array([0, 1, 0, 1], np.int32)
    user = np.arange(4, dtype=np.int32)
    et = np.zeros(4, np.int32)
    tm = np.array([70_000, 70_500, 75_000, 79_000], np.int32)
    st = hll.step(st, join, ad, user, et, tm, np.ones(4, bool))
    est, wids, st2 = hll.flush(st)
    assert np.asarray(wids)[7] == 7  # window 7 occupied
    # watermark 79k: window 7 not closed (end 80k + lateness) -> kept
    assert np.asarray(st2.window_ids)[7] == 7
    assert np.asarray(st2.registers)[..., :].sum() > 0


# ---------------------------------------------------------- count-min
def test_cms_overestimates_and_bounds_error():
    rng = np.random.default_rng(3)
    st = cms.init_state(depth=4, width=1024)
    keys = rng.zipf(1.3, 20_000).astype(np.int32) % 500
    for off in range(0, 20_000, 1000):
        k = keys[off:off + 1000]
        st = cms.update(st, k, np.ones(1000, np.int32),
                        np.ones(1000, bool))
    assert int(st.total) == 20_000
    uniq, counts = np.unique(keys, return_counts=True)
    est = np.asarray(cms.query(st, uniq.astype(np.int32)))
    assert np.all(est >= counts)              # CMS never underestimates
    assert np.mean(est - counts) < 0.01 * 20_000

    vals, idx = cms.heavy_hitters(st, uniq.astype(np.int32), k=5)
    top_true = uniq[np.argsort(-counts)[:5]]
    assert set(np.asarray(uniq[np.asarray(idx)][:3]).tolist()) \
        <= set(top_true.tolist()) | set(uniq[np.argsort(-counts)[:8]].tolist())


def test_cms_merge_is_sum():
    rng = np.random.default_rng(4)
    a = cms.init_state(4, 256)
    b = cms.init_state(4, 256)
    k1 = rng.integers(0, 50, 300).astype(np.int32)
    k2 = rng.integers(0, 50, 300).astype(np.int32)
    a = cms.update(a, k1, np.ones(300, np.int32), np.ones(300, bool))
    b = cms.update(b, k2, np.ones(300, np.int32), np.ones(300, bool))
    m = cms.merge(a, b)
    both = np.concatenate([k1, k2])
    uniq, counts = np.unique(both, return_counts=True)
    est = np.asarray(cms.query(m, uniq.astype(np.int32)))
    assert np.all(est >= counts)
    assert int(m.total) == 600


# ----------------------------------------------------------- t-digest
def test_tdigest_quantiles_close_to_exact():
    rng = np.random.default_rng(9)
    N, K = 3, 64
    st = tdigest.init_state(N, K)
    data: list[list[float]] = [[], [], []]
    for _ in range(10):
        key = rng.integers(0, N, 1024).astype(np.int32)
        val = rng.lognormal(3.0, 1.0, 1024).astype(np.float32)
        st = tdigest.update(st, key, val, np.ones(1024, bool))
        for k, v in zip(key.tolist(), val.tolist()):
            data[k].append(v)
    qs = np.array([0.5, 0.9, 0.99], np.float32)
    out = np.asarray(tdigest.quantile(st, qs))
    for k in range(N):
        exact = np.quantile(np.array(data[k]), qs)
        for j, q in enumerate(qs):
            rel = abs(out[k, j] - exact[j]) / exact[j]
            assert rel < 0.12, (k, q, out[k, j], exact[j])


def test_tdigest_weight_conservation_and_merge():
    rng = np.random.default_rng(10)
    N, K = 2, 32
    a = tdigest.init_state(N, K)
    b = tdigest.init_state(N, K)
    key = rng.integers(0, N, 512).astype(np.int32)
    val = rng.normal(100, 15, 512).astype(np.float32)
    a = tdigest.update(a, key, val, np.ones(512, bool))
    b = tdigest.update(b, key, val, np.ones(512, bool))
    m = tdigest.merge(a, b)
    assert np.allclose(np.asarray(m.weights).sum(), 1024, atol=1e-3)
    q = np.asarray(tdigest.quantile(m, np.array([0.5], np.float32)))
    med = np.median(val)
    assert abs(q[:, 0] - med).max() / med < 0.1


def test_tdigest_empty_key_returns_zero():
    st = tdigest.init_state(3, 16)
    key = np.zeros(8, np.int32)
    val = np.linspace(1, 8, 8).astype(np.float32)
    st = tdigest.update(st, key, val, np.ones(8, bool))
    q = np.asarray(tdigest.quantile(st, np.array([0.5], np.float32)))
    assert q[1, 0] == 0.0 and q[2, 0] == 0.0
    assert 3.0 < q[0, 0] < 6.0


def test_tdigest_fold_hist_out_of_range_keys_drop():
    """JAX normalizes negative scatter indices NumPy-style BEFORE the
    mode='drop' bounds check — an unmasked negative key would wrap into
    the LAST key's histogram row.  fold_hist must mask the key range
    explicitly and clamp negative values (code-review findings)."""
    hn, hw = tdigest.hist_init(4)
    key = np.array([-1, 4, 2], np.int32)
    val = np.array([5.0, 5.0, -3.0], np.float32)
    w = np.ones(3, np.float32)
    hn, hw = tdigest.fold_hist(hn, hw, jnp.asarray(key), jnp.asarray(val),
                               jnp.asarray(w), 4)
    hw_np = np.asarray(hw)
    assert hw_np[3].sum() == 0          # key -1 must NOT wrap to key 3
    assert hw_np.sum() == 1 and hw_np[2, 0] == 1  # only key 2 lands
    assert np.asarray(hn).min() >= 0.0  # value -3 clamps to 0

    # the per-batch update path applies the same key/value domain
    st = tdigest.init_state(4, 16)
    st = tdigest.update(st, jnp.asarray(key), jnp.asarray(val),
                        jnp.asarray(np.ones(3, bool)))
    wsum = np.asarray(st.weights).sum(axis=1)
    assert wsum[3] == 0 and wsum[2] == 1 and wsum.sum() == 1


def test_tdigest_tail_quantile_with_empty_centroids():
    """Digests with unoccupied centroid slots must not interpolate tail
    quantiles toward empty (mean-0) centroids (code-review finding)."""
    st = tdigest.init_state(1, compression=16)
    vals = np.full(4, 100.0, np.float32)
    st = tdigest.update(st, np.zeros(4, np.int32), vals, np.ones(4, bool))
    q = np.asarray(tdigest.quantile(st, jnp.array([0.5, 0.99, 1.0])))
    assert np.allclose(q[0], 100.0), q


def test_hll_scan_packed_bit_identical():
    """hll.scan_steps_packed over the packed wire word must match
    hll.scan_steps exactly (registers, ids, watermark, dropped)."""
    import jax.numpy as jnp

    from streambench_tpu.ops import hll
    from streambench_tpu.ops import windowcount as wc

    rng = np.random.default_rng(23)
    C, W, A, B, K = 10, 8, 40, 256, 4
    jt = np.concatenate([rng.integers(0, C, A).astype(np.int32), [-1]])
    ad = rng.integers(0, A + 1, (K, B)).astype(np.int32)
    user = rng.integers(0, 1 << 30, (K, B)).astype(np.int32)
    et = rng.integers(-1, 3, (K, B)).astype(np.int32)
    tm = np.sort(rng.integers(70_000, 200_000, (K, B))).astype(np.int32)
    va = rng.random((K, B)) < 0.9

    s0 = hll.init_state(C, W, num_registers=32)
    plain = hll.scan_steps(s0, jnp.asarray(jt), ad, user, et, tm, va)
    s1 = hll.init_state(C, W, num_registers=32)
    packed = np.stack([wc.pack_columns(ad[k], et[k], va[k])
                       for k in range(K)])
    got = hll.scan_steps_packed(s1, jnp.asarray(jt), packed, user, tm)
    assert np.array_equal(np.asarray(plain.registers),
                          np.asarray(got.registers))
    assert np.array_equal(np.asarray(plain.window_ids),
                          np.asarray(got.window_ids))
    assert int(plain.dropped) == int(got.dropped)


def test_sliding_scan_packed_bit_identical():
    import jax.numpy as jnp

    from streambench_tpu.engine.sketches import (
        _sliding_tdigest_scan,
        _sliding_tdigest_scan_packed,
    )
    from streambench_tpu.ops import sliding
    from streambench_tpu.ops import tdigest
    from streambench_tpu.ops import windowcount as wc

    rng = np.random.default_rng(29)
    C, W, A, B, K = 6, 64, 30, 128, 3
    jt = np.concatenate([rng.integers(0, C, A).astype(np.int32), [-1]])
    ad = rng.integers(0, A + 1, (K, B)).astype(np.int32)
    et = rng.integers(-1, 3, (K, B)).astype(np.int32)
    tm = np.sort(rng.integers(70_000, 120_000, (K, B))).astype(np.int32)
    va = rng.random((K, B)) < 0.9
    now = jnp.int32(130_000)

    st0 = wc.init_state(C, W)
    d0 = tdigest.init_state(C, compression=32)
    s_plain, d_plain = _sliding_tdigest_scan(
        st0, d0, jnp.asarray(jt), now, ad, et, tm, va,
        size_ms=10_000, slide_ms=1_000, lateness_ms=60_000)
    packed = np.stack([wc.pack_columns(ad[k], et[k], va[k])
                       for k in range(K)])
    st1 = wc.init_state(C, W)
    d1 = tdigest.init_state(C, compression=32)
    s_got, d_got = _sliding_tdigest_scan_packed(
        st1, d1, jnp.asarray(jt), now, packed, tm,
        size_ms=10_000, slide_ms=1_000, lateness_ms=60_000)
    assert np.array_equal(np.asarray(s_plain.counts),
                          np.asarray(s_got.counts))
    assert np.array_equal(np.asarray(d_plain.means),
                          np.asarray(d_got.means))
    assert np.array_equal(np.asarray(d_plain.weights),
                          np.asarray(d_got.weights))
