"""(epoch, campaign-set, kind) reach query-result cache (reach/cache.py,
ISSUE 14): canonical keys, LRU bounds, wholesale epoch invalidation (a
stale entry is NEVER served after a bump — the correctness property),
and the serve-layer integration (hit replies identical + instrumented).
"""

import time

import jax.numpy as jnp
import numpy as np

from streambench_tpu.obs import MetricsRegistry
from streambench_tpu.ops import minhash
from streambench_tpu.reach.cache import ReachQueryCache
from streambench_tpu.reach.serve import ReachQueryServer


def fold_state(users, C=4, k=16, R=16):
    st = minhash.init_state(C, k, R)
    join = jnp.asarray(np.arange(C, dtype=np.int32))
    B = len(users)
    return minhash.step(
        st, join,
        jnp.asarray(np.zeros(B, np.int32)),
        jnp.asarray(np.asarray(users, np.int32)),
        jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
        jnp.ones(B, bool))


# ------------------------------------------------------------ unit
def test_canonical_key_and_counts():
    c = ReachQueryCache(capacity=8)
    c.note_epoch(1)
    assert c.get(1, [2, 0, 1], "union") is None          # miss
    c.put(1, [2, 0, 1], "union", {"estimate": 5.0})
    assert c.get(1, [0, 1, 2], "union") == {"estimate": 5.0}  # order-free
    assert c.get(1, [0, 1, 2], "overlap") is None        # kind in key
    assert c.get(2, [0, 1, 2], "union") is None          # epoch in key
    s = c.summary()
    assert s["hits"] == 1 and s["misses"] == 3
    assert s["hit_ratio"] == 0.25


def test_lru_eviction_bounded():
    reg = MetricsRegistry()
    c = ReachQueryCache(capacity=3, registry=reg)
    c.note_epoch(1)
    for i in range(5):
        c.put(1, [i], "union", {"estimate": float(i)})
    assert len(c) == 3
    assert c.evictions == 2
    assert c.get(1, [0], "union") is None       # oldest evicted
    assert c.get(1, [4], "union") is not None   # newest kept
    # touching an entry protects it from the next eviction
    c.get(1, [2], "union")
    c.put(1, [9], "union", {"estimate": 9.0})
    assert c.get(1, [2], "union") is not None
    assert c.get(1, [3], "union") is None
    assert reg.counter(
        "streambench_reach_cache_evictions_total").value == 3


def test_epoch_bump_invalidates_wholesale():
    c = ReachQueryCache(capacity=8)
    c.note_epoch(1)
    c.put(1, [0], "union", {"estimate": 1.0})
    c.put(1, [1], "union", {"estimate": 2.0})
    assert len(c) == 2
    c.note_epoch(2)
    assert len(c) == 0
    assert c.invalidations == 1
    # a worker racing the bump cannot resurrect old-epoch results
    c.put(1, [0], "union", {"estimate": 1.0})
    assert len(c) == 0
    assert c.get(2, [0], "union") is None


# ------------------------------------------------------- serve layer
def drain(srv, got, n, timeout=20.0):
    deadline = time.monotonic() + timeout
    while len(got) < n and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(got) >= n, (len(got), n)


def test_cached_reply_identical_and_instrumented():
    reg = MetricsRegistry()
    cache = ReachQueryCache(capacity=64, registry=reg)
    srv = ReachQueryServer(list("abcd"), depth=32, batch=8,
                           registry=reg, cache=cache)
    st = fold_state([11, 22, 33])
    srv.update_state(st.mins, st.registers, epoch=1)
    got = []
    try:
        srv.submit(["a", "b"], "union", lambda d: got.append(d),
                   query_id=1)
        drain(srv, got, 1)
        srv.submit(["b", "a"], "union", lambda d: got.append(d),
                   query_id=2)   # same canonical set -> hit
        drain(srv, got, 2)
    finally:
        srv.close()
    miss, hit = got
    assert "cached" not in miss
    assert hit["cached"] is True
    for key in ("op", "estimate", "union", "jaccard", "bound", "epoch",
                "plane_epoch"):
        assert hit[key] == miss[key], key
    assert hit["id"] == 2
    assert srv.served == 2
    assert srv.dispatches == 1          # the hit never dispatched
    assert cache.summary()["hits"] == 1
    assert reg.counter(
        "streambench_reach_cache_hits_total").value == 1
    assert reg.counter(
        "streambench_reach_cache_misses_total").value == 1
    hist = reg.histogram("streambench_reach_cache_hit_ms")
    assert hist.summary().get("count") == 1


def test_stale_entry_never_served_after_epoch_bump():
    """THE invalidation property: after an epoch bump with different
    planes, the answer must come from the new planes — never the cached
    old-epoch result."""
    cache = ReachQueryCache(capacity=64)
    srv = ReachQueryServer(list("abcd"), depth=32, batch=8, cache=cache)
    st1 = fold_state([1, 2, 3])
    st2 = fold_state([1, 2, 3, 4, 5, 6, 7, 8])
    srv.update_state(st1.mins, st1.registers, epoch=1)
    got = []
    try:
        srv.submit(["a"], "union", lambda d: got.append(d), query_id=1)
        drain(srv, got, 1)
        srv.update_state(st2.mins, st2.registers, epoch=2)
        srv.submit(["a"], "union", lambda d: got.append(d), query_id=2)
        drain(srv, got, 2)
    finally:
        srv.close()
    old, new = got
    assert old["plane_epoch"] == 1 and new["plane_epoch"] == 2
    assert not new.get("cached")
    assert new["estimate"] != old["estimate"]  # different planes
    # and the post-bump answer seeds the NEW epoch's cache
    assert cache.summary()["epoch"] == 2
    assert cache.summary()["entries"] == 1


def test_queryattr_reconciles_with_cache_hits():
    """Cache-hit replies leave exactly one served lifecycle record, so
    the ISSUE 11 reconciliation (records == served counter) holds with
    the cache in the path."""
    from streambench_tpu.obs.queryattr import QueryLifecycle

    reg = MetricsRegistry()
    ql = QueryLifecycle(reg)
    cache = ReachQueryCache(capacity=64, registry=reg)
    srv = ReachQueryServer(list("abcd"), depth=32, batch=8,
                           registry=reg, cache=cache, queryattr=ql)
    st = fold_state([5, 6])
    srv.update_state(st.mins, st.registers, epoch=1)
    got = []
    try:
        for i in range(3):   # first round fills the cache
            srv.submit([list("abc")[i]], "union",
                       lambda d: got.append(d), query_id=i,
                       trace=f"t{i}")
        drain(srv, got, 3)
        for i in range(3, 6):   # second round hits it
            srv.submit([list("abc")[i % 3]], "union",
                       lambda d: got.append(d), query_id=i,
                       trace=f"t{i}")
        drain(srv, got, 6)
    finally:
        srv.close()
    assert srv.served == 6
    assert ql.summary()["served_records"] == 6
    assert cache.summary()["hits"] >= 1
    hits = [d for d in got if d.get("cached")]
    assert hits and all("server" in d for d in hits)
