"""Broker-edge chaos sweeps (ISSUE 20): the engine ingesting from the
fake Kafka cluster under seeded broker faults, verified oracle-EXACT.

The acceptance property: with broker-down windows, transient produce
errors and connection drops armed — plus mid-run crashes in the second
sweep — the supervised run completes, every per-window Redis count
equals the oracle exactly (``jax.sink.exactly_once``), the delivery
ledger balances (``consumed == delivered + redelivered``,
``delivered == sent``), and the conn drops PROVABLY exercised the
redelivery path (``kafka_redeliveries > 0``).  The flight recorder is
armed so a red sweep ships its black box.

Ground truth stays in the file journal: the generator writes its events
and oracle there, the same bytes are produced into the fake cluster,
and the engine consumes over the Kafka adapter — so the existing window
oracle judges the broker edge end to end.
"""

import random

from streambench_tpu.chaos import (
    FaultInjector,
    FaultPlan,
    Supervisor,
    check_exactly_once,
    check_kafka_edge,
    replay_note,
)
from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io import fakekafka, kafka
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.metrics import FaultCounters
from streambench_tpu.obs import FlightRecorder

EVENTS = 6_000
TAIL = 1_024   # records produced AFTER chaos attaches (the faulted tail)


def _setup(tmp_path, inj):
    """Generate events + oracle into the file journal, mirror every
    record into a fault-armed fake cluster, return the kafka side.

    The pre-chaos bulk goes in clean; the last ``TAIL`` records are
    produced through the armed cluster so produce faults and the
    broker-down window land on a real writer.
    """
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2,
                         jax_sink_retry_base_ms=1, jax_sink_retry_cap_ms=4,
                         jax_sink_exactly_once=True)
    r = as_redis(FakeRedisStore())
    fb = FileBroker(str(tmp_path / "journal"))
    gen.do_setup(r, cfg, broker=fb, events_num=EVENTS,
                 rng=random.Random(7), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    counters = FaultCounters()
    cl = fakekafka.FakeCluster()
    kb = kafka.KafkaBroker(fakekafka.INPROC,
                           clients=fakekafka.clients(cl),
                           counters=counters)
    kb.create_topic(cfg.kafka_topic, partitions=1)
    events = list(fb.read_all(cfg.kafka_topic))
    assert len(events) >= EVENTS
    w = kb.writer(cfg.kafka_topic)
    w.append_many(events[:-TAIL])
    w.flush()
    w.close()
    cl.attach_chaos(inj)
    wf = kafka.KafkaWriter(fakekafka.INPROC, cfg.kafka_topic,
                           clients=fakekafka.clients(cl),
                           counters=counters,
                           retry_base_ms=0.01, retry_cap_ms=0.05)
    wf.append_many(events[-TAIL:])
    wf.flush()
    wf.close()
    # every event is acked and in the log before the engine starts —
    # produce faults and the down window were absorbed, not dropped
    assert cl._topics[cfg.kafka_topic][0] == events
    return cfg, r, fb, kb, cl, mapping, counters


def _broker_fault_plan(crashes=()):
    plan = FaultPlan.generate(
        1234,
        kafka_produce_rate=0.08, kafka_conn_drop_rate=0.12,
        kafka_ops=8_000, kafka_down=((20, 28),))
    return FaultPlan(seed=plan.seed, kafka_faults=plan.kafka_faults,
                     kafka_down=plan.kafka_down, crashes=tuple(crashes))


def test_broker_faults_oracle_exact_ledger_balanced(tmp_path):
    """Down window + produce faults + conn drops, no crashes: the run
    is oracle-exact and the shared delivery ledger balances with
    genuine redeliveries."""
    inj = FaultInjector(_broker_fault_plan())
    cfg, r, fb, kb, cl, mapping, counters = _setup(tmp_path, inj)
    fr = FlightRecorder(str(tmp_path), capacity=64)
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner = StreamRunner(eng, kb.reader(cfg.kafka_topic),
                          flightrec=fr)
    runner.run_catchup()
    eng.close()

    snap = inj.counters.snapshot()
    assert snap.get("chaos_kafka_down", 0) > 0
    assert snap.get("chaos_kafka_produce", 0) > 0
    assert snap.get("chaos_kafka_conn_drop", 0) > 0
    repro = replay_note(seed=1234,
                        topic_path=fb.topic_path(cfg.kafka_topic),
                        overrides={"kafka.fake": True,
                                   "jax.sink.exactly_once": True})
    v = check_exactly_once(r, str(tmp_path), repro=repro)
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.exact == v.windows > 0
    # the broker edge: every acked produce reached the engine exactly
    # once, and the conn drops really exercised the redelivery path
    kv = check_kafka_edge(counters, require_redeliveries=True, windows=v,
                          repro=repro)
    assert kv.ok, kv.summary()
    assert kv.sent == kv.delivered == len(list(fb.read_all(cfg.kafka_topic)))
    assert eng.events_processed == EVENTS


def test_broker_faults_with_crash_resume_oracle_exact(tmp_path):
    """The full sweep: broker faults AND a mid-run crash script.  The
    supervised engine resumes from its checkpoint over the Kafka
    adapter (fresh consumer, seek to the checkpointed offset) and still
    lands oracle-exact; replayed records inflate ``delivered`` past
    ``sent`` (they are honest re-reads, not redeliveries), so the
    crash-run identity is ``consumed == delivered + redelivered`` with
    ``delivered >= sent``."""
    inj = FaultInjector(_broker_fault_plan(
        crashes=(("batch", 5), ("flush", 1), ("batch", 2))))
    cfg, r, fb, kb, cl, mapping, counters = _setup(tmp_path, inj)
    fr = FlightRecorder(str(tmp_path), capacity=64)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))

    def make_runner():
        eng = AdAnalyticsEngine(cfg, mapping, redis=r)
        return StreamRunner(eng, kb.reader(cfg.kafka_topic),
                            checkpointer=ckpt,
                            crash_points=inj.scheduler, flightrec=fr)

    sup = Supervisor(make_runner, backoff_base_ms=1, backoff_cap_ms=4,
                     seed=1, flightrec=fr)
    st = sup.run(catchup=True)
    assert st.completed, f"supervised run did not complete: {st.errors}"
    assert st.crashes >= 2
    sup.runner.engine.close()

    v = check_exactly_once(r, str(tmp_path))
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    snap = counters.snapshot()
    total = len(list(fb.read_all(cfg.kafka_topic)))
    assert snap["kafka_consumed"] == \
        snap["kafka_delivered"] + snap.get("kafka_redeliveries", 0)
    assert snap["kafka_produced"] == total
    assert snap["kafka_delivered"] >= total   # crash replays re-read
    assert snap.get("kafka_redeliveries", 0) > 0
    assert sup.runner.engine.events_processed == EVENTS


def test_no_kafka_config_keeps_hot_paths_untouched(tmp_path):
    """Default-off pin: with no kafka config every switch point stays on
    its pre-kafka path — make_broker hands back the file journal, and a
    default fault plan carries zero broker draws (byte-identity of the
    plans themselves is pinned in test_fakekafka)."""
    cfg = default_config()
    assert cfg.kafka_bootstrap == "" and cfg.kafka_fake is False
    b = kafka.make_broker(cfg.kafka_bootstrap_servers,
                          str(tmp_path / "j"), fake=cfg.kafka_fake)
    assert isinstance(b, FileBroker)
    plan = FaultPlan.generate(99, sink_rate=0.2, sink_ops=10,
                              journal_rate=0.3, journal_polls=5, crashes=2)
    assert plan.kafka_faults == {} and plan.kafka_down == ()
    # an injector over such a plan never draws a broker op, so a
    # chaos-armed FileBroker run cannot touch a kafka counter
    inj = FaultInjector(plan)
    assert not any(k.startswith("chaos_kafka")
                   for k in inj.counters.snapshot())
