"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax imports.

Multi-chip hardware is not available in CI; sharding correctness is tested on
a virtual 8-device CPU mesh, exactly like the reference tests multi-node
behavior with an embedded in-process cluster
(``ApplicationWithDCWithoutDeserializerTest.java:19-45``).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize may pre-register a hardware backend plugin and
# force jax_platforms via jax.config (overriding the env var), so pin the
# config itself too — backends are not initialized yet at conftest time.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
