"""Supervisor behavior: seeded randomized crash sweeps + give-up policy.

The property (ISSUE 1, satellite): kill the runner at randomized
batch/flush/checkpoint boundaries and the oracle bounds of
``chaos.verify`` hold EVERY time.  A fast seed subset runs in tier-1;
the full >= 20-seed sweep is ``slow``/``chaos``-marked.
"""

import random

import pytest

from streambench_tpu.chaos import (
    FaultInjector,
    FaultPlan,
    Supervisor,
    check_at_least_once,
    replay_note,
)
from streambench_tpu.chaos.plan import EngineCrash
from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """One journaled topic shared by every seed (events are immutable;
    each seed gets its own Redis + checkpoint dir)."""
    tmp = tmp_path_factory.mktemp("sup")
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2,
                         jax_sink_retry_base_ms=1, jax_sink_retry_cap_ms=4)
    broker = FileBroker(str(tmp / "broker"))
    gen.do_setup(None, cfg, broker=broker, events_num=6_000,
                 rng=random.Random(11), workdir=str(tmp))
    mapping = gen.load_ad_mapping_file(str(tmp / gen.AD_TO_CAMPAIGN_FILE))
    campaigns, _ = gen.load_ids(str(tmp))
    return tmp, cfg, broker, mapping, campaigns


def crash_sweep_seed(dataset, tmp_path, seed: int) -> None:
    """One randomized supervised run; asserts the oracle bounds."""
    tmp, cfg, broker, mapping, campaigns = dataset
    rng = random.Random(seed)
    # randomized crash script over all three boundary kinds; batch
    # ordinals spread across the ~12 boundaries a 6k-event catchup has,
    # flush/checkpoint pinned to their reachable ordinals
    crashes = []
    for _ in range(rng.randrange(1, 5)):
        kind = rng.choice(("batch", "batch", "flush", "checkpoint"))
        n = rng.randrange(1, 9) if kind == "batch" else 1
        crashes.append((kind, n))
    plan = FaultPlan(seed=seed, crashes=tuple(crashes),
                     sink_faults={i: "refused"
                                  for i in range(rng.randrange(0, 4))})
    inj = FaultInjector(plan)
    from streambench_tpu.io.redis_schema import seed_campaigns

    r = as_redis(FakeRedisStore())
    seed_campaigns(r, campaigns)
    ckpt = Checkpointer(str(tmp_path / f"ckpt-{seed}"))

    def make_runner():
        eng = AdAnalyticsEngine(cfg, mapping, redis=inj.wrap_redis(r))
        reader = inj.wrap_reader(broker.reader(cfg.kafka_topic))
        return StreamRunner(eng, reader, checkpointer=ckpt,
                            crash_points=inj.scheduler)

    # the give-up ceiling must exceed the crash-script length: a script
    # whose every crash lands before the first checkpoint makes zero
    # DURABLE progress by design, and the sweep asserts recovery, not
    # the give-up policy (tested separately below)
    sup = Supervisor(make_runner, backoff_base_ms=1, backoff_cap_ms=2,
                     seed=seed, max_no_progress_restarts=len(crashes) + 1)
    topic = broker.topic_path(cfg.kafka_topic)
    # a red seed must be one paste away from a bit-identical replay
    repro = replay_note(seed=seed, topic_path=topic,
                        overrides={"jax.batch.size": 256,
                                   "jax.scan.batches": 2})
    st = sup.run(catchup=True)
    assert st.completed and not st.gave_up, (seed, st.errors, repro)
    sup.runner.engine.close()
    v = check_at_least_once(r, str(tmp), topic,
                            st.replay_segments, st.carried, repro=repro)
    assert v.ok, (seed, v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert sup.runner.engine.events_processed == 6_000, (seed, repro)


@pytest.mark.parametrize("seed", range(4))
def test_randomized_crash_boundaries_fast(dataset, tmp_path, seed):
    crash_sweep_seed(dataset, tmp_path, seed)


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("seed", range(4, 24))
def test_randomized_crash_boundaries_sweep(dataset, tmp_path, seed):
    crash_sweep_seed(dataset, tmp_path, seed)


def test_supervisor_gives_up_after_no_progress(tmp_path):
    """A crash loop that never advances the checkpoint must end in a
    clean give-up after exactly N consecutive no-progress restarts —
    never an infinite restart spin."""
    calls = {"n": 0}

    class _Runner:
        crash_points = None

        def resume(self):
            return False

        def _reader_position(self):
            return 0            # never advances

        def run_catchup(self, **kw):
            calls["n"] += 1
            raise EngineCrash("wedged at the same offset")

        def run(self, **kw):
            return self.run_catchup(**kw)

    slept = []
    sup = Supervisor(lambda: _Runner(), max_no_progress_restarts=3,
                     backoff_base_ms=8, backoff_cap_ms=32, seed=0,
                     sleep=slept.append)
    st = sup.run(catchup=True)
    assert st.gave_up and not st.completed
    # first crash sets the baseline; 3 more at the same offset give up
    assert calls["n"] == 4 and st.crashes == 4 and st.restarts == 3
    # capped exponential backoff with jitter: nondecreasing cap, bounded
    assert len(slept) == 3
    assert all(0.004 <= s <= 0.032 for s in slept)


def test_supervisor_progress_resets_giveup_counter(tmp_path):
    """Crashes whose checkpoint ADVANCED reset the no-progress streak: a
    slowly-progressing stream is never declared wedged — even when every
    single attempt ends in a crash."""
    class _Ckpt:
        def __init__(self):
            self.offset = 0

        def load(self):
            class _Snap:
                pass
            s = _Snap()
            s.offset = self.offset
            return s if self.offset else None

    ckpt = _Ckpt()
    seq = iter([10, 20, 30, 40])

    class _Runner:
        crash_points = None
        checkpointer = ckpt

        def resume(self):
            return False

        def _reader_position(self):
            return ckpt.offset

        def run_catchup(self, **kw):
            # each attempt saves a further checkpoint, then crashes
            ckpt.offset = next(seq)
            raise EngineCrash("crash with progress")

    made = {"n": 0}

    def factory():
        made["n"] += 1
        r = _Runner()
        if made["n"] == 5:                       # attempt 5 completes
            r.run_catchup = lambda **kw: "done"
        return r

    sup = Supervisor(factory, max_no_progress_restarts=2,
                     backoff_base_ms=0, backoff_cap_ms=0, seed=0)
    st = sup.run(catchup=True)
    assert st.completed and not st.gave_up
    assert st.crashes == 4 and st.restarts == 4


def test_supervisor_counts_checkpoint_then_crash_as_progress(tmp_path):
    """A crash injected AT the checkpoint boundary (snapshot saved, then
    EngineCrash) is durable progress at THAT crash — the give-up counter
    must reset immediately, not one restart later (the seed-1234
    acceptance scenario: three no-checkpoint crashes followed by a
    checkpoint-boundary crash must not give up)."""
    class _Ckpt:
        offset = 0

        def load(self):
            if not self.offset:
                return None
            class _S:
                offset = self.offset
            return _S()

    ckpt = _Ckpt()
    attempt = {"n": 0}

    class _Runner:
        crash_points = None
        checkpointer = ckpt

        def resume(self):
            return False

        def _reader_position(self):
            return ckpt.offset

        def run_catchup(self, **kw):
            attempt["n"] += 1
            if attempt["n"] <= 3:
                raise EngineCrash("before any checkpoint")
            if attempt["n"] == 4:
                ckpt.offset = 999           # saved, THEN crashed
                raise EngineCrash("at the checkpoint boundary")
            return "done"

    sup = Supervisor(lambda: _Runner(), max_no_progress_restarts=3,
                     backoff_base_ms=0, backoff_cap_ms=0, seed=0)
    st = sup.run(catchup=True)
    assert st.completed and not st.gave_up
    assert st.crashes == 4
