"""Per-window latency attribution (ISSUE 4 tentpole, obs.lifecycle):
the five stamped segments partition each write's end-to-end latency,
stamps merge min/max across folds, the table stays bounded, the engine
integration journals an ``attribution`` block whose segment sums match
the e2e histogram, and the ``attribution`` CLI renders/diffs it."""

import json
import os
import random

import numpy as np
import pytest

import streambench_tpu.obs.lifecycle as lcmod
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.obs import MetricsRegistry
from streambench_tpu.obs.lifecycle import SEGMENTS, WindowLifecycle


class _Batch:
    """Duck-typed EncodedBatch surface note_fold reads."""

    def __init__(self, times, base=0, valid=None):
        t = np.asarray(times, np.int64)
        self.event_time = t
        self.valid = (np.ones(len(t), bool) if valid is None
                      else np.asarray(valid, bool))
        self.n = len(t)
        self.base_time_ms = base


@pytest.fixture
def clock(monkeypatch):
    """Deterministic wall clock for the lifecycle module."""
    state = {"t": 1_000}
    monkeypatch.setattr(lcmod, "now_ms", lambda: state["t"])
    return state


def test_segments_partition_e2e_exactly(clock):
    reg = MetricsRegistry()
    lc = WindowLifecycle(reg, divisor_ms=100)
    b = _Batch([10, 50, 150])          # windows ts=0 and ts=100
    b._lc_read_ms = 1_000
    b._lc_encode_ms = 1_005
    clock["t"] = 1_010
    lc.note_fold(b)
    clock["t"] = 1_020
    lc.note_flush([0, 100])
    lc.note_written([0, 100], 1_025)
    s = lc.summary()
    assert s["writes_observed"] == 2 and s["writes_untracked"] == 0
    segs = s["segments"]
    # per write the five segments sum to exactly stamp - window_ts, so
    # the histogram SUMS (exact, unlike bucketed percentiles) partition:
    # e2e = (1025-0) + (1025-100) = 1950
    assert s["e2e_ms"]["sum"] == 1_950
    assert sum(segs[k]["sum"] for k in SEGMENTS) == 1_950
    # and each segment carries the intended boundary
    assert segs["encode"]["sum"] == 10     # 5 per window
    assert segs["fold"]["sum"] == 10
    assert segs["flush"]["sum"] == 20
    assert segs["sink"]["sum"] == 10
    assert segs["ingest"]["sum"] == 1_900  # 1000 + 900
    for k in SEGMENTS:
        assert segs[k]["count"] == 2


def test_stamps_merge_across_folds(clock):
    """A window fed by several batches keeps min-first-read /
    max-last-read / max-encode / last-fold, so ``ingest`` covers the
    whole arrival wait and ``encode`` only the final batch's encode
    residency — the arrival span itself is its own histogram."""
    reg = MetricsRegistry()
    lc = WindowLifecycle(reg, divisor_ms=10_000)
    b1 = _Batch([10])
    b1._lc_read_ms, b1._lc_encode_ms = 1_000, 1_001
    clock["t"] = 1_002
    lc.note_fold(b1)
    b2 = _Batch([20])                     # same window, later stamps
    b2._lc_read_ms, b2._lc_encode_ms = 1_100, 1_101
    clock["t"] = 1_102
    lc.note_fold(b2)
    clock["t"] = 1_110
    lc.note_flush([0])
    lc.note_written([0], 1_120)
    s = lc.summary()
    segs = s["segments"]
    assert segs["ingest"]["sum"] == 1_100   # LAST read - window start
    assert segs["encode"]["sum"] == 1       # 1101 - 1100 (last read)
    assert segs["fold"]["sum"] == 1         # 1102 - 1101
    assert segs["flush"]["sum"] == 8        # 1110 - 1102
    assert segs["sink"]["sum"] == 10        # 1120 - 1110
    assert s["arrival_span_ms"]["sum"] == 100  # 1100 - 1000


def test_invalid_rows_masked_and_untracked_writes_counted(clock):
    reg = MetricsRegistry()
    lc = WindowLifecycle(reg, divisor_ms=100)
    b = _Batch([10, 950], valid=[True, False])   # window 900 never folds
    lc.note_fold(b)
    lc.note_written([0, 900], 1_050)
    s = lc.summary()
    assert s["writes_observed"] == 1
    assert s["writes_untracked"] == 1            # window 900 unseen


def test_window_table_bounded_by_cap_and_retirement(clock):
    reg = MetricsRegistry()
    lc = WindowLifecycle(reg, divisor_ms=100, lateness_ms=0,
                         max_windows=16)
    for i in range(64):
        lc.note_fold(_Batch([i * 100 + 1]))
    s = lc.summary()
    assert s["open_windows"] <= 16
    assert s["windows_evicted"] == 48
    # a written window far behind the newest one is retired outright
    lc.note_written([5_000], 7_000)              # tracked, old
    assert 5_000 not in lc._windows


def test_engine_integration_attribution_matches_e2e(tmp_path):
    """Catchup run with lifecycle attached: every observed write lands
    one sample per segment, and the segment sums partition the matched
    e2e histogram (within clamping of future-skewed events)."""
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=6000,
                 rng=random.Random(3), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    reg = MetricsRegistry()
    engine.attach_obs(reg, lifecycle=True)
    runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
    runner.run_catchup()
    engine.close()
    s = engine._obs_lifecycle.summary()
    assert s["writes_observed"] > 0
    segs = s["segments"]
    for k in SEGMENTS:
        assert segs[k]["count"] == s["writes_observed"]
    assert s["e2e_ms"]["count"] == s["writes_observed"]
    total = sum(segs[k]["sum"] for k in SEGMENTS)
    e2e = s["e2e_ms"]["sum"]
    # negative-clamped jitter aside, the partition is exact
    assert abs(total - e2e) <= max(0.1 * max(e2e, 1), 100)
    # the registry carries the same data for a scrape
    text = reg.render_prometheus()
    assert 'streambench_window_segment_ms_bucket{le=' in text
    assert 'segment="ingest"' in text
    # default path untouched: no lifecycle without the opt-in
    assert AdAnalyticsEngine(cfg, mapping)._obs_lifecycle is None


def test_collector_journals_attribution_block():
    """engine_collector puts the lifecycle summary on each snapshot so
    the final metrics.jsonl record carries the full attribution."""
    from streambench_tpu.metrics import FaultCounters
    from streambench_tpu.obs import engine_collector
    from streambench_tpu.trace import Tracer

    class _Eng:
        tracer = Tracer()
        faults = FaultCounters()
        events_processed = 0
        _obs_hist = None

        def telemetry(self):
            return {"events": 0, "windows_written": 0,
                    "watermark_lag_ms": None, "sink_dirty_rows": 0,
                    "pending_rows": 0}

    eng = _Eng()
    reg = MetricsRegistry()
    eng._obs_lifecycle = WindowLifecycle(reg, divisor_ms=100)
    rec: dict = {}
    engine_collector(eng, registry=reg)(rec, 1.0)
    assert rec["attribution"]["writes_observed"] == 0
    assert set(rec["attribution"]["segments"]) == set(SEGMENTS)
    # without the tracker the key is absent — old journals unchanged
    eng2 = _Eng()
    rec2: dict = {}
    engine_collector(eng2, registry=MetricsRegistry())(rec2, 1.0)
    assert "attribution" not in rec2


def _attribution_block(scale=1.0):
    def h(p50):
        p50 *= scale
        return {"count": 4, "sum": p50 * 4, "min": p50 / 2,
                "max": p50 * 2, "p50": p50, "p95": p50 * 1.5,
                "p99": p50 * 2}
    return {
        "writes_observed": 4, "writes_untracked": 0,
        "open_windows": 2, "windows_evicted": 0,
        "e2e_ms": h(10_000),
        "segments": {"ingest": h(9_000), "encode": h(200),
                     "fold": h(100), "flush": h(500), "sink": h(200)},
    }


def _write_attr_series(path, scale=1.0):
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "snapshot", "seq": 0, "ts_ms": 1,
                            "uptime_ms": 100}) + "\n")
        f.write(json.dumps({"kind": "final", "seq": 1, "ts_ms": 2,
                            "uptime_ms": 200,
                            "attribution": _attribution_block(scale)})
                + "\n")


def test_attribution_cli_report_and_diff(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main as obs_main

    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _write_attr_series(a)
    _write_attr_series(b, scale=2.0)
    assert obs_main(["attribution", a]) == 0
    out = capsys.readouterr().out
    assert "window latency attribution" in out
    assert "ingest" in out and "sink" in out
    assert "segment p50 sum" in out and "% of e2e p50" in out
    # A/B diff: second path
    assert obs_main(["attribution", a, b]) == 0
    out = capsys.readouterr().out
    assert "attribution diff" in out and "e2e" in out
    assert "9,000" in out and "18,000" in out
    # --json round-trips the dict
    assert obs_main(["attribution", a, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["attribution"]["segments"]["ingest"]["p50"] == 9_000
    # a run without attribution renders a pointer, not a crash
    c = str(tmp_path / "c.jsonl")
    with open(c, "w") as f:
        f.write(json.dumps({"kind": "snapshot", "seq": 0}) + "\n")
    assert obs_main(["attribution", c]) == 0
    assert "no attribution records" in capsys.readouterr().out


def test_ingest_pipeline_carries_true_read_stamps(tmp_path):
    """With the staged ingest pipeline on, the reader's wall stamp rides
    the item into the encoded batches, so ingest/encode split at the
    real read boundary (not at encode time)."""
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2,
                         jax_ingest_pipeline="on")
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=5000,
                 rng=random.Random(11), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    engine.attach_obs(MetricsRegistry(), lifecycle=True)
    runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
    runner.run_catchup()
    engine.close()
    s = engine._obs_lifecycle.summary()
    assert s["writes_observed"] > 0
    assert s["segments"]["encode"]["count"] == s["writes_observed"]
