"""Fleet chaos (ISSUE 16): network + ship-log fault injection, the
freshness-aware failover router, the replica fleet supervisor, and the
verified shed-or-answer invariants.

Tier-1 subset pins the contracts:

- fleet fault draws are seeded-deterministic and a rate-0 plan is
  bit-identical to a pre-fleet plan under the same seed (knobs
  default-off means NOTHING changes);
- a no-injector ``ChaosPubSub`` is a byte-exact pass-through;
- the ship-log filter's torn/corrupt/delayed damage is skip-and-resync
  durable: damaged records never load, the writer's own view never runs
  ahead of what it durably wrote;
- ``PubSubClient.request`` retries with FRESH ids and the server-side
  request-id dedup keeps dup-faulted traffic exactly-once-answered;
- pidfiles use the "pid starttime" format and refuse live pids while
  accepting recycled ones;
- the router is sticky by campaign-set hash, fails over in freshness
  order, and sheds honestly when every replica is stale;
- the ``FleetSupervisor`` respawns crash-killed replicas under the PR 1
  capped-backoff formula and gives up on no-progress slots;
- the ``chaos.verify`` fleet invariants catch every violation class
  they exist for.

The 20-seed randomized sweep is marked ``slow``.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from streambench_tpu.chaos import (
    ChaosPubSub,
    FaultInjector,
    FaultPlan,
    FleetSupervisor,
    check_fleet_accounting,
    check_fleet_convergence,
    check_staleness_bound,
    durable_epoch_at,
    ship_epoch_timeline,
)
from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
from streambench_tpu.dimensions.store import LOG_NAME, DurableDimensionStore
from streambench_tpu.reach.router import ReachRouter, campaign_shard
from streambench_tpu.utils.ids import now_ms
from streambench_tpu.utils.pidfile import (
    acquire_pidfile,
    pidfile_alive,
    proc_starttime,
    read_pidfile,
    release_pidfile,
)


# ----------------------------------------------------------------------
# plan: fleet draws
def test_fleet_plan_seeded_deterministic():
    kw = dict(net_drop_rate=0.2, net_delay_rate=0.1, net_dup_rate=0.1,
              net_torn_rate=0.05, net_msgs=200,
              partition_windows=((30, 10),), ship_rate=0.3, ship_ops=40)
    a = FaultPlan.generate(7, **kw)
    assert a == FaultPlan.generate(7, **kw)
    assert a != FaultPlan.generate(8, **kw)
    assert a.net_faults and a.ship_faults and not a.is_zero
    assert a.partition_windows == ((30, 10),)


def test_fleet_knobs_off_is_bit_identical_to_pre_fleet_plan():
    """Fleet draws happen AFTER the legacy surfaces' draws, so leaving
    every fleet knob at its default changes NOTHING about a legacy
    plan — the default-off guarantee at the plan layer."""
    legacy = dict(sink_rate=0.3, sink_ops=50, journal_rate=0.2,
                  journal_polls=30, crashes=3)
    a = FaultPlan.generate(42, **legacy)
    b = FaultPlan.generate(42, **legacy, net_drop_rate=0.0,
                           net_delay_rate=0.0, net_dup_rate=0.0,
                           net_torn_rate=0.0, net_msgs=500,
                           ship_rate=0.0, ship_ops=100)
    assert a == b
    assert b.is_zero is False and not b.net_faults and not b.ship_faults


def test_partition_window_outranks_rolled_kind():
    plan = FaultPlan.generate(3, net_dup_rate=1.0, net_msgs=20,
                              partition_windows=((5, 5),))
    inj = FaultInjector(plan)
    kinds = [inj.net_fault() for _ in range(20)]
    assert kinds[5:10] == ["drop"] * 5          # window wins over dup
    assert all(k == "dup" for k in kinds[:5] + kinds[10:])
    assert inj.counters.get("net_partition_drops") == 5


# ----------------------------------------------------------------------
# ship-log fault filter through the real store
def _planes(seed, camps, k=16, r=32):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 1 << 32, size=(len(camps), k),
                         dtype=np.uint32),
            rng.integers(0, 20, size=(len(camps), r)).astype(np.int32))


def test_ship_faults_skip_and_resync(tmp_path):
    """torn eats itself AND the next append (one garbage line);
    corrupt eats itself; delayed lands late; the store's own view and
    the decodable timeline only ever contain intact records."""
    camps = ["a", "b"]
    store = DurableDimensionStore(str(tmp_path))
    plan = FaultPlan(ship_faults={1: "torn", 3: "delayed"})
    FaultInjector(plan).attach_ship_chaos(store)
    for epoch in range(1, 6):     # ship indexes 0..4
        m, r = _planes(epoch, camps)
        store.put_reach_sketches(m, r, camps, epoch,
                                 submit_ms=now_ms(), folded_ms=now_ms())
    store.close()
    timeline = ship_epoch_timeline(str(tmp_path / LOG_NAME))
    # epoch 2 torn -> its stub merges with epoch 3's append into one
    # undecodable line; epoch 4 held, flushed intact before epoch 5
    assert [e for _, e in timeline] == [1, 4, 5]
    # the writer's own view never absorbed a damaged append: reopen
    # replays the log, latest DECODABLE record wins
    re = DurableDimensionStore(str(tmp_path))
    assert re.reach_sketches()["epoch"] == 5
    re.close()


def test_ship_fault_hook_default_off_is_byte_identical(tmp_path):
    camps = ["a", "b"]
    m, r = _planes(1, camps)
    plain = DurableDimensionStore(str(tmp_path / "plain"))
    plain.put_reach_sketches(m, r, camps, 1, update_time_ms=123,
                             submit_ms=456, folded_ms=455)
    plain.close()
    wired = DurableDimensionStore(str(tmp_path / "wired"))
    FaultInjector(FaultPlan.zeros()).attach_ship_chaos(wired)
    wired.put_reach_sketches(m, r, camps, 1, update_time_ms=123,
                             submit_ms=456, folded_ms=455)
    wired.close()
    read = lambda p: open(os.path.join(p, LOG_NAME), "rb").read()
    assert read(str(tmp_path / "plain")) == read(str(tmp_path / "wired"))


# ----------------------------------------------------------------------
# network chaos proxy + client retry + server dedup
def _echo_server(counts: dict):
    """A pub/sub server with a 'reach' verb that ledgers every handler
    invocation per id and echoes the payload."""
    srv = PubSubServer(port=0)
    lock = threading.Lock()

    def handle(msg, reply):
        with lock:
            counts[msg.get("id")] = counts.get(msg.get("id"), 0) + 1
        reply({"id": msg.get("id"), "v": msg.get("v"),
               "estimate": 1.0, "plane_epoch": 1})

    srv.register_query("reach", handle)
    return srv.start()


def test_chaos_proxy_no_injector_is_passthrough():
    counts: dict = {}
    srv = _echo_server(counts)
    proxy = ChaosPubSub(srv.address).start()
    try:
        direct = PubSubClient(*srv.address, timeout_s=10)
        proxied = PubSubClient(*proxy.address, timeout_s=10)
        for i in range(10):
            a = direct.request({"type": "reach", "id": f"d{i}", "v": i},
                               timeout_s=5.0)
            b = proxied.request({"type": "reach", "id": f"d{i}~p",
                                 "v": i}, timeout_s=5.0)
            assert a["v"] == b["v"] == i
        direct.close()
        proxied.close()
        assert proxy.stats["dropped"] == proxy.stats["torn"] == 0
        assert proxy.stats["dupped"] == proxy.stats["delayed"] == 0
        assert proxy.stats["msgs"] >= 20
    finally:
        proxy.close()
        srv.close()


def test_retry_plus_dedup_exactly_once_under_drops_and_dups():
    """40% drops + 20% dups on the wire: every request still returns
    exactly one answer, the server executed each delivered id at most
    once, and retries used FRESH derived ids."""
    counts: dict = {}
    srv = _echo_server(counts)
    plan = FaultPlan.generate(11, net_drop_rate=0.4, net_dup_rate=0.2,
                              net_msgs=2000)
    inj = FaultInjector(plan)
    proxy = ChaosPubSub(srv.address, inj).start()
    try:
        c = PubSubClient(*proxy.address, timeout_s=30)
        got = []
        for i in range(30):
            try:
                got.append(c.request({"type": "reach", "id": f"q{i}",
                                      "v": i},
                                     timeout_s=0.5, retries=8))
            except TimeoutError:
                pass   # honest exhaustion is allowed; double answers not
        c.close()
        vals = [d["v"] for d in got]
        assert len(vals) == len(set(vals)), "double-answered request"
        assert len(vals) >= 20
        # at the server every executed id ran exactly once — duplicated
        # request frames were absorbed by the request-id dedup
        assert all(n == 1 for n in counts.values()), counts
        assert {str(k).split("~r")[0] for k in counts} <= {
            f"q{i}" for i in range(30)}
        assert proxy.stats["dropped"] > 0 and proxy.stats["dupped"] > 0
    finally:
        proxy.close()
        srv.close()


def test_proxy_torn_frames_resync():
    """A torn frame is one undecodable line — the receiver skips it and
    the NEXT message still parses (framing never desyncs)."""
    counts: dict = {}
    srv = _echo_server(counts)
    plan = FaultPlan(net_faults={1: "torn"})
    proxy = ChaosPubSub(srv.address, FaultInjector(plan)).start()
    try:
        c = PubSubClient(*proxy.address, timeout_s=10)
        # msg idx 0 = request out intact, idx 1 = reply TORN: the torn
        # reply never decodes as t0's answer, so attempt 2 (fresh id
        # t0~r1, msg idx 2/3) lands it
        a = c.request({"type": "reach", "id": "t0", "v": 0},
                      timeout_s=1.0, retries=2)
        assert a["v"] == 0 and a["id"] == "t0~r1"
        c.close()
        assert proxy.stats["torn"] == 1
    finally:
        proxy.close()
        srv.close()


def test_proxy_drop_conns_severs_but_keeps_listening():
    counts: dict = {}
    srv = _echo_server(counts)
    proxy = ChaosPubSub(srv.address).start()
    try:
        c = PubSubClient(*proxy.address, timeout_s=10)
        assert c.request({"type": "reach", "id": "a", "v": 1},
                         timeout_s=5.0)["v"] == 1
        assert proxy.drop_conns() >= 2
        with pytest.raises((TimeoutError, ConnectionError, OSError)):
            c.request({"type": "reach", "id": "b", "v": 2},
                      timeout_s=0.5)
        c.close()
        c2 = PubSubClient(*proxy.address, timeout_s=10)   # re-dial works
        assert c2.request({"type": "reach", "id": "c", "v": 3},
                          timeout_s=5.0)["v"] == 3
        c2.close()
    finally:
        proxy.close()
        srv.close()


# ----------------------------------------------------------------------
# pidfile
def test_pidfile_format_and_refusal(tmp_path):
    path = str(tmp_path / "pids" / "replica_0")
    assert acquire_pidfile(path) == os.getpid()
    pid, started = read_pidfile(path)
    assert pid == os.getpid()
    assert started == proc_starttime(os.getpid())
    # a live pidfile refuses a second acquire
    assert acquire_pidfile(path) is None
    assert pidfile_alive(path) == os.getpid()
    release_pidfile(path)
    assert not os.path.exists(path)


def test_pidfile_recycled_pid_is_dead(tmp_path):
    """Same pid number, different starttime: the process the file named
    is GONE — a recycled pid must not block the seat."""
    path = str(tmp_path / "replica_1")
    with open(path, "w") as f:
        f.write(f"{os.getpid()} 1\n")     # our pid, wrong starttime
    assert pidfile_alive(path) is None
    assert acquire_pidfile(path) == os.getpid()
    release_pidfile(path)


def test_pidfile_release_refuses_foreign(tmp_path):
    path = str(tmp_path / "replica_2")
    with open(path, "w") as f:
        f.write(f"{os.getpid() + 1} 1\n")
    release_pidfile(path)                  # not ours: left alone
    assert os.path.exists(path)


# ----------------------------------------------------------------------
# router: stickiness / failover order / honest shed
def _fake_replica(tag: str, *, shed=None, staleness_ms=5.0, epoch=3):
    """A pub/sub endpoint impersonating a replica's reach verb."""
    srv = PubSubServer(port=0)

    def handle(msg, reply):
        if shed is not None:
            reply({"shed": True, "reason": shed, "plane_epoch": epoch,
                   "staleness_ms": staleness_ms, "id": msg.get("id")})
            return
        reply({"estimate": 1.0, "plane_epoch": epoch, "tag": tag,
               "staleness_ms": staleness_ms, "id": msg.get("id")})

    srv.register_query("reach", handle)
    return srv.start()


def _dead_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_router_sticky_by_campaign_set_hash():
    reps = [_fake_replica("r0"), _fake_replica("r1")]
    router = ReachRouter([f"127.0.0.1:{r.address[1]}" for r in reps],
                         timeout_s=5.0, retries=0).start()
    try:
        c = PubSubClient(*router.address, timeout_s=10)
        sets = [[f"c{i}"] for i in range(8)] + [["c1", "c2"]]
        for sel in sets:
            want = campaign_shard(sel, 2)
            for n in range(2):           # stickiness: same answer twice
                d = c.request({"type": "reach", "campaigns": sel,
                               "op": "union", "id": f"{sel}-{n}"},
                              timeout_s=5.0)
                assert d["tag"] == f"r{want}", (sel, d)
        c.close()
        assert router.failovers == 0
    finally:
        router.close()
        for r in reps:
            r.close()


def test_router_failover_order_and_episode_recorded():
    """Dead primary: the query lands on the freshest secondary, the
    failover counter and episode latency ring record it, and after
    SUSPECT_AFTER consecutive failures the primary is demoted."""
    live = _fake_replica("live")
    dead = _dead_port()
    # find a campaign set whose sticky primary is the dead seat 0
    sel = next([f"s{i}"] for i in range(64)
               if campaign_shard([f"s{i}"], 2) == 0)
    router = ReachRouter([f"127.0.0.1:{dead}",
                          f"127.0.0.1:{live.address[1]}"],
                         timeout_s=1.0, retries=0).start()
    try:
        c = PubSubClient(*router.address, timeout_s=30)
        for n in range(3):
            d = c.request({"type": "reach", "campaigns": sel,
                           "op": "union", "id": n}, timeout_s=10.0)
            assert d["tag"] == "live"
        c.close()
        s = router.summary()
        # queries 1+2 fail over off the dead primary; by query 3 the
        # primary is SUSPECT (2 consecutive failures) and demoted, so
        # the live replica is tried first — no failover episode
        assert s["failovers"] == 2 and s["answered"] == 3
        assert "failover_p99_ms" in s and s["failover_p99_ms"] >= 0
        assert router.handles[0].suspect()        # demoted
        assert not router.handles[1].suspect()
    finally:
        router.close()
        live.close()


def test_router_all_stale_sheds_honestly():
    reps = [_fake_replica("r0", shed="stale"),
            _fake_replica("r1", shed="stale")]
    router = ReachRouter([f"127.0.0.1:{r.address[1]}" for r in reps],
                         timeout_s=5.0, retries=0).start()
    try:
        c = PubSubClient(*router.address, timeout_s=10)
        d = c.request({"type": "reach", "campaigns": ["x"],
                       "op": "union", "id": "q"}, timeout_s=5.0)
        assert d == {"shed": True, "reason": "all_stale", "id": "q"}
        c.close()
        assert router.shed == 1 and router.answered == 0
    finally:
        router.close()
        for r in reps:
            r.close()


def test_router_forwards_client_errors_without_failover():
    srv = PubSubServer(port=0)

    def refuse(msg, reply):
        reply({"error": "bad_request", "id": msg.get("id")})

    srv.register_query("reach", refuse)
    srv.start()
    other = _fake_replica("other")
    router = ReachRouter([f"127.0.0.1:{srv.address[1]}",
                          f"127.0.0.1:{other.address[1]}"],
                         timeout_s=5.0, retries=0).start()
    try:
        sel = next([f"s{i}"] for i in range(64)
                   if campaign_shard([f"s{i}"], 2) == 0)
        c = PubSubClient(*router.address, timeout_s=10)
        d = c.request({"type": "reach", "campaigns": sel, "op": "nope",
                       "id": "e"}, timeout_s=5.0)
        assert d["error"] == "bad_request" and d["id"] == "e"
        c.close()
        assert router.failovers == 0       # malformed != failed over
    finally:
        router.close()
        srv.close()
        other.close()


# ----------------------------------------------------------------------
# fleet supervisor (injected clock + sleep: no real waiting)
class _FakeProc:
    def __init__(self):
        self.pid = 4242
        self.code = None

    def poll(self):
        return self.code

    def kill(self):
        self.code = -9

    terminate = kill


def _stepper(**kw):
    clock = {"t": 0.0}
    spawned = []

    def spawn(idx, attempt):
        p = _FakeProc()
        spawned.append((idx, attempt, p))
        return p

    sup = FleetSupervisor(spawn, 1, clock=lambda: clock["t"],
                          sleep=lambda s: None, **kw)
    return sup, clock, spawned


def test_supervisor_respawns_after_backoff_and_hooks_restart():
    restarts = []
    sup, clock, spawned = _stepper(
        backoff_base_ms=100.0, backoff_cap_ms=1000.0,
        healthy_after_s=1.0, max_restarts=3, seed=0,
        on_restart=lambda idx, attempt: restarts.append((idx, attempt)))
    sup.start()
    assert len(spawned) == 1
    clock["t"] = 5.0                      # healthy uptime
    assert sup.kill(0)
    assert sup.step() == 0                # death seen, backoff scheduled
    slot = sup.slots[0]
    assert slot.restart_at is not None
    # jittered backoff in [base/2, base): healthy death resets the
    # young-death counter so the exponent is the floor
    assert 0.05 <= slot.restart_at - 5.0 <= 0.1
    clock["t"] = slot.restart_at + 0.001
    assert sup.step() == 1
    assert len(spawned) == 2 and spawned[1][1] == 2
    assert restarts == [(0, 2)]
    assert sup.summary()["restarts"] == 1


def test_supervisor_gives_up_on_consecutive_young_deaths():
    sup, clock, spawned = _stepper(
        backoff_base_ms=10.0, backoff_cap_ms=50.0,
        healthy_after_s=10.0, max_restarts=3, seed=1)
    sup.start()
    for _ in range(3):
        spawned[-1][2].code = 1           # dies instantly (young)
        sup.step()                        # notice + schedule
        slot = sup.slots[0]
        if slot.gave_up:
            break
        clock["t"] = slot.restart_at + 0.001
        sup.step()                        # respawn
    assert sup.slots[0].gave_up
    assert sup.summary()["gave_up"] == 1
    n = len(spawned)
    sup.step()
    assert len(spawned) == n              # a given-up slot stays down


def test_supervisor_healthy_uptime_resets_young_counter():
    sup, clock, spawned = _stepper(
        backoff_base_ms=10.0, backoff_cap_ms=50.0,
        healthy_after_s=1.0, max_restarts=2, seed=2)
    sup.start()
    for _ in range(5):                    # would give up at 2 young
        clock["t"] += 5.0                 # served long enough
        spawned[-1][2].code = -9
        sup.step()
        clock["t"] = sup.slots[0].restart_at + 0.001
        sup.step()
    assert not sup.slots[0].gave_up
    assert sup.summary()["restarts"] == 5


# ----------------------------------------------------------------------
# fleet invariants
def test_accounting_exact_by_id():
    ok = check_fleet_accounting(
        ["a", "b", "c"],
        [{"id": "a", "estimate": 1.0}, {"id": "b", "shed": True},
         {"id": "c", "error": "bad_request"}])
    assert ok.ok and ok.answered == 2 and ok.shed == 1

    bad = check_fleet_accounting(
        ["a", "b"],
        [{"id": "a", "estimate": 1.0}, {"id": "a", "estimate": 1.0},
         {"id": "z", "estimate": 1.0}])
    assert not bad.ok
    assert bad.duplicate_ids == ["a"]
    assert bad.missing_ids == ["b"]
    assert bad.unexpected_ids == ["z"]


def test_staleness_bound_floor(tmp_path):
    timeline = [(1000, 1), (2000, 2), (3000, 3)]
    assert durable_epoch_at(timeline, 999) is None
    assert durable_epoch_at(timeline, 2500) == 2
    v = check_staleness_bound(
        [(3500, {"id": "ok", "plane_epoch": 2}),       # floor(2500)=2
         (3500, {"id": "old", "plane_epoch": 1}),      # below floor
         (3500, {"id": "shed", "shed": True, "plane_epoch": 0})],
        timeline, max_staleness_ms=1000)
    assert not v.ok
    assert [x[0] for x in v.stale_violations] == ["old"]


def test_convergence_and_bit_identity(tmp_path):
    camps = ["a", "b"]
    m, r = _planes(9, camps)
    for name in ("clean", "chaos", "diverged"):
        st = DurableDimensionStore(str(tmp_path / name))
        mm = m if name != "diverged" else m + 1
        st.put_reach_sketches(mm, r, camps, 7, submit_ms=now_ms())
        st.close()
    chaos = str(tmp_path / "chaos" / LOG_NAME)
    clean = str(tmp_path / "clean" / LOG_NAME)
    v = check_fleet_convergence(chaos, [7, 7], clean_ship_path=clean)
    assert v.ok and v.writer_epoch == 7 and not v.divergent

    lag = check_fleet_convergence(chaos, [7, 6], clean_ship_path=clean)
    assert not lag.ok and lag.lagging_replicas == [(1, 6, 7)]

    div = check_fleet_convergence(
        str(tmp_path / "diverged" / LOG_NAME), [7],
        clean_ship_path=clean)
    assert not div.ok and div.divergent


# ----------------------------------------------------------------------
# the randomized sweep (slow): retry+dedup exactly-once over 20 seeds
@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("seed", range(20))
def test_exactly_once_sweep_over_seeds(seed):
    counts: dict = {}
    srv = _echo_server(counts)
    plan = FaultPlan.generate(seed, net_drop_rate=0.18,
                              net_delay_rate=0.05, net_delay_ms=5,
                              net_dup_rate=0.15, net_torn_rate=0.08,
                              net_msgs=4000,
                              partition_windows=((30 + seed, 8),))
    proxy = ChaosPubSub(srv.address, FaultInjector(plan)).start()
    try:
        c = PubSubClient(*proxy.address, timeout_s=60)
        got = []
        for i in range(24):
            try:
                got.append(c.request({"type": "reach",
                                      "id": f"s{seed}q{i}", "v": i},
                                     timeout_s=0.25, retries=10))
            except (TimeoutError, ConnectionError, OSError):
                # the partition can outlast the retry budget; honest
                # failure is allowed — double answering is not
                c.close()
                c = PubSubClient(*proxy.address, timeout_s=60)
        c.close()
        vals = [d["v"] for d in got]
        assert len(vals) == len(set(vals)), "double-answered request"
        assert all(n == 1 for n in counts.values()), counts
        assert len(vals) >= 18    # the plan runs clean past net_msgs
    finally:
        proxy.close()
        srv.close()
