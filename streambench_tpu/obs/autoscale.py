"""Obs-actuated replica autoscaler (obs layer 7, ISSUE 17 tentpole).

:class:`AutoscaleController` closes the loop the ROADMAP 3(c) mapping
describes: run :func:`~streambench_tpu.obs.diagnose.diagnose` over a
window of fleet evidence on a cadence, and turn the prescribed knob —

- ``replica_count``: spawn through an injected ``spawn_replica()``
  hook (the bench wires it to ``FleetSupervisor.spawn()`` + ``router.
  add_replica``), retire through ``retire_replica()`` (graceful:
  deregister -> drain -> stop) after a sustained healthy streak;
- ``ship_cadence``: halve ``SnapshotShipper.interval_ms`` down to a
  floor;
- ``poll_interval``: halve the replica tail poll through a
  ``set_poll_ms(new_ms)`` hook down to a floor;
- ``batch_cadence``: an opaque ``tune_batch(verdict)`` hook (the
  serving tier owns its own batch/drain semantics).

Safety is structural, not hopeful: **hysteresis** (a breach must
persist ``breach_ticks`` consecutive steps before anything actuates),
**per-knob cooldowns** (chaos-induced noise inside a cooldown is
counted as a ``hold``, never acted on — ROBUSTNESS.md "controller x
fleet chaos"), **bounds** (min/max replicas, cadence/poll floors), and
a **priming step** (the first window only records state, so a
controller attached to an old journal can't mistake history for a
live breach).  The clock is injectable (the PR 16 FleetSupervisor
testing pattern) so every one of those behaviors unit-tests against a
fake clock.

Every decision is journaled as a ``kind="event"`` record
(``event="autoscale_decision"``) carrying the verdict + freshness-hop
p99 evidence that justified it, mirrored into the FlightRecorder, and
counted on ``streambench_autoscale_{decisions,replicas,
shed_redirects}_total``; ``obs fleet`` renders the summary as a
controller sub-line.  Default-off like every obs layer: nothing
constructs one unless asked, and a constructed controller with no
hooks wired actuates nothing.
"""

from __future__ import annotations

import time

from streambench_tpu.obs.diagnose import (
    KNOB_BATCH,
    KNOB_POLL,
    KNOB_REPLICAS,
    KNOB_SHIP,
    VERDICT_HEALTHY,
    diagnose,
    evidence_window,
)
from streambench_tpu.utils.ids import now_ms

#: decision journal cap (the controller runs for a bench rung, not a
#: quarter — the bound is a leak guard, not a policy)
DECISIONS_MAX = 1024


class AutoscaleController:
    """Diagnose-then-actuate on a cadence.  ``collect`` is a callable
    returning the current attributed fleet records (live
    ``FleetCollector.collect`` or any test fake); everything that
    touches the world is an optional injected hook."""

    def __init__(self, collect, *, objective: dict,
                 spawn_replica=None, retire_replica=None,
                 shipper=None, min_ship_interval_ms: int = 100,
                 set_poll_ms=None, poll_ms: "int | None" = None,
                 min_poll_ms: int = 20, tune_batch=None,
                 replicas: int = 1, min_replicas: int = 1,
                 max_replicas: int = 4,
                 breach_ticks: int = 2, healthy_ticks: int = 6,
                 cooldown_s: float = 5.0,
                 cooldowns: "dict | None" = None,
                 window_steps: int = 8,
                 sampler=None, flightrec=None, registry=None,
                 clock=time.monotonic, sleep=time.sleep):
        self.collect = collect
        self.objective = dict(objective)
        self.spawn_replica = spawn_replica
        self.retire_replica = retire_replica
        self.shipper = shipper
        self.min_ship_interval_ms = int(min_ship_interval_ms)
        self.set_poll_ms = set_poll_ms
        self._poll_ms = int(poll_ms) if poll_ms is not None else None
        self.min_poll_ms = int(min_poll_ms)
        self.tune_batch = tune_batch
        self.replicas = int(replicas)
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.breach_ticks = max(int(breach_ticks), 1)
        self.healthy_ticks = max(int(healthy_ticks), 1)
        self.cooldown_s = float(cooldown_s)
        self._cooldowns = dict(cooldowns or {})
        self.window_steps = max(int(window_steps), 1)
        self.sampler = sampler
        self.flightrec = flightrec
        self._clock = clock
        self._sleep = sleep
        self._history: list = []       # evidence windows, oldest first
        self._last_act: dict = {}      # knob -> monotonic stamp
        self._last_failovers = 0
        self._breach_streak = 0
        self._healthy_streak = 0
        self.steps = 0
        self.holds = 0                 # breach confirmed, knob cooling
        self.at_limit = 0              # knob already at its bound
        self.shed_redirects = 0
        self.decisions: list = []
        self.actions: dict = {}
        self.last_verdicts: list = []
        self._c_decisions = self._g_replicas = self._c_redirects = None
        if registry is not None:
            self._c_decisions = registry.counter(
                "streambench_autoscale_decisions_total",
                "autoscale knob actuations (scale up/down, cadence and "
                "poll tunes) with verdict evidence journaled")
            self._g_replicas = registry.gauge(
                "streambench_autoscale_replicas_total",
                "replica count the controller currently holds")
            self._g_replicas.set(self.replicas)
            self._c_redirects = registry.counter(
                "streambench_autoscale_shed_redirects_total",
                "replica sheds the router converted into failover "
                "answers while the controller held the fleet")

    # -- plumbing ------------------------------------------------------
    def _cooldown_for(self, knob: str) -> float:
        return float(self._cooldowns.get(knob, self.cooldown_s))

    def _cool(self, knob: str, now: float) -> bool:
        last = self._last_act.get(knob)
        return last is None or now - last >= self._cooldown_for(knob)

    def _journal(self, dec: dict) -> None:
        self.decisions.append(dec)
        if len(self.decisions) > DECISIONS_MAX:
            del self.decisions[0]
        self.actions[dec["decision"]] = \
            self.actions.get(dec["decision"], 0) + 1
        if self.sampler is not None:
            self.sampler.annotate(
                "autoscale_decision",
                **{k: v for k, v in dec.items() if k != "ts_ms"})
        if self.flightrec is not None:
            self.flightrec.record("autoscale", **dec)
        if self._c_decisions is not None:
            self._c_decisions.inc()
        if self._g_replicas is not None:
            self._g_replicas.set(self.replicas)

    def _decision(self, action: str, verdict: dict, **extra) -> dict:
        dec = {"decision": action, "verdict": verdict["verdict"],
               "knob": verdict.get("knob"),
               "replicas": self.replicas, "step": self.steps,
               "why": verdict.get("why"),
               "evidence": verdict.get("evidence"),
               "ts_ms": now_ms()}
        dec.update(extra)
        self._journal(dec)
        return dec

    # -- the loop body -------------------------------------------------
    def step(self, now: "float | None" = None) -> "dict | None":
        """One diagnose-maybe-actuate pass.  Returns the decision dict
        when a knob was turned (or a replica retired), else None."""
        now = self._clock() if now is None else now
        window = evidence_window(self.collect())
        prev = self._history[0] if self._history else None
        self._history.append(window)
        if len(self._history) > self.window_steps:
            del self._history[0]
        self.steps += 1
        # shed-redirect accounting rides along every step: failovers
        # are exactly "a replica shed/failed and the router answered
        # from another" — the gauge that shows the grown fleet working
        fo = int(window.get("router_failovers") or 0)
        if fo > self._last_failovers:
            d = fo - self._last_failovers
            self.shed_redirects += d
            if self._c_redirects is not None:
                self._c_redirects.inc(d)
        self._last_failovers = max(self._last_failovers, fo)
        if prev is None:
            return None   # priming: history must not read as a breach
        verdicts = diagnose(window, objective=self.objective, prev=prev)
        self.last_verdicts = verdicts
        top = verdicts[0]

        if top["verdict"] == VERDICT_HEALTHY:
            self._breach_streak = 0
            self._healthy_streak += 1
            if (self._healthy_streak >= self.healthy_ticks
                    and self.replicas > self.min_replicas
                    and self.retire_replica is not None):
                if not self._cool(KNOB_REPLICAS, now):
                    self.holds += 1
                    return None
                if self.retire_replica():
                    self.replicas -= 1
                    self._last_act[KNOB_REPLICAS] = now
                    self._healthy_streak = 0
                    return self._decision("scale_down", top)
            return None

        self._healthy_streak = 0
        self._breach_streak += 1
        if self._breach_streak < self.breach_ticks:
            return None   # hysteresis: one noisy window never actuates
        # actuate the highest-scored verdict whose knob is actionable:
        # a cooling top verdict must not starve a runner-up (fix
        # freshness first, capacity next — not freshness or nothing)
        cooling = False
        for v in verdicts:
            knob = v.get("knob")
            if v["verdict"] == VERDICT_HEALTHY or knob is None:
                continue
            if not self._cool(knob, now):
                cooling = True
                continue
            dec = self._actuate(knob, v, now)
            if dec is not None:
                return dec
        if cooling:
            self.holds += 1
        return None

    def _actuate(self, knob: str, top: dict,
                 now: float) -> "dict | None":
        if knob == KNOB_REPLICAS:
            if self.spawn_replica is None:
                return None
            if self.replicas >= self.max_replicas:
                self.at_limit += 1
                return None
            if not self.spawn_replica():
                return None
            self.replicas += 1
            self._last_act[knob] = now
            return self._decision("scale_up", top)
        if knob == KNOB_SHIP:
            if self.shipper is None:
                return None
            cur = int(self.shipper.interval_ms)
            new = max(cur // 2, self.min_ship_interval_ms)
            if new >= cur:
                self.at_limit += 1
                return None
            self.shipper.interval_ms = new
            self._last_act[knob] = now
            return self._decision("ship_faster", top,
                                  from_ms=cur, to_ms=new)
        if knob == KNOB_POLL:
            if self.set_poll_ms is None or self._poll_ms is None:
                return None
            cur = self._poll_ms
            new = max(cur // 2, self.min_poll_ms)
            if new >= cur:
                self.at_limit += 1
                return None
            self.set_poll_ms(new)
            self._poll_ms = new
            self._last_act[knob] = now
            return self._decision("poll_faster", top,
                                  from_ms=cur, to_ms=new)
        if knob == KNOB_BATCH:
            if self.tune_batch is None:
                return None
            self.tune_batch(top)
            self._last_act[knob] = now
            return self._decision("batch_tune", top)
        return None

    def run(self, duration_s: float, interval_s: float = 0.5) -> int:
        """Convenience poll loop; returns decisions made.  Bench rungs
        drive :meth:`step` from their own thread instead."""
        deadline = self._clock() + float(duration_s)
        n = 0
        while self._clock() < deadline:
            if self.step() is not None:
                n += 1
            self._sleep(interval_s)
        return n

    # -- reporting -----------------------------------------------------
    def summary(self) -> dict:
        out = {
            "replicas": self.replicas, "steps": self.steps,
            "decisions": len(self.decisions),
            "scale_ups": self.actions.get("scale_up", 0),
            "scale_downs": self.actions.get("scale_down", 0),
            "ship_tunes": self.actions.get("ship_faster", 0),
            "poll_tunes": self.actions.get("poll_faster", 0),
            "batch_tunes": self.actions.get("batch_tune", 0),
            "holds": self.holds, "at_limit": self.at_limit,
            "shed_redirects": self.shed_redirects,
            "objective": dict(self.objective),
        }
        if self.decisions:
            last = self.decisions[-1]
            out["last"] = {k: last.get(k) for k in
                           ("decision", "verdict", "knob", "replicas",
                            "ts_ms")}
        return out
