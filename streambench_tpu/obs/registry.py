"""Live metrics registry: counters, gauges, log-bucketed histograms.

The engine's existing signals are all post-hoc — the ``Tracer`` span
table, the ``LatencyTracker`` decile report, and ``FaultCounters`` only
surface after the run exits (``engine/__main__.py``).  This module is the
*live* complement: a registry of named instruments a background sampler
(``obs.sampler``) reads every tick and a Prometheus endpoint
(``obs.httpd``) exposes on demand, while the run is still going.  SALSA
(PAPERS.md, arxiv 2102.12531) makes the same argument for streaming
systems generally: adaptation needs continuous occupancy signals, not an
exit report.

Design constraints, in priority order:

- **zero hot-path cost when unused** — nothing here is ever called
  unless the engine was explicitly attached (``attach_obs``); the
  default engine carries only a ``None`` attribute.
- **O(1) ``observe``** — the streaming histogram is log-bucketed
  (geometric bucket bounds): one log + one locked increment per sample,
  no per-sample storage, so percentiles stay queryable mid-run at any
  sample volume.  It *complements* the exact close-time decile table in
  ``metrics.LatencyTracker`` — that one is exact but only available at
  the end; this one is ~±12% (one bucket) but live.
- **thread-safe** — instruments are written from the writer thread and
  read from the sampler + HTTP threads concurrently.
"""

from __future__ import annotations

import math
import threading


def _fmt_labels(labels: "dict[str, str] | None") -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    """Prometheus text-exposition number (integers without the .0)."""
    if v != v:  # NaN
        return "NaN"
    if isinstance(v, bool):
        return str(int(v))
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonic counter.  ``inc`` for push-style use; ``set_total`` for
    poll-style collectors that mirror an already-cumulative engine field
    (monotonic by construction — a lower value is ignored)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: "dict[str, str] | None" = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0
        self._lock = threading.Lock()

    @property
    def value(self):
        with self._lock:
            return self._value

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def set_total(self, total: float) -> None:
        with self._lock:
            if total > self._value:
                self._value = total

    def render(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self.value)}"]


class Gauge:
    """Point-in-time value (backlog bytes, watermark lag, RSS...)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: "dict[str, str] | None" = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value: float = 0.0
        self._lock = threading.Lock()

    @property
    def value(self):
        with self._lock:
            return self._value

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def render(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self.value)}"]


class StreamingHistogram:
    """Log-bucketed streaming histogram: O(1) observe, no sample storage.

    Bucket upper bounds grow geometrically from ``lo`` by ``growth`` per
    bucket (default ~19%/bucket: quantiles are exact to within one
    bucket, i.e. a bounded *relative* error — the right shape for
    latencies spanning ms..hours).  ``observe`` is one ``math.log`` plus
    a locked integer increment; quantile queries walk the (~100-entry)
    bucket array.  Samples at or below ``lo`` land in bucket 0; above
    ``hi`` in the overflow bucket whose reported bound is ``+Inf``.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "", lo: float = 1.0,
                 hi: float = 1e7, growth: float = 2 ** 0.25,
                 labels: "dict[str, str] | None" = None):
        if not (lo > 0 and hi > lo and growth > 1):
            raise ValueError(f"bad histogram geometry lo={lo} hi={hi} "
                             f"growth={growth}")
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lo = lo
        self._log_growth = math.log(growth)
        n = int(math.ceil(math.log(hi / lo) / self._log_growth))
        # bounds[i] is the inclusive upper bound of bucket i; one extra
        # overflow bucket past bounds[-1] catches everything else
        self._bounds = [lo * growth ** (i + 1) for i in range(n)]
        self._counts = [0] * (n + 2)   # [<=lo, n geometric, overflow]
        self._count = 0
        self._sum = 0.0
        self._min: float | None = None
        self._max: float | None = None
        self._lock = threading.Lock()

    def _index(self, x: float) -> int:
        if x <= self._lo:
            return 0
        i = int(math.log(x / self._lo) / self._log_growth) + 1
        return min(i, len(self._counts) - 1)

    def observe(self, x: float) -> None:
        i = self._index(x)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += x
            if self._min is None or x < self._min:
                self._min = x
            if self._max is None or x > self._max:
                self._max = x

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def count_le(self, x: float) -> int:
        """Samples at or below ``x``, at bucket resolution: the bucket
        containing ``x`` is counted whole (its upper bound is the first
        one >= x), so the answer can over-include by at most one
        bucket's worth — the same one-bucket error contract as the
        quantiles.  The SLO burn-rate tracker's good/bad split."""
        i = self._index(x)
        with self._lock:
            return sum(self._counts[:i + 1])

    def _upper(self, i: int) -> float:
        if i == 0:
            return self._lo
        if i - 1 < len(self._bounds):
            return self._bounds[i - 1]
        return math.inf

    def quantiles(self, qs) -> list[float]:
        """Bucket-upper-bound quantiles for each q in ``qs`` (one pass).
        Clamped to the observed max so p99 of a tight distribution
        doesn't report a bucket bound past any real sample."""
        with self._lock:
            total = self._count
            if total == 0:
                return [math.nan] * len(qs)
            counts = list(self._counts)
            mx = self._max
        out: list[float] = []
        for q in qs:
            rank = max(min(q, 1.0), 0.0) * total
            acc = 0.0
            val = mx
            for i, c in enumerate(counts):
                acc += c
                if acc >= rank and c:
                    val = min(self._upper(i), mx)
                    break
            out.append(val)
        return out

    def summary(self) -> dict:
        """Point-in-time {count, sum, min, max, p50, p95, p99} dict —
        the shape the sampler journals every tick.  Empty histograms
        emit ``{"count": 0}`` alone: NaN percentiles would round-trip
        through JSON as the non-standard ``NaN`` token (or crash strict
        parsers), and a reader must not mistake "no samples" for "zero
        latency"."""
        if self.count == 0:
            return {"count": 0}
        p50, p95, p99 = self.quantiles((0.5, 0.95, 0.99))
        with self._lock:
            return {"count": self._count, "sum": round(self._sum, 3),
                    "min": self._min, "max": self._max,
                    "p50": p50, "p95": p95, "p99": p99}

    def render(self) -> list[str]:
        """Real Prometheus histogram exposition: cumulative ``_bucket``
        lines + ``_sum``/``_count``.  Bucket lines are emitted sparsely
        — every OCCUPIED bucket, the immediate lower neighbor of each
        occupied bucket (the lower edge of every occupied range stays
        on record, so quantile interpolation keeps its one-bucket
        resolution), plus the first and the ``+Inf`` bucket.
        Semantically identical to full emission (each bucket is its
        own cumulative series; an omitted bound between two emitted
        ones whose cumulative equals its lower neighbor's carries no
        information) but keeps a ~190-bucket segment-histogram family
        from dominating every scrape with runs of repeated numbers.
        The quantile summaries the report CLI reads (``summary()``)
        are unchanged."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        lines = []
        acc = 0
        base = dict(self.labels)
        last = len(counts) - 1
        for i, c in enumerate(counts):
            acc += c
            nxt = counts[i + 1] if i < last else 0
            if not (i == 0 or i == last or c or nxt):
                continue
            ub = self._upper(i)
            le = "+Inf" if ub == math.inf else _fmt_value(round(ub, 6))
            lines.append(f"{self.name}_bucket"
                         f"{_fmt_labels({**base, 'le': le})} {acc}")
        lines.append(f"{self.name}_sum{_fmt_labels(base)} {_fmt_value(s)}")
        lines.append(f"{self.name}_count{_fmt_labels(base)} {total}")
        return lines


class MetricsRegistry:
    """Get-or-create registry of named instruments.

    Instruments are keyed by (name, sorted labels) so per-stage/per-kind
    label families (``streambench_faults_total{kind=...}``) share one
    name.  ``render_prometheus`` emits the standard text exposition
    (one ``# TYPE`` per family).
    """

    def __init__(self) -> None:
        self._metrics: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str,
             labels: "dict[str, str] | None", **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(name, help=help,
                                             labels=labels, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "",
                labels: "dict[str, str] | None" = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: "dict[str, str] | None" = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", lo: float = 1.0,
                  hi: float = 1e7, growth: float = 2 ** 0.25,
                  labels: "dict[str, str] | None" = None
                  ) -> StreamingHistogram:
        return self._get(StreamingHistogram, name, help, labels,
                         lo=lo, hi=hi, growth=growth)

    def predeclare(self, kind: str, name: str, help: str = "",
                   label_sets: "list[dict | None] | None" = None,
                   **kw) -> None:
        """Eagerly create an instrument family (one instrument per
        label set) so a scrape BEFORE the first feed returns it with
        zero samples instead of omitting the family — the lazy-
        instrument gap: per-format/per-tenant instruments created at
        first dispatch are invisible to early Prometheus scrapes, and
        harnesses end up polling the endpoint until they appear."""
        maker = {"counter": self.counter, "gauge": self.gauge,
                 "histogram": self.histogram}[kind]
        for labels in (label_sets or [None]):
            maker(name, help, labels=labels, **kw)

    def collect(self) -> list:
        with self._lock:
            return list(self._metrics.values())

    def render_prometheus(self) -> str:
        """Text exposition format (version 0.0.4): families grouped, one
        ``# HELP``/``# TYPE`` header per family name."""
        by_name: dict[str, list] = {}
        for m in self.collect():
            by_name.setdefault(m.name, []).append(m)
        lines: list[str] = []
        for name in sorted(by_name):
            fam = by_name[name]
            help_text = next((m.help for m in fam if m.help), "")
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {fam[0].kind}")
            for m in fam:
                lines.extend(m.render())
        return "\n".join(lines) + "\n"
