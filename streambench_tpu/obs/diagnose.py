"""Fleet diagnosis engine: from measured fleet evidence to a ranked
list of named bottleneck verdicts (obs layer 7, ISSUE 17).

PRs 8/11/15 built the instruments — SLO burn rates, the ingest/query
contention ratio, per-reply freshness hops that decompose a reply's
evidence age into ``fold_lag/ship_wait/tail_lag/serve`` — but reading
them has stayed a human postmortem.  This module makes the reading
executable and PURE: :func:`evidence_window` folds a window of
attributed fleet records (live ``FleetCollector.collect()`` output or
a replayed ``fleet.jsonl``) into one flat evidence dict, and
:func:`diagnose` maps that dict to verdicts, each carrying the measured
evidence that justifies it and the knob the ROADMAP 3(c) mapping
prescribes:

- ``fold_lag``   -> ship cadence (``SnapshotShipper.interval_ms``).
  A staleness breach whose age does NOT sit in the tailer is
  cadence/ingest starvation upstream of the replica.  NOTE the hop
  physics (REACH_r04): a slow ship cadence mostly ages the record
  *while it serves* — the growth lands in the ``serve`` hop, not in
  ``fold_lag`` — so the rule keys on the breach minus tail dominance,
  not on the ``fold_lag`` hop alone.
- ``tail_lag``   -> replica poll interval (``ReachReplica.poll_ms``),
  when the tail hop dominates the breached staleness: the record was
  shipped promptly and sat in the log waiting for the tailer.
- ``serve``      -> replica count, on ``overloaded`` sheds or a p99
  breach without contention evidence: the fleet is out of serving
  capacity, not out of fresh evidence.
- ``contention`` -> batch/drain cadence, when the queue segment
  dominates a p99 breach AND the measured ingest-contention ratio says
  the queue wait was spent behind ingest dispatches.
- ``healthy``    -> no knob: every objective holds in this window.

No side effects, no clocks, no I/O — the unit tests table-drive it
with synthetic journals, and :class:`~streambench_tpu.obs.autoscale.
AutoscaleController` is just this function on a cadence.
"""

from __future__ import annotations

#: verdict names (the bottleneck families the ROADMAP mapping names)
VERDICT_FOLD = "fold_lag"
VERDICT_TAIL = "tail_lag"
VERDICT_SERVE = "serve"
VERDICT_CONTENTION = "contention"
VERDICT_TENANT = "tenant_interference"
VERDICT_HEALTHY = "healthy"

#: knob names (what the controller actuates)
KNOB_SHIP = "ship_cadence"
KNOB_POLL = "poll_interval"
KNOB_REPLICAS = "replica_count"
KNOB_BATCH = "batch_cadence"
KNOB_ADMISSION = "admission"

KNOB_FOR = {
    VERDICT_FOLD: KNOB_SHIP,
    VERDICT_TAIL: KNOB_POLL,
    VERDICT_SERVE: KNOB_REPLICAS,
    VERDICT_CONTENTION: KNOB_BATCH,
    VERDICT_TENANT: KNOB_ADMISSION,
    VERDICT_HEALTHY: None,
}

#: the tail hop must carry at least this share of the breached
#: staleness (and top the other pipeline hops) before the poll knob is
#: blamed — below it, the age accrued upstream of the tailer
TAIL_DOMINANCE_SHARE = 0.35

#: queue-wait counts as contention-bound only when the measured
#: ingest-overlap ratio says at least this fraction of it was spent
#: behind ingest dispatches (PR 11's streambench_reach_contention_ratio)
CONTENTION_RATIO_MIN = 0.5


def _num(v):
    return float(v) if isinstance(v, (int, float)) else None


def _nmax(a, b):
    if b is None:
        return a
    return b if a is None else max(a, b)


def evidence_window(records: list) -> dict:
    """Fold one window of attributed fleet records into a flat
    evidence dict.

    ``records`` is FleetCollector output (live or replayed from
    ``fleet.jsonl``): dicts with ``kind`` / ``role`` / ``pid`` and the
    per-role payload blocks (``reach_query``, ``router``,
    ``reach_ship``, ``slo``).  Per (role, pid) the LATEST snapshot
    wins; gauges (staleness, p99, hop p99s, queue depth) max-merge
    across serving rows, counters (served/shed/...) sum — the window is
    the fleet's worst case plus its total work.  Counters stay
    CUMULATIVE; :func:`diagnose` differences them against a previous
    window."""
    rq_by: dict = {}
    router = None
    ship = None
    slo = None
    multitenant = None
    slo_tenants: dict = {}
    ts = 0
    for r in records:
        if not isinstance(r, dict):
            continue
        t = r.get("ts_ms")
        if isinstance(t, (int, float)):
            ts = max(ts, int(t))
        if r.get("kind") not in ("snapshot", "final"):
            continue
        rq = r.get("reach_query")
        if isinstance(rq, dict):
            rq_by[(r.get("role"), r.get("pid"))] = rq
        rt = r.get("router")
        if isinstance(rt, dict):
            router = rt
        sh = r.get("reach_ship")
        if isinstance(sh, dict):
            ship = sh
        sl = r.get("slo")
        if isinstance(sl, dict):
            slo = sl
        # multi-tenant evidence (ISSUE 19): the host's blame-matrix
        # block and the nested per-tenant SLO blocks — latest wins,
        # per tenant for the SLO map
        mt = r.get("multitenant")
        if isinstance(mt, dict):
            multitenant = mt
        st = r.get("slo_tenants")
        if isinstance(st, dict):
            for name, b in st.items():
                if isinstance(b, dict):
                    slo_tenants[str(name)] = b
    w: dict = {"ts_ms": ts, "replicas": len(rq_by),
               "staleness_ms": None, "p99_ms": None, "qps": 0.0,
               "served": 0, "shed": 0, "shed_stale": 0,
               "queue_high_water": None, "hop_p99_ms": {},
               "contention_ratio": None, "segment_p99_ms": {}}
    for rq in rq_by.values():
        w["staleness_ms"] = _nmax(w["staleness_ms"],
                                  _num(rq.get("staleness_ms")))
        w["p99_ms"] = _nmax(w["p99_ms"], _num(rq.get("p99_ms")))
        w["qps"] += _num(rq.get("qps")) or 0.0
        w["served"] += int(rq.get("served") or 0)
        w["shed"] += int(rq.get("shed") or 0)
        w["shed_stale"] += int(rq.get("shed_stale") or 0)
        w["queue_high_water"] = _nmax(w["queue_high_water"],
                                      _num(rq.get("queue_high_water")))
        fr = rq.get("freshness")
        if isinstance(fr, dict):
            for hop, h in (fr.get("hops") or {}).items():
                p = _num((h or {}).get("p99"))
                if p is not None:
                    w["hop_p99_ms"][hop] = max(
                        w["hop_p99_ms"].get(hop, 0.0), p)
        qo = rq.get("query_obs")
        if isinstance(qo, dict):
            ratio = _num((qo.get("contention") or {}).get("ratio"))
            w["contention_ratio"] = _nmax(w["contention_ratio"], ratio)
            for seg, h in (qo.get("segments") or {}).items():
                p = _num((h or {}).get("p99"))
                if p is not None:
                    w["segment_p99_ms"][seg] = max(
                        w["segment_p99_ms"].get(seg, 0.0), p)
    w["shed_overloaded"] = max(w["shed"] - w["shed_stale"], 0)
    if router is not None:
        w["router_routed"] = int(router.get("routed") or 0)
        w["router_answered"] = int(router.get("answered") or 0)
        w["router_shed"] = int(router.get("shed") or 0)
        w["router_failovers"] = int(router.get("failovers") or 0)
        w["router_replicas"] = len(router.get("replicas") or ())
        # the fleet's front-door latency: a serialized single-replica
        # handle queues AT THE ROUTER — no replica's own submit->reply
        # percentiles ever see that wait, so the router's recent-window
        # e2e p99 is the latency evidence the serve verdict needs
        w["router_e2e_p99_ms"] = _num(router.get("e2e_p99_ms"))
        w["p99_ms"] = _nmax(w["p99_ms"], w["router_e2e_p99_ms"])
    if ship is not None:
        w["ship_interval_ms"] = _num(ship.get("interval_ms"))
        w["ships"] = int(ship.get("ships") or 0)
    if slo is not None:
        burns = [b for b in (slo.get("burn") or {}).values()
                 if isinstance(b, (int, float))]
        if burns:
            w["slo_burn_max"] = max(burns)
    if slo_tenants:
        w["tenant_burn"] = {}
        w["tenant_in_breach"] = []
        for name in sorted(slo_tenants):
            b = slo_tenants[name]
            vals = [v for wins in (b.get("burn") or {}).values()
                    if isinstance(wins, dict)
                    for v in wins.values()
                    if isinstance(v, (int, float))]
            w["tenant_burn"][name] = max(vals) if vals else 0.0
            if b.get("in_breach"):
                w["tenant_in_breach"].append(name)
    if multitenant is not None:
        w["blame_offdiag_ratio"] = _num(
            multitenant.get("offdiag_ratio"))
        w["blame_matrix_ms"] = dict(
            multitenant.get("matrix_ms") or {})
    return w


def _delta(window: dict, prev, key: str) -> int:
    cur = int(window.get(key) or 0)
    if not isinstance(prev, dict):
        return cur
    return max(cur - int(prev.get(key) or 0), 0)


def diagnose(window: dict, *, objective: dict,
             prev: "dict | None" = None) -> list:
    """Rank the window's bottlenecks.  Pure: (evidence, objective) ->
    verdicts, most severe first.

    ``objective``: ``{"staleness_ms": ..., "p99_ms": ...}`` (either
    optional).  ``prev``: an earlier :func:`evidence_window` over the
    same fleet — counters are differenced against it so a historic shed
    burst can't breach forever; without it the cumulative counts stand.

    Returns ``[{"verdict", "knob", "score", "why", "evidence"}, ...]``
    — never empty: a window breaching nothing is one
    ``healthy``/no-knob verdict."""
    stale_limit = _num(objective.get("staleness_ms"))
    p99_limit = _num(objective.get("p99_ms"))
    staleness = _num(window.get("staleness_ms"))
    p99 = _num(window.get("p99_ms"))
    hops = dict(window.get("hop_p99_ms") or {})
    d_stale = _delta(window, prev, "shed_stale")
    d_over = _delta(window, prev, "shed_overloaded")
    d_router_shed = _delta(window, prev, "router_shed")
    evidence = {
        "staleness_ms": staleness, "p99_ms": p99,
        "qps": round(float(window.get("qps") or 0.0), 1),
        "hop_p99_ms": hops,
        "shed_stale": d_stale, "shed_overloaded": d_over,
        "router_shed": d_router_shed,
        "contention_ratio": window.get("contention_ratio"),
        "replicas": window.get("replicas"),
        "objective": dict(objective),
    }
    out: list = []

    # -- staleness breaches: the pipeline knobs ------------------------
    stale_breach = (stale_limit is not None and staleness is not None
                    and staleness > stale_limit)
    if stale_breach or d_stale > 0:
        sev = ((staleness / stale_limit)
               if stale_breach and stale_limit else 1.0)
        sev += min(d_stale / 10.0, 1.0)
        tail = hops.get("tail_lag")
        rest = max(hops.get("fold_lag") or 0.0,
                   hops.get("ship_wait") or 0.0)
        age = staleness if staleness is not None else sum(
            v for v in hops.values() if v is not None) or None
        tail_bound = (tail is not None and age and tail >= rest
                      and tail / age >= TAIL_DOMINANCE_SHARE)
        if tail_bound:
            out.append({
                "verdict": VERDICT_TAIL, "knob": KNOB_POLL,
                "score": round(sev, 3),
                "why": (f"staleness {staleness} breaches "
                        f"{stale_limit} ms and the tail_lag hop p99 "
                        f"({tail} ms) dominates: the record shipped "
                        "promptly and waited on the tailer"),
                "evidence": evidence})
        else:
            out.append({
                "verdict": VERDICT_FOLD, "knob": KNOB_SHIP,
                "score": round(sev, 3),
                "why": (f"staleness {staleness} breaches "
                        f"{stale_limit} ms with no tail dominance: the "
                        "evidence aged upstream of the tailer "
                        "(ship/fold cadence starvation)"),
                "evidence": evidence})

    # -- capacity breaches: serve vs contention ------------------------
    lat_breach = (p99_limit is not None and p99 is not None
                  and p99 > p99_limit)
    if lat_breach or d_over > 0:
        sev = 1.0 + min(d_over / 10.0, 2.0)
        if lat_breach and p99_limit:
            sev += max(p99 / p99_limit - 1.0, 0.0)
        ratio = _num(window.get("contention_ratio"))
        segs = window.get("segment_p99_ms") or {}
        queue_p99 = _num(segs.get("queue"))
        queue_dom = (queue_p99 is not None and segs
                     and queue_p99 >= max(
                         (v for k, v in segs.items() if k != "queue"),
                         default=0.0))
        if (lat_breach and queue_dom and ratio is not None
                and ratio >= CONTENTION_RATIO_MIN):
            out.append({
                "verdict": VERDICT_CONTENTION, "knob": KNOB_BATCH,
                "score": round(sev + ratio, 3),
                "why": (f"p99 {p99} breaches {p99_limit} ms, the queue "
                        f"segment dominates and contention_ratio "
                        f"{ratio} says the wait was spent behind "
                        "ingest dispatches"),
                "evidence": evidence})
        else:
            out.append({
                "verdict": VERDICT_SERVE, "knob": KNOB_REPLICAS,
                "score": round(sev, 3),
                "why": (f"{d_over} overloaded sheds / p99 "
                        f"{p99} vs {p99_limit} ms without contention "
                        "evidence: serving capacity, not freshness"),
                "evidence": evidence})

    # -- tenant interference: the admission knob ------------------------
    t_burn = dict(window.get("tenant_burn") or {})
    in_breach = list(window.get("tenant_in_breach") or ())
    matrix = window.get("blame_matrix_ms") or {}
    victims = in_breach or sorted(
        (t for t, b in t_burn.items() if b >= 1.0),
        key=lambda t: -t_burn[t])
    if victims and matrix:
        # the highest-burn breaching victim with cross-tenant blame
        # evidence names the aggressor; a tenant burning its own budget
        # (empty off-diagonal row) stays with the capacity verdicts
        victim = max(victims, key=lambda t: t_burn.get(t, 1.0))
        row = matrix.get(victim) or {}
        best = None
        for aggressor, ms in row.items():
            if aggressor == victim or not isinstance(ms, (int, float)):
                continue
            if ms > 0 and (best is None or ms > best[1]):
                best = (aggressor, ms)
        if best is not None:
            aggressor, blame_ms = best
            burn = t_burn.get(victim, 1.0)
            evidence = dict(evidence)
            evidence["tenant_burn"] = t_burn
            evidence["blame_row_ms"] = row
            evidence["blame_offdiag_ratio"] = window.get(
                "blame_offdiag_ratio")
            out.append({
                "verdict": VERDICT_TENANT, "knob": KNOB_ADMISSION,
                "score": round(1.0 + burn + min(blame_ms / 1e3, 2.0), 3),
                "victim": victim, "aggressor": aggressor,
                "why": (f"tenant {victim!r} burns its budget at "
                        f"{burn}x while {aggressor!r} held the device "
                        f"for {blame_ms} ms of its measured wait — "
                        "gate the aggressor's ingest"),
                "evidence": evidence})

    if not out:
        out.append({"verdict": VERDICT_HEALTHY, "knob": None,
                    "score": 0.0,
                    "why": "no objective breached in this window",
                    "evidence": evidence})
    out.sort(key=lambda v: v["score"], reverse=True)
    return out
