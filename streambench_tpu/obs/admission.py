"""Measurement-actuated admission control (obs layer 9 actuator).

The multi-tenant host's enforcement arm: when a victim tenant's SLO
burn rate breaches and the :class:`~streambench_tpu.obs.tenancy.
DeviceTimeLedger` blame matrix names another tenant as the dominant
aggressor, the controller gates the AGGRESSOR's ingest — first
**defer** (its queued batches stay queued; nothing is lost, the
backlog absorbs the flash crowd) and, if the victim keeps burning
while the gate is up, escalate to **shed** (the host drops the
aggressor's oldest queued batches, counted per tenant).  The victim's
own ingest is never touched: fairness is enforced by measurement, not
by who shouted first.

Safety is structural, the same pattern as PR 17's
:class:`~streambench_tpu.obs.autoscale.AutoscaleController`:

- **priming** — the first step only records state; history can never
  read as a live breach;
- **hysteresis** — a breach must persist ``breach_ticks`` consecutive
  steps before any gate goes up, and the gate needs cross-tenant blame
  evidence (no aggressor in the matrix -> no actuation; a tenant
  burning its own budget is the autoscaler's problem, not admission's);
- **cooldowns-as-holds** — a confirmed breach inside the per-action
  cooldown is counted as a ``hold``, never acted on (chaos windows and
  fault-injection noise land here — ROBUSTNESS.md);
- **release on sustained health** — ``healthy_ticks`` consecutive
  sub-threshold steps drop every gate, journaled like any decision;
- **journaled evidence-carrying decisions** — every defer/shed/release
  lands in the decision log, the metrics.jsonl event stream, and the
  flight recorder with the victim's burn and the blame row attached,
  capped at ``DECISIONS_MAX``.

Default-off: the host constructs a controller only when
``jax.admission.enabled`` is set, and with it off the ingest path is
byte-identical (pinned, like every prior flag).
"""

from __future__ import annotations

import time

from streambench_tpu.utils.ids import now_ms

#: decision journal cap (leak guard, not policy — the autoscale rule)
DECISIONS_MAX = 1024

ACTION_ADMIT = "admit"
ACTION_DEFER = "defer"
ACTION_SHED = "shed"


class AdmissionController:
    """Burn-watch → blame → gate loop over one shared-device host.

    ``burns`` is a callable returning ``{tenant: fast_burn_rate}`` for
    every tenant with an objective (the host wires it over its
    per-tenant SLO trackers); ``ledger`` is the shared
    :class:`DeviceTimeLedger`.  ``admit(tenant)`` is the hot-path
    check: one dict lookup returning ``"admit"``/``"defer"``/
    ``"shed"``.  Clock is injectable so hysteresis, cooldown and
    escalation all unit-test against a fake clock.
    """

    def __init__(self, ledger, burns, *, breach_burn: float = 1.0,
                 breach_ticks: int = 2, healthy_ticks: int = 4,
                 escalate_ticks: int = 6, cooldown_s: float = 3.0,
                 sampler=None, flightrec=None, registry=None,
                 lags=None, clock=time.monotonic):
        self.ledger = ledger
        self.burns = burns
        # optional callable -> {tenant: broker-side consumer lag}; when
        # wired (the multi-tenant host's reader_lags over the Kafka
        # adapter) every journaled decision carries the lag map, so a
        # defer gate's broker-backlog effect is evidence IN the
        # decision, not a separate scrape to correlate
        self.lags = lags
        self.breach_burn = float(breach_burn)
        self.breach_ticks = max(int(breach_ticks), 1)
        self.healthy_ticks = max(int(healthy_ticks), 1)
        self.escalate_ticks = max(int(escalate_ticks), 1)
        self.cooldown_s = float(cooldown_s)
        self.sampler = sampler
        self.flightrec = flightrec
        self._clock = clock
        self._reg = registry
        self.steps = 0
        self.holds = 0
        self._primed = False
        self._breach_streak: "dict[str, int]" = {}   # per victim
        self._healthy_streak = 0
        self._last_act: "float | None" = None
        #: aggressor -> {"mode", "victim", "since_step"}
        self._gates: "dict[str, dict]" = {}
        self.decisions: list = []
        self.actions: "dict[str, int]" = {}
        self.deferred = 0
        self.shed = 0
        self._c_decisions = None
        self._c_deferred: dict = {}
        self._c_shed: dict = {}
        if registry is not None:
            self._c_decisions = registry.counter(
                "streambench_admission_decisions_total",
                "admission gate changes (defer/shed/release) with "
                "blame evidence journaled")

    # -- hot path ------------------------------------------------------
    def admit(self, tenant: str) -> str:
        """What the host should do with this tenant's next ingest
        batch.  One dict lookup; ``"admit"`` when ungated."""
        g = self._gates.get(str(tenant))
        if g is None:
            return ACTION_ADMIT
        return g["mode"]

    def note_deferred(self, tenant: str, batches: int = 1) -> None:
        """The host left this many of ``tenant``'s batches queued under
        a defer gate (accounting only — the batches are NOT lost)."""
        self.deferred += int(batches)
        if self._reg is not None:
            c = self._c_deferred.get(tenant)
            if c is None:
                c = self._c_deferred[tenant] = self._reg.counter(
                    "streambench_admission_deferred_total",
                    "ingest batches held back by an admission defer "
                    "gate", labels={"tenant": str(tenant)})
            c.inc(batches)

    def note_shed(self, tenant: str, batches: int = 1) -> None:
        """The host dropped this many of ``tenant``'s batches under a
        shed gate (these ARE lost, and say so)."""
        self.shed += int(batches)
        if self._reg is not None:
            c = self._c_shed.get(tenant)
            if c is None:
                c = self._c_shed[tenant] = self._reg.counter(
                    "streambench_admission_shed_total",
                    "ingest batches dropped by an admission shed gate",
                    labels={"tenant": str(tenant)})
            c.inc(batches)

    # -- plumbing ------------------------------------------------------
    def _journal(self, dec: dict) -> None:
        self.decisions.append(dec)
        if len(self.decisions) > DECISIONS_MAX:
            del self.decisions[0]
        self.actions[dec["decision"]] = \
            self.actions.get(dec["decision"], 0) + 1
        if self.sampler is not None:
            self.sampler.annotate(
                "admission_decision",
                **{k: v for k, v in dec.items() if k != "ts_ms"})
        if self.flightrec is not None:
            self.flightrec.record("admission", **dec)
        if self._c_decisions is not None:
            self._c_decisions.inc()

    def _decision(self, action: str, *, aggressor: str, victim: str,
                  burn: float, blame_ms: float, **extra) -> dict:
        dec = {"decision": action, "tenant": aggressor,
               "victim": victim, "burn": round(float(burn), 3),
               "blame_ms": round(float(blame_ms), 3),
               "step": self.steps, "ts_ms": now_ms()}
        if self.lags is not None:
            try:
                lag = {str(k): int(v)
                       for k, v in (self.lags() or {}).items()}
            except Exception:
                lag = {}
            if lag:
                dec["lag"] = lag
        dec.update(extra)
        self._journal(dec)
        return dec

    # -- the loop body -------------------------------------------------
    def step(self, now: "float | None" = None) -> "dict | None":
        """One watch-maybe-gate pass.  Returns the decision dict when a
        gate changed, else None."""
        now = self._clock() if now is None else now
        self.steps += 1
        burns = {str(t): float(b) for t, b in (self.burns() or {}).items()}
        if not self._primed:
            self._primed = True
            return None   # priming: history must not read as a breach
        breaching = {t: b for t, b in burns.items()
                     if b >= self.breach_burn}
        for t in list(self._breach_streak):
            if t not in breaching:
                self._breach_streak[t] = 0
        for t in breaching:
            self._breach_streak[t] = self._breach_streak.get(t, 0) + 1

        if not breaching:
            self._healthy_streak += 1
            if self._gates and self._healthy_streak >= self.healthy_ticks:
                released = sorted(self._gates)
                g0 = self._gates[released[0]]
                self._gates.clear()
                self._healthy_streak = 0
                return self._decision(
                    "release", aggressor=",".join(released),
                    victim=g0["victim"], burn=max(burns.values(), default=0.0),
                    blame_ms=0.0, released=released)
            return None
        self._healthy_streak = 0

        # highest-burn victim with a confirmed (hysteresis-cleared)
        # breach drives the decision this step
        victim = max(breaching, key=lambda t: breaching[t])
        if self._breach_streak[victim] < self.breach_ticks:
            return None
        blame = self.ledger.aggressor_for(victim)
        if blame is None:
            return None   # no cross-tenant evidence -> never actuate
        aggressor, blame_ms = blame
        if aggressor == victim:
            return None
        gate = self._gates.get(aggressor)
        if gate is not None:
            # escalate a defer that isn't working to shed
            if (gate["mode"] == ACTION_DEFER
                    and self.steps - gate["since_step"]
                    >= self.escalate_ticks):
                if not self._cool(now):
                    self.holds += 1
                    return None
                gate["mode"] = ACTION_SHED
                gate["since_step"] = self.steps
                self._last_act = now
                return self._decision(
                    ACTION_SHED, aggressor=aggressor, victim=victim,
                    burn=breaching[victim], blame_ms=blame_ms,
                    escalated=True)
            return None
        if not self._cool(now):
            self.holds += 1
            return None
        self._gates[aggressor] = {"mode": ACTION_DEFER,
                                  "victim": victim,
                                  "since_step": self.steps}
        self._last_act = now
        return self._decision(
            ACTION_DEFER, aggressor=aggressor, victim=victim,
            burn=breaching[victim], blame_ms=blame_ms)

    def _cool(self, now: float) -> bool:
        return (self._last_act is None
                or now - self._last_act >= self.cooldown_s)

    # -- reporting -----------------------------------------------------
    def gates(self) -> dict:
        return {t: dict(g) for t, g in self._gates.items()}

    def summary(self) -> dict:
        out = {
            "steps": self.steps,
            "decisions": len(self.decisions),
            "defers": self.actions.get(ACTION_DEFER, 0),
            "sheds": self.actions.get(ACTION_SHED, 0),
            "releases": self.actions.get("release", 0),
            "holds": self.holds,
            "batches_deferred": self.deferred,
            "batches_shed": self.shed,
            "gates": self.gates(),
            "breach_burn": self.breach_burn,
        }
        if self.decisions:
            last = self.decisions[-1]
            out["last"] = {k: last.get(k) for k in
                           ("decision", "tenant", "victim", "burn",
                            "blame_ms", "ts_ms")}
        return out
