"""Span tracing: a bounded, thread-aware ring of closed spans, exported
as Chrome trace-event JSON (perfetto-loadable).

The existing :class:`~streambench_tpu.trace.Tracer` answers "how much
total time went to each stage" — aggregates only, no timeline.  This
module keeps the individual spans: WHEN each encode/dispatch/flush/sink
write ran, on WHICH thread, and for how long — the picture that shows
whether the writer thread actually overlaps the host loop, where the
1 Hz flush cadence sits relative to device dispatches, and what the
engine was doing in the seconds before a crash (the flight recorder
embeds the last N closed spans in its dumps).

Design constraints, matching the rest of obs/:

- **default-off, zero hot-path cost when unused** — the engine's
  ``Tracer`` gains one ``sink`` attribute (``None`` by default: one
  attribute check per span, the same price the lifecycle/flightrec
  hooks pay).  Nothing else changes until ``attach_obs(...,
  spans=SpanTracer(...))``.
- **bounded** — a deque ring of ``capacity`` closed spans; evictions
  are counted (``dropped``), never silent.  At the default 4096 the
  ring holds the last few seconds of a hot run — exactly the window a
  postmortem wants.
- **cheap** — one dict + deque append under a lock per CLOSED span
  (~1 µs); open spans carry no state beyond the caller's stack.

Export format is the Chrome trace-event JSON object form
(``{"traceEvents": [...]}``): ``"X"`` complete events with
microsecond ``ts``/``dur`` on the span's real thread id, plus one
``"M"`` ``thread_name`` metadata event per thread — load the file in
https://ui.perfetto.dev or ``chrome://tracing`` as-is.  The ``obs
trace`` CLI validates and summarizes one.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

from streambench_tpu.utils.ids import now_ms

#: Chrome trace phase codes this module emits.
PH_COMPLETE = "X"
PH_METADATA = "M"


class SpanTracer:
    """Bounded ring of closed spans + Chrome trace export.

    ``add`` records one closed span (any thread); ``span`` is the
    context-manager form; ``sink`` has the exact signature
    ``Tracer.sink`` calls with, so ``tracer.sink = spans.sink`` (or
    ``spans.attach(tracer)``) forwards every existing stage span —
    encode, device_step/device_scan, drain, redis_flush, warmup,
    decode_probe — without touching a single call site.  The staged
    ingest pipeline and the serial runner loops add their read spans
    the same way.
    """

    def __init__(self, capacity: int = 4096, registry=None):
        self.capacity = max(int(capacity), 16)
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dropped = 0
        # trace epoch: spans are stamped relative to this perf_counter
        # origin; wall0_ms lets a reader line the trace up with the
        # metrics.jsonl / flight-recorder wall clocks
        self._t0_ns = time.perf_counter_ns()
        self.wall0_ms = now_ms()
        # t0_ns is public API: the query-contention tracker
        # (obs.queryattr) converts its own perf_counter_ns stamps into
        # ring-relative time to intersect with ingest dispatch spans
        self._c_spans = self._c_dropped = None
        if registry is not None:
            self._c_spans = registry.counter(
                "streambench_spans_total",
                "closed spans recorded by the span tracer")
            self._c_dropped = registry.counter(
                "streambench_spans_dropped_total",
                "spans evicted from the bounded ring")

    @property
    def t0_ns(self) -> int:
        """The ring's ``perf_counter_ns`` origin: ``ts_us`` fields are
        relative to this stamp."""
        return self._t0_ns

    # ------------------------------------------------------------------
    def add(self, name: str, start_ns: int, dur_ns: int,
            cat: str = "engine", args: "dict | None" = None) -> None:
        """Record one closed span.  ``start_ns`` is a
        ``perf_counter_ns`` stamp (the Tracer's native clock); the
        thread identity is captured HERE — call from the thread that
        ran the span."""
        t = threading.current_thread()
        rec = {
            "name": name,
            "cat": cat,
            "ts_us": round((start_ns - self._t0_ns) / 1e3, 3),
            "dur_us": round(dur_ns / 1e3, 3),
            "tid": t.ident or 0,
            "thread": t.name,
        }
        if args:
            rec["args"] = dict(args)
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped += 1
            self._buf.append(rec)
        if self._c_spans is not None:
            self._c_spans.inc()
            if self.dropped:
                self._c_dropped.set_total(self.dropped)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "engine",
             args: "dict | None" = None):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.add(name, t0, time.perf_counter_ns() - t0,
                     cat=cat, args=args)

    def sink(self, stage: str, start_ns: int, dur_ns: int) -> None:
        """``Tracer.sink`` adapter: stage spans arrive under the
        ``"stage"`` category."""
        self.add(stage, start_ns, dur_ns, cat="stage")

    def attach(self, tracer) -> "SpanTracer":
        """Forward every span the given Tracer records into this ring."""
        tracer.sink = self.sink
        return self

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self) -> list[dict]:
        """Ring contents, oldest first."""
        with self._lock:
            return list(self._buf)

    def tail(self, n: int = 32) -> list[dict]:
        """The last ``n`` closed spans (flight-recorder embedding)."""
        with self._lock:
            if n >= len(self._buf):
                return list(self._buf)
            return list(self._buf)[-n:]

    # ------------------------------------------------------------------
    def chrome_trace(self, run: str = "") -> dict:
        """The ring as a Chrome trace-event JSON object (perfetto/
        chrome://tracing load it directly): ``X`` complete events on
        real thread ids + one ``thread_name`` metadata event per
        thread."""
        spans = self.snapshot()
        pid = os.getpid()
        events: list[dict] = []
        threads: dict[int, str] = {}
        for s in spans:
            threads.setdefault(s["tid"], s["thread"])
        for tid, name in sorted(threads.items()):
            events.append({"name": "thread_name", "ph": PH_METADATA,
                           "pid": pid, "tid": tid,
                           "args": {"name": name}})
        for s in spans:
            ev = {"name": s["name"], "cat": s["cat"], "ph": PH_COMPLETE,
                  "ts": s["ts_us"], "dur": s["dur_us"],
                  "pid": pid, "tid": s["tid"]}
            if "args" in s:
                ev["args"] = s["args"]
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "run": run,
                "wall0_ms": self.wall0_ms,
                "spans": len(spans),
                "spans_dropped": self.dropped,
            },
        }

    def dump(self, path: str, run: str = "") -> str:
        """Write the Chrome trace to ``path`` (tmp + rename, so a torn
        write is never mistaken for a complete trace)."""
        doc = self.chrome_trace(run=run)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


# ----------------------------------------------------------------------
# trace-file validation + summary (the ``obs trace`` CLI)
def validate_chrome_trace(doc) -> list[str]:
    """Schema problems in a Chrome trace-event object ([] = loadable).
    Checks the subset perfetto requires: a ``traceEvents`` list whose
    events carry ``name``/``ph``/``pid``/``tid``, ``X`` events with
    numeric ``ts``+``dur``, ``M`` events with an ``args`` dict."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"top level is {type(doc).__name__}, not an object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                problems.append(f"{where}: missing {key!r}")
        ph = ev.get("ph")
        if ph == PH_COMPLETE:
            for key in ("ts", "dur"):
                if not isinstance(ev.get(key), (int, float)):
                    problems.append(f"{where}: X event {key!r} not "
                                    "numeric")
        elif ph == PH_METADATA:
            if not isinstance(ev.get("args"), dict):
                problems.append(f"{where}: M event without args dict")
        elif ph is not None:
            problems.append(f"{where}: unsupported ph {ph!r}")
        if len(problems) >= 20:
            problems.append("... (truncated)")
            break
    return problems


def summarize_trace(doc, path: str = "") -> dict:
    """Per-name totals + thread table of one Chrome trace object."""
    events = [e for e in doc.get("traceEvents", [])
              if isinstance(e, dict)]
    xs = [e for e in events if e.get("ph") == PH_COMPLETE]
    threads = {e["tid"]: (e.get("args") or {}).get("name", "?")
               for e in events if e.get("ph") == PH_METADATA}
    by_name: dict[str, dict] = {}
    for e in xs:
        agg = by_name.setdefault(e.get("name", "?"),
                                 {"count": 0, "total_ms": 0.0,
                                  "max_ms": 0.0})
        dur_ms = float(e.get("dur", 0)) / 1e3
        agg["count"] += 1
        agg["total_ms"] = round(agg["total_ms"] + dur_ms, 3)
        agg["max_ms"] = round(max(agg["max_ms"], dur_ms), 3)
    span_us = ((max(e["ts"] + e.get("dur", 0) for e in xs)
                - min(e["ts"] for e in xs)) if xs else 0.0)
    other = doc.get("otherData") or {}
    return {
        "path": path,
        "events": len(xs),
        "threads": {str(k): v for k, v in sorted(threads.items())},
        "trace_span_ms": round(span_us / 1e3, 3),
        "spans_dropped": other.get("spans_dropped"),
        "run": other.get("run"),
        "by_name": dict(sorted(by_name.items(),
                               key=lambda kv: -kv[1]["total_ms"])),
    }


def render_trace_summary(s: dict) -> str:
    lines = [f"span trace: {s['path'] or '(doc)'}",
             f"  events {s['events']}  span {s['trace_span_ms']:,.1f} ms"
             + (f"  dropped {s['spans_dropped']}"
                if s.get("spans_dropped") else "")]
    if s["threads"]:
        lines.append("  threads: "
                     + ", ".join(f"{tid}={name}"
                                 for tid, name in s["threads"].items()))
    if s["by_name"]:
        width = max(len(n) for n in s["by_name"])
        lines.append(f"  {'name':<{width}}  {'count':>8}  "
                     f"{'total_ms':>12}  {'max_ms':>10}")
        for name, agg in s["by_name"].items():
            lines.append(f"  {name:<{width}}  {agg['count']:>8}  "
                         f"{agg['total_ms']:>12,.1f}  "
                         f"{agg['max_ms']:>10,.3f}")
    return "\n".join(lines)
