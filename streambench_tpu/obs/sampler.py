"""Background metrics sampler: a ``metrics.jsonl`` time-series journal.

One daemon thread wakes every ``jax.metrics.interval.ms`` and appends a
snapshot record to ``metrics.jsonl`` in the run's workdir — the run's
flight recorder.  Everything is *pulled* from the engine's existing
host-side bookkeeping (``events_processed``, the ``Tracer`` table,
``FaultCounters``, the journal reader's byte position): the hot loop is
never instrumented beyond what already exists, so a disabled sampler
costs the hot path nothing at all.

Record schema (one JSON object per line):

- ``{"kind": "snapshot", "seq": N, "ts_ms": ..., "uptime_ms": ...,``
  ``"events": cum, "events_per_s": delta-rate, "windows_written": cum,``
  ``"backlog_bytes": ..., "watermark_lag_ms": ..., "sink_dirty_rows": ...,``
  ``"rss_bytes": ..., "latency_ms": {count,p50,p95,p99,min,max,sum},``
  ``"stages": {name: {"calls": Δ, "ms": Δ}}, "faults": cum,``
  ``"fault_deltas": Δ}`` — per-tick state; deltas are since the
  previous record.  When the staged ingest pipeline is live the record
  also carries ``"ingest": {block_queue_depth, batch_queue_depth,``
  ``reader_stalls, encode_stalls, ...}`` (``IngestPipeline.telemetry``).
- ``{"kind": "event", "event": "...", ...}`` — out-of-band annotations
  (supervisor restarts, give-ups) injected between snapshots.
- ``{"kind": "final", ..., "run_stats": {...}}`` — one last snapshot at
  close, carrying the exit ``RunStats`` verbatim so the time series and
  the run's JSON stats line can be reconciled record-for-record.
"""

from __future__ import annotations

import json
import os
import threading
import time

from streambench_tpu.utils.ids import now_ms


def rss_sample() -> "tuple[int | None, str]":
    """``(bytes, field_name)`` resident-set reading for this process.

    The primary ``/proc/self/statm`` path reads CURRENT RSS and labels
    it ``rss_bytes``; the portability fallback only has ``ru_maxrss`` —
    the PEAK, which never goes down — so it is labeled
    ``rss_peak_bytes`` instead of being passed off as current (a report
    reading a flat "rss" line would otherwise conclude memory is stable
    while the process leaks toward its peak)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return (int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE"),
                    "rss_bytes")
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return (resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                    * 1024, "rss_peak_bytes")
        except Exception:
            return None, "rss_bytes"


def rss_bytes() -> int | None:
    """Resident set size of this process, or None when unreadable.
    NOTE: on hosts without ``/proc`` this is the peak, not current —
    use :func:`rss_sample` when the distinction matters."""
    return rss_sample()[0]


def engine_collector(engine, reader=None, runner=None, registry=None):
    """Collector closure over one engine (+ optional reader/runner).

    Each call folds the engine's current cumulative bookkeeping into the
    snapshot ``record`` (rates and per-stage/fault deltas computed
    against the previous call) and mirrors the same values into
    ``registry`` instruments so a Prometheus scrape and the jsonl stream
    always tell one story.  Everything is duck-typed reads of host-side
    fields — no device sync, no locks beyond the instruments' own.
    """
    prev = {"events": 0, "windows": 0, "stages": {}, "faults": {}}
    reg = registry
    if reg is not None:
        c_events = reg.counter("streambench_events_total",
                               "events folded into device state")
        c_windows = reg.counter("streambench_windows_written_total",
                                "window rows written to the sink")
        g_eps = reg.gauge("streambench_events_per_s",
                          "ingest rate over the last sample interval")
        g_backlog = reg.gauge("streambench_backlog_bytes",
                              "journal bytes appended but not consumed")
        g_wm = reg.gauge("streambench_watermark_lag_ms",
                         "now - max folded event time")
        g_dirty = reg.gauge("streambench_sink_dirty_rows",
                            "failed-writeback rows retained for retry")

    def collect(rec: dict, dt_s: float) -> None:
        tel = engine.telemetry()
        events = tel["events"]
        rec["events"] = events
        rec["events_per_s"] = (round((events - prev["events"]) / dt_s, 1)
                               if dt_s > 0 else 0.0)
        rec["windows_written"] = tel["windows_written"]
        rec["watermark_lag_ms"] = tel["watermark_lag_ms"]
        rec["sink_dirty_rows"] = tel["sink_dirty_rows"]
        rec["pending_rows"] = tel["pending_rows"]
        if "sink_fence" in tel:
            # exactly-once writeback: the (epoch, seq) fence plus the
            # reconcile flag — a resumed-in-reconcile run is visible in
            # the time series, not only in the fault counters
            rec["sink_fence"] = tel["sink_fence"]
            if reg is not None:
                reg.gauge("streambench_sink_fence_seq",
                          "last committed exactly-once flush seq"
                          ).set(tel["sink_fence"]["seq"])
        # sketch-memory census (ISSUE 13): engines with a counter-plane
        # family (the session engine's fixed/salsa/two-stage sketch)
        # publish mode + measured state bytes + merge counts, feeding
        # the `obs report/diff` sketch rows and the devmem story
        sk = getattr(engine, "sketch_summary", None)
        if sk is not None:
            try:
                rec["sketch"] = sk()
            except Exception:
                pass
        if reader is not None:
            bb = getattr(reader, "backlog_bytes", None)
            rec["backlog_bytes"] = bb() if bb is not None else None
        if runner is not None:
            rec["batches"] = runner.stats.batches
            rec["flushes"] = runner.stats.flushes
            # staged ingest pipeline (engine.ingest): stage queue depths
            # + stall/starvation counters, present only while a pipeline
            # is live (looked up per tick — the runner builds it inside
            # run(), after this collector was wired)
            pipe = getattr(runner, "_pipeline", None)
            if pipe is not None:
                ing = pipe.telemetry()
                rec["ingest"] = ing
                if reg is not None:
                    reg.gauge("streambench_ingest_block_queue_depth",
                              "raw journal blocks queued ahead of encode"
                              ).set(ing["block_queue_depth"])
                    reg.gauge("streambench_ingest_batch_queue_depth",
                              "encoded batch groups queued ahead of "
                              "device dispatch"
                              ).set(ing["batch_queue_depth"])
                    reg.counter("streambench_ingest_reader_stalls_total",
                                "reader blocked on a full block queue"
                                ).set_total(ing["reader_stalls"])
                    reg.counter("streambench_ingest_encode_stalls_total",
                                "encode blocked on a full batch queue"
                                ).set_total(ing["encode_stalls"])
        # per-stage span deltas (thread-safe Tracer snapshot)
        stages = {}
        for name, (calls, total_ns, _mx) in engine.tracer.snapshot().items():
            pc, pn = prev["stages"].get(name, (0, 0))
            if calls != pc or total_ns != pn:
                stages[name] = {"calls": calls - pc,
                                "ms": round((total_ns - pn) / 1e6, 3)}
            prev["stages"][name] = (calls, total_ns)
        rec["stages"] = stages
        faults = engine.faults.snapshot()
        rec["faults"] = faults
        rec["fault_deltas"] = {
            k: v - prev["faults"].get(k, 0)
            for k, v in faults.items() if v != prev["faults"].get(k, 0)}
        prev["faults"] = faults
        prev["events"] = events
        hist = getattr(engine, "_obs_hist", None)
        if hist is not None and hist.count:
            rec["latency_ms"] = hist.summary()
        # window-lifecycle attribution (obs.lifecycle): the per-segment
        # decomposition of the latency histogram above, present only
        # when the engine was attached with lifecycle=True
        lc = getattr(engine, "_obs_lifecycle", None)
        if lc is not None:
            rec["attribution"] = lc.summary()
        # measured device occupancy (obs.occupancy): sampled busy ratio
        # + recompile counters, present only when attached
        occ = getattr(engine, "_obs_occupancy", None)
        if occ is not None:
            rec["occupancy"] = occ.summary()
        # host->device transfer ledger (obs.xfer): exact payload bytes
        # per wire format + sampled timed transfers
        xf = getattr(engine, "_obs_xfer", None)
        if xf is not None:
            rec["xfer"] = xf.summary()
        # per-shard routed-row skew (obs.xfer.ShardSkew): materializing
        # the device accumulators syncs, but only at sampler cadence
        sk = getattr(engine, "_obs_shard", None)
        if sk is not None:
            shard = sk.summary()
            if shard is not None:
                rec["shard_skew"] = shard
        rss, rss_label = rss_sample()
        rec[rss_label] = rss
        if reg is not None:
            c_events.set_total(events)
            c_windows.set_total(rec["windows_written"])
            g_eps.set(rec["events_per_s"])
            if rec.get("backlog_bytes") is not None:
                g_backlog.set(rec["backlog_bytes"])
            if rec.get("watermark_lag_ms") is not None:
                g_wm.set(rec["watermark_lag_ms"])
            g_dirty.set(rec["sink_dirty_rows"])
            if rss is not None:
                # gauge name follows the sample's semantics (current vs
                # peak) — get-or-create, so only the taken path exists
                reg.gauge(f"streambench_{rss_label}",
                          "resident set size of the engine process"
                          if rss_label == "rss_bytes" else
                          "peak resident set size (ru_maxrss fallback)"
                          ).set(rss)
            for name, d in stages.items():
                reg.counter("streambench_stage_calls_total",
                            "tracer span calls per stage",
                            labels={"stage": name}).inc(d["calls"])
                reg.counter("streambench_stage_ms_total",
                            "tracer span time per stage (ms)",
                            labels={"stage": name}).inc(d["ms"])
            for k, v in faults.items():
                reg.counter("streambench_faults_total",
                            "fault/retry/recovery events by kind",
                            labels={"kind": k}).set_total(v)

    return collect


def kafka_collector(counters, lag=None, registry=None):
    """Collector over the Kafka adapter's shared delivery ledger.

    ``counters`` is the :class:`FaultCounters` a
    :class:`~streambench_tpu.io.kafka.KafkaBroker` threads through
    every writer/reader it hands out (``kafka_produced``,
    ``kafka_delivered``, ``kafka_redeliveries``, retry/backoff
    counters); ``lag`` is an optional callable returning the
    broker-side consumer lag in records.  Each tick lands the ledger
    under ``rec["kafka"]`` (prefix stripped) and mirrors the headline
    instruments into ``registry``.  The instrument family is
    predeclared up front — the scrape-gap rule: a Prometheus scrape
    BEFORE the first fault must see zeroed series, not a missing
    family.
    """
    reg = registry
    if reg is not None:
        reg.predeclare(
            "counter", "streambench_kafka_redeliveries_total",
            "records the broker re-sent after a connection drop and "
            "the reader filtered (duplicates never reach the engine)")
        reg.predeclare(
            "counter", "streambench_kafka_produce_retries_total",
            "transient produce errors retried with capped backoff")
        reg.predeclare(
            "counter", "streambench_kafka_broker_down_ms_total",
            "milliseconds spent in retry backoff against a faulted "
            "broker")
        reg.predeclare(
            "gauge", "streambench_kafka_consumer_lag",
            "broker log end minus the consumer's position (records "
            "not yet fetched)")

    def collect(rec: dict, dt_s: float) -> None:
        snap = counters.snapshot()
        blk = {k[len("kafka_"):]: v for k, v in snap.items()
               if k.startswith("kafka_")}
        if lag is not None:
            try:
                blk["consumer_lag"] = int(lag())
            except Exception:
                pass
        rec["kafka"] = blk
        if reg is not None:
            reg.counter("streambench_kafka_redeliveries_total"
                        ).set_total(blk.get("redeliveries", 0))
            reg.counter("streambench_kafka_produce_retries_total"
                        ).set_total(blk.get("produce_retries", 0))
            reg.counter("streambench_kafka_broker_down_ms_total"
                        ).set_total(blk.get("broker_down_ms", 0))
            if "consumer_lag" in blk:
                reg.gauge("streambench_kafka_consumer_lag"
                          ).set(blk["consumer_lag"])

    return collect


class MetricsSampler:
    """The sampling thread + jsonl writer.

    ``add_collector`` registers callables ``fn(record, dt_s)`` that fold
    state into each snapshot; ``start`` launches the daemon thread;
    ``annotate`` injects an out-of-band event record (any thread);
    ``collect_now`` runs the collectors without journaling (the
    Prometheus handler's pre-scrape refresh); ``close`` stops the thread
    and writes the final record.  All journal writes go through one lock
    so records never interleave.
    """

    def __init__(self, path: str, interval_ms: int = 1000,
                 registry=None, max_bytes: int = 0,
                 role: "str | None" = None,
                 tenant: "str | None" = None):
        self.path = path
        self.interval_ms = max(int(interval_ms), 1)
        self.registry = registry
        # fleet attribution (ISSUE 15): every record carries this
        # process's pid, and its fleet role when one is declared
        # ("writer"/"replica"), so the FleetCollector can merge many
        # roles' journals into one attributed stream.  pid is stamped
        # unconditionally — it costs one int per record and makes any
        # journal self-identifying.  ``tenant`` (ISSUE 19) is the same
        # idea one level down: a sampler journaling for exactly one
        # tenant's topology stamps that name next to role/pid.  A
        # multi-tenant host journaling for all tenants at once leaves
        # it None and nests per-tenant blocks inside each record
        # instead (``rec["tenants"][name]``).
        self.role = role
        self.tenant = tenant
        self._pid = os.getpid()
        # journal size cap (``jax.metrics.max.bytes``; 0 = unbounded):
        # a record that would push past it rotates metrics.jsonl to
        # metrics.jsonl.1 (replacing any previous .1) — a week-long
        # chaos sweep keeps at most ~2x the cap on disk, never an
        # unbounded time series
        self.max_bytes = max(int(max_bytes or 0), 0)
        self.rotations = 0
        self._collectors: list = []
        self._seq = 0
        self._t0 = time.monotonic()
        self._last_collect = self._t0
        self._io_lock = threading.Lock()
        self._collect_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._bytes = self._f.tell()   # append mode: existing size

    def add_collector(self, fn) -> None:
        self._collectors.append(fn)

    # ------------------------------------------------------------------
    def _write(self, rec: dict) -> None:
        line = json.dumps(rec) + "\n"
        with self._io_lock:
            if (self.max_bytes and self._bytes
                    and self._bytes + len(line) > self.max_bytes):
                # rotate BEFORE the write, so no single file ever
                # exceeds the cap and the newest record is never split
                self._f.close()
                os.replace(self.path, self.path + ".1")
                self._f = open(self.path, "a", encoding="utf-8")
                self._bytes = 0
                self.rotations += 1
            self._f.write(line)
            self._f.flush()
            self._bytes += len(line)

    def _snapshot_record(self, kind: str = "snapshot") -> dict:
        with self._collect_lock:
            now = time.monotonic()
            dt_s = now - self._last_collect
            self._last_collect = now
            rec = {"kind": kind, "seq": self._seq, "ts_ms": now_ms(),
                   "uptime_ms": int((now - self._t0) * 1000),
                   "pid": self._pid}
            if self.role is not None:
                rec["role"] = self.role
            if self.tenant is not None:
                rec["tenant"] = self.tenant
            self._seq += 1
            for fn in self._collectors:
                fn(rec, dt_s)
        return rec

    def collect_now(self) -> dict:
        """Run the collectors once, off-cadence, without journaling —
        refreshes the registry so a scrape never serves stale values."""
        return self._snapshot_record(kind="scrape")

    def annotate(self, event: str, **fields) -> None:
        """Inject an out-of-band event record (supervisor restarts...)."""
        rec = {"kind": "event", "event": event, "ts_ms": now_ms(),
               "uptime_ms": int((time.monotonic() - self._t0) * 1000),
               "pid": self._pid}
        if self.role is not None:
            rec["role"] = self.role
        if self.tenant is not None:
            rec["tenant"] = self.tenant
        rec.update(fields)
        self._write(rec)

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            self._write(self._snapshot_record())

    def start(self) -> "MetricsSampler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="metrics-sampler")
            self._thread.start()
        return self

    def close(self, final: dict | None = None) -> None:
        """Stop sampling; journal one ``final`` record carrying the
        collectors' last word plus the exit ``run_stats`` verbatim."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        rec = self._snapshot_record(kind="final")
        if final is not None:
            rec["run_stats"] = final
        self._write(rec)
        with self._io_lock:
            self._f.close()
