"""Per-query latency attribution for the reach serving tier.

The ingest data path has had decomposed latency since PR 4: every
written window's e2e splits into ingest/encode/fold/flush/sink segments
that sum to it.  The reach query path (PR 10) had only the aggregate
``streambench_reach_latency_ms`` histogram — when the 1200-query storm
shows p99 481 ms, nothing can say whether the time was queue wait,
batch assembly, the device dispatch, or the reply write, nor how much
of the queue wait was caused by the device being busy folding ingest
batches.  This module is the query-side mirror of
:class:`~streambench_tpu.obs.lifecycle.WindowLifecycle`:

- every admitted query gets a :class:`QueryRecord` stamped at
  **admission**, **queue-exit**, **dispatch-submit**,
  **dispatch-complete** and **reply-write**; the submit -> reply e2e is
  decomposed into four segments that sum to it exactly:

  - ``queue``    — admission until the worker popped it for a batch
  - ``batch``    — queue-exit until the padded dispatch was submitted
    (mask assembly; shared by every query in the batch)
  - ``dispatch`` — dispatch submit until the results were materialized
    on the host (device compute + transfer back)
  - ``reply``    — results in hand until this query's reply was written

  Segments land in one ``streambench_reach_segment_ms`` histogram
  family (label ``segment=...``) plus a matched
  ``streambench_reach_query_e2e_ms`` over the SAME tracked queries, so
  segment p50s explain the e2e p50 apples-to-apples (the serving
  histogram ``streambench_reach_latency_ms`` is unchanged).

- **shed queries stamp too**: a shed victim contributes one
  ``streambench_reach_shed_queue_ms`` sample (admission -> shed; a
  queue-only record, deliberately OUTSIDE the segment family so the
  segment/e2e distributions stay matched) and one ``shed_records``
  count that reconciles exactly against
  ``streambench_reach_shed_total``.

- a bounded **slow-query log** keeps the full decomposition of every
  query slower than ``slo_ms`` (cap + oldest-first eviction, evictions
  counted — the lifecycle-table rule).

- **contention attribution**: each answered query's queue-wait
  interval is intersected with the known *ingest-busy* intervals —
  both sides stamp the same ``perf_counter_ns`` clock — and the
  accumulated overlap/wait ratio is exported as
  ``streambench_reach_contention_ratio``: the fraction of query queue
  time during which the device was occupied by an ingest dispatch.
  ~1.0 means queries wait *because* ingest owns the device (sharded
  reach needs its own device or a replica tier, ROADMAP item 3); ~0.0
  means the queue wait is the server's own batching cadence.

  Busy evidence comes from two merged sources, because an async
  dispatch stream hides its own device time: (a) ingest dispatch spans
  (``device_step``/``device_scan``/``drain``) from the wired
  :class:`~streambench_tpu.obs.spans.SpanTracer` ring — meaningful
  exactly where the span covers a real device wait (the ``drain``
  sync; synchronous-dispatch backends), and (b) explicit
  ``note_ingest_busy(start_ns, end_ns)`` intervals — the engine CLI
  wires the OccupancySampler's 1-in-N ``block_until_ready``-timed
  windows here (sampled evidence, same caveat as the busy ratio), and
  the bench's backpressured ingest loop feeds its measured fold-sync
  windows.  Absent both, the gauge stays 0 — missing evidence is
  never fabricated.

Default-off like every obs layer: the reach server carries a ``None``
attribute and reply payloads are byte-identical until
``jax.obs.query`` is set.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from streambench_tpu.utils.ids import now_ms

#: Segment order is pipeline order; renderers preserve it.
SEGMENTS = ("queue", "batch", "dispatch", "reply")

_SEGMENT_HELP = {
    "queue": "admission -> popped from the bounded queue by the worker",
    "batch": "queue-exit -> padded batch dispatch submitted",
    "dispatch": "dispatch submit -> results materialized on host",
    "reply": "results materialized -> this query's reply written",
}

#: Tracer stage-span names that mean "the device is running an ingest
#: dispatch" (engine/pipeline.py span sites) — the numerator of the
#: contention ratio.
INGEST_DISPATCH_SPANS = frozenset(
    ("device_step", "device_scan", "drain"))


class QueryRecord:
    """Stamps of one query's journey (``perf_counter_ns`` clock, the
    span tracer's clock).  Created at admission; ``t_exit`` is set when
    the worker pops it; the batch-level submit/done stamps are passed
    to ``note_reply`` rather than stored per record."""

    __slots__ = ("trace", "qid", "client_ms", "t_admit", "t_exit")

    def __init__(self, trace=None, qid=None, client_ms=None):
        self.trace = trace
        self.qid = qid
        self.client_ms = client_ms
        self.t_admit = time.perf_counter_ns()
        self.t_exit = 0


class QueryLifecycle:
    """Tracks per-query stage stamps and feeds the segment histograms.

    One instance per reach server; the pub/sub handler threads call
    ``admit`` (under the server's admission path) and the single worker
    thread calls ``note_queue_exit``/``note_reply``; shed replies call
    ``note_shed`` from whichever thread sheds.  One lock guards the
    slow log and contention accumulators; the histograms carry their
    own.
    """

    def __init__(self, registry, slo_ms: int = 0, slowlog_max: int = 128,
                 sample_every: int = 1, spans=None):
        self.slo_ms = max(int(slo_ms), 0)
        self.slowlog_max = max(int(slowlog_max), 1)
        self.sample_every = max(int(sample_every), 1)
        self._spans = spans
        self._lock = threading.Lock()
        self.served_records = 0
        self.shed_records = 0
        self.slowlog_evicted = 0
        self._slowlog: deque = deque(maxlen=self.slowlog_max)
        # contention accumulators (ns, answered queries only)
        self._queue_wait_ns = 0
        self._ingest_overlap_ns = 0
        self._device_samples = 0
        # explicit ingest-busy intervals (perf_counter_ns), bounded:
        # the occupancy sampler / bench ingest loop feed measured
        # device-busy windows here (async dispatch spans cannot)
        self._busy: deque = deque(maxlen=4096)
        self.ingest_busy_intervals = 0
        # Same tight growth as the window attribution (~9%/bucket):
        # the contract is "segment p50s explain the e2e p50", and
        # bucket error is that comparison's noise floor.  lo=0.01 ms:
        # batch assembly on a warm server is tens of microseconds.
        growth = 2 ** 0.125
        self._hists = {
            seg: registry.histogram(
                "streambench_reach_segment_ms",
                "reach query latency attribution by segment (ms)",
                lo=0.01, hi=1e7, growth=growth, labels={"segment": seg})
            for seg in SEGMENTS}
        self._e2e = registry.histogram(
            "streambench_reach_query_e2e_ms",
            "submit -> reply e2e of attribution-tracked reach queries "
            "(ms)", lo=0.01, hi=1e7, growth=growth)
        # NOT part of the segment partition: how long a shed victim sat
        # queued before the shed (its whole server-side life)
        self._shed_hist = registry.histogram(
            "streambench_reach_shed_queue_ms",
            "admission -> shed of load-shed reach queries (ms)",
            lo=0.01, hi=1e7, growth=growth)
        self._g_contention = registry.gauge(
            "streambench_reach_contention_ratio",
            "fraction of reach-query queue wait during which the "
            "device was occupied by an ingest dispatch (needs "
            "jax.obs.spans for the ingest span stream)")
        self._hist_device = registry.histogram(
            "streambench_reach_dispatch_device_ms",
            "sampled block_until_ready-timed reach dispatch device "
            "time (ms)", lo=0.001, hi=1e5)
        self._c_tracked = registry.counter(
            "streambench_reach_tracked_total",
            "reach queries with a full lifecycle record (answered)")
        self._c_shed_tracked = registry.counter(
            "streambench_reach_shed_tracked_total",
            "shed reach queries with a queue-only lifecycle record")

    # ------------------------------------------------------------------
    def admit(self, trace=None, qid=None, client_ms=None) -> QueryRecord:
        """One query entered the bounded queue; returns the record that
        rides the queue item.  ``trace``/``client_ms`` come off the
        wire message (``trace``/``sent_ms`` fields) when the client
        propagated them."""
        return QueryRecord(trace=trace, qid=qid, client_ms=client_ms)

    # ------------------------------------------------------------------
    def note_queue_exit(self, recs: list) -> None:
        """The worker popped these records into one batch (stamp
        ``t_exit`` first, then call this): accumulates queue-wait vs
        ingest-dispatch overlap for the contention ratio.  One span-ring
        snapshot per BATCH, not per query."""
        if not recs:
            return
        busy = self._ingest_busy_intervals(
            min(r.t_admit for r in recs),
            max(r.t_exit for r in recs))
        wait_ns = overlap_ns = 0
        for r in recs:
            w = r.t_exit - r.t_admit
            if w <= 0:
                continue
            wait_ns += w
            if busy:
                overlap_ns += _interval_overlap_ns(
                    r.t_admit, r.t_exit, busy)
        with self._lock:
            self._queue_wait_ns += wait_ns
            self._ingest_overlap_ns += overlap_ns
            ratio = (self._ingest_overlap_ns / self._queue_wait_ns
                     if self._queue_wait_ns else 0.0)
        self._g_contention.set(round(ratio, 4))

    def note_ingest_busy(self, start_ns: int, end_ns: int) -> None:
        """One measured ingest device-busy window (``perf_counter_ns``
        stamps): the OccupancySampler's sampled ``block_until_ready``
        wait, or a backpressured ingest loop's fold-sync window.  An
        async dispatch's span only covers the submit call, so THIS is
        how real device occupancy reaches the contention numerator."""
        if end_ns > start_ns:
            with self._lock:
                self._busy.append((int(start_ns), int(end_ns)))
                self.ingest_busy_intervals += 1

    def _ingest_busy_intervals(self, lo_ns: int, hi_ns: int) -> list:
        """Merged [start_ns, end_ns) ingest-busy intervals overlapping
        [lo_ns, hi_ns): span-ring dispatch spans + explicitly fed busy
        windows.  Empty when neither source is wired (the contention
        gauge then stays 0 — absent evidence, not fabricated)."""
        if hi_ns <= lo_ns:
            return []
        raw = []
        if self._spans is not None:
            t0 = self._spans.t0_ns
            for s in self._spans.snapshot():
                if (s.get("cat") != "stage"
                        or s.get("name") not in INGEST_DISPATCH_SPANS):
                    continue
                s_ns = t0 + int(s["ts_us"] * 1e3)
                e_ns = s_ns + int(s["dur_us"] * 1e3)
                if e_ns <= lo_ns or s_ns >= hi_ns:
                    continue
                raw.append((s_ns, e_ns))
        with self._lock:
            busy = list(self._busy)
        raw.extend((s_ns, e_ns) for s_ns, e_ns in busy
                   if not (e_ns <= lo_ns or s_ns >= hi_ns))
        if not raw:
            return []
        raw.sort()
        merged = [list(raw[0])]
        for s_ns, e_ns in raw[1:]:
            if s_ns <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e_ns)
            else:
                merged.append([s_ns, e_ns])
        return merged

    # ------------------------------------------------------------------
    def note_reply(self, rec: QueryRecord, t_submit_ns: int,
                   t_done_ns: int) -> None:
        """This record's reply was just written; observe one sample per
        segment.  The four segments sum to ``now - t_admit`` exactly —
        the same partition contract as the window attribution."""
        now = time.perf_counter_ns()
        segs = (
            ("queue", rec.t_exit - rec.t_admit),
            ("batch", t_submit_ns - rec.t_exit),
            ("dispatch", t_done_ns - t_submit_ns),
            ("reply", now - t_done_ns),
        )
        for name, ns in segs:
            self._hists[name].observe(max(ns, 0) / 1e6)
        e2e_ms = max(now - rec.t_admit, 0) / 1e6
        self._e2e.observe(e2e_ms)
        with self._lock:
            self.served_records += 1
        self._c_tracked.inc()
        if self.slo_ms and e2e_ms > self.slo_ms:
            entry = {
                "ts_ms": now_ms(),
                "id": rec.qid,
                "e2e_ms": round(e2e_ms, 3),
                **{f"{name}_ms": round(max(ns, 0) / 1e6, 3)
                   for name, ns in segs},
            }
            if rec.trace is not None:
                entry["trace"] = rec.trace
            with self._lock:
                if len(self._slowlog) == self.slowlog_max:
                    self.slowlog_evicted += 1
                self._slowlog.append(entry)

    def note_shed(self, rec: QueryRecord) -> float:
        """This record's query was shed; observes the queue-only sample
        and returns the queue-wait in ms (the shed reply carries it)."""
        queue_ms = max(time.perf_counter_ns() - rec.t_admit, 0) / 1e6
        self._shed_hist.observe(queue_ms)
        with self._lock:
            self.shed_records += 1
        self._c_shed_tracked.inc()
        return queue_ms

    # ------------------------------------------------------------------
    def device_sample_due(self, dispatch_no: int) -> bool:
        """1-in-N dispatch sampling cadence for the explicit
        ``block_until_ready`` device timing (OccupancySampler's rule)."""
        return dispatch_no % self.sample_every == 0

    def note_device_sample(self, device_ms: float) -> None:
        self._hist_device.observe(device_ms)
        with self._lock:
            self._device_samples += 1

    # ------------------------------------------------------------------
    def server_block(self, rec: QueryRecord, t_submit_ns: int,
                     t_done_ns: int) -> dict:
        """The server-side decomposition a reply payload carries (up to
        reply-write START — the write itself cannot describe its own
        duration), so a client can split round-trip time into
        server-vs-network halves."""
        now = time.perf_counter_ns()
        out = {
            "queue_ms": round(max(rec.t_exit - rec.t_admit, 0) / 1e6, 3),
            "batch_ms": round(max(t_submit_ns - rec.t_exit, 0) / 1e6, 3),
            "dispatch_ms": round(max(t_done_ns - t_submit_ns, 0) / 1e6,
                                 3),
            "total_ms": round(max(now - rec.t_admit, 0) / 1e6, 3),
        }
        if rec.trace is not None:
            out["trace"] = rec.trace
        return out

    # ------------------------------------------------------------------
    def contention_ratio(self) -> float:
        with self._lock:
            if not self._queue_wait_ns:
                return 0.0
            return self._ingest_overlap_ns / self._queue_wait_ns

    def segment_quantiles(self) -> dict:
        """Compact {segment: {p50, p99}} for SLO breach events — which
        segment is burning the budget when the reach objective trips."""
        out = {}
        for seg in SEGMENTS:
            s = self._hists[seg].summary()
            if s.get("count"):
                out[seg] = {"p50": s.get("p50"), "p99": s.get("p99")}
        return out

    def slowlog(self) -> list[dict]:
        with self._lock:
            return list(self._slowlog)

    def summary(self) -> dict:
        """The ``query_obs`` block the reach server's summary / the
        ``reach_query`` metrics.jsonl block carries."""
        with self._lock:
            wait_ns = self._queue_wait_ns
            overlap_ns = self._ingest_overlap_ns
            slowlog = list(self._slowlog)
            served = self.served_records
            shed = self.shed_records
            evicted = self.slowlog_evicted
            device_samples = self._device_samples
        out = {
            "served_records": served,
            "shed_records": shed,
            "segments": {seg: self._hists[seg].summary()
                         for seg in SEGMENTS},
            "e2e_ms": self._e2e.summary(),
            "shed_queue_ms": self._shed_hist.summary(),
            "contention": {
                "queue_wait_ms": round(wait_ns / 1e6, 3),
                "ingest_overlap_ms": round(overlap_ns / 1e6, 3),
                "ratio": round(overlap_ns / wait_ns, 4) if wait_ns
                else 0.0,
                "spans_wired": self._spans is not None,
                "busy_intervals": self.ingest_busy_intervals,
            },
            "slow_queries": len(slowlog),
            "slowlog_evicted": evicted,
            "slowlog": slowlog,
        }
        if self.slo_ms:
            out["slo_ms"] = self.slo_ms
        if device_samples:
            out["device_dispatch_ms"] = self._hist_device.summary()
        return out


def _interval_overlap_ns(lo: int, hi: int, merged: list) -> int:
    """Overlap of [lo, hi) with a sorted list of merged intervals."""
    total = 0
    for s_ns, e_ns in merged:
        if e_ns <= lo:
            continue
        if s_ns >= hi:
            break
        total += min(hi, e_ns) - max(lo, s_ns)
    return total


def segment_help(seg: str) -> str:
    """Human description of one segment (report rendering)."""
    return _SEGMENT_HELP.get(seg, "")
