"""Cross-process clock-domain correction for the fleet freshness ledger.

The freshness hops (obs/fleet.py) subtract wall-clock stamps taken in
DIFFERENT processes — writer stamps (fold/ship) minus replica stamps
(load/reply).  On one host those clocks are the same ``CLOCK_REALTIME``
and the deltas are honest; across hosts they can be skewed by arbitrary
amounts, and a skewed ``tail_lag`` would silently mis-attribute
staleness to the wrong hop.

This module estimates the wall-clock offset between a replica and its
writer with the classic NTP midpoint method over the pub/sub ``ping``
verb (dimensions/pubsub.py):

- the client stamps ``t0`` (local wall), pings, the server answers with
  its own wall stamp ``ts``, the client stamps ``t1``;
- assuming symmetric network delay, the server's clock read maps to the
  local midpoint: ``offset = ts - (t0 + t1) / 2``;
- the asymmetry error is bounded by half the round trip, so the sample
  with the SMALLEST rtt carries the tightest bound — that one wins;
- the bound is RECORDED (``uncertainty_ms``), and a noisy estimate is
  never silently applied: when the winning sample's uncertainty or the
  spread of per-sample offsets exceeds ``jitter_threshold_ms`` the
  estimate comes back ``applied=False`` and callers must keep raw
  stamps (an honest uncorrected delta beats a confidently wrong one).

``offset_from_samples`` is the pure estimator (unit-testable with
synthetic delays); ``sync_pubsub`` drives it over a live endpoint.
"""

from __future__ import annotations

import time

#: past this (winning-sample uncertainty OR cross-sample offset spread,
#: ms) the estimate is reported but NOT applied — the correction would
#: be noisier than the skew it fixes on any same-site deployment
DEFAULT_JITTER_THRESHOLD_MS = 50.0

#: quantization floor: server stamps are integer ms, so even a zero-rtt
#: exchange carries this much rounding uncertainty
QUANTIZATION_MS = 0.5


def offset_from_samples(samples, *,
                        jitter_threshold_ms: float =
                        DEFAULT_JITTER_THRESHOLD_MS) -> dict:
    """Fold ``(t0_local_ms, t_server_ms, t1_local_ms)`` ping samples
    into one offset estimate.

    Returns ``{offset_ms, uncertainty_ms, rtt_min_ms, jitter_ms,
    samples, applied}`` where ``offset_ms`` is ``server - local`` (add
    it to a LOCAL stamp to express it in the server's clock, subtract
    it from a server stamp to map into local time... the ledger does
    ``server_stamp + (-offset)``; see :func:`to_local_ms`).  With a
    symmetric network delay the midpoint method is EXACT; asymmetric
    delay errs by at most ``rtt/2``, which is what ``uncertainty_ms``
    reports.  ``applied=False`` when either the uncertainty or the
    offset spread across samples exceeds the jitter threshold — the
    refusal contract: corrections are never silently applied past it.
    """
    rows = []
    for t0, ts, t1 in samples:
        rtt = float(t1) - float(t0)
        if rtt < 0:
            continue   # a backwards local clock read: unusable sample
        mid = (float(t0) + float(t1)) / 2.0
        rows.append((rtt, float(ts) - mid))
    if not rows:
        return {"offset_ms": 0.0, "uncertainty_ms": None,
                "rtt_min_ms": None, "jitter_ms": None, "samples": 0,
                "applied": False}
    rows.sort()
    rtt_min, offset = rows[0]
    uncertainty = rtt_min / 2.0 + QUANTIZATION_MS
    # jitter over the BEST half of the samples (lowest rtt): one
    # scheduler stall mid-burst would otherwise blow the spread and
    # refuse an estimate the quiet samples agree on perfectly — the
    # gate exists to catch disagreeing GOOD samples, not slow ones
    best = rows[:max((len(rows) + 1) // 2, 1)]
    offsets = [o for _, o in best]
    jitter = max(offsets) - min(offsets)
    applied = (uncertainty <= jitter_threshold_ms
               and jitter <= jitter_threshold_ms)
    return {
        "offset_ms": round(offset, 3),
        "uncertainty_ms": round(uncertainty, 3),
        "rtt_min_ms": round(rtt_min, 3),
        "jitter_ms": round(jitter, 3),
        "samples": len(rows),
        "applied": applied,
    }


def to_local_ms(remote_stamp_ms: float, estimate: "dict | None") -> float:
    """Map a remote (writer-clock) wall stamp into the local clock,
    applying the offset only when the estimate passed the jitter gate.
    ``offset = remote - local``, so ``local = remote - offset``."""
    if estimate and estimate.get("applied"):
        return float(remote_stamp_ms) - float(estimate["offset_ms"])
    return float(remote_stamp_ms)


def sync_pubsub(host: str, port: int, *, n: int = 8,
                timeout_s: float = 5.0,
                jitter_threshold_ms: float =
                DEFAULT_JITTER_THRESHOLD_MS) -> dict:
    """Estimate the offset to the pub/sub server at ``host:port`` via
    ``n`` round trips of its ``ping`` query verb.  Raises ``OSError``
    (connect/timeout) like any socket client — callers treat a failed
    sync as ``applied=False`` evidence, not a fatal error."""
    from streambench_tpu.dimensions.pubsub import PubSubClient

    c = PubSubClient(host, port, timeout_s=timeout_s)
    samples = []
    try:
        for i in range(max(int(n), 1)):
            t0 = time.time() * 1000.0
            c.request({"type": "ping", "id": i})
            data = c.recv().get("data") or {}
            t1 = time.time() * 1000.0
            ts = data.get("t")
            if isinstance(ts, (int, float)):
                samples.append((t0, float(ts), t1))
    finally:
        c.close()
    out = offset_from_samples(samples,
                              jitter_threshold_ms=jitter_threshold_ms)
    out["endpoint"] = f"{host}:{port}"
    return out
