"""Live telemetry: metrics registry, sampler thread, scrape endpoint.

The observability substrate for the engine (ISSUE: the run's flight
recorder).  Four pieces, all stdlib, all default-off:

- ``registry``  — counters/gauges + O(1) log-bucketed streaming
  histograms (live p50/p95/p99 while the run is going)
- ``sampler``   — background thread journaling one snapshot record per
  ``jax.metrics.interval.ms`` to ``metrics.jsonl`` in the workdir
- ``httpd``     — localhost Prometheus text-exposition endpoint
  (``jax.metrics.port``)
- ``report``    — ``python -m streambench_tpu.obs`` renders a run
  report from ``metrics.jsonl`` and diffs two runs
- ``lifecycle`` — per-window latency attribution: the YSB latency
  decomposed into ingest/encode/fold/flush/sink segments
  (``jax.obs.lifecycle``; ``python -m streambench_tpu.obs attribution``)
- ``flightrec`` — bounded crash flight recorder dumping
  ``flight_<reason>.jsonl`` on crash/give_up/SIGTERM
  (``jax.obs.flightrec.enabled``); dumps embed the last closed spans
  when span tracing is on
- ``spans``     — bounded thread-aware span tracer exporting Chrome
  trace-event JSON (``jax.obs.spans``; ``trace_<run>.json`` loads in
  perfetto; ``python -m streambench_tpu.obs trace`` validates)
- ``occupancy`` — MEASURED device occupancy: sampled
  ``block_until_ready``-timed dispatches -> ``device_busy_ratio`` +
  per-dispatch device-time histogram + the ``streambench_compiles_*``
  recompile detector (``jax.obs.occupancy``)
- ``slo``       — config-driven objectives (``jax.slo.p99.ms``,
  ``jax.slo.rate.evps``) with multi-window burn-rate breach gates and
  a pass/fail verdict in the RunStats close line
- ``regress``   — tolerance-driven A/B comparator over bench artifacts
  or metrics journals (``python -m streambench_tpu.obs regress``, the
  CI regression gate)
- ``xfer``      — host->device transfer ledger (exact payload bytes per
  dispatch by wire format + sampled timed transfers;
  ``jax.obs.xfer``) and the per-shard routed-row skew tracker for the
  sharded engines (``jax.obs.shard``)
- ``devmem``    — device-memory ledger: compiled-kernel
  ``memory_analysis`` footprints + a sampled ``jax.live_arrays``
  census (``jax.obs.devmem``)
- ``capture``   — bounded TRIGGERED profiler capture (SLO breach /
  SIGUSR2 / config one-shot, with cooldown + cap;
  ``jax.obs.capture.*``); also owns the one process-global profiler
  start/stop path ``trace.device_trace`` delegates to
- ``fleet``     — fleet observability (obs layer 6, ``jax.obs.fleet``):
  metrics federation (every role's ``metrics.jsonl`` merged into one
  attributed ``fleet.jsonl``; ``python -m streambench_tpu.obs fleet``),
  cross-process trace stitching (``obs trace --merge``), and the
  end-to-end reply-freshness ledger
  (``streambench_fleet_freshness_ms{hop=}``)
- ``clock``     — cross-process clock-offset estimation (midpoint
  method over the pub/sub ``ping`` verb, bounded uncertainty, never
  silently applied past a jitter threshold)
- ``queryattr`` — per-query latency attribution for the reach serving
  tier (``jax.obs.query``): submit->reply decomposed into
  queue/batch/dispatch/reply segments that sum to it, a bounded
  slow-query log, and the ingest-contention ratio
  (``streambench_reach_contention_ratio``) computed from the span
  ring's ingest dispatch spans
- ``tenancy``   — multi-tenant observability (obs layer 9,
  ``jax.tenants``): tenant-scoped ``TenantRegistry`` views over one
  shared registry (every instrument carries ``tenant=``) and the
  ``DeviceTimeLedger`` blame matrix — victim wait ∩ aggressor
  device-busy, with a tested partition invariant
- ``admission`` — measurement-actuated admission control
  (``jax.admission.enabled``): defer/shed an aggressor tenant's
  ingest when the blame matrix says its dispatches are burning a
  victim's SLO budget (priming/hysteresis/cooldowns, journaled
  evidence-carrying decisions, default-off)

Enable on the engine CLI via config keys (``jax.metrics.interval.ms``
> 0 and/or ``jax.metrics.port`` >= 0); embed via::

    registry = MetricsRegistry()
    engine.attach_obs(registry)
    sampler = MetricsSampler(path, interval_ms=1000, registry=registry)
    sampler.add_collector(engine_collector(engine, reader=reader,
                                           runner=runner,
                                           registry=registry))
    sampler.start()
    server = MetricsServer(registry, port=0, refresh=sampler.collect_now)
"""

from streambench_tpu.obs.admission import AdmissionController  # noqa: F401
from streambench_tpu.obs.autoscale import AutoscaleController  # noqa: F401
from streambench_tpu.obs.capture import (  # noqa: F401
    CaptureManager,
    profiler_window,
)
from streambench_tpu.obs.diagnose import (  # noqa: F401
    diagnose,
    evidence_window,
)
from streambench_tpu.obs.clock import (  # noqa: F401
    offset_from_samples,
    sync_pubsub,
)
from streambench_tpu.obs.devmem import DeviceMemoryLedger  # noqa: F401
from streambench_tpu.obs.fleet import (  # noqa: F401
    FleetCollector,
    merge_traces,
    summarize_fleet,
)
from streambench_tpu.obs.flightrec import FlightRecorder  # noqa: F401
from streambench_tpu.obs.httpd import MetricsServer  # noqa: F401
from streambench_tpu.obs.lifecycle import WindowLifecycle  # noqa: F401
from streambench_tpu.obs.occupancy import (  # noqa: F401
    CompileWatcher,
    OccupancySampler,
)
from streambench_tpu.obs.queryattr import QueryLifecycle  # noqa: F401
from streambench_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    MetricsRegistry,
    StreamingHistogram,
)
from streambench_tpu.obs.sampler import (  # noqa: F401
    MetricsSampler,
    engine_collector,
    kafka_collector,
    rss_bytes,
    rss_sample,
)
from streambench_tpu.obs.slo import SloTracker  # noqa: F401
from streambench_tpu.obs.spans import SpanTracer  # noqa: F401
from streambench_tpu.obs.tenancy import (  # noqa: F401
    DeviceTimeLedger,
    TenantRegistry,
)
from streambench_tpu.obs.xfer import (  # noqa: F401
    ShardSkew,
    TransferLedger,
)
