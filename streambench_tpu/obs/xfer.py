"""Host->device transfer ledger + shard-skew gauges (obs layer 4).

Every transfer claim in this repo used to live in a code comment: the
packed wire word "halves host->device bytes" (``ops/windowcount.py``),
the device-decode raw format is "~250 B/ev vs 8 B/ev packed"
(BENCH_r06).  Against a tunneled accelerator the host->device link is
the throughput ceiling, so ROADMAP items 1-2 gate the next chip session
on *measuring the data path*, not just compute.  This module is that
measurement:

- :class:`TransferLedger` — hooked at the same ``_fold`` /
  ``_fold_group`` / ``_fold_prepared`` dispatch points as the PR 8
  ``OccupancySampler``: every dispatch's host->device payload is
  accounted EXACTLY (bytes computed from the dispatched buffers' dtypes
  and shapes, sharded data-axis padding included), keyed by wire format
  — ``packed`` (the int32 wire word + time, 8 B/ev for the exact
  engine), ``unpacked`` (the separate columns; ``valid`` ships as
  1-byte bools, so 13 B/ev), ``devdecode`` (the raw-bytes format: the
  padded journal buffer + (start, len) row vectors, ~250 B/ev).  One
  dispatch in ``sample_every`` additionally TIMES an equivalent-size
  ``jax.device_put`` + ``block_until_ready`` round trip, so the split
  between transfer and compute is measured, not inferred.

Two byte accountings per format, both honest and clearly labeled:

- ``wire_bytes`` / ``bytes_per_event`` — the exact bytes of the
  dispatched host buffers (what the PCIe/tunnel link actually moves).
- ``col_bytes`` / ``col_bytes_per_event`` — the same columns normalized
  to the kernel's int32 width (4 B per column element).  This is the
  accounting ``parallel.collectives`` uses for ICI payloads, so the
  host-wire table and the HLO collective table are directly comparable:
  ``packed_unpacked_ratio`` on this basis is exactly the 0.5 the
  MULTICHIP_r06 ``packed_col_ratio`` records (the raw wire ratio is
  8/13 ~= 0.62 only because ``valid`` travels as bools).

- :class:`ShardSkew` — per-shard routed-row and drop accounting for the
  sharded engines: the ``shard_stats`` kernel variants
  (``parallel/sharded.py`` / ``parallel/sketches.py``) ride per-shard
  routed/wanted vectors out of the existing scan ys, and this tracker
  accumulates them device-side (no sync on the hot path) into
  ``streambench_shard_rows{shard=}`` gauges plus an imbalance ratio —
  the straggler evidence a real-mesh run needs next to the collective
  table.

Default-off like the rest of obs/: the engine carries ``None``
attributes and one None check per dispatch until
``attach_obs(..., xfer=TransferLedger(...))``.
"""

from __future__ import annotations

import threading
import time


class TransferLedger:
    """Exact per-dispatch host->device payload accounting by wire format.

    ``note_dispatch`` is called from the host loop only (single-writer
    ints, the same rule as the occupancy/ingest counters); ``summary``
    may be read from the sampler thread at any cadence (the per-format
    totals are plain ints, consistent under the GIL).

    ``sample_every``: one dispatch in N pays a timed ``device_put`` +
    ``block_until_ready`` of the SAME host buffers — a redundant
    transfer of identical size, so the recorded ``streambench_xfer_ms``
    isolates the transfer half of a dispatch without instrumenting the
    async hot path.  0 disables timing entirely (byte accounting only).
    """

    #: wire formats the engines can dispatch — pre-declared at
    #: construction so a scrape before the first dispatch already
    #: returns every per-format family with zero samples (the lazy-
    #: instrument scrape gap; an unlisted format still get-or-creates
    #: its instruments lazily at its first dispatch)
    KNOWN_FORMATS = ("packed", "unpacked", "devdecode")

    def __init__(self, registry=None, sample_every: int = 32):
        self.sample_every = max(int(sample_every), 0)
        self.dispatches = 0
        self.sampled = 0
        self.sampled_ns = 0
        self.sampled_bytes = 0
        # fmt -> [dispatches, events, wire_bytes, col_bytes]
        self._formats: dict[str, list] = {}
        self._reg = registry
        self._hist = None
        self._c_sampled = None
        self._per_fmt: dict[str, tuple] = {}
        if registry is not None:
            self._hist = registry.histogram(
                "streambench_xfer_ms",
                "sampled host->device transfer time per dispatch "
                "payload (device_put + block_until_ready), ms",
                lo=0.001, hi=1e5)
            self._c_sampled = registry.counter(
                "streambench_xfer_sampled_total",
                "dispatch payloads whose transfer was timed (1/N)")
            for fmt in self.KNOWN_FORMATS:
                self._instruments(fmt)

    # ------------------------------------------------------------------
    def _instruments(self, fmt: str) -> tuple:
        inst = self._per_fmt.get(fmt)
        if inst is None and self._reg is not None:
            inst = (
                self._reg.counter(
                    "streambench_xfer_bytes_total",
                    "exact host->device payload bytes dispatched",
                    labels={"format": fmt}),
                self._reg.counter(
                    "streambench_xfer_col_bytes_total",
                    "payload bytes at kernel (int32) column width — "
                    "the parallel.collectives accounting basis",
                    labels={"format": fmt}),
                self._reg.counter(
                    "streambench_xfer_events_total",
                    "parsed events carried by the dispatched payloads",
                    labels={"format": fmt}),
                self._reg.counter(
                    "streambench_xfer_dispatches_total",
                    "device dispatches seen by the transfer ledger",
                    labels={"format": fmt}),
                self._reg.gauge(
                    "streambench_xfer_bytes_per_event",
                    "derived wire bytes per parsed event",
                    labels={"format": fmt}),
            )
            self._per_fmt[fmt] = inst
        return inst

    def note_dispatch(self, fmt: str, events: int, wire_bytes: int,
                      col_bytes: "int | None" = None,
                      sample_arrays=None) -> None:
        """One device dispatch shipped ``wire_bytes`` of host buffers
        carrying ``events`` parsed events in wire format ``fmt``.
        ``col_bytes`` defaults to ``wire_bytes`` (formats with no bool
        columns).  ``sample_arrays`` (host numpy buffers of the same
        sizes as the payload) enables the 1-in-N timed transfer."""
        if col_bytes is None:
            col_bytes = wire_bytes
        self.dispatches += 1
        tot = self._formats.get(fmt)
        if tot is None:
            tot = self._formats[fmt] = [0, 0, 0, 0]
        tot[0] += 1
        tot[1] += int(events)
        tot[2] += int(wire_bytes)
        tot[3] += int(col_bytes)
        inst = self._instruments(fmt)
        if inst is not None:
            c_wire, c_col, c_ev, c_disp, g_bpe = inst
            c_wire.inc(int(wire_bytes))
            c_col.inc(int(col_bytes))
            c_ev.inc(int(events))
            c_disp.inc()
            if tot[1]:
                g_bpe.set(round(tot[2] / tot[1], 3))
        if (not self.sample_every or sample_arrays is None
                or self.dispatches % self.sample_every):
            return
        import jax

        arrays = list(sample_arrays)
        t0 = time.perf_counter_ns()
        put = [jax.device_put(a) for a in arrays]
        jax.block_until_ready(put)
        dt = time.perf_counter_ns() - t0
        del put
        self.sampled += 1
        self.sampled_ns += dt
        self.sampled_bytes += sum(int(a.nbytes) for a in arrays)
        if self._hist is not None:
            self._hist.observe(dt / 1e6)
            self._c_sampled.set_total(self.sampled)

    # ------------------------------------------------------------------
    def bytes_per_event(self, fmt: str) -> "float | None":
        tot = self._formats.get(fmt)
        if not tot or not tot[1]:
            return None
        return tot[2] / tot[1]

    def summary(self) -> dict:
        """The ``"xfer"`` block a metrics.jsonl snapshot / bench
        artifact carries."""
        formats = {}
        for fmt, (disp, ev, wire, col) in sorted(self._formats.items()):
            formats[fmt] = {
                "dispatches": disp,
                "events": ev,
                "wire_bytes": wire,
                "col_bytes": col,
                "bytes_per_event": round(wire / ev, 3) if ev else None,
                "col_bytes_per_event": (round(col / ev, 3)
                                        if ev else None),
            }
        out: dict = {"dispatches": self.dispatches,
                     "sample_every": self.sample_every,
                     "formats": formats}
        pk, up = formats.get("packed"), formats.get("unpacked")
        if pk and up and up["col_bytes_per_event"]:
            # column-width-normalized, the MULTICHIP packed_col_ratio
            # basis (module docstring): exactly 0.5 for the exact engine
            out["packed_unpacked_ratio"] = round(
                pk["col_bytes_per_event"] / up["col_bytes_per_event"], 4)
            out["ratio_basis"] = "col_bytes"
        if self.sampled:
            ms = self.sampled_ns / 1e6
            out["sampled"] = self.sampled
            out["sampled_ms_total"] = round(ms, 3)
            out["sampled_bytes"] = self.sampled_bytes
            if ms > 0:
                # MB/s over the timed transfers — the measured link rate
                out["xfer_mb_s"] = round(
                    self.sampled_bytes / 1e6 / (ms / 1e3), 2)
        if self._hist is not None and self._hist.count:
            out["xfer_ms"] = self._hist.summary()
        return out


class ShardSkew:
    """Per-shard routed-row / drop accumulation for the sharded engines.

    ``note(wanted_vec, routed_vec)`` receives two replicated ``[S]``
    int32 DEVICE vectors from a ``shard_stats`` kernel dispatch — rows
    whose campaign maps to each shard (pre-lateness, the same basis as
    the global ``dropped`` accounting) and rows each shard actually
    counted.  Accumulation is a device-side add (async, no sync on the
    hot path); ``summary()`` materializes the totals — call it from the
    sampler thread or at close, never the host loop.

    Thread-safety: ``note`` runs on the host loop only; ``summary``
    snapshots the accumulator references under a lock so a concurrent
    ``note`` never interleaves mid-read.
    """

    def __init__(self, registry=None, n_shards: int = 1):
        self.n_shards = max(int(n_shards), 1)
        self.dispatches = 0
        self._wanted = None      # device [S] running totals
        self._routed = None
        self._lock = threading.Lock()
        self._reg = registry
        self._g_imb = None
        self._g_rows: list = []
        self._g_drop: list = []
        if registry is not None:
            self._g_imb = registry.gauge(
                "streambench_shard_imbalance_ratio",
                "max/mean routed rows across campaign shards "
                "(1.0 = perfectly balanced)")
            for s in range(self.n_shards):
                self._g_rows.append(registry.gauge(
                    "streambench_shard_rows",
                    "rows routed to (counted by) this campaign shard",
                    labels={"shard": str(s)}))
                self._g_drop.append(registry.gauge(
                    "streambench_shard_dropped",
                    "rows wanted by this shard's campaigns but not "
                    "counted (late / lost slot)",
                    labels={"shard": str(s)}))

    def note(self, wanted_vec, routed_vec) -> None:
        """Accumulate one dispatch's per-shard vectors (device add)."""
        with self._lock:
            self.dispatches += 1
            if self._wanted is None:
                self._wanted = wanted_vec
                self._routed = routed_vec
            else:
                self._wanted = self._wanted + wanted_vec
                self._routed = self._routed + routed_vec

    def summary(self) -> "dict | None":
        """Materialize totals (device sync — sampler/close cadence
        only).  None until the first dispatch."""
        import numpy as np

        with self._lock:
            if self._routed is None:
                return None
            wanted_d, routed_d = self._wanted, self._routed
            dispatches = self.dispatches
        wanted = np.asarray(wanted_d).astype(np.int64)
        routed = np.asarray(routed_d).astype(np.int64)
        dropped = np.maximum(wanted - routed, 0)
        mean = routed.mean() if routed.size else 0.0
        imbalance = float(routed.max() / mean) if mean > 0 else 1.0
        for s, g in enumerate(self._g_rows):
            if s < routed.size:
                g.set(int(routed[s]))
        for s, g in enumerate(self._g_drop):
            if s < dropped.size:
                g.set(int(dropped[s]))
        if self._g_imb is not None:
            self._g_imb.set(round(imbalance, 4))
        return {
            "shards": int(routed.size),
            "dispatches": dispatches,
            "rows": routed.tolist(),
            "wanted": wanted.tolist(),
            "dropped": dropped.tolist(),
            "imbalance_ratio": round(imbalance, 4),
        }
