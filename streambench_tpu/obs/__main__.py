"""Telemetry reporting CLI.

    python -m streambench_tpu.obs report RUN/metrics.jsonl
    python -m streambench_tpu.obs diff  A/metrics.jsonl B/metrics.jsonl
    python -m streambench_tpu.obs attribution RUN/metrics.jsonl [B/metrics.jsonl]
    python -m streambench_tpu.obs trace RUN/trace_1234.json
    python -m streambench_tpu.obs regress BASELINE.json CANDIDATE.json

``report`` renders one run's time series as a summary (throughput,
live-latency percentiles, backlog/watermark/RSS maxima, fault counters,
stage totals, annotations); ``diff`` lines two runs up with absolute and
relative deltas; ``attribution`` renders the per-window latency
attribution (obs.lifecycle: ingest/encode/fold/flush/sink segment
percentiles and shares), diffing A/B when a second path is given;
``trace`` validates a Chrome trace-event file (obs.spans) and prints a
per-span-name summary; ``regress`` compares two bench artifacts or
metrics journals under per-metric tolerances and exits non-zero on a
regression (the CI gate — ``--advisory`` reports without gating).
``--json`` emits the summary dict(s) instead, for harness consumption.
Rotated journals (``metrics.jsonl.1``) are stitched in automatically.
"""

from __future__ import annotations

import argparse
import json
import sys

from streambench_tpu.obs.report import (
    load_records,
    render_attribution,
    render_attribution_diff,
    render_diff,
    render_report,
    render_serve,
    render_serve_diff,
    summarize,
    summarize_attribution,
    summarize_serve,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="streambench-obs")
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="summarize one metrics.jsonl")
    rep.add_argument("path")
    rep.add_argument("--json", action="store_true",
                     help="emit the summary dict instead of text")
    dif = sub.add_parser("diff", help="diff two metrics.jsonl runs (B vs A)")
    dif.add_argument("path_a")
    dif.add_argument("path_b")
    dif.add_argument("--json", action="store_true",
                     help="emit both summary dicts instead of text")
    att = sub.add_parser(
        "attribution",
        help="per-window latency attribution (segment table; give a "
             "second path to diff B vs A)")
    att.add_argument("path")
    att.add_argument("path_b", nargs="?", default=None)
    att.add_argument("--json", action="store_true",
                     help="emit the attribution dict(s) instead of text")
    srv = sub.add_parser(
        "serve",
        help="reach serving-layer attribution (query segment table, "
             "contention ratio, slow-query log; give a second path to "
             "diff B vs A)")
    srv.add_argument("path")
    srv.add_argument("path_b", nargs="?", default=None)
    srv.add_argument("--json", action="store_true",
                     help="emit the serving dict(s) instead of text")
    trc = sub.add_parser(
        "trace", help="validate + summarize a Chrome trace-event file "
                      "(obs.spans trace_<run>.json)")
    trc.add_argument("path")
    trc.add_argument("--json", action="store_true",
                     help="emit the summary dict instead of text")
    reg = sub.add_parser(
        "regress",
        help="compare candidate B against baseline A under per-metric "
             "tolerances; exit 1 on regression (CI gate)")
    reg.add_argument("path_a", help="baseline artifact or metrics.jsonl")
    reg.add_argument("path_b", help="candidate artifact or metrics.jsonl")
    reg.add_argument("--tol", action="append", default=[],
                     metavar="METRIC=FRAC",
                     help="override one metric's relative tolerance "
                          "(e.g. --tol catchup_events_per_s=0.3)")
    reg.add_argument("--advisory", action="store_true",
                     help="report regressions but always exit 0")
    reg.add_argument("--strict-missing", action="store_true",
                     help="count metrics missing from B as regressions")
    reg.add_argument("--json", action="store_true",
                     help="emit the comparison dict instead of text")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "regress":
            from streambench_tpu.obs.regress import run_cli

            return run_cli(args.path_a, args.path_b, tol_args=args.tol,
                           as_json=args.json, advisory=args.advisory,
                           strict_missing=args.strict_missing)
        if args.cmd == "trace":
            from streambench_tpu.obs.spans import (
                render_trace_summary,
                summarize_trace,
                validate_chrome_trace,
            )

            with open(args.path, "r", encoding="utf-8") as f:
                try:
                    doc = json.load(f)
                except json.JSONDecodeError as e:
                    print(f"error: {args.path}: not JSON: {e}",
                          file=sys.stderr)
                    return 2
            problems = validate_chrome_trace(doc)
            if problems:
                print(f"error: {args.path}: not a loadable Chrome "
                      "trace:", file=sys.stderr)
                for pr in problems:
                    print(f"  {pr}", file=sys.stderr)
                return 2
            s = summarize_trace(doc, path=args.path)
            print(json.dumps(s) if args.json
                  else render_trace_summary(s))
            return 0
        if args.cmd == "serve":
            a = summarize_serve(load_records(args.path),
                                path=args.path)
            if args.path_b:
                b = summarize_serve(load_records(args.path_b),
                                    path=args.path_b)
                print(json.dumps({"a": a, "b": b}) if args.json
                      else render_serve_diff(a, b))
            else:
                print(json.dumps(a) if args.json else render_serve(a))
            return 0
        if args.cmd == "report":
            s = summarize(load_records(args.path), path=args.path)
            print(json.dumps(s) if args.json else render_report(s))
        elif args.cmd == "attribution":
            a = summarize_attribution(load_records(args.path),
                                      path=args.path)
            if args.path_b:
                b = summarize_attribution(load_records(args.path_b),
                                          path=args.path_b)
                print(json.dumps({"a": a, "b": b}) if args.json
                      else render_attribution_diff(a, b))
            else:
                print(json.dumps(a) if args.json
                      else render_attribution(a))
        else:
            a = summarize(load_records(args.path_a), path=args.path_a)
            b = summarize(load_records(args.path_b), path=args.path_b)
            print(json.dumps({"a": a, "b": b}) if args.json
                  else render_diff(a, b))
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
