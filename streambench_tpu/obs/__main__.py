"""Telemetry reporting CLI.

    python -m streambench_tpu.obs report RUN/metrics.jsonl
    python -m streambench_tpu.obs diff  A/metrics.jsonl B/metrics.jsonl
    python -m streambench_tpu.obs attribution RUN/metrics.jsonl [B/metrics.jsonl]
    python -m streambench_tpu.obs trace RUN/trace_1234.json
    python -m streambench_tpu.obs trace writer=A/trace_1.json \
        replica=B/trace_2.json --merge --out merged_trace.json
    python -m streambench_tpu.obs fleet writer=A/metrics.jsonl \
        replica=B/metrics.jsonl [--out fleet.jsonl]
    python -m streambench_tpu.obs regress BASELINE.json CANDIDATE.json

``report`` renders one run's time series as a summary (throughput,
live-latency percentiles, backlog/watermark/RSS maxima, fault counters,
stage totals, annotations); ``diff`` lines two runs up with absolute and
relative deltas; ``attribution`` renders the per-window latency
attribution (obs.lifecycle: ingest/encode/fold/flush/sink segment
percentiles and shares), diffing A/B when a second path is given;
``trace`` validates a Chrome trace-event file (obs.spans) and prints a
per-span-name summary; ``regress`` compares two bench artifacts or
metrics journals under per-metric tolerances and exits non-zero on a
regression (the CI gate — ``--advisory`` reports without gating).
``--json`` emits the summary dict(s) instead, for harness consumption.
Rotated journals (``metrics.jsonl.1``) are stitched in automatically.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

try:
    import streambench_tpu  # noqa: F401
except ModuleNotFoundError:
    # executed by file path (python .../streambench_tpu/obs/__main__.py)
    # from a cwd where the package isn't importable: python put obs/ on
    # sys.path, not the repo root — self-locate it.  (`python -m` from
    # a foreign cwd without an install still needs PYTHONPATH or the
    # `streambench-obs` entry point — the interpreter fails before any
    # package code runs.)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))

from streambench_tpu.obs.report import (
    load_records,
    render_attribution,
    render_attribution_diff,
    render_diff,
    render_report,
    render_serve,
    render_serve_diff,
    summarize,
    summarize_attribution,
    summarize_serve,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="streambench-obs")
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="summarize one metrics.jsonl")
    rep.add_argument("path")
    rep.add_argument("--json", action="store_true",
                     help="emit the summary dict instead of text")
    dif = sub.add_parser("diff", help="diff two metrics.jsonl runs (B vs A)")
    dif.add_argument("path_a")
    dif.add_argument("path_b")
    dif.add_argument("--json", action="store_true",
                     help="emit both summary dicts instead of text")
    att = sub.add_parser(
        "attribution",
        help="per-window latency attribution (segment table; give a "
             "second path to diff B vs A)")
    att.add_argument("path")
    att.add_argument("path_b", nargs="?", default=None)
    att.add_argument("--json", action="store_true",
                     help="emit the attribution dict(s) instead of text")
    srv = sub.add_parser(
        "serve",
        help="reach serving-layer attribution (query segment table, "
             "contention ratio, slow-query log; give a second path to "
             "diff B vs A)")
    srv.add_argument("path")
    srv.add_argument("path_b", nargs="?", default=None)
    srv.add_argument("--json", action="store_true",
                     help="emit the serving dict(s) instead of text")
    trc = sub.add_parser(
        "trace", help="validate + summarize a Chrome trace-event file "
                      "(obs.spans trace_<run>.json); several paths + "
                      "--merge stitch one perfetto-loadable fleet "
                      "trace with a process_name lane per file")
    trc.add_argument("paths", nargs="+", metavar="path",
                     help="trace file(s); with --merge each may be "
                          "role=path to name its process lane")
    trc.add_argument("--merge", action="store_true",
                     help="stitch all inputs into one trace (clocks "
                          "aligned on each file's wall0_ms epoch)")
    trc.add_argument("--out", default=None,
                     help="where --merge writes the stitched trace "
                          "(default: merged_trace.json)")
    trc.add_argument("--json", action="store_true",
                     help="emit the summary dict instead of text")
    flt = sub.add_parser(
        "fleet", help="merge every role's metrics.jsonl into one "
                      "fleet.jsonl and render the per-role table "
                      "(ingest rate, qps, cache hits, staleness, "
                      "freshness hops, restarts)")
    flt.add_argument("paths", nargs="+", metavar="path",
                     help="role=metrics.jsonl specs, bare journal "
                          "paths (role inferred), or ONE fleet "
                          "directory to scan")
    flt.add_argument("--out", default=None,
                     help="write the merged attributed journal here "
                          "(default: no file, table only)")
    flt.add_argument("--json", action="store_true",
                     help="emit the summary dict instead of text")
    flt.add_argument("--watch", action="store_true",
                     help="re-render periodically (re-scanning a "
                          "directory spec, so replicas the autoscaler "
                          "spawns appear as they journal)")
    flt.add_argument("--interval-s", type=float, default=2.0,
                     help="--watch re-render period (default 2 s)")
    flt.add_argument("--iterations", type=int, default=0,
                     help="with --watch: stop after N renders "
                          "(0 = until interrupted; CI/tests bound it)")
    reg = sub.add_parser(
        "regress",
        help="compare candidate B against baseline A under per-metric "
             "tolerances; exit 1 on regression (CI gate)")
    reg.add_argument("path_a", help="baseline artifact or metrics.jsonl")
    reg.add_argument("path_b", help="candidate artifact or metrics.jsonl")
    reg.add_argument("--tol", action="append", default=[],
                     metavar="METRIC=FRAC",
                     help="override one metric's relative tolerance "
                          "(e.g. --tol catchup_events_per_s=0.3)")
    reg.add_argument("--advisory", action="store_true",
                     help="report regressions but always exit 0")
    reg.add_argument("--strict-missing", action="store_true",
                     help="count metrics missing from B as regressions")
    reg.add_argument("--json", action="store_true",
                     help="emit the comparison dict instead of text")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "regress":
            from streambench_tpu.obs.regress import run_cli

            return run_cli(args.path_a, args.path_b, tol_args=args.tol,
                           as_json=args.json, advisory=args.advisory,
                           strict_missing=args.strict_missing)
        if args.cmd == "trace":
            from streambench_tpu.obs.spans import (
                render_trace_summary,
                summarize_trace,
                validate_chrome_trace,
            )

            if args.merge or len(args.paths) > 1:
                from streambench_tpu.obs.fleet import (
                    merge_traces,
                    parse_role_spec,
                    trace_process_names,
                )

                if not args.merge:
                    print("error: several trace paths need --merge",
                          file=sys.stderr)
                    return 2
                inputs = [parse_role_spec(p) for p in args.paths]
                try:
                    doc = merge_traces(inputs)
                except (OSError, json.JSONDecodeError) as e:
                    print(f"error: {e}", file=sys.stderr)
                    return 2
                problems = validate_chrome_trace(doc)
                if problems:
                    print("error: merged trace failed validation:",
                          file=sys.stderr)
                    for pr in problems:
                        print(f"  {pr}", file=sys.stderr)
                    return 2
                out_path = args.out or "merged_trace.json"
                with open(out_path, "w", encoding="utf-8") as f:
                    json.dump(doc, f)
                s = summarize_trace(doc, path=out_path)
                s["processes"] = {str(pid): name for pid, name in
                                  sorted(trace_process_names(doc).items())}
                print(json.dumps(s) if args.json
                      else render_trace_summary(s)
                      + "\n  processes: "
                      + ", ".join(f"{pid}={name}" for pid, name in
                                  s["processes"].items()))
                return 0
            path = args.paths[0]
            with open(path, "r", encoding="utf-8") as f:
                try:
                    doc = json.load(f)
                except json.JSONDecodeError as e:
                    print(f"error: {path}: not JSON: {e}",
                          file=sys.stderr)
                    return 2
            problems = validate_chrome_trace(doc)
            if problems:
                print(f"error: {path}: not a loadable Chrome "
                      "trace:", file=sys.stderr)
                for pr in problems:
                    print(f"  {pr}", file=sys.stderr)
                return 2
            s = summarize_trace(doc, path=path)
            print(json.dumps(s) if args.json
                  else render_trace_summary(s))
            return 0
        if args.cmd == "fleet":
            from streambench_tpu.obs.fleet import (
                FleetCollector,
                discover_roles,
                parse_role_spec,
                render_fleet,
                summarize_fleet,
            )

            import time as _time

            is_dir = (len(args.paths) == 1
                      and os.path.isdir(args.paths[0]))
            n = 0
            while True:
                # a directory spec re-scans each iteration: replicas
                # spawned mid-watch appear as soon as they journal
                if is_dir:
                    roles = discover_roles(args.paths[0])
                    if not roles and not args.watch:
                        print(f"error: no metrics.jsonl under "
                              f"{args.paths[0]}", file=sys.stderr)
                        return 2
                else:
                    roles = [parse_role_spec(p) for p in args.paths]
                coll = FleetCollector(roles, out_path=args.out)
                records = coll.collect()
                s = summarize_fleet(records,
                                    path=args.out or args.paths[0])
                s["sources"] = coll.sources
                print(json.dumps(s) if args.json else render_fleet(s),
                      flush=True)
                n += 1
                if not args.watch or (args.iterations
                                      and n >= args.iterations):
                    return 0
                try:
                    _time.sleep(args.interval_s)
                except KeyboardInterrupt:
                    return 0
        if args.cmd == "serve":
            a = summarize_serve(load_records(args.path),
                                path=args.path)
            if args.path_b:
                b = summarize_serve(load_records(args.path_b),
                                    path=args.path_b)
                print(json.dumps({"a": a, "b": b}) if args.json
                      else render_serve_diff(a, b))
            else:
                print(json.dumps(a) if args.json else render_serve(a))
            return 0
        if args.cmd == "report":
            s = summarize(load_records(args.path), path=args.path)
            print(json.dumps(s) if args.json else render_report(s))
        elif args.cmd == "attribution":
            a = summarize_attribution(load_records(args.path),
                                      path=args.path)
            if args.path_b:
                b = summarize_attribution(load_records(args.path_b),
                                          path=args.path_b)
                print(json.dumps({"a": a, "b": b}) if args.json
                      else render_attribution_diff(a, b))
            else:
                print(json.dumps(a) if args.json
                      else render_attribution(a))
        else:
            a = summarize(load_records(args.path_a), path=args.path_a)
            b = summarize(load_records(args.path_b), path=args.path_b)
            print(json.dumps({"a": a, "b": b}) if args.json
                  else render_diff(a, b))
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
