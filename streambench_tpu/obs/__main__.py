"""Telemetry reporting CLI.

    python -m streambench_tpu.obs report RUN/metrics.jsonl
    python -m streambench_tpu.obs diff  A/metrics.jsonl B/metrics.jsonl
    python -m streambench_tpu.obs attribution RUN/metrics.jsonl [B/metrics.jsonl]

``report`` renders one run's time series as a summary (throughput,
live-latency percentiles, backlog/watermark/RSS maxima, fault counters,
stage totals, annotations); ``diff`` lines two runs up with absolute and
relative deltas; ``attribution`` renders the per-window latency
attribution (obs.lifecycle: ingest/encode/fold/flush/sink segment
percentiles and shares), diffing A/B when a second path is given.
``--json`` emits the summary dict(s) instead, for harness consumption.
"""

from __future__ import annotations

import argparse
import json
import sys

from streambench_tpu.obs.report import (
    load_records,
    render_attribution,
    render_attribution_diff,
    render_diff,
    render_report,
    summarize,
    summarize_attribution,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="streambench-obs")
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="summarize one metrics.jsonl")
    rep.add_argument("path")
    rep.add_argument("--json", action="store_true",
                     help="emit the summary dict instead of text")
    dif = sub.add_parser("diff", help="diff two metrics.jsonl runs (B vs A)")
    dif.add_argument("path_a")
    dif.add_argument("path_b")
    dif.add_argument("--json", action="store_true",
                     help="emit both summary dicts instead of text")
    att = sub.add_parser(
        "attribution",
        help="per-window latency attribution (segment table; give a "
             "second path to diff B vs A)")
    att.add_argument("path")
    att.add_argument("path_b", nargs="?", default=None)
    att.add_argument("--json", action="store_true",
                     help="emit the attribution dict(s) instead of text")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "report":
            s = summarize(load_records(args.path), path=args.path)
            print(json.dumps(s) if args.json else render_report(s))
        elif args.cmd == "attribution":
            a = summarize_attribution(load_records(args.path),
                                      path=args.path)
            if args.path_b:
                b = summarize_attribution(load_records(args.path_b),
                                          path=args.path_b)
                print(json.dumps({"a": a, "b": b}) if args.json
                      else render_attribution_diff(a, b))
            else:
                print(json.dumps(a) if args.json
                      else render_attribution(a))
        else:
            a = summarize(load_records(args.path_a), path=args.path_a)
            b = summarize(load_records(args.path_b), path=args.path_b)
            print(json.dumps({"a": a, "b": b}) if args.json
                  else render_diff(a, b))
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
