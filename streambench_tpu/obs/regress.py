"""Tolerance-driven regression comparison of two run artifacts.

Six committed ``BENCH_*.json`` artifacts exist with no machine-checked
comparison between them — every "did this PR regress the bench?" answer
has been a human eyeballing numbers.  ``obs regress A B`` makes the
comparison executable: A is the baseline, B the candidate; each metric
is judged against a per-metric relative tolerance with a declared
direction (throughput regresses DOWN, latency/RSS regress UP), and the
exit code is the CI gate (0 = within tolerance, 1 = regressed,
2 = unusable input).  ``--advisory`` reports but always exits 0 — the
right mode on hosts whose run-to-run variance exceeds any honest
tolerance (this repo's 1-core container shows 2-4x swings; see
ADVICE.md).

Inputs are auto-detected per file:

- a **bench artifact** (``bench_latency.json`` / committed ``BENCH_*``
  shape): one JSON object — catchup throughput, sweep, config rows,
  occupancy;
- a **metrics journal** (``metrics.jsonl``): line-JSON; summarized via
  ``obs.report`` (rotated ``.1`` stitched in), compared on its final
  throughput/latency/RSS numbers.

Both normalize into one flat metric dict, so a bench artifact can even
be compared against a telemetry journal where their metrics overlap.
Metrics present in A but missing in B are reported (``missing``) and
count as regressions only with ``--strict-missing``.
"""

from __future__ import annotations

import json
import os

#: metric -> (direction, default relative tolerance).  direction
#: "higher" = bigger is better (regression when B < A*(1-tol));
#: "lower" = smaller is better (regression when B > A*(1+tol)).
#: Tolerances are deliberately generous: the gate exists to catch
#: collapses (a 2x loss), not noise (see module docstring).
DEFAULT_TOLERANCES: dict = {
    "catchup_events_per_s": ("higher", 0.5),
    "max_sustained_rate": ("higher", 0.5),
    "events_per_s_mean": ("higher", 0.5),
    "events_per_s_max": ("higher", 0.5),
    "paced_p50_ms": ("lower", 1.0),
    "paced_p99_ms": ("lower", 1.0),
    "latency_p50_ms": ("lower", 1.0),
    "latency_p99_ms": ("lower", 1.0),
    "device_busy_ratio": ("higher", 0.8),
    "windows_written": ("higher", 0.5),
    "rss_bytes_max": ("lower", 1.0),
    # data-path obs (ISSUE 9): measured host->device bytes per event
    # are near-deterministic for a fixed config (padding is the only
    # nondeterminism), so tolerances are tighter than the timing rows;
    # direction-aware — MORE bytes per event is the regression.
    "xfer_packed_bytes_per_event": ("lower", 0.25),
    "xfer_unpacked_bytes_per_event": ("lower", 0.25),
    "xfer_devdecode_bytes_per_event": ("lower", 0.25),
    # col-basis packed/unpacked ratio: 0.5 by construction; drifting UP
    # means the packed word stopped halving the wire
    "packed_unpacked_ratio": ("lower", 0.15),
    "devmem_peak_footprint_bytes": ("lower", 1.0),
    # reach serving (ISSUE 10): query throughput regresses DOWN, query
    # latency UP; generous like the other timing rows (1-core variance)
    "reach_qps": ("higher", 0.5),
    "reach_p99_ms": ("lower", 1.0),
    # query-path attribution (ISSUE 11): per-segment p50s regress UP
    # (more time in any segment is worse), as does the fraction of
    # query queue-wait spent behind ingest dispatches.  Generous: the
    # timing rows share the 1-core host's 2-4x variance, and the
    # contention ratio depends on the concurrent-ingest pacing.
    "reach_segment_queue_ms": ("lower", 1.0),
    "reach_segment_batch_ms": ("lower", 1.0),
    "reach_segment_dispatch_ms": ("lower", 1.0),
    "reach_segment_reply_ms": ("lower", 1.0),
    "reach_contention_ratio": ("lower", 1.0),
    # reach scale-out (ISSUE 14): the cache's hit ratio on the repeated
    # -query mix regresses DOWN (near-deterministic for a fixed mix:
    # tight); replica staleness regresses UP (bounded by cadence + poll
    # when healthy, but wall-timing on the 1-core host: generous), as
    # does the off-writer contention ratio the replica rung re-measures
    "reach_cache_hit_ratio": ("higher", 0.1),
    "reach_staleness_ms": ("lower", 1.0),
    "reach_offwriter_contention_ratio": ("lower", 1.0),
    # fleet freshness ledger (ISSUE 15): the end-to-end age of the
    # evidence behind replica replies regresses UP, as does each hop's
    # p99 — generous like every wall-timing row on the 1-core host
    # (cadence waits dominate and the ship interval is a config knob)
    "fleet_freshness_ms": ("lower", 1.0),
    "fleet_fold_lag_p99_ms": ("lower", 1.0),
    "fleet_ship_wait_p99_ms": ("lower", 1.0),
    "fleet_tail_lag_p99_ms": ("lower", 1.0),
    "fleet_serve_p99_ms": ("lower", 1.0),
    # fleet chaos router (ISSUE 16): the failover episode tail and the
    # honest-shed fraction of the seeded chaos rung both regress UP.
    # Advisory-by-tolerance like every wall-timing row here: the
    # failover episode is dominated by connect/timeout wall time on
    # the 1-core host, and the shed ratio by where the seeded faults
    # land relative to the storm's pacing.
    "router_failover_p99_ms": ("lower", 2.0),
    "router_shed_ratio": ("lower", 2.0),
    # SLO autopilot (ISSUE 17): the controller-on arm's breach fraction
    # under the seeded QPS ramp regresses UP (the autopilot's whole job
    # is keeping it low), the decision count regresses DOWN (a
    # controller that stopped deciding stopped controlling).  Both
    # advisory-by-tolerance: where the ramp's bursts land vs the
    # 1-core wall clock moves both run to run.
    "autoscale_breach_ratio_on": ("lower", 2.0),
    "autoscale_decisions": ("higher", 0.75),
    # sliding A/B (ISSUE 12): both arms' catchup throughput regresses
    # DOWN; generous like every timing row on the 1-core host
    "sliding_evps": ("higher", 0.5),
    "sliding_sliced_evps": ("higher", 0.5),
    # sketch memory (ISSUE 13): bytes of device state per distinct key
    # at the top cardinality rung regress UP (the whole point of the
    # SALSA plane is fewer of them), as does the p99 point-query count
    # error at matched device-memory budget.  bytes/key is
    # near-deterministic for a fixed geometry (tight tolerance); the
    # error row is statistical across the rung's hash draw (looser).
    "sketch_bytes_per_key": ("lower", 0.1),
    "sketch_p99_err": ("lower", 0.5),
    "sketch_salsa_evps": ("higher", 0.5),
    "sketch_fixed_evps": ("higher", 0.5),
    # delta shipping (ISSUE 18): writer-side ship cost per cadence
    # tick.  bytes/tick is near-deterministic for a fixed journal
    # (encoded size of the touched rows: tight-ish); ship wall ms is
    # 1-core wall timing (generous); the full/delta bytes ratio is the
    # headline O(C)->O(ΔC) claim and regresses DOWN.
    "ship_bytes_per_tick": ("lower", 0.5),
    "ship_ms_per_tick": ("lower", 2.0),
    "ship_bytes_ratio": ("higher", 0.5),
    # multi-tenant admission (ISSUE 19, baseline MTEN_r01): the
    # admission-ON arm's victim breach fraction under the seeded flash
    # crowd regresses UP (the controller's whole job), as does the
    # blame matrix's off-diagonal share in the OFF arm (more of the
    # victim's wait attributed to other tenants).  Advisory-by-
    # tolerance: both are wall-timing on the 1-core host — where
    # queries land relative to the aggressor's fold dispatches moves
    # run to run.
    "tenant_victim_breach_ratio": ("lower", 2.0),
    "tenant_blame_offdiag_ratio": ("lower", 2.0),
    # Kafka ingest edge (ISSUE 20): broker-surface delivery accounting
    # from the adapter's shared ledger.  Redeliveries/retries regress
    # UP (more faults surviving to the reader means the broker edge got
    # flakier for the same plan); consumer lag regresses UP (a consumer
    # that stopped draining).  All advisory-by-tolerance: fault
    # placement is plan-seeded but the op interleaving under wall-clock
    # pacing moves counts run to run.
    "kafka_redeliveries": ("lower", 2.0),
    "kafka_produce_retries": ("lower", 2.0),
    "kafka_consumer_lag": ("lower", 2.0),
}


def _first(d: dict, *keys, default=None):
    for k in keys:
        v = d.get(k)
        if v is not None:
            return v
    return default


def _num(v):
    return float(v) if isinstance(v, (int, float)) else None


def normalize_bench(doc: dict, path: str = "") -> dict:
    """Flatten a bench artifact into the comparable metric dict."""
    out: dict = {"kind": "bench", "path": path}
    out["catchup_events_per_s"] = _num(
        _first(doc, "catchup_events_per_s", "value"))
    out["max_sustained_rate"] = _num(doc.get("max_sustained_rate"))
    out["device_busy_ratio"] = _num(
        (doc.get("occupancy") or {}).get("device_busy_ratio")
        if isinstance(doc.get("occupancy"), dict)
        else doc.get("device_busy_ratio"))
    # the exact-count row's paced run (first sustained sweep rung falls
    # back to the exact config row's paced block)
    paced = None
    for row in doc.get("configs") or []:
        if row.get("config") == "exact_count":
            paced = row.get("paced")
            break
    if paced is None:
        sustained = [x for x in (doc.get("rates") or [])
                     if x.get("sustained")]
        paced = sustained[-1] if sustained else None
    if isinstance(paced, dict):
        out["paced_p50_ms"] = _num(paced.get("p50_ms"))
        out["paced_p99_ms"] = _num(paced.get("p99_ms"))
        slo = paced.get("slo")
        if isinstance(slo, dict):
            out["slo_pass"] = bool(slo.get("pass"))
    # data-path obs blocks (ISSUE 9): per-format measured bytes/event
    # + the packed/unpacked ratio + the devmem peak footprint
    xfer = doc.get("xfer")
    if isinstance(xfer, dict):
        for fmt, d in (xfer.get("formats") or {}).items():
            if isinstance(d, dict):
                out[f"xfer_{fmt}_bytes_per_event"] = _num(
                    d.get("bytes_per_event"))
        out["packed_unpacked_ratio"] = _num(
            xfer.get("packed_unpacked_ratio"))
    dm = doc.get("devmem")
    if isinstance(dm, dict):
        out["devmem_peak_footprint_bytes"] = _num(
            dm.get("peak_footprint_bytes"))
    # sliding A/B block (ISSUE 12): legacy vs sliced fold ev/s
    sab = doc.get("sliding_ab")
    if isinstance(sab, dict):
        out["sliding_evps"] = _num(sab.get("sliding_evps"))
        out["sliding_sliced_evps"] = _num(sab.get("sliding_sliced_evps"))
    # sketch-memory block (bench_sketch.py artifact, ISSUE 13): the
    # headline rung's bytes/key + p99 error + per-arm fold throughput
    sketch = doc.get("sketch")
    if isinstance(sketch, dict):
        out["sketch_bytes_per_key"] = _num(sketch.get("bytes_per_key"))
        out["sketch_p99_err"] = _num(sketch.get("p99_err"))
        out["sketch_salsa_evps"] = _num(sketch.get("salsa_evps"))
        out["sketch_fixed_evps"] = _num(sketch.get("fixed_evps"))
    # reach serving block (bench_reach.py artifact / engine stats line)
    reach = doc.get("reach")
    if isinstance(reach, dict):
        out["reach_qps"] = _num(reach.get("qps"))
        out["reach_p99_ms"] = _num(reach.get("p99_ms"))
        # ISSUE 11: per-segment p50s + contention ratio from the
        # attribution phase (segments values are p50 scalars, or full
        # summaries when an engine stats line is compared directly)
        for seg, v in (reach.get("segments") or {}).items():
            if isinstance(v, dict):
                v = v.get("p50")
            out[f"reach_segment_{seg}_ms"] = _num(v)
        out["reach_contention_ratio"] = _num(
            reach.get("contention_ratio"))
        # ISSUE 14 scale-out keys (bench_reach REACH_r03 schema, or an
        # engine/replica stats line's nested cache block)
        cache = reach.get("cache")
        out["reach_cache_hit_ratio"] = _num(
            cache.get("hit_ratio") if isinstance(cache, dict)
            else reach.get("cache_hit_ratio"))
        out["reach_staleness_ms"] = _num(reach.get("staleness_ms"))
        out["reach_offwriter_contention_ratio"] = _num(
            reach.get("offwriter_contention_ratio"))
        # ISSUE 15 fleet freshness keys (bench_reach replica rung with
        # --fleet replicas: total reply-age p99 + per-hop p99s)
        fresh = reach.get("freshness")
        if isinstance(fresh, dict):
            out["fleet_freshness_ms"] = _num(fresh.get("total_p99_ms"))
            for hop in ("fold_lag", "ship_wait", "tail_lag", "serve"):
                out[f"fleet_{hop}_p99_ms"] = _num(
                    fresh.get(f"{hop}_p99_ms"))
        # ISSUE 16 fleet chaos keys (bench_reach fleet_chaos rung, or a
        # router stats line / metrics record compared directly)
        rt = reach.get("router")
        if isinstance(rt, dict):
            out["router_failover_p99_ms"] = _num(
                rt.get("failover_p99_ms"))
            out["router_shed_ratio"] = _num(rt.get("shed_ratio"))
        # ISSUE 17 autopilot keys (bench_reach run_autoscale rung):
        # controller-on breach fraction + decision count
        asc = reach.get("autoscale")
        if isinstance(asc, dict):
            out["autoscale_breach_ratio_on"] = _num(
                asc.get("breach_ratio_on"))
            out["autoscale_decisions"] = _num(asc.get("decisions"))
        # ISSUE 18 delta-ship keys (bench_reach run_deltaship rung):
        # the delta arm's per-tick ship cost + the full/delta ratio
        ds = reach.get("deltaship")
        if isinstance(ds, dict):
            out["ship_bytes_per_tick"] = _num(
                ds.get("ship_bytes_per_tick"))
            out["ship_ms_per_tick"] = _num(ds.get("ship_ms_per_tick"))
            out["ship_bytes_ratio"] = _num(ds.get("bytes_ratio"))
    # ISSUE 19 multi-tenant keys (bench_multitenant MTEN_r01 schema):
    # the admission-ON arm's victim breach fraction + the OFF arm's
    # blame-matrix off-diagonal share
    mt = doc.get("multitenant")
    if isinstance(mt, dict):
        out["tenant_victim_breach_ratio"] = _num(
            mt.get("victim_breach_ratio_on"))
        out["tenant_blame_offdiag_ratio"] = _num(
            mt.get("blame_offdiag_ratio"))
    # ISSUE 20 kafka-edge keys (engine stats line / metrics summary
    # "kafka" block: the adapter ledger kafka_collector journals)
    kf = doc.get("kafka")
    if isinstance(kf, dict):
        out["kafka_redeliveries"] = _num(kf.get("redeliveries"))
        out["kafka_produce_retries"] = _num(kf.get("produce_retries"))
        out["kafka_consumer_lag"] = _num(kf.get("consumer_lag"))
    return {k: v for k, v in out.items() if v is not None}


def normalize_metrics(records: list, path: str = "") -> dict:
    """Flatten a metrics.jsonl record stream (obs.report summary)."""
    from streambench_tpu.obs.report import summarize

    s = summarize(records, path=path)
    lat = s.get("latency_ms") or {}
    out = {
        "kind": "metrics", "path": path,
        "events_per_s_mean": _num(s.get("events_per_s_mean")),
        "events_per_s_max": _num(s.get("events_per_s_max")),
        "windows_written": _num(s.get("windows_written")),
        "latency_p50_ms": _num(lat.get("p50")),
        "latency_p99_ms": _num(lat.get("p99")),
        "rss_bytes_max": _num(s.get("rss_bytes_max")),
    }
    xfer = s.get("xfer")
    if isinstance(xfer, dict):
        for fmt, d in (xfer.get("formats") or {}).items():
            if isinstance(d, dict):
                out[f"xfer_{fmt}_bytes_per_event"] = _num(
                    d.get("bytes_per_event"))
        out["packed_unpacked_ratio"] = _num(
            xfer.get("packed_unpacked_ratio"))
    dm = s.get("devmem")
    if isinstance(dm, dict):
        out["devmem_peak_footprint_bytes"] = _num(
            dm.get("peak_footprint_bytes"))
    rs = s.get("run_stats")
    if isinstance(rs, dict):
        if rs.get("events_per_s") is not None:
            out["catchup_events_per_s"] = _num(rs["events_per_s"])
        if rs.get("device_busy_ratio") is not None:
            out["device_busy_ratio"] = _num(rs["device_busy_ratio"])
        if isinstance(rs.get("slo"), dict):
            out["slo_pass"] = bool(rs["slo"].get("pass"))
    # ISSUE 20: the kafka_collector's broker-edge ledger block
    kf = s.get("kafka")
    if isinstance(kf, dict):
        out["kafka_redeliveries"] = _num(kf.get("redeliveries"))
        out["kafka_produce_retries"] = _num(kf.get("produce_retries"))
        out["kafka_consumer_lag"] = _num(kf.get("consumer_lag"))
    return {k: v for k, v in out.items() if v is not None}


def load_artifact(path: str) -> dict:
    """Load + normalize one input, auto-detecting its shape."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            return normalize_bench(doc, path=path)
    except json.JSONDecodeError:
        pass
    # line-JSON journal: reuse the report loader (stitches .1 rotation)
    from streambench_tpu.obs.report import load_records

    records = load_records(path)
    if not records:
        raise ValueError(f"{path}: neither a JSON artifact nor a "
                         "metrics.jsonl journal")
    return normalize_metrics(records, path=path)


# ----------------------------------------------------------------------
def compare(a: dict, b: dict,
            tolerances: "dict | None" = None,
            strict_missing: bool = False) -> dict:
    """Judge candidate ``b`` against baseline ``a``.

    Returns {"rows": [...], "regressions": n, "missing": n,
    "pass": bool}; each row is {metric, a, b, delta_pct, tol_pct,
    direction, verdict} with verdict in OK / IMPROVED / REGRESSED /
    MISSING.  ``slo_pass`` is boolean-compared: True -> False is a
    regression outright.
    """
    tols = dict(DEFAULT_TOLERANCES)
    for k, v in (tolerances or {}).items():
        direction = tols.get(k, ("higher", None))[0]
        tols[k] = (direction, float(v))
    rows: list[dict] = []
    regressions = missing = 0
    keys = [k for k in a if k not in ("kind", "path")]
    for k in keys:
        va = a[k]
        vb = b.get(k)
        if k == "slo_pass":
            if vb is None:
                continue
            bad = bool(va) and not bool(vb)
            rows.append({"metric": k, "a": va, "b": vb,
                         "verdict": "REGRESSED" if bad else "OK"})
            regressions += bad
            continue
        direction, tol = tols.get(k, ("higher", 0.5))
        if vb is None:
            missing += 1
            rows.append({"metric": k, "a": va, "b": None,
                         "tol_pct": round(tol * 100, 1),
                         "direction": direction, "verdict": "MISSING"})
            if strict_missing:
                regressions += 1
            continue
        delta = (vb - va) / va if va else 0.0
        if direction == "higher":
            verdict = ("REGRESSED" if delta < -tol
                       else "IMPROVED" if delta > tol else "OK")
        else:
            verdict = ("REGRESSED" if delta > tol
                       else "IMPROVED" if delta < -tol else "OK")
        regressions += verdict == "REGRESSED"
        rows.append({"metric": k, "a": va, "b": vb,
                     "delta_pct": round(delta * 100, 1),
                     "tol_pct": round(tol * 100, 1),
                     "direction": direction, "verdict": verdict})
    return {"a": a.get("path"), "b": b.get("path"), "rows": rows,
            "regressions": regressions, "missing": missing,
            "pass": regressions == 0}


def render(result: dict) -> str:
    lines = ["regression gate:",
             f"  A (baseline):  {result['a']}",
             f"  B (candidate): {result['b']}",
             f"  {'metric':<24} {'A':>14} {'B':>14} {'delta':>9} "
             f"{'tol':>7}  verdict"]

    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, bool):
            return str(v)
        return f"{v:,.1f}" if isinstance(v, float) else str(v)

    for r in result["rows"]:
        delta = (f"{r['delta_pct']:+.1f}%"
                 if r.get("delta_pct") is not None else "-")
        tol = (f"{r['tol_pct']:.0f}%"
               if r.get("tol_pct") is not None else "-")
        lines.append(f"  {r['metric']:<24} {fmt(r.get('a')):>14} "
                     f"{fmt(r.get('b')):>14} {delta:>9} {tol:>7}  "
                     f"{r['verdict']}")
    lines.append(f"  => {'PASS' if result['pass'] else 'FAIL'} "
                 f"({result['regressions']} regressed, "
                 f"{result['missing']} missing)")
    return "\n".join(lines)


def run_cli(path_a: str, path_b: str, tol_args: "list[str] | None" = None,
            as_json: bool = False, advisory: bool = False,
            strict_missing: bool = False, out=print) -> int:
    """The ``obs regress`` entry: load, compare, render, gate."""
    tols: dict = {}
    for spec in tol_args or []:
        if "=" not in spec:
            out(f"error: --tol expects metric=frac, got {spec!r}")
            return 2
        k, _, v = spec.partition("=")
        try:
            tols[k.strip()] = float(v)
        except ValueError:
            out(f"error: --tol {spec!r}: not a number")
            return 2
    try:
        a = load_artifact(path_a)
        b = load_artifact(path_b)
    except (OSError, ValueError) as e:
        out(f"error: {e}")
        return 2
    result = compare(a, b, tolerances=tols,
                     strict_missing=strict_missing)
    out(json.dumps(result) if as_json else render(result))
    if advisory and not result["pass"]:
        out("advisory mode: regressions reported, exit forced 0")
        return 0
    return 0 if result["pass"] else 1


def _default_baseline() -> "str | None":
    """The committed smoke baseline, when running from a checkout."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    p = os.path.join(root, "BASELINE_bench_smoke.json")
    return p if os.path.exists(p) else None
