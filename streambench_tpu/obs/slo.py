"""Config-driven latency/throughput objectives with burn-rate gates.

ROADMAP item 4's live serving workload needs "load-shedding and latency
SLOs measured by the obs stack"; until now a run could only be judged
after the fact, by a human reading percentiles.  This module makes the
objective explicit and machine-checked while the run is going:

- ``jax.slo.p99.ms``   — window-latency objective: a written window is
  *bad* when its end-to-end latency exceeds this.  Evaluated over the
  lifecycle e2e histogram when attribution is on (the tracked-window
  distribution) or the writeback-latency histogram otherwise, using
  the histogram's bucket-resolution ``count_le`` — O(buckets) per
  tick, no per-window state.
- ``jax.slo.rate.evps`` — ingest objective: a sample interval is *bad*
  when its event rate falls below this while the run is supposed to be
  under load.

Judgment is the SRE *multi-window burn rate*, not a point threshold:
an error budget (``jax.slo.budget``, default 1% of windows may be
bad) burns at ``rate = bad_fraction / budget``; a **breach** is
declared only when the budget is burning at >= ``BREACH_BURN`` over
BOTH the fast and the slow window (``jax.slo.window.{fast,slow}.s``) —
fast-only spikes get flagged as warnings in the gauges but don't flip
the verdict, and a slow-only residue of an early incident doesn't
re-page.  This is the standard two-window construction (fast window
catches onset, slow window confirms it's real) scaled down to
benchmark-run durations.

Every breach transition is journaled to ``metrics.jsonl`` as an event
record and ticked into the flight recorder; ``streambench_slo_*``
gauges expose the live burn rates; ``verdict()`` is the pass/fail
block the RunStats close line and the bench artifact carry.
"""

from __future__ import annotations

import time

#: Burn-rate threshold for a breach: the budget is being consumed at
#: at least this multiple of the sustainable rate on both windows.
#: 1.0 = "exactly on budget"; requiring > 1 on two windows keeps a
#: single straggler window from failing a whole run.
BREACH_BURN = 1.0


class SloTracker:
    """Burn-rate tracking over the live histograms.

    ``collect(rec, dt_s)`` has the MetricsSampler collector signature —
    add it AFTER ``engine_collector`` so ``rec["events"]`` is already
    populated (the rate objective reads it; absent, rate burn stays 0).
    Each tick appends one (t, windows_total, windows_bad, events,
    interval_bad) sample to a bounded ring and recomputes fast/slow
    burn rates from the ring's deltas.
    """

    def __init__(self, registry, p99_ms: int = 0, rate_evps: int = 0,
                 reach_p99_ms: int = 0,
                 budget: float = 0.01, fast_s: float = 30.0,
                 slow_s: float = 180.0, use_lifecycle: bool = False,
                 annotate=None, flightrec=None, capture=None,
                 queryattr=None, tenant: "str | None" = None,
                 clock=time.monotonic):
        # multi-tenant (ISSUE 19): a tracker scoped to one tenant is
        # built over that tenant's TenantRegistry view — its gauges and
        # get-or-create histograms pick up the ``tenant=`` label from
        # the view, so N trackers over one shared registry never share
        # an instrument.  ``tenant`` here only steers the JOURNAL shape
        # (the per-tenant block nests under
        # ``rec["slo_tenants"][name]`` instead of claiming the
        # process-wide ``rec["slo"]`` key) and stamps breach events
        # with the tenant name.
        self.tenant = tenant
        self.p99_ms = max(int(p99_ms), 0)
        self.rate_evps = max(int(rate_evps), 0)
        # jax.reach.slo.p99.ms — reach-serving latency objective: a
        # served reach query slower than this (submit -> reply) is
        # "bad".  Judged over the reach server's latency histogram with
        # the SAME two-window burn construction as the window objective.
        self.reach_p99_ms = max(int(reach_p99_ms), 0)
        self.budget = min(max(float(budget), 1e-6), 1.0)
        self.fast_s = max(float(fast_s), 1.0)
        self.slow_s = max(float(slow_s), self.fast_s)
        self.annotate = annotate       # sampler.annotate or None
        self.flightrec = flightrec
        # obs.capture.CaptureManager (or None): a breach TRANSITION
        # fires a bounded profiler capture — the deep "why was the
        # dispatch slow" evidence next to the flight dump's "that it
        # was".  The manager owns cooldown/cap policy, so a flapping
        # breach cannot profile the run to death.
        self.capture = capture
        # obs.queryattr.QueryLifecycle (or None): when the reach
        # objective breaches, the breach event carries the per-segment
        # attribution — WHICH segment (queue/batch/dispatch/reply) was
        # burning the budget, not just that the budget burned.
        self.queryattr = queryattr
        self._clock = clock
        # latency source: get-or-create with the SAME geometry as the
        # producer so the registry hands back the shared instrument
        # (lifecycle's e2e at growth 2^0.125, or attach_obs's writeback
        # histogram at the defaults)
        if use_lifecycle:
            self._hist = registry.histogram(
                "streambench_window_e2e_ms",
                "end-to-end latency of attribution-tracked windows (ms)",
                lo=0.1, hi=1e7, growth=2 ** 0.125)
        else:
            self._hist = registry.histogram(
                "streambench_window_latency_ms",
                "window writeback latency (time_updated - window_ts), ms")
        # reach latency source: get-or-create the SAME instrument the
        # ReachQueryServer feeds (default geometry on both sides)
        self._reach_hist = None
        if self.reach_p99_ms:
            from streambench_tpu.reach.serve import LATENCY_HIST

            self._reach_hist = registry.histogram(
                LATENCY_HIST,
                "reach query latency, submit to reply (ms)")
        # sample ring: (t, windows_total, windows_bad, rate_ticks,
        # rate_bad_ticks) — bounded by the slow window at the sampler's
        # cadence; 4096 covers a 1 s cadence for over an hour
        self._ring: list[tuple] = []
        self._ring_cap = 4096
        self._rate_ticks = 0
        self._rate_bad = 0
        self.breaches = 0
        self._in_breach = False
        g = registry.gauge
        self._gauges = {
            ("latency", "fast"): g("streambench_slo_burn_rate",
                                   "error-budget burn rate",
                                   labels={"objective": "latency",
                                           "window": "fast"}),
            ("latency", "slow"): g("streambench_slo_burn_rate", "",
                                   labels={"objective": "latency",
                                           "window": "slow"}),
            ("rate", "fast"): g("streambench_slo_burn_rate", "",
                                labels={"objective": "rate",
                                        "window": "fast"}),
            ("rate", "slow"): g("streambench_slo_burn_rate", "",
                                labels={"objective": "rate",
                                        "window": "slow"}),
            ("reach", "fast"): g("streambench_slo_burn_rate", "",
                                 labels={"objective": "reach",
                                         "window": "fast"}),
            ("reach", "slow"): g("streambench_slo_burn_rate", "",
                                 labels={"objective": "reach",
                                         "window": "slow"}),
        }
        self._g_bad = g("streambench_slo_bad_windows_total",
                        "windows whose e2e latency exceeded the "
                        "jax.slo.p99.ms objective (bucket resolution)")
        self._c_breach = registry.counter(
            "streambench_slo_breaches_total",
            "breach transitions: both burn windows over threshold")

    @property
    def active(self) -> bool:
        return bool(self.p99_ms or self.rate_evps or self.reach_p99_ms)

    # ------------------------------------------------------------------
    def _window_burn(self, window_s: float, idx_total: int,
                     idx_bad: int) -> float:
        """Burn rate over the trailing ``window_s``: bad/total deltas
        between now and the newest sample at least ``window_s`` old
        (or the oldest available — early in a run the window is
        whatever history exists)."""
        if len(self._ring) < 2:
            return 0.0
        newest = self._ring[-1]
        cutoff = newest[0] - window_s
        base = self._ring[0]
        for s in reversed(self._ring[:-1]):
            if s[0] <= cutoff:
                base = s
                break
        d_total = newest[idx_total] - base[idx_total]
        d_bad = newest[idx_bad] - base[idx_bad]
        if d_total <= 0:
            return 0.0
        return (d_bad / d_total) / self.budget

    def burn_rates(self) -> dict:
        """{"latency": {"fast": x, "slow": y}, "rate": {...}} from the
        current ring."""
        out: dict = {}
        if self.p99_ms:
            out["latency"] = {
                "fast": round(self._window_burn(self.fast_s, 1, 2), 3),
                "slow": round(self._window_burn(self.slow_s, 1, 2), 3)}
        if self.rate_evps:
            out["rate"] = {
                "fast": round(self._window_burn(self.fast_s, 3, 4), 3),
                "slow": round(self._window_burn(self.slow_s, 3, 4), 3)}
        if self.reach_p99_ms:
            out["reach"] = {
                "fast": round(self._window_burn(self.fast_s, 5, 6), 3),
                "slow": round(self._window_burn(self.slow_s, 5, 6), 3)}
        return out

    def fast_burn(self) -> float:
        """Worst fast-window burn across this tracker's objectives —
        the scalar the admission controller's ``burns()`` callable
        reports per tenant (fast window: admission wants onset, the
        two-window breach verdict stays the pass/fail arbiter)."""
        burns = self.burn_rates()
        vals = [wins.get("fast", 0.0) for wins in burns.values()]
        return max(vals) if vals else 0.0

    # ------------------------------------------------------------------
    def collect(self, rec: dict, dt_s: float) -> None:
        """Sampler-collector hook: append one sample, recompute burns,
        journal breach transitions, and put the ``"slo"`` block on the
        snapshot record."""
        if not self.active:
            return
        now = self._clock()
        total = bad = 0
        if self.p99_ms:
            total = self._hist.count
            bad = total - self._hist.count_le(float(self.p99_ms))
        r_total = r_bad = 0
        if self._reach_hist is not None:
            r_total = self._reach_hist.count
            r_bad = r_total - self._reach_hist.count_le(
                float(self.reach_p99_ms))
        if self.rate_evps and dt_s > 0:
            events = rec.get("events")
            rate = rec.get("events_per_s")
            # judge only intervals that MOVED events or follow one that
            # did — a run that has not started yet is not "below rate"
            if isinstance(rate, (int, float)) and isinstance(
                    events, (int, float)) and events > 0:
                self._rate_ticks += 1
                if rate < self.rate_evps:
                    self._rate_bad += 1
        self._ring.append((now, total, bad,
                           self._rate_ticks, self._rate_bad,
                           r_total, r_bad))
        if len(self._ring) > self._ring_cap:
            del self._ring[:len(self._ring) - self._ring_cap]
        burns = self.burn_rates()
        for obj, wins in burns.items():
            for win, v in wins.items():
                self._gauges[(obj, win)].set(v)
        self._g_bad.set(bad)
        breaching = any(
            wins["fast"] >= BREACH_BURN and wins["slow"] >= BREACH_BURN
            for wins in burns.values())
        if breaching and not self._in_breach:
            self.breaches += 1
            self._c_breach.inc()
            fields = {"burn": burns, "bad_windows": bad,
                      "total_windows": total}
            if self.tenant is not None:
                fields["tenant"] = self.tenant
            if self.reach_p99_ms and self.queryattr is not None:
                # per-segment burn attribution: the breach event says
                # where the slow queries' time went
                segs = self.queryattr.segment_quantiles()
                if segs:
                    fields["reach_segments"] = segs
                fields["reach_contention_ratio"] = round(
                    self.queryattr.contention_ratio(), 4)
            if self.annotate is not None:
                try:
                    self.annotate("slo_breach", **fields)
                except Exception:
                    pass   # a closing sampler must not kill the tick
            if self.flightrec is not None:
                self.flightrec.record("slo_breach", **fields)
            if self.capture is not None:
                try:
                    self.capture.trigger("slo_breach")
                except Exception:
                    pass   # capture failure must not kill the tick
        elif not breaching and self._in_breach:
            rcv = ({"burn": burns} if self.tenant is None
                   else {"burn": burns, "tenant": self.tenant})
            if self.annotate is not None:
                try:
                    self.annotate("slo_recovered", **rcv)
                except Exception:
                    pass
            if self.flightrec is not None:
                self.flightrec.record("slo_recovered", **rcv)
        self._in_breach = breaching
        block = {"burn": burns, "bad_windows": bad,
                 "total_windows": total, "breaches": self.breaches,
                 "in_breach": breaching}
        if self.reach_p99_ms:
            block["bad_reach"] = r_bad
            block["total_reach"] = r_total
        if self.tenant is None:
            rec["slo"] = block
        else:
            rec.setdefault("slo_tenants", {})[self.tenant] = block

    # ------------------------------------------------------------------
    def verdict(self) -> dict:
        """The pass/fail block the RunStats close line carries.  PASS =
        the run never breached AND is not ending inside one."""
        burns = self.burn_rates()
        total = self._hist.count if self.p99_ms else 0
        bad = (total - self._hist.count_le(float(self.p99_ms))
               if self.p99_ms else 0)
        out = {
            "objectives": {
                **({"p99_ms": self.p99_ms} if self.p99_ms else {}),
                **({"rate_evps": self.rate_evps}
                   if self.rate_evps else {}),
                **({"reach_p99_ms": self.reach_p99_ms}
                   if self.reach_p99_ms else {}),
            },
            "budget": self.budget,
            "windows_s": {"fast": self.fast_s, "slow": self.slow_s},
            "burn": burns,
            "bad_windows": bad,
            "total_windows": total,
            "breaches": self.breaches,
            "pass": self.breaches == 0 and not self._in_breach,
        }
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self._reach_hist is not None:
            r_total = self._reach_hist.count
            out["bad_reach"] = r_total - self._reach_hist.count_le(
                float(self.reach_p99_ms))
            out["total_reach"] = r_total
            if self.queryattr is not None:
                segs = self.queryattr.segment_quantiles()
                if segs:
                    out["reach_segments"] = segs
                out["reach_contention_ratio"] = round(
                    self.queryattr.contention_ratio(), 4)
        return out
