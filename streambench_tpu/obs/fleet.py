"""Fleet observability: metrics federation + cross-process trace
stitching (obs layer 6, ISSUE 15).

Since the reach tier scaled out, one "run" is a FLEET — an engine
writer, a snapshot shipper, N replica processes, pub/sub clients,
supervisor-restarted children — and each process journals its own
``metrics.jsonl`` and dumps its own ``trace_<pid>.json``.  Nothing
spans them.  This module is the spanning instrument:

- :class:`FleetCollector` tails every role's ``metrics.jsonl``
  (reusing ``load_records``' rotation stitch, so a rotated writer
  journal is covered end to end) into ONE ``fleet.jsonl`` whose every
  record carries ``role``/``pid`` attribution, merged in ``ts_ms``
  order;
- :func:`summarize_fleet` folds the merged stream into a per-role
  table — ingest rate, qps, cache hit ratio, staleness, freshness
  hops, restarts — rendered by ``python -m streambench_tpu.obs
  fleet``;
- :func:`merge_traces` folds every role's Chrome trace file into one
  perfetto-loadable document: per-file clocks are aligned on the
  recorded ``wall0_ms`` epochs, real pids keep the lanes apart, and
  ``process_name`` metadata names each lane — writer folds and replica
  query batches sit on one timeline.

Like every obs layer: read-side only, nothing here runs unless asked.
"""

from __future__ import annotations

import json
import os

#: the merged-journal filename the collector writes
FLEET_LOG = "fleet.jsonl"


def _role_of(path: str, records: list) -> str:
    """Role attribution for one journal: the records' own ``role``
    stamp wins (MetricsSampler writes it), else the journal's parent
    directory name — good enough for ``<fleetdir>/<role>/metrics.jsonl``
    layouts."""
    for r in records:
        role = r.get("role")
        if isinstance(role, str) and role:
            return role
    parent = os.path.basename(os.path.dirname(os.path.abspath(path)))
    return parent or "unknown"


def parse_role_spec(spec: str) -> tuple:
    """``role=path`` or a bare path -> (role | None, path)."""
    if "=" in spec and not os.path.exists(spec):
        role, _, path = spec.partition("=")
        return role.strip() or None, path.strip()
    return None, spec


class FleetCollector:
    """Merge every role's ``metrics.jsonl`` into one attributed stream.

    ``roles`` is a list of ``(role_or_None, path)`` pairs; a ``None``
    role is inferred from the records / directory name.  ``collect()``
    re-reads every journal (rotation-stitched), attributes, merges by
    ``ts_ms``, optionally writes ``fleet.jsonl``, and returns the
    merged record list — cheap enough to run per report; an always-on
    tailer would be a daemon this repo doesn't need yet.
    """

    def __init__(self, roles: list, out_path: "str | None" = None):
        self.roles = [tuple(r) for r in roles]
        self.out_path = out_path
        self.sources: list[dict] = []   # per-source read stats

    def collect(self) -> list[dict]:
        from streambench_tpu.obs.report import load_records

        self.sources = []
        merged: list[dict] = []
        for role, path in self.roles:
            try:
                records = load_records(path)   # stitches <path>.1 first
            except OSError as e:
                self.sources.append({"role": role, "path": path,
                                     "error": repr(e), "records": 0})
                continue
            role = role or _role_of(path, records)
            for r in records:
                out = dict(r)
                out["role"] = role
                out.setdefault("pid", None)
                merged.append(out)
            self.sources.append({"role": role, "path": path,
                                 "records": len(records)})
        merged.sort(key=lambda r: (r.get("ts_ms") or 0))
        if self.out_path:
            tmp = self.out_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for r in merged:
                    f.write(json.dumps(r) + "\n")
            os.replace(tmp, self.out_path)
        return merged


# ----------------------------------------------------------------------
# per-role summary + rendering (the `obs fleet` table)
def summarize_fleet(records: list[dict], path: str = "") -> dict:
    """Fold an attributed record stream into per-(role, pid) rows.

    Columns are the fleet health set the ISSUE names: ingest rate
    (writer), qps / cache hit ratio / staleness / freshness hop p99s
    (any serving role), restart count (supervisor annotations), plus
    the clock-offset evidence when a replica estimated one."""
    by_role: dict = {}
    for r in records:
        role = r.get("role") or "unknown"
        key = (role, r.get("pid"))
        agg = by_role.setdefault(key, {
            "role": role, "pid": r.get("pid"), "snapshots": 0,
            "restarts": 0, "events": None, "events_per_s_mean": None,
            "_rates": [],
        })
        kind = r.get("kind")
        if kind == "event":
            # "restart" = the writer supervisor's annotation; "replica_
            # restart" = the fleet supervisor respawning a replica
            # process (ISSUE 16) — one restart column covers both roles
            if r.get("event") in ("restart", "replica_restart"):
                agg["restarts"] += 1
            # controller decisions (ISSUE 17): a journal that carries
            # only the event stream (no summary snapshot yet) still
            # renders a decision count + the last verdict/knob
            if r.get("event") == "autoscale_decision":
                a = agg.setdefault("autoscale", {})
                a["decisions"] = a.get("decisions", 0) + 1
                a["last"] = {k: r.get(k) for k in
                             ("decision", "verdict", "knob",
                              "replicas")}
            continue
        if kind not in ("snapshot", "final"):
            continue
        agg["snapshots"] += 1
        if isinstance(r.get("events"), (int, float)):
            agg["events"] = r["events"]
        eps = r.get("events_per_s")
        if isinstance(eps, (int, float)) and eps > 0:
            agg["_rates"].append(eps)
        rq = r.get("reach_query")
        if isinstance(rq, dict):
            agg["qps"] = rq.get("qps")
            agg["served"] = rq.get("served")
            agg["shed"] = rq.get("shed")
            agg["plane_epoch"] = rq.get("plane_epoch")
            agg["staleness_ms"] = rq.get("staleness_ms")
            cache = rq.get("cache")
            if isinstance(cache, dict):
                agg["cache_hit_ratio"] = cache.get("hit_ratio")
            fr = rq.get("freshness")
            if isinstance(fr, dict):
                agg["freshness_p99_ms"] = {
                    hop: (s or {}).get("p99")
                    for hop, s in (fr.get("hops") or {}).items()}
                agg["freshness_high_water_ms"] = fr.get("high_water_ms")
        # router journal (ISSUE 16): the fronting router's sampler
        # writes rec["router"] = ReachRouter.summary() — folded into
        # the same serving columns (routed qps, answered as served) so
        # the router reads as one more row of the fleet table, plus
        # its own failover/shed evidence as a sub-line
        rt = r.get("router")
        if isinstance(rt, dict):
            agg["qps"] = rt.get("qps")
            agg["served"] = rt.get("answered")
            agg["shed"] = rt.get("shed")
            agg["router"] = {
                "routed": rt.get("routed"),
                "failovers": rt.get("failovers"),
                "shed_ratio": rt.get("shed_ratio"),
                "failover_p99_ms": rt.get("failover_p99_ms"),
                "replicas": len(rt.get("replicas") or ()),
                "suspect": sum(1 for h in (rt.get("replicas") or ())
                               if isinstance(h, dict)
                               and h.get("suspect")),
            }
        # autoscale controller (ISSUE 17): the controller's sampler
        # journals rec["autoscale"] = AutoscaleController.summary();
        # the snapshot's counts override the event-derived ones (they
        # are authoritative) and feed the controller sub-line
        asc = r.get("autoscale")
        if isinstance(asc, dict):
            a = agg.setdefault("autoscale", {})
            for k2 in ("replicas", "decisions", "scale_ups",
                       "scale_downs", "ship_tunes", "poll_tunes",
                       "holds", "shed_redirects"):
                if asc.get(k2) is not None:
                    a[k2] = asc[k2]
            last = asc.get("last")
            if isinstance(last, dict):
                a["last"] = {k2: last.get(k2) for k2 in
                             ("decision", "verdict", "knob",
                              "replicas")}
        # shipper cost (ISSUE 18): the writer's sampler journals
        # rec["ship"] = SnapshotShipper/DeltaShipper.summary() — the
        # per-tick bytes/rows/ms evidence the delta path is judged by,
        # rendered as a ship sub-line under the writer row
        sp = r.get("ship")
        if isinstance(sp, dict):
            agg["ship"] = {k2: sp.get(k2) for k2 in
                           ("mode", "ships", "bytes_per_tick",
                            "rows_per_tick", "ship_ms_per_tick",
                            "bytes_total", "bases", "deltas",
                            "cutovers")}
        # chaos fault counters (ISSUE 16): any role may journal its
        # injector's snapshot under "faults"; the net_faults column is
        # the fleet-wide message-fault evidence next to restarts
        faults = r.get("faults")
        if isinstance(faults, dict):
            n = faults.get("net_faults")
            if isinstance(n, (int, float)):
                agg["net_faults"] = int(n)
        clock = r.get("clock")
        if isinstance(clock, dict):
            agg["clock"] = {k: clock.get(k) for k in
                            ("offset_ms", "uncertainty_ms", "applied")}
        # multi-tenant host (ISSUE 19): the host's sampler journals a
        # per-tenant block (rec["tenants"]), per-tenant burn gauges
        # (rec["slo_tenants"]), the device-time ledger's blame matrix
        # (rec["multitenant"]) and the admission controller's summary
        # (rec["admission"]) — folded into one tenant sub-table under
        # the host row, last snapshot wins like the other columns
        tn = r.get("tenants")
        if isinstance(tn, dict):
            tens = agg.setdefault("tenants", {})
            for name, t in tn.items():
                if not isinstance(t, dict):
                    continue
                row = tens.setdefault(name, {})
                row["kind"] = t.get("kind")
                for k2 in ("events", "events_per_s", "queued_batches",
                           "folded_batches", "dropped_batches"):
                    if t.get(k2) is not None:
                        row[k2] = t[k2]
        st = r.get("slo_tenants")
        if isinstance(st, dict):
            tens = agg.setdefault("tenants", {})
            for name, s in st.items():
                if not isinstance(s, dict):
                    continue
                row = tens.setdefault(name, {})
                fast = [b.get("fast") for b in (s.get("burn") or
                                                {}).values()
                        if isinstance(b, dict)
                        and isinstance(b.get("fast"), (int, float))]
                if fast:
                    row["burn_fast"] = round(max(fast), 2)
                row["in_breach"] = s.get("in_breach")
        mt = r.get("multitenant")
        if isinstance(mt, dict):
            tens = agg.setdefault("tenants", {})
            for name in (mt.get("tenants") or ()):
                row = tens.setdefault(name, {})
                row["busy_ms"] = (mt.get("busy_ms") or {}).get(name)
                row["wait_ms"] = (mt.get("wait_ms") or {}).get(name)
            agg["blame"] = {
                "matrix_ms": mt.get("matrix_ms"),
                "offdiag_ratio": mt.get("offdiag_ratio"),
                "partition_ok": (mt.get("partition") or {}).get("ok"),
            }
        adm = r.get("admission")
        if isinstance(adm, dict):
            agg["admission"] = {k2: adm.get(k2) for k2 in
                                ("defers", "sheds", "releases", "holds",
                                 "batches_deferred", "batches_shed",
                                 "gates", "last")}
        # Kafka delivery ledger (ISSUE 20): any role ingesting through
        # the Kafka adapter journals rec["kafka"] (kafka_collector) —
        # last snapshot wins, rendered as a sub-line under the row
        kf = r.get("kafka")
        if isinstance(kf, dict):
            agg["kafka"] = {k2: kf.get(k2) for k2 in
                            ("produced", "delivered", "redeliveries",
                             "produce_retries", "consume_retries",
                             "broker_down_ms", "consumer_lag")}
    rows = []
    for agg in by_role.values():
        rates = agg.pop("_rates")
        if rates:
            agg["events_per_s_mean"] = round(sum(rates) / len(rates), 1)
        rows.append(agg)
    rows.sort(key=lambda a: (a["role"], a["pid"] or 0))
    return {"path": path, "records": len(records),
            "processes": len(rows), "roles": rows}


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.1f}"
    if isinstance(v, int):
        return f"{v:,}"
    return str(v)


def render_fleet(s: dict) -> str:
    lines = [f"fleet report: {s['path'] or '(records)'}",
             f"  {s['processes']} process(es), {s['records']} records",
             f"  {'role':<10} {'pid':>8} {'ev/s':>10} {'qps':>8} "
             f"{'hit%':>6} {'stale ms':>9} {'epoch':>6} {'restarts':>8} "
             f"{'netflt':>6}"]
    for a in s["roles"]:
        hit = a.get("cache_hit_ratio")
        lines.append(
            f"  {a['role']:<10} {_fmt(a.get('pid')):>8} "
            f"{_fmt(a.get('events_per_s_mean')):>10} "
            f"{_fmt(a.get('qps')):>8} "
            f"{(f'{hit * 100:.0f}%' if isinstance(hit, (int, float)) else '-'):>6} "
            f"{_fmt(a.get('staleness_ms')):>9} "
            f"{_fmt(a.get('plane_epoch')):>6} "
            f"{_fmt(a.get('restarts')):>8} "
            f"{_fmt(a.get('net_faults')):>6}")
        rt = a.get("router")
        if rt:
            ratio = rt.get("shed_ratio")
            ratio_s = (f"{ratio:.3f}"
                       if isinstance(ratio, (int, float)) else "-")
            lines.append(
                f"    router: routed {_fmt(rt.get('routed'))}  "
                f"failovers {_fmt(rt.get('failovers'))}  "
                f"shed_ratio {ratio_s}  "
                f"failover p99 {_fmt(rt.get('failover_p99_ms'))} ms  "
                f"replicas {_fmt(rt.get('replicas'))} "
                f"({_fmt(rt.get('suspect'))} suspect)")
        asc = a.get("autoscale")
        if asc:
            last = asc.get("last") or {}
            last_s = (f"{last.get('decision')}"
                      f"[{last.get('verdict')}->{last.get('knob')}]"
                      if last.get("decision") else "-")
            lines.append(
                f"    autoscale: replicas {_fmt(asc.get('replicas'))}  "
                f"decisions {_fmt(asc.get('decisions'))} "
                f"(up {_fmt(asc.get('scale_ups'))}, "
                f"down {_fmt(asc.get('scale_downs'))}, "
                f"ship {_fmt(asc.get('ship_tunes'))})  "
                f"holds {_fmt(asc.get('holds'))}  "
                f"redirects {_fmt(asc.get('shed_redirects'))}  "
                f"last {last_s}")
        sp = a.get("ship")
        if sp:
            chain = ""
            if sp.get("deltas") is not None:
                chain = (f"  bases {_fmt(sp.get('bases'))}  "
                         f"deltas {_fmt(sp.get('deltas'))}  "
                         f"cutovers {_fmt(sp.get('cutovers'))}")
            lines.append(
                f"    ship[{sp.get('mode') or 'full'}]: "
                f"ships {_fmt(sp.get('ships'))}  "
                f"bytes/tick {_fmt(sp.get('bytes_per_tick'))}  "
                f"rows/tick {_fmt(sp.get('rows_per_tick'))}  "
                f"ms/tick {_fmt(sp.get('ship_ms_per_tick'))}{chain}")
        kf = a.get("kafka")
        if kf:
            lines.append(
                f"    kafka: produced {_fmt(kf.get('produced'))}  "
                f"delivered {_fmt(kf.get('delivered'))}  "
                f"redeliveries {_fmt(kf.get('redeliveries'))}  "
                f"retries {_fmt(kf.get('produce_retries'))}/"
                f"{_fmt(kf.get('consume_retries'))}  "
                f"lag {_fmt(kf.get('consumer_lag'))}")
        fr = a.get("freshness_p99_ms")
        if fr:
            hops = "  ".join(f"{hop} {_fmt(fr.get(hop))}"
                             for hop in ("fold_lag", "ship_wait",
                                         "tail_lag", "serve", "total"))
            lines.append(f"    freshness p99 (ms): {hops}")
        clock = a.get("clock")
        if clock:
            lines.append(
                f"    clock offset {_fmt(clock.get('offset_ms'))} ms "
                f"+-{_fmt(clock.get('uncertainty_ms'))} "
                f"({'applied' if clock.get('applied') else 'NOT applied'})")
        tens = a.get("tenants")
        if tens:
            adm = a.get("admission") or {}
            gates = adm.get("gates") or {}
            lines.append(
                f"    {'tenant':<8} {'kind':<8} {'events':>10} "
                f"{'folded':>7} {'queued':>7} {'busy ms':>11} "
                f"{'wait ms':>11} {'burn':>6} {'gate':>6}")
            for name in sorted(tens):
                t = tens[name]
                gate = gates.get(name)
                if isinstance(gate, dict):
                    gate = gate.get("mode")
                lines.append(
                    f"    {name:<8} {t.get('kind') or '-':<8} "
                    f"{_fmt(t.get('events')):>10} "
                    f"{_fmt(t.get('folded_batches')):>7} "
                    f"{_fmt(t.get('queued_batches')):>7} "
                    f"{_fmt(t.get('busy_ms')):>11} "
                    f"{_fmt(t.get('wait_ms')):>11} "
                    f"{_fmt(t.get('burn_fast')):>6} "
                    f"{gate or '-':>6}")
            bl = a.get("blame")
            if bl and bl.get("offdiag_ratio") is not None:
                ok = bl.get("partition_ok")
                lines.append(
                    f"    blame offdiag {_fmt(bl['offdiag_ratio'])}  "
                    f"partition {'ok' if ok else 'FAIL' if ok is False else '-'}")
            if adm:
                last = adm.get("last") or {}
                last_s = (f"{last.get('decision')}"
                          f"[{last.get('tenant')}->"
                          f"{last.get('victim')}]"
                          if last.get("decision") else "-")
                lines.append(
                    f"    admission: defers {_fmt(adm.get('defers'))}  "
                    f"sheds {_fmt(adm.get('sheds'))}  "
                    f"releases {_fmt(adm.get('releases'))}  "
                    f"deferred {_fmt(adm.get('batches_deferred'))}  "
                    f"shed {_fmt(adm.get('batches_shed'))}  "
                    f"last {last_s}")
    return "\n".join(lines)


def discover_roles(directory: str) -> list:
    """``(role, path)`` pairs under one fleet directory: a top-level
    ``metrics.jsonl`` plus every ``<sub>/metrics.jsonl`` one level
    down (the writer-workdir + per-replica-subdir layout the CI fleet
    leg uses)."""
    out = []
    top = os.path.join(directory, "metrics.jsonl")
    if os.path.exists(top):
        out.append((None, top))
    for name in sorted(os.listdir(directory)):
        p = os.path.join(directory, name, "metrics.jsonl")
        if os.path.isdir(os.path.join(directory, name)) \
                and os.path.exists(p):
            out.append((None, p))
    return out


# ----------------------------------------------------------------------
# cross-process trace stitching (`obs trace --merge`)
def merge_traces(inputs: list, run: str = "fleet") -> dict:
    """Fold per-process Chrome trace files into one document.

    ``inputs``: ``(role_or_None, path)`` pairs.  Every SpanTracer dump
    stamps ``otherData.wall0_ms`` — the wall-clock epoch its relative
    ``ts`` values are measured from — so aligning clocks is exact up to
    wall-clock skew between the processes: each file's events shift by
    ``(wall0_ms - min(wall0_ms)) * 1000`` µs.  Events keep their real
    pids (distinct per process), and one ``process_name`` metadata
    event per file names the lane, which is exactly what perfetto
    needs to draw writer folds above replica query batches on one
    timeline."""
    events: list[dict] = []
    meta = []
    docs = []
    for role, path in inputs:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        wall0 = (doc.get("otherData") or {}).get("wall0_ms")
        docs.append((role or os.path.splitext(
            os.path.basename(path))[0], path, doc,
            float(wall0) if isinstance(wall0, (int, float)) else None))
    known = [w for _, _, _, w in docs if w is not None]
    base = min(known) if known else 0.0
    # tenant lanes (ISSUE 19): N tenants in ONE process dump N trace
    # files sharing one real pid, which would merge their lanes and
    # let the last process_name win.  When a later file claims a pid
    # an earlier file already used, remap its events onto a synthetic
    # pid (deterministic per file order) so every role/tenant keeps a
    # named lane of its own.
    claimed: dict = {}
    for fi, (role, path, doc, wall0) in enumerate(docs):
        shift_us = ((wall0 - base) * 1000.0) if wall0 is not None else 0.0
        remap: dict = {}
        pids = set()
        for ev in doc.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            out = dict(ev)
            if out.get("ph") == "X":
                out["ts"] = round(float(out.get("ts", 0)) + shift_us, 3)
            pid = out.get("pid")
            if pid is not None:
                if pid not in remap:
                    owner = claimed.setdefault(pid, fi)
                    remap[pid] = (pid if owner == fi
                                  else pid * 1000 + fi)
                out["pid"] = remap[pid]
            pids.add(out.get("pid"))
            events.append(out)
        for pid in sorted(p for p in pids if p is not None):
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": role}})
        meta.append({"role": role, "path": os.path.basename(path),
                     "wall0_ms": wall0,
                     "shift_us": round(shift_us, 3)})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"run": run, "merged": meta,
                      "wall0_ms": base, "processes": len(docs)},
    }


def trace_process_names(doc: dict) -> dict:
    """{pid: process_name} out of a merged trace (validation helper)."""
    out = {}
    for ev in doc.get("traceEvents", []):
        if (isinstance(ev, dict) and ev.get("ph") == "M"
                and ev.get("name") == "process_name"):
            out[ev.get("pid")] = (ev.get("args") or {}).get("name")
    return out
