"""Bounded triggered profiler capture: deep evidence when a run goes bad.

Before this module the repo had exactly one profiler entry point — the
ad-hoc ``jax.profiler.start_trace`` hook in ``trace.py`` (``--traceDir``
traces a WHOLE run) — and an SLO breach (PR 8) left only a flight dump:
counters that say a dispatch was slow, nothing that says WHY.  This
module makes profiler capture *triggered and bounded*:

- :class:`CaptureManager` fires a short ``jax.profiler.start_trace`` /
  ``stop_trace`` window (``window_s``) into
  ``<workdir>/xprof_<ms>_<reason>/`` when an SLO breach transitions on
  (``obs/slo.py`` hook), on SIGUSR2 (the operator's "grab me a trace
  NOW" signal, wired in the engine CLI), or as a config one-shot at
  startup.  A cooldown and a max-capture cap bound the disk and
  profiler overhead no matter how often the trigger fires; suppressed
  triggers are counted, never silent.  Every capture is recorded in the
  flight recorder and the metrics journal, and the capture dirs ride
  the RunStats close line — a postmortem knows exactly where its deep
  evidence lives.

- :func:`profiler_window` is the ONE low-level start/stop path.
  ``jax.profiler`` is a process-global singleton (a second
  ``start_trace`` raises), so every profiler user — this manager AND
  ``trace.device_trace`` (which now delegates here) — goes through the
  same lock; a capture requested while another is active is counted as
  suppressed instead of crashing the run.

Default-off (``jax.obs.capture.enabled``): nothing is constructed, no
signal handler installed, the hot path unchanged.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from streambench_tpu.utils.ids import now_ms

# process-global profiler ownership: jax.profiler allows ONE active
# trace; all start/stop goes through this lock + flag.
_profiler_lock = threading.Lock()
_active_logdir: "str | None" = None


def _begin(logdir: str) -> bool:
    """Start a profiler trace if none is active.  False (no-op) when
    the profiler is busy or unavailable."""
    global _active_logdir
    with _profiler_lock:
        if _active_logdir is not None:
            return False
        try:
            import jax

            jax.profiler.start_trace(logdir)
        except Exception:
            return False
        _active_logdir = logdir
        return True


def _end(logdir: str) -> None:
    global _active_logdir
    with _profiler_lock:
        if _active_logdir != logdir:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        _active_logdir = None


@contextlib.contextmanager
def profiler_window(logdir: "str | None"):
    """Scoped profiler trace under ``logdir`` (no-op if None or if the
    profiler is already owned by a triggered capture).  The single
    start/stop path — ``trace.device_trace`` delegates here."""
    if not logdir:
        yield
        return
    started = _begin(logdir)
    try:
        yield
    finally:
        if started:
            _end(logdir)


class CaptureManager:
    """Trigger-driven bounded profiler captures.

    ``trigger(reason)`` is safe from any thread (SLO collector, signal
    handler, host loop): under the policy lock it checks the cap, the
    cooldown, and profiler availability, then starts a capture whose
    ``stop`` is scheduled on a daemon timer ``window_s`` later — the
    triggering thread never blocks on the capture.
    """

    def __init__(self, workdir: str, *, cooldown_s: float = 60.0,
                 max_captures: int = 3, window_s: float = 3.0,
                 registry=None, flightrec=None, annotate=None,
                 clock=time.monotonic):
        self.workdir = workdir
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.max_captures = max(int(max_captures), 1)
        self.window_s = max(float(window_s), 0.1)
        self.flightrec = flightrec
        self.annotate = annotate          # sampler.annotate or None
        self._clock = clock
        self._lock = threading.Lock()
        self._last_end: "float | None" = None
        self._current: "str | None" = None
        self._timer: "threading.Timer | None" = None
        self.captures: list[dict] = []
        self.suppressed = 0
        self._c_caps = self._c_supp = None
        if registry is not None:
            self._c_caps = registry.counter(
                "streambench_captures_total",
                "triggered profiler captures started")
            self._c_supp = registry.counter(
                "streambench_captures_suppressed_total",
                "capture triggers suppressed by cooldown/cap/busy")

    # ------------------------------------------------------------------
    def trigger(self, reason: str) -> "str | None":
        """Request a capture; returns its directory, or None when
        suppressed (cap reached, cooling down, or profiler busy)."""
        with self._lock:
            now = self._clock()
            if (self._current is not None
                    or len(self.captures) >= self.max_captures
                    or (self._last_end is not None
                        and now - self._last_end < self.cooldown_s)):
                self.suppressed += 1
                if self._c_supp is not None:
                    self._c_supp.inc()
                return None
            logdir = os.path.join(
                self.workdir, f"xprof_{now_ms()}_{reason}")
            os.makedirs(logdir, exist_ok=True)
            if not _begin(logdir):
                self.suppressed += 1
                if self._c_supp is not None:
                    self._c_supp.inc()
                return None
            self._current = logdir
            rec = {"dir": logdir, "reason": reason, "ts_ms": now_ms(),
                   "window_s": self.window_s}
            self.captures.append(rec)
            if self._c_caps is not None:
                self._c_caps.inc()
            self._timer = threading.Timer(self.window_s, self._finish,
                                          args=(logdir,))
            self._timer.daemon = True
            self._timer.start()
        if self.flightrec is not None:
            self.flightrec.record("profiler_capture", dir=logdir,
                                  reason=reason)
        if self.annotate is not None:
            try:
                self.annotate("profiler_capture", dir=logdir,
                              reason=reason)
            except Exception:
                pass   # a closing sampler must not kill the trigger
        return logdir

    def _finish(self, logdir: str) -> None:
        _end(logdir)
        with self._lock:
            if self._current == logdir:
                self._current = None
                self._last_end = self._clock()

    # ------------------------------------------------------------------
    @property
    def active(self) -> "str | None":
        with self._lock:
            return self._current

    def close(self) -> None:
        """Stop any in-flight capture NOW (run is ending; a dangling
        profiler would drop its trace on interpreter exit)."""
        with self._lock:
            timer, current = self._timer, self._current
            self._timer = None
        if timer is not None:
            timer.cancel()
        if current is not None:
            self._finish(current)

    def summary(self) -> dict:
        """The ``"capture"`` block for the RunStats close line."""
        with self._lock:
            return {
                "captures": [dict(c) for c in self.captures],
                "suppressed": self.suppressed,
                "cooldown_s": self.cooldown_s,
                "max_captures": self.max_captures,
                "window_s": self.window_s,
            }
