"""Device-memory ledger: compiled-kernel footprints + live-array census.

HBM claims in this repo were comments and arithmetic ("config #5's
C=1e6 would be a [8192, 1e6] f32 operand — 32 GB",
``engine/pipeline.py``); nothing measured what the compiled programs
actually reserve or what the process actually holds on device.  Two
measured signals, both off the hot path:

- **per-kernel footprints** — :func:`kernel_memory` runs
  ``fn.lower(*args).compile().memory_analysis()`` and reports XLA's own
  argument/output/temp/alias byte accounting for that executable.
  :meth:`DeviceMemoryLedger.analyze_engine` does it for every hot
  kernel an engine dispatches (the engine's ``_devmem_kernels`` hook,
  which fails CLOSED for subclasses with overridden device hooks) and
  folds them into a per-engine **peak-footprint estimate**: persistent
  state bytes + the largest single kernel's (argument + output + temp).
  CAUTION (the PR 7 gotcha as a design rule): ``lower().compile()``
  does NOT share the jit call cache — each analysis costs one extra
  compile, so analysis runs once, after warmup construction and BEFORE
  ``mark_steady()``, never per tick.

- **live-array census** — a sampled ``jax.live_arrays()`` walk (count +
  bytes, bucketed by power-of-two array size) journaled by the existing
  ``MetricsSampler`` via :meth:`DeviceMemoryLedger.collect`.  The
  census is O(live arrays) per sample, so it runs every
  ``census_every`` ticks, not every tick.

Default-off: nothing here is constructed unless ``jax.obs.devmem``
(engine CLI) or a bench phase asks for it.
"""

from __future__ import annotations

_MA_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "code_bytes"),
)


def kernel_memory(fn, *args, **kwargs) -> dict:
    """One compiled kernel's memory analysis as a plain dict.

    ``fn`` is a jitted callable; statics go in ``kwargs``.  Returns
    ``{"supported": False, "error": ...}`` when the backend has no
    ``memory_analysis`` (never raises into obs callers).  NOTE: costs
    one out-of-line compile (see module docstring)."""
    try:
        ma = fn.lower(*args, **kwargs).compile().memory_analysis()
    except Exception as e:
        return {"supported": False, "error": repr(e)}
    if ma is None:
        return {"supported": False, "error": "memory_analysis() is None"}
    out: dict = {"supported": True}
    for attr, key in _MA_FIELDS:
        v = getattr(ma, attr, None)
        if v is not None:
            out[key] = int(v)
    # transient working set of one dispatch: inputs + outputs + scratch
    # (aliased/donated bytes are counted inside argument_size already)
    out["total_bytes"] = (out.get("argument_bytes", 0)
                          + out.get("output_bytes", 0)
                          + out.get("temp_bytes", 0))
    return out


def state_nbytes(state) -> int:
    """Bytes of a pytree of device arrays (an engine's persistent
    state)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


def live_array_census(buckets: int = 24) -> dict:
    """One ``jax.live_arrays()`` walk: count + bytes, bucketed by
    power-of-two array size (bucket label = upper bound in bytes)."""
    import jax

    count = 0
    total = 0
    by_bucket: dict[str, list] = {}
    try:
        arrays = jax.live_arrays()
    except Exception as e:
        return {"supported": False, "error": repr(e)}
    for a in arrays:
        nb = int(getattr(a, "nbytes", 0) or 0)
        count += 1
        total += nb
        b = 1
        while b < nb:
            b <<= 1
        key = str(b)
        slot = by_bucket.get(key)
        if slot is None:
            slot = by_bucket[key] = [0, 0]
        slot[0] += 1
        slot[1] += nb
    top = sorted(by_bucket.items(), key=lambda kv: -kv[1][1])[:buckets]
    return {
        "supported": True,
        "count": count,
        "bytes": total,
        "buckets": {k: {"count": c, "bytes": nb} for k, (c, nb) in top},
    }


class DeviceMemoryLedger:
    """Aggregates kernel footprints + the sampled live-array census.

    ``analyze_engine(engine)`` runs once (post-warmup, pre-steady);
    ``collect(rec, dt_s)`` has the MetricsSampler collector signature
    and puts the ``"devmem"`` block on snapshot records, refreshing the
    census every ``census_every`` ticks.
    """

    def __init__(self, registry=None, census_every: int = 8):
        self.census_every = max(int(census_every), 1)
        self.kernels: dict[str, dict] = {}
        self.state_bytes = 0
        self._ticks = 0
        self._census: "dict | None" = None
        self._g_live = self._g_live_bytes = self._g_peak = None
        if registry is not None:
            self._g_live = registry.gauge(
                "streambench_devmem_live_arrays",
                "jax.live_arrays() count at the last census")
            self._g_live_bytes = registry.gauge(
                "streambench_devmem_live_bytes",
                "bytes held by live jax arrays at the last census")
            self._g_peak = registry.gauge(
                "streambench_devmem_peak_footprint_bytes",
                "persistent state + largest compiled kernel's "
                "argument+output+temp bytes (memory_analysis)")

    # ------------------------------------------------------------------
    def note_kernel(self, name: str, fn, *args, **kwargs) -> dict:
        """Analyze one kernel and record it under ``name``."""
        rep = kernel_memory(fn, *args, **kwargs)
        self.kernels[name] = rep
        if self._g_peak is not None:
            self._g_peak.set(self.peak_footprint_bytes())
        return rep

    def analyze_engine(self, engine) -> dict:
        """Analyze every hot kernel ``engine`` exposes via its
        ``_devmem_kernels()`` hook (fails closed: engines whose device
        hooks this ledger cannot describe return an empty list) and
        record the persistent state footprint."""
        self.state_bytes = state_nbytes(getattr(engine, "state", None))
        try:
            kernels = engine._devmem_kernels()
        except Exception as e:
            self.kernels["_error"] = {"supported": False,
                                      "error": repr(e)}
            kernels = []
        for name, fn, args, statics in kernels:
            self.note_kernel(name, fn, *args, **statics)
        return self.summary(census=False)

    def peak_footprint_bytes(self) -> int:
        """Persistent state + the largest single kernel working set —
        the per-engine peak-footprint ESTIMATE (concurrent in-flight
        dispatches can stack temps beyond it; stated, not hidden)."""
        worst = max((k.get("total_bytes", 0)
                     for k in self.kernels.values()
                     if k.get("supported")), default=0)
        return self.state_bytes + worst

    # ------------------------------------------------------------------
    def refresh_census(self) -> "dict | None":
        self._census = live_array_census()
        if self._g_live is not None and self._census.get("supported"):
            self._g_live.set(self._census["count"])
            self._g_live_bytes.set(self._census["bytes"])
        return self._census

    def collect(self, rec: dict, dt_s: float) -> None:
        """MetricsSampler collector: ``rec["devmem"]`` every tick, with
        the census refreshed every ``census_every`` ticks."""
        if self._ticks % self.census_every == 0:
            self.refresh_census()
        self._ticks += 1
        rec["devmem"] = self.summary()

    def summary(self, census: bool = True) -> dict:
        out: dict = {
            "state_bytes": self.state_bytes,
            "peak_footprint_bytes": self.peak_footprint_bytes(),
            "kernels": self.kernels,
        }
        if census and self._census is not None:
            out["live"] = self._census
        return out
