"""Multi-tenant observability: tenant-scoped metric views + the
shared-device time ledger and blame matrix (obs layer 9).

Everything before this layer assumed one workload per process: one
metric namespace, one SLO, one occupancy sampler.  The north star is
the opposite shape — N topologies sharing one process and one
accelerator — and the moment two engines share a device the first
operational question becomes *whose dispatch burned whose SLO budget*.
This module adds the tenant dimension in two pieces:

- :class:`TenantRegistry` — a thin view over one shared
  :class:`~streambench_tpu.obs.registry.MetricsRegistry` that injects a
  ``tenant=<name>`` label into every instrument it creates.  The
  registry already keys instruments by ``(name, sorted labels)``, so
  two tenants touching the same family get *disjoint* instruments for
  free — isolation is a property of the keying, not of any new
  bookkeeping, and one Prometheus scrape federates all tenants with
  the label doing the namespacing.  Engines, SLO trackers and query
  lifecycles take the view wherever they took the registry; they
  cannot tell the difference (same ``counter/gauge/histogram/
  predeclare`` surface).

- :class:`DeviceTimeLedger` — attribution of *device time* to tenants.
  Each tenant's :class:`~streambench_tpu.obs.occupancy.OccupancySampler`
  feeds its sampled ``block_until_ready`` busy windows into the ledger
  via :meth:`DeviceTimeLedger.busy_sink` (the same hook PR 11 used to
  feed the reach contention ratio), and each tenant's measured *wait*
  intervals — host queue wait for batch tenants, the reach server's
  admit→pop pairs for a serving tenant — land via :meth:`note_wait`.
  The **blame matrix** generalizes PR 11's single contention ratio to
  N×N: cell ``[victim][aggressor]`` is the overlap of the victim's
  wait intervals with the aggressor's merged device-busy windows.  The
  diagonal is self-inflicted wait (your own dispatches ahead of you);
  off-diagonal mass is cross-tenant interference — the evidence an
  admission controller acts on and a diagnose verdict names.

  Clock discipline: busy windows stamp ``perf_counter_ns`` (the
  occupancy sampler's clock) and the reach server's wait pairs stamp
  ``monotonic``-derived ns — on Linux both read CLOCK_MONOTONIC, so
  the intersection is well-defined; on platforms where they diverge
  the overlap degrades toward zero (missing evidence, never wrong
  evidence — the queryattr rule).

  The **partition invariant** (tested, same ±slack discipline as the
  PR 15 freshness hops): the per-tenant attributed busy totals must
  sum to the samplers' total measured busy time.  Attribution that
  loses or double-counts device time would silently skew every blame
  cell; :meth:`partition_check` makes the conservation law executable.

Default-off like every obs layer: nothing here is constructed unless
the host was started with tenants declared.
"""

from __future__ import annotations

import threading
from collections import deque

from streambench_tpu.obs.queryattr import _interval_overlap_ns

#: Bounded per-tenant interval rings (busy + wait): a week-long run
#: keeps the *recent* interference picture, while the ns totals (which
#: the partition check audits) accumulate unbounded alongside.
INTERVALS_MAX = 4096

#: Partition-check slack: sampled busy windows and their attributed
#: copies are the same integers, so the expected error is zero — the
#: slack only absorbs float/rounding noise, same discipline as the
#: freshness-hop reconciliation.
PARTITION_SLACK = 0.01


def _merge(intervals: list) -> list:
    """Sort-and-merge [start_ns, end_ns) pairs (the queryattr merge)."""
    if not intervals:
        return []
    intervals = sorted(intervals)
    merged = [list(intervals[0])]
    for s_ns, e_ns in intervals[1:]:
        if s_ns <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e_ns)
        else:
            merged.append([s_ns, e_ns])
    return merged


class TenantRegistry:
    """Tenant-scoped view over a shared :class:`MetricsRegistry`.

    Injects ``tenant=<name>`` into the labels of every instrument
    created through it, then delegates — the shared registry's
    ``(name, sorted labels)`` keying does the isolation.  A caller
    passing an explicit ``tenant`` label that disagrees with the view's
    own name is a bug caught loudly, not silently relabeled.
    """

    def __init__(self, registry, tenant: str):
        if not tenant:
            raise ValueError("tenant name must be non-empty")
        self.registry = registry
        self.tenant = str(tenant)

    def _labels(self, labels: "dict | None") -> dict:
        out = dict(labels or {})
        prev = out.setdefault("tenant", self.tenant)
        if prev != self.tenant:
            raise ValueError(
                f"instrument labeled tenant={prev!r} created through "
                f"the {self.tenant!r} view — cross-tenant label bleed")
        return out

    def counter(self, name: str, help: str = "", labels=None):
        return self.registry.counter(name, help,
                                     labels=self._labels(labels))

    def gauge(self, name: str, help: str = "", labels=None):
        return self.registry.gauge(name, help,
                                   labels=self._labels(labels))

    def histogram(self, name: str, help: str = "", lo: float = 1.0,
                  hi: float = 1e7, growth: float = 2 ** 0.25,
                  labels=None):
        return self.registry.histogram(name, help, lo=lo, hi=hi,
                                       growth=growth,
                                       labels=self._labels(labels))

    def predeclare(self, kind: str, name: str, help: str = "",
                   label_sets=None, **kw) -> None:
        self.registry.predeclare(
            kind, name, help,
            label_sets=[self._labels(ls) for ls in (label_sets or [None])],
            **kw)

    # federation helpers ------------------------------------------------
    def collect(self) -> list:
        """Only this tenant's instruments (label-filtered)."""
        return [m for m in self.registry.collect()
                if m.labels.get("tenant") == self.tenant]

    def render_prometheus(self) -> str:
        """The WHOLE shared exposition — a scrape is per-process, and
        the ``tenant=`` label is the namespacing, not the endpoint."""
        return self.registry.render_prometheus()


class DeviceTimeLedger:
    """Per-tenant device-time attribution + the N×N blame matrix.

    ``busy_sink(tenant)`` returns the callable an OccupancySampler's
    ``busy_sink`` hook wants; ``note_wait`` records a tenant's measured
    wait interval (host queue wait, reach admit→pop).  All writes are
    O(1) appends under one lock; the matrix is computed on demand
    (sampler cadence / bench close), never on the hot path.
    """

    def __init__(self, registry=None, max_intervals: int = INTERVALS_MAX):
        self._lock = threading.Lock()
        self._max = max(int(max_intervals), 1)
        self._busy: "dict[str, deque]" = {}
        self._wait: "dict[str, deque]" = {}
        self.busy_ns: "dict[str, int]" = {}
        self.wait_ns: "dict[str, int]" = {}
        self._reg = registry
        self._c_busy: dict = {}
        self._c_wait: dict = {}

    def _tenant(self, tenant: str) -> str:
        t = str(tenant)
        if t not in self._busy:
            self._busy[t] = deque(maxlen=self._max)
            self._wait[t] = deque(maxlen=self._max)
            self.busy_ns.setdefault(t, 0)
            self.wait_ns.setdefault(t, 0)
            if self._reg is not None:
                self._c_busy[t] = self._reg.counter(
                    "streambench_tenant_device_busy_ms_total",
                    "sampled device-busy time attributed to a tenant "
                    "(ms)", labels={"tenant": t})
                self._c_wait[t] = self._reg.counter(
                    "streambench_tenant_wait_ms_total",
                    "measured queue/stall wait attributed to a tenant "
                    "(ms)", labels={"tenant": t})
        return t

    def declare(self, tenant: str) -> None:
        """Pre-declare a tenant (zero-valued rows from the first
        scrape, the same lazy-instrument fix as the registry's
        ``predeclare``)."""
        with self._lock:
            self._tenant(tenant)

    # writes ------------------------------------------------------------
    def note_busy(self, tenant: str, t0_ns: int, t1_ns: int) -> None:
        if t1_ns <= t0_ns:
            return
        with self._lock:
            t = self._tenant(tenant)
            self._busy[t].append((int(t0_ns), int(t1_ns)))
            self.busy_ns[t] += int(t1_ns) - int(t0_ns)
            c = self._c_busy.get(t)
        if c is not None:
            c.inc((t1_ns - t0_ns) / 1e6)

    def note_wait(self, tenant: str, t0_ns: int, t1_ns: int) -> None:
        if t1_ns <= t0_ns:
            return
        with self._lock:
            t = self._tenant(tenant)
            self._wait[t].append((int(t0_ns), int(t1_ns)))
            self.wait_ns[t] += int(t1_ns) - int(t0_ns)
            c = self._c_wait.get(t)
        if c is not None:
            c.inc((t1_ns - t0_ns) / 1e6)

    def busy_sink(self, tenant: str):
        """The ``fn(t0_ns, t1_ns)`` an OccupancySampler's ``busy_sink``
        hook takes, bound to one tenant."""
        with self._lock:
            self._tenant(tenant)
        return lambda t0_ns, t1_ns: self.note_busy(tenant, t0_ns, t1_ns)

    # reads -------------------------------------------------------------
    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._busy)

    def merged_busy(self, tenant: str) -> list:
        with self._lock:
            raw = list(self._busy.get(str(tenant), ()))
        return _merge(raw)

    def blame_matrix(self) -> dict:
        """The N×N interference picture.

        ``matrix_ms[victim][aggressor]`` = victim's wait intervals ∩
        aggressor's merged busy windows, in ms.  Also reports each
        victim's total wait, each tenant's attributed busy total, and
        ``offdiag_ratio`` — cross-tenant blame mass over total blame
        mass (0.0 = everyone only waits on themselves; the regress key
        ``tenant_blame_offdiag_ratio`` reads this).
        """
        with self._lock:
            names = sorted(self._busy)
            waits = {t: list(self._wait[t]) for t in names}
            raw_busy = {t: list(self._busy[t]) for t in names}
            busy_ns = dict(self.busy_ns)
            wait_ns = dict(self.wait_ns)
        merged = {t: _merge(raw_busy[t]) for t in names}
        matrix: "dict[str, dict[str, float]]" = {}
        diag = offdiag = 0.0
        for victim in names:
            row: dict[str, float] = {}
            for aggressor in names:
                ov = 0
                if merged[aggressor]:
                    for w0, w1 in waits[victim]:
                        ov += _interval_overlap_ns(
                            w0, w1, merged[aggressor])
                ms = round(ov / 1e6, 3)
                row[aggressor] = ms
                if victim == aggressor:
                    diag += ms
                else:
                    offdiag += ms
            matrix[victim] = row
        total = diag + offdiag
        return {
            "tenants": names,
            "matrix_ms": matrix,
            "wait_ms": {t: round(wait_ns[t] / 1e6, 3) for t in names},
            "busy_ms": {t: round(busy_ns[t] / 1e6, 3) for t in names},
            "offdiag_ratio": round(offdiag / total, 4) if total else 0.0,
        }

    def aggressor_for(self, victim: str) -> "tuple[str, float] | None":
        """The OTHER tenant whose busy windows overlap this victim's
        waits the most: ``(name, blame_ms)``, or None when no
        cross-tenant blame exists — an admission controller must not
        act on absent evidence."""
        m = self.blame_matrix()
        row = m["matrix_ms"].get(str(victim))
        if not row:
            return None
        best = None
        for aggressor, ms in row.items():
            if aggressor == str(victim) or ms <= 0:
                continue
            if best is None or ms > best[1]:
                best = (aggressor, ms)
        return best

    # invariants --------------------------------------------------------
    def partition_check(self, sampler_busy_ns,
                        slack: float = PARTITION_SLACK) -> dict:
        """Conservation law: Σ per-tenant attributed busy ==
        Σ samplers' measured busy, within ``slack`` (relative).

        ``sampler_busy_ns`` is ``{tenant: busy_ns}`` read straight off
        each tenant's OccupancySampler — the ground truth the ledger's
        attribution must neither lose nor double-count.  Returns the
        check record the bench artifact commits; ``ok`` False means
        attribution is broken and every blame cell is suspect.
        """
        with self._lock:
            attributed = dict(self.busy_ns)
        total_attr = sum(attributed.values())
        total_meas = sum(int(v) for v in sampler_busy_ns.values())
        err = (abs(total_attr - total_meas) / total_meas
               if total_meas else (1.0 if total_attr else 0.0))
        per_tenant = {}
        ok = err <= slack
        for t, meas in sampler_busy_ns.items():
            a = attributed.get(str(t), 0)
            t_err = abs(a - int(meas)) / int(meas) if meas else (
                1.0 if a else 0.0)
            per_tenant[str(t)] = {
                "attributed_ms": round(a / 1e6, 3),
                "measured_ms": round(int(meas) / 1e6, 3),
                "rel_err": round(t_err, 6),
            }
            ok = ok and t_err <= slack
        return {
            "ok": ok,
            "attributed_ms": round(total_attr / 1e6, 3),
            "measured_ms": round(total_meas / 1e6, 3),
            "rel_err": round(err, 6),
            "slack": slack,
            "tenants": per_tenant,
        }

    def summary(self) -> dict:
        """The ``multitenant`` block a metrics.jsonl snapshot carries:
        the blame matrix plus interval census."""
        m = self.blame_matrix()
        with self._lock:
            m["intervals"] = {
                t: {"busy": len(self._busy[t]),
                    "wait": len(self._wait[t])}
                for t in sorted(self._busy)}
        return m
