"""Per-window latency attribution: where inside the engine each window's
latency was spent.

The benchmark's headline metric is one opaque number per window —
``time_updated - window_ts`` (``core.clj:149``) — and the telemetry
layer so far only reports its aggregate distribution.  This module makes
the reference's per-tuple timestamp idiom (SURVEY.md §5.1: stamps ride
the dataflow) first-class end to end: every emitted window's journey is
stamped at five points and the end-to-end latency decomposed into

- ``ingest_ms``  — window start until its LAST contributing event was
  read off the journal (includes the window's own span: a 10 s
  tumbling window cannot complete before it has existed for 10 s, so
  this segment dominating is the *healthy* shape; the separate
  ``arrival_span_ms`` histogram — last read minus FIRST read — shows
  how long events for one window kept arriving)
- ``encode_ms``  — last read until the last event was encoded (encode
  residency, read-ahead included)
- ``fold_ms``    — last encode until the last device fold dispatch
- ``flush_ms``   — last fold until ``flush()`` submitted the window's
  rows to the sink writer (device-drain + 1 Hz cadence residency)
- ``sink_ms``    — submit until the writer's actual write stamp
  (queue wait + Redis round trip + any outage backoff)

Each writeback of a window contributes one sample per segment, so the
five segments sum to exactly that write's end-to-end latency (clamping
of clock jitter aside) and the per-segment distributions explain the
aggregate one.  Segments land in the shared :class:`MetricsRegistry`
as one ``streambench_window_segment_ms`` histogram family (label
``segment=...``), are journaled in every ``metrics.jsonl`` snapshot
under ``"attribution"``, and are rendered by
``python -m streambench_tpu.obs attribution`` (with A/B diff).

Cost model (SALSA's bar: cheap enough to leave on): one ``np.unique``
over the batch's window ids per fold (~tens of µs at B=8192), dict
upkeep per open window, O(1) histogram observes per written window.
Default-off — the engine carries only a ``None`` attribute until
``attach_obs(..., lifecycle=True)``, so the disabled hot path is
byte-for-byte the pre-attribution one.
"""

from __future__ import annotations

import threading

import numpy as np

from streambench_tpu.utils.ids import now_ms

#: Segment order is pipeline order; renderers preserve it.
SEGMENTS = ("ingest", "encode", "fold", "flush", "sink")

_SEGMENT_HELP = {
    "ingest": "window start -> last contributing event read",
    "encode": "last read -> last event encoded",
    "fold": "last encode -> last device fold dispatch",
    "flush": "last fold -> flush() submit to the sink writer",
    "sink": "writer submit -> actual sink write (time_updated)",
}


class WindowLifecycle:
    """Tracks per-window stage stamps and feeds segment histograms.

    One instance per engine, shared across the host loop (``note_fold``,
    ``note_flush``), the ingest encode thread (stamps ride the batches,
    see ``engine.ingest``), and the sink writer thread
    (``note_written``) — one lock guards the window table; the
    histograms carry their own.

    The table is bounded two ways: windows closed past
    ``lateness + 2 x divisor`` behind the newest seen window are dropped
    at write time, and a hard ``max_windows`` cap evicts oldest-first
    (evictions are counted, never silent).
    """

    def __init__(self, registry, divisor_ms: int, lateness_ms: int = 0,
                 max_windows: int = 8192):
        self.divisor_ms = max(int(divisor_ms), 1)
        self.lateness_ms = max(int(lateness_ms), 0)
        self.max_windows = max(int(max_windows), 16)
        self._lock = threading.Lock()
        # abs_window_ts -> [first_read_ms, last_read_ms, last_encode_ms,
        #                   last_fold_ms, flush_submit_ms | None]
        self._windows: dict[int, list] = {}
        self._max_wid_ts: int | None = None
        self.windows_evicted = 0
        self.writes_observed = 0
        self.writes_untracked = 0   # written windows never seen folding
        #   (restored-from-checkpoint pending, reclaims after eviction)
        # Tighter growth than the general-purpose histograms (~9% per
        # bucket vs ~19%): the attribution contract is "segment p50s
        # explain the e2e p50", and bucket error is the noise floor of
        # that comparison.  ~190 buckets — still O(1) observe.
        growth = 2 ** 0.125
        self._hists = {
            seg: registry.histogram(
                "streambench_window_segment_ms",
                "window latency attribution by segment (ms)",
                lo=0.1, hi=1e7, growth=growth, labels={"segment": seg})
            for seg in SEGMENTS}
        # e2e over the SAME tracked windows, so segment sums and the
        # end-to-end distribution are apples-to-apples (the writeback
        # histogram streambench_window_latency_ms also counts untracked
        # windows; this one never does)
        self._e2e = registry.histogram(
            "streambench_window_e2e_ms",
            "end-to-end latency of attribution-tracked windows (ms)",
            lo=0.1, hi=1e7, growth=growth)
        # NOT part of the partition: how long one window's events kept
        # arriving (last read - first read) — distinguishes "the window
        # was still filling" from "one late straggler reopened it"
        self._span = registry.histogram(
            "streambench_window_arrival_span_ms",
            "first-to-last journal read of one window's events (ms)",
            lo=0.1, hi=1e7, growth=growth)

    # ------------------------------------------------------------------
    def stamp_encoded(self, batches, read_ms: int | None = None) -> None:
        """Hang read/encode wall stamps on freshly encoded batches.

        Called by the engine's encode halves (serial paths: read and
        encode are adjacent, the read stamp defaults to now — the gap is
        bounded by ``buffer_timeout_ms``, noise against a window span)
        and overridden with the TRUE read time by the staged ingest
        pipeline's encode stage, where read-ahead makes the gap real.
        """
        now = now_ms()
        if read_ms is None:
            read_ms = now
        for b in batches:
            if getattr(b, "_lc_read_ms", None) is None:
                b._lc_read_ms = read_ms
            b._lc_encode_ms = now

    # ------------------------------------------------------------------
    def note_fold(self, batch) -> None:
        """One encoded batch was dispatched to the device (host loop,
        called from the engine's watermark-note hook).  Attributes the
        batch's read/encode stamps to every window its valid rows touch:
        first-read keeps the min, encode/fold keep the max."""
        n = batch.n
        if not n:
            return
        vt = batch.event_time[:n]
        v = batch.valid[:n]
        if not v.all():
            vt = vt[v]
            if vt.size == 0:
                return
        base = batch.base_time_ms
        wids = np.unique(vt // self.divisor_ms)
        now = now_ms()
        read = getattr(batch, "_lc_read_ms", None) or now
        enc = getattr(batch, "_lc_encode_ms", None) or now
        with self._lock:
            for wid in wids.tolist():
                ts = base + int(wid) * self.divisor_ms
                ent = self._windows.get(ts)
                if ent is None:
                    self._windows[ts] = [read, read, enc, now, None]
                else:
                    if read < ent[0]:
                        ent[0] = read
                    if read > ent[1]:
                        ent[1] = read
                    if enc > ent[2]:
                        ent[2] = enc
                    ent[3] = now
                if self._max_wid_ts is None or ts > self._max_wid_ts:
                    self._max_wid_ts = ts
            # hard cap: evict oldest-first (insertion order tracks time)
            while len(self._windows) > self.max_windows:
                self._windows.pop(next(iter(self._windows)))
                self.windows_evicted += 1

    def note_flush(self, window_ts) -> None:
        """``flush()`` is submitting these windows' rows to the sink
        writer now (host loop).  ``window_ts`` is any iterable of
        absolute window timestamps; duplicates are fine."""
        now = now_ms()
        with self._lock:
            for ts in set(int(t) for t in window_ts):
                ent = self._windows.get(ts)
                if ent is not None:
                    ent[4] = now

    def note_written(self, window_ts, stamp: int) -> None:
        """These windows' rows actually landed in the sink at ``stamp``
        (writer thread).  Observes one sample per segment per window and
        retires windows closed well past lateness."""
        horizon = None
        with self._lock:
            if self._max_wid_ts is not None:
                horizon = (self._max_wid_ts - self.lateness_ms
                           - 2 * self.divisor_ms)
            for ts in window_ts:
                ts = int(ts)
                ent = self._windows.get(ts)
                if ent is None:
                    self.writes_untracked += 1
                    continue
                self.writes_observed += 1
                first_read, last_read, last_enc, last_fold, flush_sub = ent
                if flush_sub is None:
                    flush_sub = last_fold   # direct write, no 1 Hz hop
                e2e = stamp - ts
                segs = (
                    ("ingest", last_read - ts),
                    ("encode", last_enc - last_read),
                    ("fold", last_fold - last_enc),
                    ("flush", flush_sub - last_fold),
                    ("sink", stamp - flush_sub),
                )
                for name, ms in segs:
                    self._hists[name].observe(max(float(ms), 0.0))
                self._e2e.observe(max(float(e2e), 0.0))
                self._span.observe(max(float(last_read - first_read),
                                       0.0))
                if horizon is not None and ts < horizon:
                    del self._windows[ts]       # closed for good
                else:
                    ent[4] = None               # may be written again

    # ------------------------------------------------------------------
    def summary(self) -> dict:
        """The ``"attribution"`` block a metrics.jsonl snapshot carries:
        per-segment histogram summaries + the matched e2e distribution +
        table health counters."""
        with self._lock:
            open_windows = len(self._windows)
        return {
            "writes_observed": self.writes_observed,
            "writes_untracked": self.writes_untracked,
            "open_windows": open_windows,
            "windows_evicted": self.windows_evicted,
            "e2e_ms": self._e2e.summary(),
            "arrival_span_ms": self._span.summary(),
            "segments": {seg: self._hists[seg].summary()
                         for seg in SEGMENTS},
        }


def segment_help(seg: str) -> str:
    """Human description of one segment (report rendering)."""
    return _SEGMENT_HELP.get(seg, "")
