"""Run reports from ``metrics.jsonl`` time series: summarize and diff.

The reporting half of the telemetry subsystem: ``summarize`` folds one
run's snapshot stream into a scalar summary, ``render_report`` prints it
human-readable, and ``render_diff`` lines two runs up column-for-column
with absolute and relative deltas — the before/after reading every perf
PR needs (the reference has nothing like it; its numbers are read off
scattered engine logs by hand).
"""

from __future__ import annotations

import json


def load_records(path: str, stitch_rotated: bool = True) -> list[dict]:
    """Parse a metrics.jsonl file, skipping torn/blank lines (a killed
    run can leave a partial last record; the series before it is still
    a valid report).

    ``jax.metrics.max.bytes`` rotation moves the OLDER half of a long
    run to ``<path>.1`` — when that file exists its records are
    stitched in FIRST, so ``report``/``diff`` cover the whole run
    instead of silently summarizing only the post-rotation tail
    (events/s means and fault totals were wrong for exactly the long
    chaos sweeps the rotation exists for)."""
    import os

    paths = [path]
    if stitch_rotated and os.path.exists(path + ".1"):
        paths.insert(0, path + ".1")
    out: list[dict] = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    return out


def summarize(records: list[dict], path: str = "") -> dict:
    """Fold one run's records into the scalar summary the renderers use."""
    snaps = [r for r in records if r.get("kind") in ("snapshot", "final")]
    final = next((r for r in reversed(records)
                  if r.get("kind") == "final"), None)
    last = final or (snaps[-1] if snaps else {})
    events_ann = [r for r in records if r.get("kind") == "event"]
    rates = [r["events_per_s"] for r in snaps
             if isinstance(r.get("events_per_s"), (int, float))
             and r["events_per_s"] > 0]

    def col_max(key):
        vals = [r[key] for r in snaps
                if isinstance(r.get(key), (int, float))]
        return max(vals) if vals else None

    stages: dict[str, dict] = {}
    for r in snaps:
        for name, d in (r.get("stages") or {}).items():
            agg = stages.setdefault(name, {"calls": 0, "ms": 0.0})
            agg["calls"] += d.get("calls", 0)
            agg["ms"] = round(agg["ms"] + d.get("ms", 0.0), 3)
    latency = None
    for r in reversed(snaps):
        if r.get("latency_ms"):
            latency = r["latency_ms"]
            break

    def last_block(key):
        for r in reversed(snaps):
            if isinstance(r.get(key), dict):
                return r[key]
        return None

    return {
        "path": path,
        "snapshots": len(snaps),
        "duration_s": round(last.get("uptime_ms", 0) / 1000.0, 1),
        "events": last.get("events"),
        "windows_written": last.get("windows_written"),
        "events_per_s_mean": (round(sum(rates) / len(rates), 1)
                              if rates else None),
        "events_per_s_max": max(rates) if rates else None,
        "backlog_bytes_max": col_max("backlog_bytes"),
        "watermark_lag_ms_max": col_max("watermark_lag_ms"),
        "sink_dirty_rows_max": col_max("sink_dirty_rows"),
        "rss_bytes_max": col_max("rss_bytes"),
        # the ru_maxrss fallback path journals PEAK rss under its own
        # key (obs.sampler.rss_sample) — keep the two apart here too
        "rss_peak_bytes_max": col_max("rss_peak_bytes"),
        "latency_ms": latency,
        # data-path obs (layer 4): newest transfer / device-memory /
        # shard-skew blocks, when those ledgers were armed
        "xfer": last_block("xfer"),
        "devmem": last_block("devmem"),
        "shard_skew": last_block("shard_skew"),
        # sketch-memory census (ISSUE 13): counter-plane family + state
        # bytes, journaled by engines exposing sketch_summary()
        "sketch": last_block("sketch"),
        # serving-tier obs (layer 5, jax.obs.query): newest per-query
        # attribution block the reach collector journals
        "reach_query": last_block("reach_query"),
        # multi-tenant host (layer 9): per-tenant namespaces, burn
        # gauges, the device-time blame matrix, and the admission
        # controller's decision counters
        "tenants": last_block("tenants"),
        "slo_tenants": last_block("slo_tenants"),
        "multitenant": last_block("multitenant"),
        "admission": last_block("admission"),
        # Kafka delivery ledger (ISSUE 20): the broker-edge accounting
        # block the kafka_collector journals (produced/delivered/
        # redeliveries/retries + consumer lag)
        "kafka": last_block("kafka"),
        "faults": last.get("faults") or {},
        "stages": stages,
        "annotations": [{k: r.get(k) for k in ("event", "uptime_ms")}
                        | {k: v for k, v in r.items()
                           if k not in ("kind", "ts_ms")}
                        for r in events_ann],
        "run_stats": (final or {}).get("run_stats"),
    }


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.1f}"
    if isinstance(v, int):
        return f"{v:,}"
    return str(v)


# (label, summary key) rows shared by report and diff so the two views
# never drift apart
_SCALAR_ROWS = (
    ("duration_s", "duration_s"),
    ("snapshots", "snapshots"),
    ("events", "events"),
    ("events/s mean", "events_per_s_mean"),
    ("events/s max", "events_per_s_max"),
    ("windows written", "windows_written"),
    ("backlog bytes max", "backlog_bytes_max"),
    ("watermark lag ms max", "watermark_lag_ms_max"),
    ("sink dirty rows max", "sink_dirty_rows_max"),
    ("rss bytes max", "rss_bytes_max"),
    ("rss PEAK bytes max", "rss_peak_bytes_max"),
)


def _latency_rows(s: dict) -> list[tuple[str, object]]:
    lat = s.get("latency_ms") or {}
    return [(f"latency {k}", lat.get(k))
            for k in ("p50", "p95", "p99", "max", "count")]


def render_report(s: dict) -> str:
    lines = [f"telemetry report: {s['path'] or '(records)'}"]
    for label, key in _SCALAR_ROWS:
        lines.append(f"  {label:<22} {_fmt(s.get(key))}")
    for label, v in _latency_rows(s):
        lines.append(f"  {label:<22} {_fmt(v)}")
    xfer = s.get("xfer")
    if xfer and xfer.get("formats"):
        lines.append("  transfer (host->device bytes, measured):")
        for fmt, d in sorted(xfer["formats"].items()):
            lines.append(
                f"    {fmt:<10} {_fmt(d.get('dispatches')):>8} disp "
                f"{_fmt(d.get('events')):>12} ev "
                f"{_fmt(d.get('bytes_per_event')):>10} B/ev "
                f"({_fmt(d.get('col_bytes_per_event'))} B/ev int32)")
        if xfer.get("packed_unpacked_ratio") is not None:
            lines.append(f"    packed/unpacked ratio  "
                         f"{xfer['packed_unpacked_ratio']} "
                         f"({xfer.get('ratio_basis')})")
        if xfer.get("xfer_mb_s") is not None:
            lines.append(f"    sampled link rate      "
                         f"{_fmt(xfer['xfer_mb_s'])} MB/s over "
                         f"{_fmt(xfer.get('sampled'))} timed transfers")
    dm = s.get("devmem")
    if dm:
        lines.append("  memory (device, measured):")
        lines.append(f"    state bytes            "
                     f"{_fmt(dm.get('state_bytes'))}")
        lines.append(f"    peak footprint bytes   "
                     f"{_fmt(dm.get('peak_footprint_bytes'))}")
        for name, k in sorted((dm.get("kernels") or {}).items()):
            if k.get("supported"):
                lines.append(f"    kernel {name:<16} "
                             f"{_fmt(k.get('total_bytes')):>12} B "
                             f"(arg {_fmt(k.get('argument_bytes'))} + "
                             f"out {_fmt(k.get('output_bytes'))} + "
                             f"tmp {_fmt(k.get('temp_bytes'))})")
        live = dm.get("live")
        if live and live.get("supported"):
            lines.append(f"    live arrays            "
                         f"{_fmt(live.get('count'))} holding "
                         f"{_fmt(live.get('bytes'))} B")
    sk = s.get("shard_skew")
    if sk:
        lines.append("  shard skew (routed rows per campaign shard):")
        lines.append(f"    rows {sk.get('rows')}  dropped "
                     f"{sk.get('dropped')}  imbalance "
                     f"{_fmt(sk.get('imbalance_ratio'))}")
    sm = s.get("sketch")
    if sm:
        lines.append("  sketch memory (counter plane, measured):")
        lines.append(f"    mode {sm.get('mode')}  stages "
                     f"{sm.get('stages')}  state bytes "
                     f"{_fmt(sm.get('state_bytes'))}")
        if sm.get("merged_pairs") is not None:
            lines.append(f"    merged pairs {_fmt(sm.get('merged_pairs'))}"
                         f"  quads {_fmt(sm.get('merged_quads'))} of "
                         f"{_fmt(sm.get('cells'))} cells")
    rqf = (s.get("reach_query") or {}).get("freshness")
    if rqf and rqf.get("hops"):
        # fleet freshness ledger (ISSUE 15): per-hop p99 of the age of
        # the evidence behind every served reply
        lines.append("  reply freshness (age by hop, p99 ms):")
        lines.append("    " + "  ".join(
            f"{hop} {_fmt((rqf['hops'].get(hop) or {}).get('p99'))}"
            for hop in ("fold_lag", "ship_wait", "tail_lag", "serve",
                        "total")))
        clock = rqf.get("clock")
        if clock:
            lines.append(
                f"    clock offset {_fmt(clock.get('offset_ms'))} ms "
                f"+-{_fmt(clock.get('uncertainty_ms'))} "
                f"({'applied' if clock.get('applied') else 'NOT applied'})")
    rqo = (s.get("reach_query") or {}).get("query_obs")
    if rqo:
        lines.append("  reach query attribution (submit -> reply):")
        lines.append(f"    tracked {_fmt(rqo.get('served_records'))}  "
                     f"shed {_fmt(rqo.get('shed_records'))}  "
                     f"slow {_fmt(rqo.get('slow_queries'))}")
        for seg, summ in (rqo.get("segments") or {}).items():
            if summ.get("count"):
                lines.append(
                    f"    seg {seg:<9} p50 {_fmt(summ.get('p50')):>10} "
                    f"ms  p99 {_fmt(summ.get('p99')):>10} ms")
        e2e = rqo.get("e2e_ms") or {}
        if e2e.get("count"):
            lines.append(
                f"    e2e           p50 {_fmt(e2e.get('p50')):>10} ms  "
                f"p99 {_fmt(e2e.get('p99')):>10} ms")
        cont = rqo.get("contention") or {}
        if cont:
            lines.append(
                f"    contention ratio {_fmt(cont.get('ratio'))} "
                f"(queue wait {_fmt(cont.get('queue_wait_ms'))} ms, "
                f"ingest overlap {_fmt(cont.get('ingest_overlap_ms'))} "
                "ms)")
    tn = s.get("tenants")
    if tn:
        mt = s.get("multitenant") or {}
        slo_t = s.get("slo_tenants") or {}
        busy = mt.get("busy_ms") or {}
        wait = mt.get("wait_ms") or {}
        lines.append("  tenants (disjoint namespaces, one device):")
        lines.append(f"    {'tenant':<8} {'kind':<8} {'events':>10} "
                     f"{'folded':>7} {'busy ms':>11} {'wait ms':>11} "
                     f"{'burn':>6}")
        for name in sorted(tn):
            t = tn[name] if isinstance(tn[name], dict) else {}
            fast = [b.get("fast")
                    for b in ((slo_t.get(name) or {}).get("burn")
                              or {}).values()
                    if isinstance(b, dict)
                    and isinstance(b.get("fast"), (int, float))]
            lines.append(
                f"    {name:<8} {t.get('kind') or '-':<8} "
                f"{_fmt(t.get('events')):>10} "
                f"{_fmt(t.get('folded_batches')):>7} "
                f"{_fmt(busy.get(name)):>11} "
                f"{_fmt(wait.get(name)):>11} "
                f"{_fmt(round(max(fast), 2) if fast else None):>6}")
        if mt.get("offdiag_ratio") is not None:
            ok = (mt.get("partition") or {}).get("ok")
            lines.append(
                f"    blame offdiag {_fmt(mt['offdiag_ratio'])}  "
                f"partition {'ok' if ok else 'FAIL' if ok is False else '-'}")
        adm = s.get("admission")
        if adm:
            lines.append(
                f"    admission: defers {_fmt(adm.get('defers'))}  "
                f"sheds {_fmt(adm.get('sheds'))}  "
                f"releases {_fmt(adm.get('releases'))}  "
                f"deferred {_fmt(adm.get('batches_deferred'))}  "
                f"shed {_fmt(adm.get('batches_shed'))}")
    kf = s.get("kafka")
    if kf:
        lines.append(
            "  kafka edge (broker delivery ledger):")
        lines.append(
            f"    produced {_fmt(kf.get('produced'))}  "
            f"delivered {_fmt(kf.get('delivered'))}  "
            f"redeliveries {_fmt(kf.get('redeliveries'))}  "
            f"lag {_fmt(kf.get('consumer_lag'))}")
        lines.append(
            f"    produce retries {_fmt(kf.get('produce_retries'))}  "
            f"consume retries {_fmt(kf.get('consume_retries'))}  "
            f"dr failures {_fmt(kf.get('dr_failures'))}  "
            f"backoff ms {_fmt(kf.get('broker_down_ms'))}")
    if s["faults"]:
        lines.append("  faults:")
        for k in sorted(s["faults"]):
            lines.append(f"    {k:<26} {_fmt(s['faults'][k])}")
    if s["stages"]:
        lines.append("  stages (calls, total_ms):")
        width = max(len(n) for n in s["stages"])
        for name, agg in sorted(s["stages"].items(),
                                key=lambda kv: -kv[1]["ms"]):
            lines.append(f"    {name:<{width}}  {agg['calls']:>8}  "
                         f"{agg['ms']:>12.1f}")
    if s["annotations"]:
        lines.append("  events:")
        for a in s["annotations"]:
            extras = {k: v for k, v in a.items()
                      if k not in ("event", "uptime_ms")}
            lines.append(f"    +{(a.get('uptime_ms') or 0) / 1000.0:.1f}s "
                         f"{a.get('event')} {extras or ''}".rstrip())
    if s.get("run_stats"):
        lines.append(f"  run_stats: {json.dumps(s['run_stats'])}")
    return "\n".join(lines)


# ----------------------------------------------------------------------
# serving-layer query attribution (obs.queryattr): the "reach_query"
# block each snapshot carries, rendered by `obs serve A [B]`
def summarize_serve(records: list[dict], path: str = "") -> dict:
    """Newest ``reach_query`` block out of one run's records (final
    record first, torn tail falls back to the last intact snapshot)."""
    rq = None
    for r in reversed(records):
        if isinstance(r.get("reach_query"), dict):
            rq = r["reach_query"]
            break
    return {"path": path, "reach_query": rq}


def render_serve(s: dict) -> str:
    """One run's serving-layer table: admission/shed counters, the
    segment decomposition, contention, and the slow-query log tail."""
    rq = s.get("reach_query") or {}
    qobs = rq.get("query_obs")
    lines = [f"reach serving attribution: {s['path'] or '(records)'}"]
    if not qobs:
        lines.append("  no reach_query records "
                     "(run --engine reach with jax.obs.query: true)")
        return "\n".join(lines)
    lines.append(f"  served {_fmt(rq.get('served'))}  "
                 f"shed {_fmt(rq.get('shed'))}  "
                 f"rejected {_fmt(rq.get('rejected'))}  "
                 f"dispatches {_fmt(rq.get('dispatches'))}  "
                 f"queue high-water {_fmt(rq.get('queue_high_water'))}"
                 f"/{_fmt(rq.get('queue_depth'))}")
    # scale-out rows (ISSUE 14): planes epoch/staleness + cache hit mix
    if rq.get("plane_epoch") is not None:
        stale = rq.get("staleness_ms")
        lines.append(
            f"  plane epoch {_fmt(rq['plane_epoch'])}"
            + (f"  staleness {_fmt(stale)} ms"
               f"/{_fmt(rq.get('max_staleness_ms'))} bound"
               if stale is not None else "")
            + (f"  stale sheds {_fmt(rq['shed_stale'])}"
               if rq.get("shed_stale") else ""))
    cache = rq.get("cache")
    if isinstance(cache, dict):
        lines.append(
            f"  cache hit ratio {_fmt(cache.get('hit_ratio'))} "
            f"({_fmt(cache.get('hits'))} hits / "
            f"{_fmt(cache.get('misses'))} misses; "
            f"{_fmt(cache.get('entries'))}/{_fmt(cache.get('capacity'))}"
            f" entries, {_fmt(cache.get('evictions'))} evicted, "
            f"{_fmt(cache.get('invalidations'))} epoch invalidations)")
    fr = rq.get("freshness")
    if isinstance(fr, dict) and fr.get("hops"):
        lines.append("  reply freshness p99 (ms): " + "  ".join(
            f"{hop} {_fmt((fr['hops'].get(hop) or {}).get('p99'))}"
            for hop in ("fold_lag", "ship_wait", "tail_lag", "serve",
                        "total"))
            + f"  (high water {_fmt(fr.get('high_water_ms'))})")
    lines.append(f"  lifecycle records: {_fmt(qobs.get('served_records'))}"
                 f" served + {_fmt(qobs.get('shed_records'))} shed")
    segs = qobs.get("segments") or {}
    p50_sum = sum(_p50(v) for v in segs.values())
    lines.append(f"  {'segment':<10} {'count':>8} {'p50_ms':>12} "
                 f"{'p95_ms':>12} {'p99_ms':>12} {'share':>7}")
    for name, summ in segs.items():
        share = (f"{_p50(summ) / p50_sum * 100:.1f}%" if p50_sum else "-")
        lines.append(
            f"  {name:<10} {_fmt(summ.get('count') or 0):>8} "
            f"{_fmt(summ.get('p50')):>12} {_fmt(summ.get('p95')):>12} "
            f"{_fmt(summ.get('p99')):>12} {share:>7}")
    e2e = qobs.get("e2e_ms") or {}
    lines.append(f"  {'e2e':<10} {_fmt(e2e.get('count') or 0):>8} "
                 f"{_fmt(e2e.get('p50')):>12} {_fmt(e2e.get('p95')):>12} "
                 f"{_fmt(e2e.get('p99')):>12}")
    if _p50(e2e):
        cov = p50_sum / _p50(e2e) * 100
        lines.append(f"  segment p50 sum {p50_sum:,.1f} ms = {cov:.1f}% "
                     "of e2e p50")
    shed_q = qobs.get("shed_queue_ms") or {}
    if shed_q.get("count"):
        lines.append(f"  shed queue wait    p50 {_fmt(shed_q.get('p50'))}"
                     f" ms over {_fmt(shed_q['count'])} shed records")
    cont = qobs.get("contention") or {}
    lines.append(f"  contention ratio {_fmt(cont.get('ratio'))} "
                 f"(ingest overlap {_fmt(cont.get('ingest_overlap_ms'))}"
                 f" ms of {_fmt(cont.get('queue_wait_ms'))} ms queue "
                 f"wait; busy evidence: "
                 f"{_fmt(cont.get('busy_intervals'))} windows)")
    if qobs.get("slow_queries"):
        lines.append(f"  slow queries {_fmt(qobs['slow_queries'])} "
                     f"(> {_fmt(qobs.get('slo_ms'))} ms; "
                     f"{_fmt(qobs.get('slowlog_evicted'))} evicted)")
        for e in (qobs.get("slowlog") or [])[-5:]:
            lines.append(
                f"    id={e.get('id')} e2e {_fmt(e.get('e2e_ms'))} ms = "
                f"queue {_fmt(e.get('queue_ms'))} + batch "
                f"{_fmt(e.get('batch_ms'))} + dispatch "
                f"{_fmt(e.get('dispatch_ms'))} + reply "
                f"{_fmt(e.get('reply_ms'))}")
    return "\n".join(lines)


def render_serve_diff(a: dict, b: dict) -> str:
    """Two runs' serving segment p50/p99 side by side (B vs A)."""
    lines = ["reach serving diff:",
             f"  A: {a['path']}",
             f"  B: {b['path']}"]
    qa = (a.get("reach_query") or {}).get("query_obs")
    qb = (b.get("reach_query") or {}).get("query_obs")
    if not qa or not qb:
        lines.append("  missing reach_query records in "
                     + ("both runs" if not (qa or qb)
                        else ("A" if not qa else "B")))
        return "\n".join(lines)
    lines.append(f"  {'segment':<10} {'A p50':>12} {'B p50':>12} "
                 f"{'delta':>12} {'A p99':>12} {'B p99':>12}")
    segs = list((qa.get("segments") or {}).keys())
    for extra in (qb.get("segments") or {}):
        if extra not in segs:
            segs.append(extra)
    rows = [(name, (qa.get("segments") or {}).get(name),
             (qb.get("segments") or {}).get(name)) for name in segs]
    rows.append(("e2e", qa.get("e2e_ms"), qb.get("e2e_ms")))
    for name, sa, sb in rows:
        pa, pb = _p50(sa), _p50(sb)
        lines.append(
            f"  {name:<10} {_fmt((sa or {}).get('p50')):>12} "
            f"{_fmt((sb or {}).get('p50')):>12} "
            f"{_fmt(round(pb - pa, 3)):>12} "
            f"{_fmt((sa or {}).get('p99')):>12} "
            f"{_fmt((sb or {}).get('p99')):>12}")
    ca = (qa.get("contention") or {}).get("ratio")
    cb = (qb.get("contention") or {}).get("ratio")
    lines.append(f"  contention ratio: A {_fmt(ca)}  B {_fmt(cb)}")
    ha = ((a.get("reach_query") or {}).get("cache") or {}).get(
        "hit_ratio")
    hb = ((b.get("reach_query") or {}).get("cache") or {}).get(
        "hit_ratio")
    if ha is not None or hb is not None:
        lines.append(f"  cache hit ratio:  A {_fmt(ha)}  B {_fmt(hb)}")
    return "\n".join(lines)


# ----------------------------------------------------------------------
# per-window latency attribution (obs.lifecycle): the "attribution"
# block each snapshot carries, summarized from the run's last word
def summarize_attribution(records: list[dict], path: str = "") -> dict:
    """Pull the newest ``attribution`` block out of one run's records
    (the final record normally carries the complete picture; a torn
    tail falls back to the last intact snapshot)."""
    att = None
    for r in reversed(records):
        if isinstance(r.get("attribution"), dict):
            att = r["attribution"]
            break
    return {"path": path, "attribution": att}


def _p50(summary: "dict | None") -> float:
    v = (summary or {}).get("p50")
    return float(v) if isinstance(v, (int, float)) else 0.0


def render_attribution(s: dict) -> str:
    """One run's segment table: counts, percentiles, and each segment's
    share of the summed p50 — where the window latency actually went."""
    att = s.get("attribution")
    lines = [f"window latency attribution: {s['path'] or '(records)'}"]
    if not att:
        lines.append("  no attribution records "
                     "(run with jax.obs.lifecycle: true)")
        return "\n".join(lines)
    lines.append(f"  writes observed        {_fmt(att.get('writes_observed'))}")
    lines.append(f"  writes untracked       {_fmt(att.get('writes_untracked'))}")
    lines.append(f"  open windows           {_fmt(att.get('open_windows'))}")
    if att.get("windows_evicted"):
        lines.append(f"  windows evicted        "
                     f"{_fmt(att['windows_evicted'])}")
    segs = att.get("segments") or {}
    p50_sum = sum(_p50(v) for v in segs.values())
    lines.append(f"  {'segment':<10} {'count':>8} {'p50_ms':>12} "
                 f"{'p95_ms':>12} {'p99_ms':>12} {'share':>7}")
    for name, summ in segs.items():
        share = (f"{_p50(summ) / p50_sum * 100:.1f}%" if p50_sum else "-")
        lines.append(
            f"  {name:<10} {_fmt(summ.get('count') or 0):>8} "
            f"{_fmt(summ.get('p50')):>12} {_fmt(summ.get('p95')):>12} "
            f"{_fmt(summ.get('p99')):>12} {share:>7}")
    e2e = att.get("e2e_ms") or {}
    lines.append(f"  {'e2e':<10} {_fmt(e2e.get('count') or 0):>8} "
                 f"{_fmt(e2e.get('p50')):>12} {_fmt(e2e.get('p95')):>12} "
                 f"{_fmt(e2e.get('p99')):>12}")
    if _p50(e2e):
        cov = p50_sum / _p50(e2e) * 100
        lines.append(f"  segment p50 sum {p50_sum:,.1f} ms = {cov:.1f}% "
                     "of e2e p50")
    return "\n".join(lines)


def render_attribution_diff(a: dict, b: dict) -> str:
    """Two runs' segment p50/p99 side by side (B vs A) — which stage a
    perf change actually moved."""
    lines = ["attribution diff:",
             f"  A: {a['path']}",
             f"  B: {b['path']}"]
    aa, ab = a.get("attribution") or {}, b.get("attribution") or {}
    if not aa or not ab:
        lines.append("  missing attribution records in "
                     + ("both runs" if not (aa or ab)
                        else ("A" if not aa else "B")))
        return "\n".join(lines)
    lines.append(f"  {'segment':<10} {'A p50':>12} {'B p50':>12} "
                 f"{'delta':>12} {'A p99':>12} {'B p99':>12}")
    segs = list((aa.get("segments") or {}).keys())
    for extra in (ab.get("segments") or {}):
        if extra not in segs:
            segs.append(extra)
    rows = [(name, (aa.get("segments") or {}).get(name),
             (ab.get("segments") or {}).get(name)) for name in segs]
    rows.append(("e2e", aa.get("e2e_ms"), ab.get("e2e_ms")))
    for name, sa, sb in rows:
        pa, pb = _p50(sa), _p50(sb)
        lines.append(
            f"  {name:<10} {_fmt((sa or {}).get('p50')):>12} "
            f"{_fmt((sb or {}).get('p50')):>12} "
            f"{_fmt(round(pb - pa, 3)):>12} "
            f"{_fmt((sa or {}).get('p99')):>12} "
            f"{_fmt((sb or {}).get('p99')):>12}")
    return "\n".join(lines)


def render_diff(a: dict, b: dict) -> str:
    """Two runs side-by-side with absolute + relative deltas (B vs A)."""
    rows = list(_SCALAR_ROWS)
    lines = ["telemetry diff:",
             f"  A: {a['path']}",
             f"  B: {b['path']}",
             f"  {'metric':<22} {'A':>14} {'B':>14} "
             f"{'delta':>14} {'pct':>8}"]

    def emit(label, va, vb):
        delta = pct = None
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            delta = round(vb - va, 3)
            if va:
                pct = f"{(vb - va) / va * 100:+.1f}%"
        lines.append(f"  {label:<22} {_fmt(va):>14} {_fmt(vb):>14} "
                     f"{_fmt(delta):>14} {pct or '-':>8}")

    for label, key in rows:
        emit(label, a.get(key), b.get(key))
    la = dict(_latency_rows(a))
    lb = dict(_latency_rows(b))
    for label in la:
        emit(label, la[label], lb.get(label))
    xa = (a.get("xfer") or {}).get("formats") or {}
    xb = (b.get("xfer") or {}).get("formats") or {}
    for fmt in sorted(set(xa) | set(xb)):
        emit(f"xfer {fmt} B/ev",
             (xa.get(fmt) or {}).get("bytes_per_event"),
             (xb.get(fmt) or {}).get("bytes_per_event"))
    da = a.get("devmem") or {}
    db = b.get("devmem") or {}
    if da or db:
        emit("devmem peak bytes", da.get("peak_footprint_bytes"),
             db.get("peak_footprint_bytes"))
    ska = a.get("sketch") or {}
    skb = b.get("sketch") or {}
    if ska or skb:
        emit("sketch state bytes", ska.get("state_bytes"),
             skb.get("state_bytes"))
        emit("sketch merged pairs", ska.get("merged_pairs"),
             skb.get("merged_pairs"))
    qa = (a.get("reach_query") or {}).get("query_obs") or {}
    qb = (b.get("reach_query") or {}).get("query_obs") or {}
    if qa or qb:
        sa, sb = qa.get("segments") or {}, qb.get("segments") or {}
        for seg in sorted(set(sa) | set(sb)):
            emit(f"reach {seg} p50 ms", (sa.get(seg) or {}).get("p50"),
                 (sb.get(seg) or {}).get("p50"))
        emit("reach contention",
             (qa.get("contention") or {}).get("ratio"),
             (qb.get("contention") or {}).get("ratio"))
    ta, tb = a.get("tenants") or {}, b.get("tenants") or {}
    for name in sorted(set(ta) | set(tb)):
        emit(f"tenant {name} events", (ta.get(name) or {}).get("events"),
             (tb.get(name) or {}).get("events"))
    ma, mb = a.get("multitenant") or {}, b.get("multitenant") or {}
    if ma or mb:
        wa, wb = ma.get("wait_ms") or {}, mb.get("wait_ms") or {}
        for name in sorted(set(wa) | set(wb)):
            emit(f"tenant {name} wait ms", wa.get(name), wb.get(name))
        emit("blame offdiag ratio", ma.get("offdiag_ratio"),
             mb.get("offdiag_ratio"))
    fault_keys = sorted(set(a["faults"]) | set(b["faults"]))
    for k in fault_keys:
        emit(f"fault {k}", a["faults"].get(k, 0), b["faults"].get(k, 0))
    stage_keys = sorted(set(a["stages"]) | set(b["stages"]))
    for k in stage_keys:
        emit(f"stage {k} ms", (a["stages"].get(k) or {}).get("ms", 0),
             (b["stages"].get(k) or {}).get("ms", 0))
    return "\n".join(lines)
