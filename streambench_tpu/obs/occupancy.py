"""Measured device occupancy + recompile detection.

The README's "~5% device occupancy" was an *estimate* (pipelined chunk
time minus host encode time); the async hot path never observes device
completion, so nothing on the default path can measure how busy the
chip actually is.  This module measures it the only way an async
dispatch stream allows — by *sampling*: one dispatch in ``sample_every``
is timed to ``jax.block_until_ready`` completion; every other dispatch
stays fully async, so the hot path keeps its pipelining (at the default
1/32 the sync cost is amortized to noise).  The accumulated sampled
busy time, extrapolated by the sampling factor and divided by wall
time, is ``device_busy_ratio`` — a measured figure that replaces the
estimate, with its bias stated rather than hidden: a sampled wait
covers the device finishing everything enqueued up to that dispatch,
so each sample is an upper bound on that dispatch alone, and the ratio
reads as "fraction of wall time the device had work in flight."

Per-dispatch sampled device times also land in a
``streambench_device_dispatch_ms`` histogram (tail visibility: one slow
dispatch under a backed-up transfer queue is a different disease than a
uniformly slow kernel).

The recompile detector rides ``jax.monitoring``: every XLA backend
compile fires ``/jax/core/compile/backend_compile_duration``, which the
:class:`CompileWatcher` counts into ``streambench_compiles_total``.
``mark_steady()`` (call it after ``engine.warmup()``) starts the
``streambench_compiles_steady_total`` counter — the PR 7 gotcha
("``fn.lower().compile()`` does not share the jit call cache; the
collective report costs an extra compile") becomes an asserted
invariant: a warmed steady-state run must show ZERO steady compiles,
and the engine CLI/bench surface any violation instead of silently
stalling for seconds mid-run.

Default-off like the rest of obs/: the engine carries a ``None``
attribute and one None check per dispatch until ``attach_obs(...,
occupancy=OccupancySampler(...))``.
"""

from __future__ import annotations

import threading
import time

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# jax.monitoring listeners cannot be unregistered individually (only a
# global clear exists), so ONE module-level listener dispatches to the
# live watchers — watchers come and go (tests, bench reps) without
# stacking listeners.
_watchers: "set[CompileWatcher]" = set()
_listener_registered = False
_listener_lock = threading.Lock()


def _dispatch_compile_event(event: str, duration_secs: float,
                            **_kw) -> None:
    if event != _COMPILE_EVENT:
        return
    for w in list(_watchers):
        w._on_compile(duration_secs)


def _ensure_listener() -> bool:
    """Register the module listener once.  False when jax.monitoring is
    unavailable (compile counting then reports ``supported: False``
    instead of silently showing zero)."""
    global _listener_registered
    with _listener_lock:
        if _listener_registered:
            return True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _dispatch_compile_event)
        except Exception:
            return False
        _listener_registered = True
        return True


class CompileWatcher:
    """Counts XLA backend compiles; ``mark_steady`` starts the
    steady-state counter whose invariant value is zero."""

    def __init__(self, registry=None):
        self.supported = _ensure_listener()
        self.compiles = 0
        self.compile_s = 0.0
        self.steady_compiles = 0
        self._steady = False
        self._lock = threading.Lock()
        self._c_total = self._c_steady = None
        if registry is not None:
            self._c_total = registry.counter(
                "streambench_compiles_total",
                "XLA backend compiles observed in this process")
            self._c_steady = registry.counter(
                "streambench_compiles_steady_total",
                "backend compiles AFTER mark_steady (warmup) — the "
                "steady-state invariant value is zero")
        if self.supported:
            _watchers.add(self)

    def _on_compile(self, duration_secs: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compile_s += duration_secs
            if self._steady:
                self.steady_compiles += 1
        if self._c_total is not None:
            self._c_total.inc()
            if self._steady:
                self._c_steady.inc()

    def mark_steady(self) -> None:
        """Everything is compiled now (post-warmup); any compile from
        here on is a mid-run stall worth flagging."""
        with self._lock:
            self._steady = True

    def assert_steady_zero(self) -> None:
        """Raise if a compile landed after ``mark_steady`` — the
        executable form of the steady-state-zero invariant."""
        with self._lock:
            n = self.steady_compiles
        if n:
            raise AssertionError(
                f"{n} XLA compile(s) landed after warmup — a program "
                "shape escaped warmup or something called "
                "lower().compile() on the hot path")

    def summary(self) -> dict:
        with self._lock:
            return {"supported": self.supported,
                    "compiles_total": self.compiles,
                    "compile_s": round(self.compile_s, 3),
                    "compiles_steady": self.steady_compiles}

    def close(self) -> None:
        _watchers.discard(self)


class OccupancySampler:
    """Sampled ``block_until_ready``-timed dispatches -> busy ratio.

    The engine calls ``note_dispatch(state)`` after every device
    dispatch (one None check + one counter increment off-sample); one
    dispatch in ``sample_every`` blocks on ``state`` and times the
    wait.  ``sample_every=1`` times every dispatch (bench probes);
    the default 32 keeps the hot path effectively async.
    """

    def __init__(self, registry=None, sample_every: int = 32,
                 watch_compiles: bool = True):
        self.sample_every = max(int(sample_every), 1)
        self.dispatches = 0
        self.sampled = 0
        self.busy_ns = 0
        # Optional ``fn(start_ns, end_ns)`` fed every sampled busy
        # window (obs.queryattr.QueryLifecycle.note_ingest_busy): the
        # reach contention ratio's production evidence — an async
        # dispatch span cannot cover device time, a sampled
        # block_until_ready window does.
        self.busy_sink = None
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._hist = self._g_ratio = None
        self._c_disp = self._c_sampled = None
        if registry is not None:
            self._hist = registry.histogram(
                "streambench_device_dispatch_ms",
                "sampled dispatch-to-completion device time (ms)",
                lo=0.001, hi=1e5)
            self._g_ratio = registry.gauge(
                "streambench_device_busy_ratio",
                "measured device-busy / wall-time ratio (sampled "
                "block_until_ready extrapolated by the sampling factor)")
            self._c_disp = registry.counter(
                "streambench_device_dispatches_total",
                "device dispatches seen by the occupancy sampler")
            self._c_sampled = registry.counter(
                "streambench_device_sampled_dispatches_total",
                "dispatches timed to completion (1/N sampling)")
        self.compile_watcher = (CompileWatcher(registry)
                                if watch_compiles else None)

    # ------------------------------------------------------------------
    def note_dispatch(self, state) -> None:
        """One device dispatch just happened; sample 1-in-N to
        completion.  Host-loop thread only (the counter is unlocked by
        design — the single-writer rule the ingest counters also use)."""
        self.dispatches += 1
        if self._c_disp is not None:
            self._c_disp.set_total(self.dispatches)
        if self.dispatches % self.sample_every:
            return
        import jax

        t0 = time.perf_counter_ns()
        jax.block_until_ready(state)
        dt = time.perf_counter_ns() - t0
        if self.busy_sink is not None:
            try:
                self.busy_sink(t0, t0 + dt)
            except Exception:
                pass   # a broken sink must not kill the hot path
        with self._lock:
            self.sampled += 1
            self.busy_ns += dt
        if self._hist is not None:
            self._hist.observe(dt / 1e6)
            self._c_sampled.set_total(self.sampled)
            self._g_ratio.set(self.busy_ratio())

    def mark_steady(self) -> None:
        if self.compile_watcher is not None:
            self.compile_watcher.mark_steady()

    # ------------------------------------------------------------------
    def wall_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1e3

    def busy_ratio(self) -> float:
        """Extrapolated device-busy / wall ratio (0.0 before the first
        sample)."""
        wall = self.wall_ms()
        if wall <= 0:
            return 0.0
        with self._lock:
            busy_ms = self.busy_ns / 1e6 * self.sample_every
        return busy_ms / wall

    def summary(self) -> dict:
        """The ``"occupancy"`` block a metrics.jsonl snapshot / bench
        artifact carries."""
        with self._lock:
            sampled = self.sampled
            busy_ms = round(self.busy_ns / 1e6, 3)
        out = {
            "dispatches": self.dispatches,
            "sampled": sampled,
            "sample_every": self.sample_every,
            "device_busy_ms_sampled": busy_ms,
            "wall_ms": round(self.wall_ms(), 1),
            "device_busy_ratio": round(self.busy_ratio(), 4),
        }
        if self._hist is not None and self._hist.count:
            out["dispatch_ms"] = self._hist.summary()
        if self.compile_watcher is not None:
            out["compiles"] = self.compile_watcher.summary()
        return out

    def close(self) -> None:
        if self.compile_watcher is not None:
            self.compile_watcher.close()
