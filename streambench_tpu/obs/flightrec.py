"""Crash flight recorder: a bounded black box every failed run leaves.

``metrics.jsonl`` is a time series for runs that LIVE; a run that dies
mid-window leaves at best a truncated tail and a bare traceback.  The
:class:`FlightRecorder` is the postmortem complement: a lock-guarded
bounded ring of structured records that the runner (flush-cadence ticks,
checkpoint offsets), the staged ingest pipeline (stalls, stage errors),
and the chaos supervisor (crash/restart annotations) feed continuously
— and that is dumped to ``<workdir>/flight_<reason>.jsonl`` the moment
something terminal happens: an engine crash, a supervisor ``give_up``, a
fatal exception, or SIGTERM.  The airliner model exactly: recording is
cheap and always-on (when enabled), the file only exists after an
incident, and the LAST record is the terminal fault that ended the run.

Cost: one dict + deque append under a lock per record; the feeders
record at flush cadence (~1 Hz) plus rare events, so the hot path never
sees it.  Default-off (``jax.obs.flightrec.enabled``): a ``None``
recorder costs the engine one attribute check per flush cycle.
"""

from __future__ import annotations

import json
import os
import re
import threading
from collections import deque

from streambench_tpu.utils.ids import now_ms


class FlightRecorder:
    """Bounded ring of structured records + atomic crash dumps.

    ``record(kind, **fields)`` appends one record (any thread); ``dump``
    freezes the ring into ``flight_<reason>.jsonl``, appending the
    caller's ``terminal`` record last so a reader can open the file and
    see what killed the run on the final line.  Sequence numbers are
    process-monotonic across all feeders, so interleaved runner /
    pipeline / supervisor records read back in true order.
    """

    #: Spans embedded per dump when a span source is wired (bounded so
    #: the black box stays a black box, not a full trace file).
    SPAN_TAIL = 64

    def __init__(self, workdir: str, capacity: int = 512):
        self.workdir = workdir
        self.capacity = max(int(capacity), 8)
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps: list[str] = []   # paths written, in order
        # Optional ``tail(n) -> list[dict]`` of recently closed spans
        # (obs.spans.SpanTracer.tail): when set, every dump embeds a
        # ``spans`` record just before the terminal one, so the crash
        # postmortem carries the timing context of the final seconds —
        # what the engine was actually DOING when it died, not only
        # what its counters said.
        self.span_source = None

    # ------------------------------------------------------------------
    def _stamp(self, kind: str, fields: dict) -> dict:
        self._seq += 1
        return {"seq": self._seq, "ts_ms": now_ms(), "kind": kind,
                **fields}

    def record(self, kind: str, **fields) -> None:
        """Append one record (any thread, any feeder)."""
        with self._lock:
            self._buf.append(self._stamp(kind, fields))

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self) -> list[dict]:
        """Current ring contents, oldest first (tests/diagnostics)."""
        with self._lock:
            return list(self._buf)

    # ------------------------------------------------------------------
    def dump(self, reason: str, terminal: "dict | None" = None) -> str:
        """Write the ring to ``<workdir>/flight_<reason>.jsonl``.

        ``terminal`` (recommended) is appended as the LAST record —
        stamped like any other, ``kind`` defaulting to ``"fault"`` — so
        the file ends with what ended the run.  Never overwrites: a
        second dump for the same reason gets a ``.2``/``.3`` suffix
        (every supervised crash keeps its own black box).  The write is
        tmp + rename, so a half-written dump is never mistaken for a
        complete one.
        """
        spans = None
        if self.span_source is not None:
            try:
                spans = list(self.span_source(self.SPAN_TAIL))
            except Exception:
                spans = None   # a broken tracer must not eat the dump
        with self._lock:
            records = list(self._buf)
            if spans is not None:
                # dump-only record (never enters the ring: a later dump
                # for a different reason gets ITS OWN fresh span tail,
                # and the bounded ring keeps its capacity for feeders)
                records.append(self._stamp("spans", {"spans": spans}))
            if terminal is not None:
                t = dict(terminal)
                kind = t.pop("kind", "fault")
                term = self._stamp(kind, t)
                self._buf.append(term)
                records.append(term)
        safe = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(reason)) or "unknown"
        os.makedirs(self.workdir, exist_ok=True)
        path = os.path.join(self.workdir, f"flight_{safe}.jsonl")
        i = 2
        while os.path.exists(path):
            path = os.path.join(self.workdir, f"flight_{safe}.{i}.jsonl")
            i += 1
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        os.replace(tmp, path)
        with self._lock:
            self.dumps.append(path)
        return path
