"""Opt-in localhost Prometheus scrape endpoint (stdlib only).

``GET /metrics`` renders the registry in text exposition format 0.0.4;
``GET /healthz`` answers 200 while the process lives.  Bound to
127.0.0.1 on the configured ``jax.metrics.port`` (0 = OS-assigned
ephemeral port, reported via ``.port`` and the engine's startup line).
A ``ThreadingHTTPServer`` on a daemon thread: scrapes never touch the
host loop, and an abandoned endpoint cannot keep the process alive.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Serves one registry.  ``refresh`` (optional) runs before every
    scrape — wire the sampler's ``collect_now`` there so scrape values
    are current, not last-tick."""

    def __init__(self, registry, port: int = 0, host: str = "127.0.0.1",
                 refresh=None):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # scrapes are not news
                pass

            def do_GET(self) -> None:
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain"
                elif path in ("/", "/metrics"):
                    if server.refresh is not None:
                        try:
                            server.refresh()
                        except Exception:
                            pass  # stale values beat a failed scrape
                    body = server.registry.render_prometheus().encode()
                    ctype = PROM_CONTENT_TYPE
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.registry = registry
        self.refresh = refresh
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="metrics-httpd")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
