"""Recycled-pid-safe pidfiles: the PR 10 ``<pid> <starttime>`` format.

``stream_bench.py`` proved the format for the harness-managed
services: a pidfile records the kernel start time (``/proc/<pid>/stat``
field 22) next to the pid, so liveness checks and STOP paths can tell
a recycled pid — same number, different process — from the process
they actually started, and never signal a stranger.  ISSUE 16 extends
the same lifecycle to fleet roles (replicas, the router): each CLI
writes ``pids/<role>_<n>`` on start, refuses to start when the file
names a LIVE process, and removes it on clean exit.  The fleet
supervisor reads the same files to decide restarts.
"""

from __future__ import annotations

import os


def proc_starttime(pid: int) -> str | None:
    """Kernel start time of ``pid`` (/proc stat field 22), or None
    when the process doesn't exist.  Parsed from after the LAST ')' —
    comm may contain parens and spaces."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[19]
    except (OSError, IndexError):
        return None


def read_pidfile(path: str) -> "tuple[int, str | None] | None":
    """``(pid, starttime_or_None)`` from a pidfile, or None when the
    file is missing/unparseable."""
    try:
        with open(path) as f:
            parts = f.read().split()
    except OSError:
        return None
    if not parts:
        return None
    try:
        pid = int(parts[0])
    except ValueError:
        return None
    return pid, (parts[1] if len(parts) > 1 else None)


def pidfile_alive(path: str) -> int | None:
    """The live pid a pidfile names, or None.  A starttime mismatch is
    a RECYCLED pid — a different process entirely — and reports dead;
    a pre-starttime pidfile (no second field) falls back to a bare
    existence check."""
    rec = read_pidfile(path)
    if rec is None:
        return None
    pid, started = rec
    now_started = proc_starttime(pid)
    if now_started is None:
        return None
    if started is not None and now_started != started:
        return None
    return pid


def acquire_pidfile(path: str, pid: int | None = None) -> int | None:
    """Write ``<pid> <starttime>`` to ``path``; returns the pid, or
    None (refusal) when the file already names a live process — two
    replicas must never share a slot.  A stale file (dead or recycled
    pid) is overwritten."""
    if pidfile_alive(path) is not None:
        return None
    pid = os.getpid() if pid is None else int(pid)
    started = proc_starttime(pid)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{pid} {started}" if started else str(pid))
    os.replace(tmp, path)
    return pid


def release_pidfile(path: str) -> None:
    """Remove the pidfile IF it still names this process (a successor
    that already took the slot keeps its file)."""
    rec = read_pidfile(path)
    if rec is not None and rec[0] != os.getpid():
        return
    try:
        os.remove(path)
    except OSError:
        pass
