from streambench_tpu.utils.ids import make_ids, now_ms  # noqa: F401
