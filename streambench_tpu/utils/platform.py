"""JAX platform pinning for CLI entry points.

The image's sitecustomize may pre-register a hardware backend plugin and
force ``jax_platforms`` via jax.config — overriding the ``JAX_PLATFORMS``
environment variable.  Harness-driven test runs (``JAX_PLATFORMS=cpu``)
must still land on the requested platform, so every process entry point
re-pins the config before any array op initializes a backend.  (Package
imports are guaranteed backend-init-free — see
``tests/test_import_side_effects.py`` — which is what makes pinning at
main() time sufficient.)
"""

from __future__ import annotations

import os


def pin_jax_platform(platform: str | None = None) -> None:
    """Pin jax to ``platform`` (default: the JAX_PLATFORMS env var).
    No-op when neither is set."""
    platform = platform or os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    import jax

    jax.config.update("jax_platforms", platform)
