"""JAX platform pinning for CLI entry points.

The image's sitecustomize may pre-register a hardware backend plugin and
force ``jax_platforms`` via jax.config — overriding the ``JAX_PLATFORMS``
environment variable.  Harness-driven test runs (``JAX_PLATFORMS=cpu``)
must still land on the requested platform, so every process entry point
re-pins the config before any array op initializes a backend.  (Package
imports are guaranteed backend-init-free — see
``tests/test_import_side_effects.py`` — which is what makes pinning at
main() time sufficient.)
"""

from __future__ import annotations

import os


def pin_jax_platform(platform: str | None = None) -> None:
    """Pin jax to ``platform`` (default: the JAX_PLATFORMS env var).
    No-op when neither is set."""
    platform = platform or os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    import jax

    jax.config.update("jax_platforms", platform)


def probe_backend(env: dict | None = None,
                  timeout_s: float = 90.0) -> tuple[bool, str]:
    """Initialize jax in a THROWAWAY subprocess; return (ok, detail).

    In-process init can hang indefinitely when the hardware backend is
    wedged (a dead chip tunnel); a subprocess can always be killed.  The
    probe re-pins the config from JAX_PLATFORMS exactly like
    ``pin_jax_platform`` (the image's sitecustomize overrides the env
    var via jax.config).  THE one copy, shared by bench.py's platform
    resolution and the harness's engine-spawn guard."""
    import subprocess
    import sys

    code = ("import os, jax\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "d = jax.devices(); print(jax.default_backend(), len(d))")
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            env=dict(os.environ) if env is None else env,
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s"
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()[-1:]
        return False, f"probe rc={p.returncode}: {' '.join(tail)}"
    return True, p.stdout.strip()
