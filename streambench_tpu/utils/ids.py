"""Identifier and clock helpers shared across the framework.

The reference identifies campaigns/ads/users/pages by random UUID strings
(``data/src/setup/core.clj:20-22``, ``JsonGenerator.java:111-124``).  We keep
that wire format — the engine interns strings to dense int32 ids at ingest.
"""

from __future__ import annotations

import random
import time
import uuid


def make_ids(n: int, rng: random.Random | None = None) -> list[str]:
    """``n`` random UUID strings (``core.clj:20-22``: ``make-ids``).

    A seeded ``rng`` gives deterministic ids for the catchup/golden-model
    datasets while staying UUID-shaped on the wire.
    """
    if rng is None:
        return [str(uuid.uuid4()) for _ in range(n)]
    return [str(uuid.UUID(int=rng.getrandbits(128), version=4)) for _ in range(n)]


def now_ms() -> int:
    """Wall clock in integer milliseconds (``System.currentTimeMillis`` analog)."""
    return time.time_ns() // 1_000_000
