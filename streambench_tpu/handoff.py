"""Host<->device columnar handoff benchmark (the fork's r2c/c2r experiment).

The reference fork measured the cost of a windowed row->column transpose
into a page-aligned mmap'd shared file and the column->row read-back —
the shape of a host->accelerator batch handoff
(``WindowedArrowFormatBolter``, ``AdvertisingTopologyNative.java:278-356``)
— and persisted three per-window latencies to Redis hashes
``<table>_window`` / ``<table>_r2c`` / ``<table>_c2r`` keyed by window
start (``LatencyRecordBolter``, ``:358-385``).

The TPU equivalent measured here, per window of ``batch_size`` events:

- ``window``: queueing delay — receive time minus window start (same as
  the reference's ``receive_time - start_time``).
- ``r2c``  : row->column *and* host->HBM — parse/int-encode the raw JSON
  rows into dense int32 columns (the encoder is the transpose) and
  ``jax.device_put`` them onto the accelerator, blocking until resident.
- ``c2r``  : column->row read-back — device arrays back to host numpy and
  reassembled into row tuples, like the reference's column->row loop.

Same Redis schema as the reference, so the same tooling can read both.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from streambench_tpu.io.redis_schema import RedisLike
from streambench_tpu.utils.ids import now_ms


@dataclass
class HandoffSample:
    window_start_ms: int
    window_ms: int   # receive - window_start (queueing)
    r2c_ms: float    # rows -> int32 columns -> device HBM (blocking)
    c2r_ms: float    # device columns -> host rows


def run_handoff(encoder, lines_per_window: list[list[bytes]],
                window_starts_ms: list[int] | None = None,
                rows_back: int = 64) -> list[HandoffSample]:
    """Measure the handoff for each pre-built window of raw event lines."""
    import jax

    samples: list[HandoffSample] = []
    for i, lines in enumerate(lines_per_window):
        start = (window_starts_ms[i] if window_starts_ms is not None
                 else now_ms())
        receive = now_ms()

        t0 = time.perf_counter_ns()
        batch = encoder.encode(lines, len(lines))
        cols = [jax.device_put(c) for c in
                (batch.ad_idx, batch.event_type, batch.event_time,
                 batch.user_idx, batch.page_idx, batch.ad_type)]
        for c in cols:
            c.block_until_ready()
        t1 = time.perf_counter_ns()

        host = [np.asarray(c) for c in cols]
        n = min(rows_back, batch.n)
        rows = [tuple(col[j] for col in host) for j in range(n)]
        assert len(rows) == n
        t2 = time.perf_counter_ns()

        samples.append(HandoffSample(
            window_start_ms=start,
            window_ms=receive - start,
            r2c_ms=(t1 - t0) / 1e6,
            c2r_ms=(t2 - t1) / 1e6,
        ))
    return samples


def dump_handoff(r: RedisLike, table: str,
                 samples: list[HandoffSample]) -> None:
    """Persist per-window latencies in the reference's three-hash schema
    (``LatencyRecordBolter``: HSET ``<table>_window/_r2c/_c2r``)."""
    cmds = []
    for s in samples:
        key = str(s.window_start_ms)
        cmds.append(("HSET", f"{table}_window", key, str(s.window_ms)))
        cmds.append(("HSET", f"{table}_r2c", key, f"{s.r2c_ms:.3f}"))
        cmds.append(("HSET", f"{table}_c2r", key, f"{s.c2r_ms:.3f}"))
    r.pipeline_execute(cmds)


def read_handoff(r: RedisLike, table: str) -> dict[int, tuple[int, float, float]]:
    """window_start -> (window_ms, r2c_ms, c2r_ms)."""
    window = r.hgetall(f"{table}_window")
    r2c = r.hgetall(f"{table}_r2c")
    c2r = r.hgetall(f"{table}_c2r")
    return {int(k): (int(v), float(r2c.get(k, "nan")), float(c2r.get(k, "nan")))
            for k, v in window.items()}


def _main(argv: list[str] | None = None) -> int:
    """CLI: synthesize windows, run the handoff bench, dump to Redis,
    print a JSON summary line."""
    import argparse
    import json
    import random
    import sys

    p = argparse.ArgumentParser(prog="streambench-handoff")
    p.add_argument("--confPath", default="./benchmarkConf.yaml")
    p.add_argument("--windows", type=int, default=20)
    p.add_argument("--batchSize", type=int, default=5000)
    p.add_argument("--table", default=None,
                   help="Redis hash prefix (default <redis.hashtable>_handoff)")
    p.add_argument("--inprocessRedis", action="store_true")
    args = p.parse_args(argv)

    from streambench_tpu.config import ConfigError, load_config_or_default
    from streambench_tpu.datagen import gen
    from streambench_tpu.encode.native_encoder import make_encoder
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.redis_schema import as_redis
    from streambench_tpu.io.resp import RespClient

    try:
        cfg = load_config_or_default(
            args.confPath,
            is_default_path=args.confPath == p.get_default("confPath"))
    except ConfigError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    table = args.table or f"{cfg.redis_hashtable}_handoff"
    rng = random.Random(1234)
    campaigns = gen.make_ids(cfg.jax_num_campaigns, rng)
    ads = gen.make_ids(cfg.jax_num_campaigns * cfg.jax_ads_per_campaign, rng)
    mapping = {a: campaigns[i % len(campaigns)] for i, a in enumerate(ads)}
    src = gen.EventSource(ads=ads, user_ids=gen.make_ids(100, rng),
                          page_ids=gen.make_ids(100, rng), rng=rng)
    base = now_ms()
    windows, starts = [], []
    for w in range(args.windows):
        ts = [base + w * cfg.jax_time_divisor_ms + i
              for i in range(args.batchSize)]
        windows.append([e.encode() for e in src.events_at(ts)])
        starts.append(base + w * cfg.jax_time_divisor_ms)
    encoder = make_encoder(mapping, campaigns,
                           divisor_ms=cfg.jax_time_divisor_ms,
                           lateness_ms=cfg.jax_allowed_lateness_ms)

    samples = run_handoff(encoder, windows, starts)
    if len(samples) > 1:
        samples = samples[1:]  # drop the compile/warm-up window
    if not samples:
        print(json.dumps({"windows": 0, "batch_size": args.batchSize,
                          "table": table}), flush=True)
        return 0

    if args.inprocessRedis:
        r = as_redis(make_store())
    else:
        r = RespClient(cfg.redis_host, cfg.redis_port)
    dump_handoff(r, table, samples)

    r2c = sorted(s.r2c_ms for s in samples)
    c2r = sorted(s.c2r_ms for s in samples)
    mid = len(samples) // 2
    print(json.dumps({
        "windows": len(samples), "batch_size": args.batchSize,
        "r2c_ms_p50": round(r2c[mid], 3), "r2c_ms_max": round(r2c[-1], 3),
        "c2r_ms_p50": round(c2r[mid], 3), "c2r_ms_max": round(c2r[-1], 3),
        "events_per_s_r2c": round(args.batchSize / (r2c[mid] / 1e3), 1),
        "table": table,
    }), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    from streambench_tpu.utils.platform import pin_jax_platform

    pin_jax_platform()  # honor JAX_PLATFORMS before any backend init
    sys.exit(_main())
