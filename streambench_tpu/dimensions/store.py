"""Durable aggregate store: the HDHT-store peer, latency-aware.

The Apex reference persists dimensional aggregates in an HDHT store (an
HDFS-backed hash table, ``ApplicationDimensionComputation.createStore``,
``:201-211``) wrapped by ``ProcessTimeAwareStore`` which records
per-(key, bucket) update times and reports a latency decile table
(``ProcessTimeAwareStore.java:62-89,115-176``).  SURVEY.md §5.4 classifies
it as a *durable sink*, not a resumable checkpoint — same here.

This peer is an append-only JSON-lines log plus an in-memory index:

- ``put_rows`` appends one record per (key, bucket) with its final
  aggregate values and the update time, updates the index, and feeds the
  latency tracker (the ProcessTimeAwareStore role);
- reopening replays the log to rebuild the index (crash-durable up to the
  last fsync; ``sync_every`` bounds the window);
- ``compact`` rewrites the log keeping only each (key, bucket)'s latest
  record — the HDHT compaction analog.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Iterator

import numpy as np

from streambench_tpu.metrics import LatencyTracker
from streambench_tpu.utils.ids import now_ms

LOG_NAME = "dimensions.log"


class DurableDimensionStore:
    def __init__(self, directory: str, bucket_ms: int = 10_000,
                 ignore_first: int = 10, sync_every: int = 1):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.path = os.path.join(directory, LOG_NAME)
        self.bucket_ms = bucket_ms
        # (key, bucket_ms) -> {"<value>:<AGG>": final, "_updated": ms}
        self.index: dict[tuple[str, int], dict] = {}
        self.latency = LatencyTracker(window_ms=bucket_ms,
                                      ignore_first=ignore_first)
        self._sync_every = max(sync_every, 0)
        self._since_sync = 0
        # latest materialized reach-sketch record (reach/; ISSUE 10):
        # {"mins": [C,k] uint32, "registers": [C,R] int32,
        #  "campaigns": [...], "epoch": int, "_updated": ms} or None
        self._reach: dict | None = None
        # delta-ship chain bookkeeping (ISSUE 18): the newest intact
        # base record (raw parsed dict), the delta records folded on
        # top of it in order, and the seq of the last chained record
        # (None = no chain / chain broken — deltas are dropped until
        # the next base).  compact() dumps base + chain verbatim so a
        # mid-chain compaction never orphans deltas.
        self._reach_base: dict | None = None
        self._reach_chain: list[dict] = []
        self._reach_seq: int | None = None
        # chaos hook (ISSUE 16): when set, every put_reach_sketches
        # line passes through ``hook(line) -> (data, intact)`` before
        # hitting the file — the ship-log fault surface.  None (the
        # default) is a byte-exact pass-through.
        self.ship_fault_hook = None
        if os.path.exists(self.path):
            self._replay()
        self._f = open(self.path, "a", encoding="utf-8")

    # -- write path ----------------------------------------------------
    def put_rows(self, rows: list[tuple[str, int, dict]],
                 update_time_ms: int | None = None) -> int:
        """``rows``: (key, bucket_start_ms, {"value:AGG": final}).  Returns
        rows written."""
        stamp = now_ms() if update_time_ms is None else update_time_ms
        for key, bucket, aggs in rows:
            rec = {"k": key, "b": bucket, "t": stamp, "a": aggs}
            self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            self.index[(key, bucket)] = {**aggs, "_updated": stamp}
            self.latency.record(key, bucket, stamp)
        self._since_sync += len(rows)
        if self._sync_every and self._since_sync >= self._sync_every:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._since_sync = 0
        return len(rows)

    def put_reach_sketches(self, mins: np.ndarray, registers: np.ndarray,
                           campaigns: list[str], epoch: int,
                           update_time_ms: int | None = None,
                           watermark: int | None = None,
                           folded_ms: int | None = None,
                           submit_ms: int | None = None,
                           origin: dict | None = None,
                           seq: int | None = None) -> int:
        """Materialize the reach sketch planes (reach/; ISSUE 10) as one
        durable log record, so a reopened store can serve audience
        queries without re-folding the journal.  Latest record wins on
        replay; ``compact`` keeps only it.

        This record is also the replica shipping format (ISSUE 14): the
        snapshot shipper appends one per cadence tick and read-replica
        processes tail the log for them; ``watermark`` rides along so a
        replica can report how much event time its planes cover.

        Fleet freshness stamps (ISSUE 15, all optional): ``folded_ms``
        is the writer wall time of the last fold into these planes,
        ``submit_ms`` the wall time the ship was submitted (``fm`` /
        ``sm`` on the wire — the writer-side hop boundaries of the
        freshness ledger), and ``origin`` names the writer's pub/sub
        endpoint + pid so replicas can ping it for the clock-offset
        estimate (obs/clock.py).

        ``seq`` (ISSUE 18) is the delta-ship chain stamp: a base
        record carrying one restarts the chain — subsequent
        ``reach_delta`` records link off it via ``ps``.  Legacy
        full-ship callers omit it.  Returns the encoded record size in
        bytes (pre-fault-hook — what the writer produced)."""
        stamp = now_ms() if update_time_ms is None else update_time_ms
        mins = np.ascontiguousarray(mins, dtype=np.uint32)
        regs = np.ascontiguousarray(registers, dtype=np.int32)
        rec = {"kind": "reach_sketch", "t": stamp, "epoch": int(epoch),
               "c": list(campaigns),
               "k": int(mins.shape[1]), "r": int(regs.shape[1]),
               "mins": base64.b64encode(mins.tobytes()).decode(),
               "regs": base64.b64encode(regs.tobytes()).decode()}
        if watermark is not None:
            rec["wm"] = int(watermark)
        if folded_ms is not None:
            rec["fm"] = int(folded_ms)
        if submit_ms is not None:
            rec["sm"] = int(submit_ms)
        if origin is not None:
            rec["origin"] = dict(origin)
        if seq is not None:
            rec["seq"] = int(seq)
        data = json.dumps(rec, separators=(",", ":")) + "\n"
        nbytes = len(data)
        intact = True
        hook = self.ship_fault_hook
        if hook is not None:
            # ship-log fault surface (ISSUE 16): the hook may tear,
            # corrupt, or delay the appended record; a damaged record
            # must not be absorbed — the writer's own replay view
            # stays no fresher than what it durably wrote
            data, intact = hook(data)
        if data:
            self._f.write(data)
        self._f.flush()
        os.fsync(self._f.fileno())
        if intact:
            self._absorb_reach(rec)
        return nbytes

    def put_reach_delta(self, row_idx: np.ndarray, rows: dict, *,
                        epoch: int, seq: int, prev_seq: int,
                        update_time_ms: int | None = None,
                        watermark: int | None = None,
                        folded_ms: int | None = None,
                        submit_ms: int | None = None,
                        origin: dict | None = None) -> int:
        """Append one chain-stamped dirty-row delta record (ISSUE 18):
        only the rows in ``row_idx`` of each plane, linked to the
        previous ship via ``ps=prev_seq``.  ``rows`` maps wire plane
        names (``mins`` / ``regs``) to ``[n, width]`` arrays.  Goes
        through the same ship-fault hook as bases (PR 16's torn/
        corrupt faults land on delta records too).  Returns the
        encoded record size in bytes (pre-hook)."""
        stamp = now_ms() if update_time_ms is None else update_time_ms
        idx = np.ascontiguousarray(np.asarray(row_idx).ravel(),
                                   dtype=np.int32)
        mins = np.ascontiguousarray(rows["mins"], dtype=np.uint32)
        regs = np.ascontiguousarray(rows["regs"], dtype=np.int32)
        rec = {"kind": "reach_delta", "t": stamp, "epoch": int(epoch),
               "seq": int(seq), "ps": int(prev_seq),
               "k": int(mins.shape[1]) if mins.ndim == 2 else 0,
               "r": int(regs.shape[1]) if regs.ndim == 2 else 0,
               "idx": base64.b64encode(idx.tobytes()).decode(),
               "mins": base64.b64encode(mins.tobytes()).decode(),
               "regs": base64.b64encode(regs.tobytes()).decode()}
        if watermark is not None:
            rec["wm"] = int(watermark)
        if folded_ms is not None:
            rec["fm"] = int(folded_ms)
        if submit_ms is not None:
            rec["sm"] = int(submit_ms)
        if origin is not None:
            rec["origin"] = dict(origin)
        data = json.dumps(rec, separators=(",", ":")) + "\n"
        nbytes = len(data)
        intact = True
        hook = self.ship_fault_hook
        if hook is not None:
            data, intact = hook(data)
        if data:
            self._f.write(data)
        self._f.flush()
        os.fsync(self._f.fileno())
        if intact:
            self._absorb_reach_delta(rec)
        return nbytes

    def _absorb_reach(self, rec: dict) -> None:
        try:
            c = list(rec["c"])
            k, r = int(rec["k"]), int(rec["r"])
            mins = np.frombuffer(base64.b64decode(rec["mins"]),
                                 np.uint32).reshape(len(c), k)
            regs = np.frombuffer(base64.b64decode(rec["regs"]),
                                 np.int32).reshape(len(c), r)
        except (KeyError, ValueError, TypeError):
            return   # torn/corrupt sketch record: keep the previous one
        self._reach = {"mins": mins, "registers": regs, "campaigns": c,
                       "epoch": int(rec.get("epoch", 0)),
                       "watermark": int(rec.get("wm", 0)),
                       "_updated": int(rec.get("t", 0)),
                       # fleet freshness stamps + origin (ISSUE 15);
                       # absent on pre-fleet records
                       "folded_ms": rec.get("fm"),
                       "submit_ms": rec.get("sm"),
                       "origin": rec.get("origin")}
        # every intact base restarts the delta chain (ISSUE 18); a
        # legacy base without seq still loads but nothing chains off it
        self._reach_base = rec
        self._reach_chain = []
        self._reach_seq = rec.get("seq")

    def _absorb_reach_delta(self, rec: dict) -> None:
        """Fold one intact delta record into the materialized view iff
        it chains off the last absorbed record; otherwise mark the
        chain broken so later deltas are dropped until the next base
        (the store's view must never be half-folded)."""
        if self._reach is None or self._reach_seq is None:
            return
        from streambench_tpu.reach.deltaship import (
            decode_delta_record, merge_rows)
        d = decode_delta_record(rec)
        if d is None:
            self._reach_seq = None
            return
        C = len(self._reach["campaigns"])
        if (d["epoch"] != self._reach["epoch"]
                or d["ps"] != self._reach_seq
                or (d["idx"].size and (int(d["idx"].min()) < 0
                                       or int(d["idx"].max()) >= C))):
            self._reach_seq = None
            return
        merge_rows(self._reach, d["idx"], d["rows"])
        if d["watermark"] is not None:
            self._reach["watermark"] = int(d["watermark"])
        self._reach["_updated"] = d["shipped_ms"]
        self._reach["folded_ms"] = d["folded_ms"]
        self._reach["submit_ms"] = d["submit_ms"]
        if d["origin"] is not None:
            self._reach["origin"] = d["origin"]
        self._reach_chain.append(rec)
        self._reach_seq = d["seq"]

    def reach_sketches(self) -> dict | None:
        """Latest materialized reach-sketch record (or None)."""
        return self._reach

    # -- read path -----------------------------------------------------
    def get(self, key: str, bucket_ms: int) -> dict | None:
        return self.index.get((key, bucket_ms))

    def scan_key(self, key: str) -> dict[int, dict]:
        return {b: v for (k, b), v in self.index.items() if k == key}

    def buckets(self) -> list[int]:
        return sorted({b for _, b in self.index})

    def __len__(self) -> int:
        return len(self.index)

    def items(self) -> Iterator[tuple[tuple[str, int], dict]]:
        return iter(self.index.items())

    # -- durability ----------------------------------------------------
    def _replay(self) -> None:
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail record from a crash mid-append
                if rec.get("kind") == "reach_sketch":
                    self._absorb_reach(rec)
                    continue
                if rec.get("kind") == "reach_delta":
                    # must precede the (k, b) index fallback: delta
                    # records carry "k" (plane width) but no "b"
                    self._absorb_reach_delta(rec)
                    continue
                self.index[(rec["k"], rec["b"])] = {
                    **rec["a"], "_updated": rec["t"]}
                self.latency.record(rec["k"], rec["b"], rec["t"])

    def compact(self) -> None:
        """Rewrite the log with only each (key, bucket)'s latest record.

        Reach records keep the newest base PLUS its subsequent delta
        chain verbatim (ISSUE 18): "keep latest record" would orphan
        the deltas folded on top of the base — a tailer replaying the
        compacted log must land on the exact same folded view (seq
        stamps and freshness fields included), so the raw records are
        preserved, not re-synthesized from the folded planes."""
        tmp = self.path + ".compact"
        with open(tmp, "w", encoding="utf-8") as f:
            for (key, bucket), val in self.index.items():
                aggs = {k: v for k, v in val.items() if k != "_updated"}
                rec = {"k": key, "b": bucket, "t": val["_updated"],
                       "a": aggs}
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            if self._reach_base is not None:
                f.write(json.dumps(self._reach_base,
                                   separators=(",", ":")) + "\n")
                for rec in self._reach_chain:
                    f.write(json.dumps(rec,
                                       separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()

    def __enter__(self) -> "DurableDimensionStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
