"""Durable aggregate store: the HDHT-store peer, latency-aware.

The Apex reference persists dimensional aggregates in an HDHT store (an
HDFS-backed hash table, ``ApplicationDimensionComputation.createStore``,
``:201-211``) wrapped by ``ProcessTimeAwareStore`` which records
per-(key, bucket) update times and reports a latency decile table
(``ProcessTimeAwareStore.java:62-89,115-176``).  SURVEY.md §5.4 classifies
it as a *durable sink*, not a resumable checkpoint — same here.

This peer is an append-only JSON-lines log plus an in-memory index:

- ``put_rows`` appends one record per (key, bucket) with its final
  aggregate values and the update time, updates the index, and feeds the
  latency tracker (the ProcessTimeAwareStore role);
- reopening replays the log to rebuild the index (crash-durable up to the
  last fsync; ``sync_every`` bounds the window);
- ``compact`` rewrites the log keeping only each (key, bucket)'s latest
  record — the HDHT compaction analog.
"""

from __future__ import annotations

import json
import os
from typing import Iterator

from streambench_tpu.metrics import LatencyTracker
from streambench_tpu.utils.ids import now_ms

LOG_NAME = "dimensions.log"


class DurableDimensionStore:
    def __init__(self, directory: str, bucket_ms: int = 10_000,
                 ignore_first: int = 10, sync_every: int = 1):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.path = os.path.join(directory, LOG_NAME)
        self.bucket_ms = bucket_ms
        # (key, bucket_ms) -> {"<value>:<AGG>": final, "_updated": ms}
        self.index: dict[tuple[str, int], dict] = {}
        self.latency = LatencyTracker(window_ms=bucket_ms,
                                      ignore_first=ignore_first)
        self._sync_every = max(sync_every, 0)
        self._since_sync = 0
        if os.path.exists(self.path):
            self._replay()
        self._f = open(self.path, "a", encoding="utf-8")

    # -- write path ----------------------------------------------------
    def put_rows(self, rows: list[tuple[str, int, dict]],
                 update_time_ms: int | None = None) -> int:
        """``rows``: (key, bucket_start_ms, {"value:AGG": final}).  Returns
        rows written."""
        stamp = now_ms() if update_time_ms is None else update_time_ms
        for key, bucket, aggs in rows:
            rec = {"k": key, "b": bucket, "t": stamp, "a": aggs}
            self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            self.index[(key, bucket)] = {**aggs, "_updated": stamp}
            self.latency.record(key, bucket, stamp)
        self._since_sync += len(rows)
        if self._sync_every and self._since_sync >= self._sync_every:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._since_sync = 0
        return len(rows)

    # -- read path -----------------------------------------------------
    def get(self, key: str, bucket_ms: int) -> dict | None:
        return self.index.get((key, bucket_ms))

    def scan_key(self, key: str) -> dict[int, dict]:
        return {b: v for (k, b), v in self.index.items() if k == key}

    def buckets(self) -> list[int]:
        return sorted({b for _, b in self.index})

    def __len__(self) -> int:
        return len(self.index)

    def items(self) -> Iterator[tuple[tuple[str, int], dict]]:
        return iter(self.index.items())

    # -- durability ----------------------------------------------------
    def _replay(self) -> None:
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail record from a crash mid-append
                self.index[(rec["k"], rec["b"])] = {
                    **rec["a"], "_updated": rec["t"]}
                self.latency.record(rec["k"], rec["b"], rec["t"])

    def compact(self) -> None:
        """Rewrite the log with only each (key, bucket)'s latest record."""
        tmp = self.path + ".compact"
        with open(tmp, "w", encoding="utf-8") as f:
            for (key, bucket), val in self.index.items():
                aggs = {k: v for k, v in val.items() if k != "_updated"}
                rec = {"k": key, "b": bucket, "t": val["_updated"],
                       "a": aggs}
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()

    def __enter__(self) -> "DurableDimensionStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
