"""Pub/sub query channel: WebSocket endpoint + JSON-lines fallback.

The Apex reference exposes live aggregate queries through a gateway
pub/sub endpoint (``ws://<gateway>/pubsub``, built by
``ConfigUtil.java:22-34``, wired as PubSubWebSocketAppData query/result
operators, ``ApplicationDimensionComputation.java:236-259``).  One TCP
server here speaks BOTH transports on the same port, sniffed from the
first bytes of each connection:

- a ``GET /pubsub ...`` HTTP request upgrades to a real RFC 6455
  WebSocket (handshake + masked client frames + ping/pong/close), the
  reference's wire protocol;
- anything else is treated as newline-delimited JSON over the raw
  socket (the hermetic/test transport — no handshake round trip).

The message contract is the gateway pub/sub protocol on either
transport:

- client -> server: ``{"type": "subscribe", "topic": T}`` (repeatable),
  ``{"type": "unsubscribe", "topic": T}``,
  ``{"type": "publish", "topic": T, "data": ...}``
- server -> subscriber: ``{"type": "data", "topic": T, "data": ...}``

Slow consumers are disconnected rather than allowed to backpressure the
engine (send buffers are bounded) — queries must never stall aggregation.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import socketserver
import struct
import threading
import time
from collections import deque

_WS_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

#: request() keeps at most this many in-flight send stamps per client
#: (latency_split pops them; an abandoned query must not grow memory)
MAX_INFLIGHT_STAMPS = 4096


class _LatencySplitMixin:
    """Client-side half of the query-path decomposition (ISSUE 11):
    ``request()`` stamps each outgoing query's send time; when the
    answer carries the server-side ``server`` block (the reach server
    includes it under ``jax.obs.query``), ``latency_split`` divides the
    measured round trip into server-vs-network halves — the piece no
    server-side histogram can see."""

    def _note_request(self, msg: dict) -> None:
        stamps = getattr(self, "_inflight", None)
        if stamps is None:
            stamps = self._inflight = {}
        qid = msg.get("id")
        if qid is None:
            return
        while len(stamps) >= MAX_INFLIGHT_STAMPS:
            stamps.pop(next(iter(stamps)))
        stamps[qid] = time.monotonic()

    def latency_split(self, data: dict) -> "dict | None":
        """Split one answered query's round trip.  ``data`` is the
        payload ``recv()`` returned (the ``"data"`` member of the data
        message).  Returns ``{"rtt_ms", "server_ms", "network_ms"}``
        when the reply carries the server decomposition, ``{"rtt_ms"}``
        when it does not (query obs off server-side), or None when the
        answer's id was never stamped by ``request()``."""
        stamps = getattr(self, "_inflight", None)
        t0 = stamps.pop(data.get("id"), None) if stamps else None
        if t0 is None:
            return None
        rtt_ms = (time.monotonic() - t0) * 1000.0
        out = {"rtt_ms": round(rtt_ms, 3)}
        server = data.get("server")
        if isinstance(server, dict) and isinstance(
                server.get("total_ms"), (int, float)):
            out["server_ms"] = server["total_ms"]
            out["network_ms"] = round(
                max(rtt_ms - server["total_ms"], 0.0), 3)
        return out


def query_uri(host: str, port: int) -> str:
    """The reference's query endpoint shape (``ConfigUtil.java:22-34``)."""
    return f"ws://{host}:{port}/pubsub"


def _ws_accept(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1(key.encode() + _WS_GUID).digest()).decode()


def ws_encode(payload: bytes, opcode: int = 0x1, mask: bool = False,
              fin: bool = True) -> bytes:
    """One frame (FIN by default).  Servers send unmasked; clients MUST
    mask (RFC 6455 §5.1)."""
    head = bytes([(0x80 if fin else 0x00) | opcode])
    n = len(payload)
    mbit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mbit | n])
    elif n < (1 << 16):
        head += bytes([mbit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mbit | 127]) + struct.pack(">Q", n)
    if mask:
        mk = os.urandom(4)
        return head + mk + bytes(b ^ mk[i % 4]
                                 for i, b in enumerate(payload))
    return head + payload


def ws_read_frame(rfile) -> tuple[int, bytes] | None:
    """Read one frame from a BLOCKING file-like -> (opcode, payload);
    None on EOF, including mid-frame (the peer is gone either way).
    (Client/test path; the server reads frames through ``_SockStream``,
    whose buffer survives socket timeouts.)"""
    h = rfile.read(2)
    if len(h) < 2:
        return None
    opcode = h[0] & 0x0F
    masked = bool(h[1] & 0x80)
    n = h[1] & 0x7F
    if n == 126:
        ext = rfile.read(2)
        if len(ext) < 2:
            return None
        n = struct.unpack(">H", ext)[0]
    elif n == 127:
        ext = rfile.read(8)
        if len(ext) < 8:
            return None
        n = struct.unpack(">Q", ext)[0]
    mk = None
    if masked:
        mk = rfile.read(4)
        if len(mk) < 4:
            return None
    payload = rfile.read(n)
    if len(payload) < n:
        return None
    if mk:
        payload = bytes(b ^ mk[i % 4] for i, b in enumerate(payload))
    return opcode, payload


# Per-message bounds: query messages are small JSON; anything larger is
# a malformed or hostile client and the connection closes rather than
# letting it grow server memory (frame lengths are client-controlled
# 64-bit values, and fragmented messages could otherwise accumulate
# without limit).
MAX_FRAME_BYTES = 4 << 20
MAX_LINE_BYTES = 64 << 10

#: per-connection reply-queue bounds.  A consumer that falls this far
#: behind — by message count (small-payload storms) or by queued bytes
#: (fat aggregate streams) — is dropped, same policy as the old
#: synchronous send-timeout, but the DECISION no longer costs the
#: producer anything: send() enqueues and the per-connection writer
#: thread eats the socket stall.
REPLY_QUEUE_MAX = 1024
REPLY_QUEUE_MAX_BYTES = 2 << 20

#: per-connection request-id dedup window (ISSUE 16): a duplicated
#: query-verb message (net_dup, or a client retry racing its own
#: predecessor) is answered at most once per id within this many most
#: recent ids — bounded so a hostile/id-less client can't grow memory
QUERY_DEDUP_MAX = 1024


class _SockStream:
    """recv-based reader whose buffer SURVIVES socket timeouts.

    ``BufferedReader.read`` can discard already-received bytes when the
    underlying recv times out mid-request — for a framed protocol that
    desyncs the stream (a later read would parse payload bytes as a
    frame header).  Here a timeout just leaves the accumulated bytes in
    place; the caller decides whether an EMPTY-buffer timeout means
    "idle, keep listening" (frame/message boundary) or keeps waiting
    (mid-frame: the rest is in flight).
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()
        self._eof = False

    def _fill(self) -> bool:
        """One recv into the buffer; False on EOF.  Propagates timeout."""
        if self._eof:
            return False
        chunk = self._sock.recv(65536)
        if not chunk:
            self._eof = True
            return False
        self._buf += chunk
        return True

    def readline(self) -> bytes:
        """One newline-terminated line; idle timeouts keep waiting.
        Returns b'' on EOF with an empty buffer, and b'' (dropping the
        buffer) when a "line" exceeds MAX_LINE_BYTES — callers treat
        that as a dead peer and close."""
        while b"\n" not in self._buf:
            if len(self._buf) > MAX_LINE_BYTES:
                self._buf.clear()
                self._eof = True
                return b""
            try:
                if not self._fill():
                    break
            except (TimeoutError, socket.timeout):
                continue
        i = self._buf.find(b"\n")
        end = len(self._buf) if i < 0 else i + 1
        out = bytes(self._buf[:end])
        del self._buf[:end]
        return out

    def read_exact(self, n: int, idle_raises: bool = False
                   ) -> bytes | None:
        """Exactly ``n`` bytes, or None on EOF mid-request.

        ``idle_raises``: a timeout while the buffer is EMPTY propagates
        (the caller's idle tick, only safe at a frame boundary); once
        any byte is buffered the frame is committed and timeouts keep
        waiting for the rest.
        """
        while len(self._buf) < n:
            try:
                if not self._fill():
                    return None
            except (TimeoutError, socket.timeout):
                if idle_raises and not self._buf:
                    raise
                continue
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


def read_ws_frame_stream(stream: _SockStream
                         ) -> tuple[int, bytes, bool] | None:
    """Server-side frame read over ``_SockStream``: idle timeouts at the
    frame boundary propagate; mid-frame the stream waits for the rest.
    Returns ``(opcode, payload, fin)`` or None on EOF (clean or
    mid-frame: either way the peer is gone)."""
    h = stream.read_exact(2, idle_raises=True)
    if h is None:
        return None
    fin = bool(h[0] & 0x80)
    opcode = h[0] & 0x0F
    masked = bool(h[1] & 0x80)
    n = h[1] & 0x7F
    if n == 126:
        ext = stream.read_exact(2)
        if ext is None:
            return None
        n = struct.unpack(">H", ext)[0]
    elif n == 127:
        ext = stream.read_exact(8)
        if ext is None:
            return None
        n = struct.unpack(">Q", ext)[0]
    if n > MAX_FRAME_BYTES:  # hostile/corrupt length: drop the peer
        return None
    mk = stream.read_exact(4) if masked else None
    if masked and mk is None:
        return None
    payload = stream.read_exact(n)
    if payload is None:
        return None
    if mk:
        payload = bytes(b ^ mk[i % 4] for i, b in enumerate(payload))
    return opcode, payload, fin


class _Handler(socketserver.StreamRequestHandler):
    # One shared socket timeout bounds BOTH reads (the subscribe loop
    # retries on timeout) and writes (a send that can't complete within it
    # marks the subscriber dead) — queries must never stall aggregation.
    timeout_s = 1.0

    def _ws_handshake(self, stream: _SockStream) -> bool:
        """Complete the RFC 6455 upgrade (request line already read)."""
        headers: dict[str, str] = {}
        while True:
            line = stream.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
            name, _, value = line.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        key = headers.get("sec-websocket-key")
        if (key is None
                or "websocket" not in headers.get("upgrade", "").lower()):
            self.connection.sendall(
                b"HTTP/1.1 400 Bad Request\r\n\r\n")
            return False
        self.connection.sendall(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: " + _ws_accept(key).encode()
            + b"\r\n\r\n")
        return True

    def _messages(self, stream: _SockStream):
        """Yield decoded JSON messages from either transport."""
        try:
            yield from self._messages_inner(stream)
        except OSError:
            return  # reset/aborted connection (port scans, dead peers)

    def _messages_inner(self, stream: _SockStream):
        first = stream.readline()  # idle-tolerant: waits for a client
        if not first:
            return
        if first.startswith(b"GET "):
            if not self._ws_handshake(stream):
                return
            self.ws = True
            fragments = b""  # FIN=0 fragments awaiting continuation
            fragmented = False
            while True:
                try:
                    frame = read_ws_frame_stream(stream)
                except (TimeoutError, socket.timeout):
                    continue  # idle subscriber: keep listening
                except OSError:
                    return
                if frame is None:
                    return
                opcode, payload, fin = frame
                if opcode == 0x8:  # close
                    self.send_raw(ws_encode(payload, opcode=0x8))
                    return
                if opcode == 0x9:  # ping -> pong
                    self.send_raw(ws_encode(payload, opcode=0xA))
                    continue
                if opcode in (0x1, 0x2) and not fin:
                    fragments, fragmented = payload, True
                    continue
                if opcode == 0x0:  # continuation
                    if not fragmented:
                        continue  # stray continuation: drop
                    fragments += payload
                    if len(fragments) > MAX_FRAME_BYTES:
                        return  # unbounded reassembly: drop the peer
                    if not fin:
                        continue
                    payload, fragments, fragmented = fragments, b"", False
                elif opcode not in (0x1, 0x2):
                    continue
                try:
                    yield json.loads(payload)
                except json.JSONDecodeError:
                    continue
            return
        # JSON-lines transport; `first` is already a message line
        raw = first
        while True:
            if raw.strip():
                try:
                    yield json.loads(raw)
                except json.JSONDecodeError:
                    pass
            try:
                raw = stream.readline()
            except OSError:
                return
            if not raw:
                return  # client closed

    def handle(self) -> None:
        server: PubSubServer = self.server.pubsub  # type: ignore[attr-defined]
        self.connection.settimeout(self.timeout_s)
        self.ws = False
        self._wlock = threading.Lock()
        # Per-connection reply queue (ISSUE 14): send() ENQUEUES and a
        # lazily-started writer thread drains, so a slow client socket
        # blocks only its own writer — never the reach worker's reply
        # loop over a whole batch (the PR 10 shed-reply-under-cv-lock
        # bug, one layer down: the old path serialized every reply
        # through a bounded-but-BLOCKING sendall on the caller).
        self._rq: deque = deque()
        self._rq_bytes = 0
        self._rq_cv = threading.Condition()
        self._rq_dead = False
        self._rq_thread: threading.Thread | None = None
        # request-id dedup window (ISSUE 16): ids this connection has
        # already routed to a query verb; duplicates are dropped so
        # net_dup / client retries stay exactly-once-answered
        self._seen_ids: deque = deque()
        self._seen_idset: set = set()
        my_topics: set[str] = set()
        try:
            for msg in self._messages(_SockStream(self.connection)):
                if not isinstance(msg, dict):
                    continue  # '5' / '[1,2]' are valid JSON, not messages
                kind = msg.get("type")
                qfn = server._query_handler(kind)
                if qfn is not None:
                    # registered query verb (e.g. "reach"): the handler
                    # enqueues and replies LATER from its worker thread
                    # — send() serializes under _wlock, so replies can
                    # interleave with pub/sub traffic safely.  The
                    # topic defaults to the verb name; the reply rides
                    # the normal data-message shape.
                    qid = msg.get("id")
                    if isinstance(qid, (str, int)):
                        if qid in self._seen_idset:
                            continue   # duplicate delivery: answered once
                        self._seen_idset.add(qid)
                        self._seen_ids.append(qid)
                        if len(self._seen_ids) > QUERY_DEDUP_MAX:
                            self._seen_idset.discard(
                                self._seen_ids.popleft())
                    self._answer_query(server, qfn, msg,
                                       str(msg.get("topic") or kind))
                    continue
                topic = str(msg.get("topic", ""))
                if not topic:
                    continue
                if kind == "subscribe":
                    my_topics.add(topic)
                    server._subscribe(topic, self)
                elif kind == "unsubscribe":
                    my_topics.discard(topic)
                    server._unsubscribe(topic, self)
                elif kind == "publish":
                    # gateway parity: clients may publish into a topic
                    server.publish(topic, msg.get("data"))
        finally:
            with self._rq_cv:
                self._rq_dead = True
                self._rq_cv.notify()
            for t in my_topics:
                server._unsubscribe(t, self)

    def _answer_query(self, server: "PubSubServer", qfn, msg: dict,
                      topic: str) -> None:
        """Route one query-verb message; the handler's reply callback
        writes a standard data message back on THIS connection (from
        whatever thread answers).  Handler errors are contained — a bad
        query must never tear down the pub/sub connection."""

        def reply(data) -> None:
            payload = (json.dumps({"type": "data", "topic": topic,
                                   "data": data},
                                  separators=(",", ":")) + "\n").encode()
            self.send(payload)

        try:
            qfn(msg, reply)
        except Exception:
            try:
                reply({"error": "query_failed"})
            except Exception:
                pass

    def send_raw(self, data: bytes) -> bool:
        # serialize writers: the reply-writer thread drains the queue
        # while the handler thread answers pings — interleaved sendall
        # calls would corrupt websocket framing mid-frame
        with self._wlock:
            try:
                self.connection.sendall(data)
                return True
            except (TimeoutError, socket.timeout, OSError):
                return False

    def _drain_replies(self) -> None:
        """Per-connection writer: drains the reply queue in order.  A
        send that fails (timeout = the client's TCP window stayed full
        past timeout_s, or a dead socket) marks the connection dead and
        drops the backlog — exactly the old synchronous policy, minus
        the producer-side stall."""
        while True:
            with self._rq_cv:
                while not self._rq and not self._rq_dead:
                    self._rq_cv.wait(timeout=1.0)
                if self._rq_dead and not self._rq:
                    return
                data = self._rq.popleft()
                self._rq_bytes -= len(data)
            if not self.send_raw(data):
                with self._rq_cv:
                    self._rq_dead = True
                    self._rq.clear()
                    self._rq_bytes = 0
                return

    def send(self, payload: bytes) -> bool:
        """Enqueue one pub/sub message for this connection's writer
        thread (started lazily at the first send).  NEVER blocks the
        caller on the client's socket: a queue past REPLY_QUEUE_MAX
        marks the consumer dead instead (publish() then drops it from
        the topic).  ``payload`` is the JSON line; websocket subscribers
        get it as one text frame.  Returns False once the connection is
        known dead — an enqueued message may still be lost to a later
        socket failure, the same at-most-once delivery the synchronous
        path had."""
        data = ws_encode(payload.rstrip(b"\n")) if self.ws else payload
        with self._rq_cv:
            if self._rq_dead:
                return False
            if (len(self._rq) >= REPLY_QUEUE_MAX
                    or self._rq_bytes + len(data)
                    > REPLY_QUEUE_MAX_BYTES):
                self._rq_dead = True
                self._rq.clear()
                self._rq_bytes = 0
                self._rq_cv.notify()
                return False
            self._rq.append(data)
            self._rq_bytes += len(data)
            if self._rq_thread is None:
                self._rq_thread = threading.Thread(
                    target=self._drain_replies, daemon=True,
                    name="pubsub-reply-writer")
                self._rq_thread.start()
            self._rq_cv.notify()
        return True


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PubSubServer:
    """Threaded topic pub/sub over TCP JSON lines."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = _Server((host, port), _Handler)
        self._srv.pubsub = self  # type: ignore[attr-defined]
        self._subs: dict[str, set[_Handler]] = {}
        # query verbs (e.g. "reach"): message type -> fn(msg, reply);
        # the gateway's request/response half next to topic pub/sub.
        # "ping" is built in (ISSUE 15): it answers with this server's
        # wall clock so peers can estimate the cross-process clock
        # offset (obs/clock.py midpoint method) over the same socket
        # they query through; register_query may override it.
        self._queries: dict[str, object] = {"ping": self._handle_ping}
        self._lock = threading.Lock()
        self._started = False
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    @property
    def address(self) -> tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "PubSubServer":
        self._thread.start()
        self._started = True
        return self

    @staticmethod
    def _handle_ping(msg: dict, reply) -> None:
        """Built-in clock-probe verb: one wall-clock read, echoed with
        the caller's id.  The reply rides the normal data-message shape
        on the asking connection, so the round trip measures exactly
        the path a real query's reply takes."""
        from streambench_tpu.utils.ids import now_ms

        reply({"t": now_ms(), "id": msg.get("id")})

    def register_query(self, kind: str, fn) -> None:
        """Register a query verb: messages with ``type == kind`` are
        routed to ``fn(msg, reply)`` instead of the pub/sub arms.
        Reserved types (subscribe/unsubscribe/publish/data) refuse."""
        if kind in ("subscribe", "unsubscribe", "publish", "data"):
            raise ValueError(f"query verb {kind!r} shadows the pub/sub "
                             "protocol")
        with self._lock:
            self._queries[str(kind)] = fn

    def _query_handler(self, kind):
        # lock-free read: dict.get is atomic under the GIL and "ping"
        # is always registered, so taking the lock here would tax every
        # pub/sub message for the rare register_query mutation
        return self._queries.get(kind)

    def _subscribe(self, topic: str, h: _Handler) -> None:
        with self._lock:
            self._subs.setdefault(topic, set()).add(h)

    def _unsubscribe(self, topic: str, h: _Handler) -> None:
        with self._lock:
            self._subs.get(topic, set()).discard(h)

    def subscriber_count(self, topic: str) -> int:
        with self._lock:
            return len(self._subs.get(topic, ()))

    def publish(self, topic: str, data) -> int:
        """Fan a payload out to current subscribers; returns receivers.
        Dead/slow connections are dropped from the topic."""
        payload = (json.dumps({"type": "data", "topic": topic,
                               "data": data},
                              separators=(",", ":")) + "\n").encode()
        with self._lock:
            subs = list(self._subs.get(topic, ()))
        sent = 0
        for h in subs:
            if h.send(payload):
                sent += 1
            else:
                self._unsubscribe(topic, h)
        return sent

    def close(self) -> None:
        # shutdown() blocks on an ack from the serve_forever loop; if
        # start() never ran there is no loop to ack and close() would
        # hang forever (the PR 10 gotcha).  server_close() alone
        # releases the listening socket either way.
        if self._started:
            self._srv.shutdown()
        self._srv.server_close()


class WebSocketClient(_LatencySplitMixin):
    """Minimal RFC 6455 client for the ``ws://<host>:<port>/pubsub``
    endpoint (tests + CLI queries over the reference's wire protocol).
    Client frames are masked, as the RFC requires."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 path: str = "/pubsub"):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._file = self._sock.makefile("rwb")
        key = base64.b64encode(os.urandom(16)).decode()
        self._file.write(
            (f"GET {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
             f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
        self._file.flush()
        status = self._file.readline()
        if b"101" not in status:
            raise ConnectionError(f"websocket upgrade refused: {status!r}")
        expect = _ws_accept(key)
        accept = None
        while True:
            line = self._file.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
            name, _, value = line.decode("latin-1").partition(":")
            if name.strip().lower() == "sec-websocket-accept":
                accept = value.strip()
        if accept != expect:
            raise ConnectionError(
                f"bad Sec-WebSocket-Accept: {accept!r} != {expect!r}")
        self._pending: list[dict] = []  # data frames that raced a pong

    def subscribe(self, topic: str) -> None:
        self._send({"type": "subscribe", "topic": topic})

    def unsubscribe(self, topic: str) -> None:
        self._send({"type": "unsubscribe", "topic": topic})

    def publish(self, topic: str, data) -> None:
        self._send({"type": "publish", "topic": topic, "data": data})

    def request(self, msg: dict) -> None:
        """Send a query-verb message (e.g. ``{"type": "reach",
        "campaigns": [...], "op": "union"}``); the answer arrives as a
        normal data message via ``recv()``.  The send time is stamped
        per id so ``latency_split`` can divide the round trip."""
        self._note_request(msg)
        self._send(msg)

    def _send(self, msg: dict) -> None:
        self._file.write(ws_encode(json.dumps(msg).encode(), mask=True))
        self._file.flush()

    def ping(self, payload: bytes = b"hb") -> bytes:
        """Round-trip a ping; returns the pong payload.  Data frames
        that race the pong are queued for the next ``recv()``, not
        dropped."""
        self._file.write(ws_encode(payload, opcode=0x9, mask=True))
        self._file.flush()
        while True:
            opcode, data = self._expect_frame()
            if opcode == 0xA:
                return data
            if opcode in (0x1, 0x2):
                self._pending.append(json.loads(data))
            elif opcode == 0x8:
                raise ConnectionError("server sent close")

    def _expect_frame(self) -> tuple[int, bytes]:
        frame = ws_read_frame(self._file)
        if frame is None:
            raise ConnectionError("pub/sub server closed the connection")
        return frame

    def recv(self) -> dict:
        if self._pending:
            return self._pending.pop(0)
        while True:
            opcode, data = self._expect_frame()
            if opcode in (0x1, 0x2):
                return json.loads(data)
            if opcode == 0x8:
                raise ConnectionError("server sent close")
            # ignore unsolicited pongs/pings here

    def close(self) -> None:
        try:
            self._file.write(ws_encode(b"", opcode=0x8, mask=True))
            self._file.flush()
        except OSError:
            pass
        try:
            self._file.close()
        finally:
            self._sock.close()


#: bounded buffers on the client's synchronous request path: pending
#: out-of-turn messages kept for recv(), and abandoned retry ids whose
#: late replies must be discarded rather than surfaced
CLIENT_PENDING_MAX = 1024
CLIENT_STALE_IDS_MAX = 4096


class PubSubClient(_LatencySplitMixin):
    """Blocking JSON-lines client (tests + CLI queries).

    Reads go through an internal recv buffer rather than the makefile
    reader: ``BufferedReader.readline`` silently DISCARDS a partial
    line when the socket times out mid-read, which desyncs the framing
    exactly when the timeout/retry path (ISSUE 16) needs it intact.
    Here a timeout leaves the partial line buffered; the next read
    resumes where it stopped.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._timeout_s = timeout_s
        self._file = self._sock.makefile("wb")
        self._rbuf = bytearray()
        self._pending: list = []        # out-of-turn messages for recv()
        self._stale_ids: dict = {}      # abandoned retry ids (ordered)
        self._auto_id = 0

    def subscribe(self, topic: str) -> None:
        self._send({"type": "subscribe", "topic": topic})

    def unsubscribe(self, topic: str) -> None:
        self._send({"type": "unsubscribe", "topic": topic})

    def request(self, msg: dict, *, timeout_s: float | None = None,
                retries: int = 0):
        """Send a query-verb message.

        Legacy mode (``timeout_s=None``): fire-and-forget — the answer
        arrives as a normal data message via ``recv()``, and a dropped
        reply blocks that recv forever.  Returns None.

        Synchronous mode (``timeout_s`` set, ISSUE 16): waits for the
        id-matched reply and returns its DATA payload.  Each timed-out
        attempt retries with a FRESH derived id (``<id>~r<n>``) — the
        server answers each id at most once (request-id dedup), so a
        retry racing its predecessor's late reply stays exactly-once-
        answered: the first reply wins and the other ids' replies are
        discarded.  Raises TimeoutError when every attempt times out.
        The send time is stamped per id either way so ``latency_split``
        can divide the round trip.
        """
        if timeout_s is None:
            self._note_request(msg)
            self._send(msg)
            return None
        base = msg.get("id")
        if base is None:
            self._auto_id += 1
            base = f"q{self._auto_id}"
        attempt_ids = []
        for attempt in range(max(int(retries), 0) + 1):
            qid = base if attempt == 0 else f"{base}~r{attempt}"
            attempt_ids.append(qid)
            m = dict(msg)
            m["id"] = qid
            self._note_request(m)
            self._send(m)
            deadline = time.monotonic() + timeout_s
            try:
                data = self._recv_reply(qid, deadline)
            except (TimeoutError, socket.timeout):
                # abandoned attempt: a late reply to this id must be
                # dropped, not surfaced as someone else's answer
                self._mark_stale(qid)
                continue
            for other in attempt_ids[:-1]:
                self._mark_stale(other)
            return data
        raise TimeoutError(
            f"pub/sub request timed out after {len(attempt_ids)} "
            f"attempt(s) ({timeout_s}s each)")

    def _mark_stale(self, qid) -> None:
        self._stale_ids[qid] = True
        while len(self._stale_ids) > CLIENT_STALE_IDS_MAX:
            self._stale_ids.pop(next(iter(self._stale_ids)))

    def _recv_reply(self, qid, deadline: float) -> dict:
        """Drain messages until the data reply carrying ``qid``
        arrives.  Torn frames (undecodable lines) are skipped — the
        framing resyncs on the next newline; out-of-turn messages are
        buffered for ``recv()``; late replies to abandoned retry ids
        are discarded."""
        while True:
            line = self._readline(deadline)
            if not line:
                raise ConnectionError(
                    "pub/sub server closed the connection")
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue   # damaged frame: the message is lost, the
                #            stream is not — resync on the next line
            if not isinstance(msg, dict):
                continue
            data = msg.get("data")
            rid = data.get("id") if isinstance(data, dict) else None
            if msg.get("type") == "data" and rid is not None:
                if rid == qid:
                    return data
                if self._stale_ids.pop(rid, None) is not None:
                    continue   # late reply to an abandoned attempt
            self._pending.append(msg)
            if len(self._pending) > CLIENT_PENDING_MAX:
                self._pending.pop(0)

    def _readline(self, deadline: float | None = None) -> bytes:
        """One newline-terminated line from the recv buffer.  With a
        deadline, raises TimeoutError when it passes — the partial
        line stays buffered for the next read.  Returns b'' on EOF
        with an empty buffer (a partial line at EOF is returned as
        is; its json parse fails like any damaged frame)."""
        while b"\n" not in self._rbuf:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("pub/sub read deadline passed")
                self._sock.settimeout(remaining)
            try:
                chunk = self._sock.recv(65536)
            finally:
                if deadline is not None:
                    self._sock.settimeout(self._timeout_s)
            if not chunk:
                out = bytes(self._rbuf)
                self._rbuf.clear()
                return out
            self._rbuf += chunk
        i = self._rbuf.find(b"\n")
        out = bytes(self._rbuf[:i + 1])
        del self._rbuf[:i + 1]
        return out

    def _send(self, msg: dict) -> None:
        self._file.write(json.dumps(msg).encode() + b"\n")
        self._file.flush()

    def recv(self) -> dict:
        if self._pending:
            return self._pending.pop(0)
        line = self._readline()
        if not line:
            raise ConnectionError("pub/sub server closed the connection")
        return json.loads(line)

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self._sock.close()
