"""JSON-lines pub/sub query channel: the WebSocket gateway analog.

The Apex reference exposes live aggregate queries through a gateway
pub/sub endpoint (``ws://<gateway>/pubsub``, built by
``ConfigUtil.java:22-34``, wired as PubSubWebSocketAppData query/result
operators, ``ApplicationDimensionComputation.java:236-259``).  No
websocket stack is assumed here; the same publish/subscribe contract runs
over a plain TCP socket speaking newline-delimited JSON:

- client -> server: ``{"type": "subscribe", "topic": T}`` (repeatable),
  ``{"type": "unsubscribe", "topic": T}``
- server -> subscriber: ``{"type": "data", "topic": T, "data": ...}``

Slow consumers are disconnected rather than allowed to backpressure the
engine (send buffers are bounded) — queries must never stall aggregation.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading


class _Handler(socketserver.StreamRequestHandler):
    # One shared socket timeout bounds BOTH reads (the subscribe loop
    # retries on timeout) and writes (a send that can't complete within it
    # marks the subscriber dead) — queries must never stall aggregation.
    timeout_s = 1.0

    def handle(self) -> None:
        server: PubSubServer = self.server.pubsub  # type: ignore[attr-defined]
        self.connection.settimeout(self.timeout_s)
        my_topics: set[str] = set()
        try:
            while True:
                try:
                    raw = self.rfile.readline()
                except (TimeoutError, socket.timeout):
                    continue  # idle subscriber: keep listening
                except OSError:
                    break
                if not raw:
                    break  # client closed
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                topic = str(msg.get("topic", ""))
                if msg.get("type") == "subscribe" and topic:
                    my_topics.add(topic)
                    server._subscribe(topic, self)
                elif msg.get("type") == "unsubscribe" and topic:
                    my_topics.discard(topic)
                    server._unsubscribe(topic, self)
        finally:
            for t in my_topics:
                server._unsubscribe(t, self)

    def send(self, payload: bytes) -> bool:
        """Bounded write: a consumer whose TCP window stays full past the
        socket timeout is reported dead (and dropped by publish())."""
        try:
            self.connection.sendall(payload)
            return True
        except (TimeoutError, socket.timeout, OSError):
            return False


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PubSubServer:
    """Threaded topic pub/sub over TCP JSON lines."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = _Server((host, port), _Handler)
        self._srv.pubsub = self  # type: ignore[attr-defined]
        self._subs: dict[str, set[_Handler]] = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    @property
    def address(self) -> tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "PubSubServer":
        self._thread.start()
        return self

    def _subscribe(self, topic: str, h: _Handler) -> None:
        with self._lock:
            self._subs.setdefault(topic, set()).add(h)

    def _unsubscribe(self, topic: str, h: _Handler) -> None:
        with self._lock:
            self._subs.get(topic, set()).discard(h)

    def subscriber_count(self, topic: str) -> int:
        with self._lock:
            return len(self._subs.get(topic, ()))

    def publish(self, topic: str, data) -> int:
        """Fan a payload out to current subscribers; returns receivers.
        Dead/slow connections are dropped from the topic."""
        payload = (json.dumps({"type": "data", "topic": topic,
                               "data": data},
                              separators=(",", ":")) + "\n").encode()
        with self._lock:
            subs = list(self._subs.get(topic, ()))
        sent = 0
        for h in subs:
            if h.send(payload):
                sent += 1
            else:
                self._unsubscribe(topic, h)
        return sent

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class PubSubClient:
    """Blocking JSON-lines client (tests + CLI queries)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._file = self._sock.makefile("rwb")

    def subscribe(self, topic: str) -> None:
        self._send({"type": "subscribe", "topic": topic})

    def unsubscribe(self, topic: str) -> None:
        self._send({"type": "unsubscribe", "topic": topic})

    def _send(self, msg: dict) -> None:
        self._file.write(json.dumps(msg).encode() + b"\n")
        self._file.flush()

    def recv(self) -> dict:
        line = self._file.readline()
        if not line:
            raise ConnectionError("pub/sub server closed the connection")
        return json.loads(line)

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self._sock.close()
