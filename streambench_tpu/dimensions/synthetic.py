"""Synthetic dimension-tuple source: the hardcoded-dimensions path.

Peer of the reference's synthetic generators
(``DimensionTupleGenerator.java`` / ``DimensionTupleGenerateOperator.java``
— 1M random campaign ids by default, ``:16``): emits (campaignId,
eventTime, clicks) batches straight into the dimension kernel, bypassing
JSON entirely.  Because the campaign universe is huge and unknown up
front, keys go through ``KeyInterner`` — overflow beyond the configured
capacity maps to -1 and the kernel counts those events as dropped.
"""

from __future__ import annotations

import random

import numpy as np

from streambench_tpu.dimensions.compute import (
    DimensionsComputation,
    KeyInterner,
)
from streambench_tpu.dimensions.schema import DimensionalSchema, parse_schema

SYNTH_SCHEMA = {
    "keys": [{"name": "campaignId", "type": "string"}],
    "timeBuckets": ["10s"],
    "values": [{"name": "clicks", "type": "long", "aggregators": ["SUM"]}],
    "dimensions": [{"combination": ["campaignId"]}],
}


class SyntheticDimensionSource:
    """Random (campaignId, eventTime, clicks) batches."""

    def __init__(self, num_campaigns: int = 1_000_000,
                 start_ms: int = 0, rate_per_s: int = 100_000,
                 rng: random.Random | None = None):
        self.rng = rng or random.Random(0)
        self.num_campaigns = num_campaigns
        self._t = start_ms
        self._step_us = max(1_000_000 // rate_per_s, 1)

    def next_batch(self, n: int) -> tuple[list[str], np.ndarray, np.ndarray]:
        keys = [f"campaign-{self.rng.randrange(self.num_campaigns):07d}"
                for _ in range(n)]
        times = (self._t + (np.arange(n, dtype=np.int64) * self._step_us)
                 // 1000).astype(np.int64)
        self._t = int(times[-1]) + 1
        clicks = np.ones(n, np.int32)
        return keys, times, clicks


def run_synthetic(n_events: int = 100_000, batch: int = 8192,
                  num_campaigns: int = 1_000_000,
                  key_capacity: int = 1 << 16,
                  window_slots: int = 16, lateness_ms: int = 0,
                  schema: DimensionalSchema | dict | None = None,
                  rng: random.Random | None = None):
    """Drive the kernel from the synthetic source.

    Returns ``(rows, interner, dropped)``: final aggregate rows (with
    resolved key names), the interner, and the count of events lost to
    key-capacity overflow (+ lateness, if any).
    """
    if schema is None:
        schema = SYNTH_SCHEMA
    if isinstance(schema, dict):
        schema = parse_schema(schema)
    src = SyntheticDimensionSource(num_campaigns=num_campaigns, rng=rng)
    interner = KeyInterner(key_capacity)
    dc = DimensionsComputation(schema, num_keys=key_capacity,
                               window_slots=window_slots,
                               lateness_ms=lateness_ms)
    state = dc.init_state()
    value_names = {v.name for v in schema.values}
    done = 0
    while done < n_events:
        n = min(batch, n_events - done)
        keys, times, clicks = src.next_batch(n)
        key_idx = interner.intern_many(keys)
        # source times start at start_ms (default 0) and stay well within
        # int32 ms for any realistic synthetic run (< ~24 days)
        rel_t = times.astype(np.int32)
        values = {}
        if "clicks" in value_names:
            values["clicks"] = clicks
        state = dc.step(state, key_idx, rel_t, np.ones(n, bool), values)
        done += n
    rows, state = dc.flush_closed(state, drain=True)
    names = interner.names()
    named = [(names[k], wid, aggs) for k, wid, aggs in rows]
    return named, interner, int(state.dropped)
