"""The dimension-computation application: source -> convert -> cube ->
durable store -> pub/sub.

Peer of the Apex dimensional app family:

- ``ApplicationDimensionComputation`` (generator -> dimensions -> HDHT
  store, optional WebSocket query, ``:92-147``);
- ``ApplicationWithGenerator`` (in-process JSON generator source,
  ``ApplicationWithGenerator.java:22-58``);
- ``ApplicationWithDCWithoutDeserializer`` whose hermeticity flags
  ``includeRedisJoin`` / ``includeQuery`` make it runnable without Redis
  or a gateway (``:26,56-66``) — the missing join is backfilled with the
  sentinel campaign id (``DimensionTuple.java:27-34``).

The converter keeps the reference's validity semantics
(``TupleToDimensionTupleConverter``): tuples that cannot produce a
dimension row are counted, not crashed on.  Values per the schema:
``clicks`` defaults to 1 per event (``Tuple.clicks == null -> 1``,
``DimensionTuple.java:50``) and ``latency`` is ``now − event_time`` at
conversion (``getLatency``, ``:66-69``), computed vectorized per batch.
"""

from __future__ import annotations

import numpy as np

from streambench_tpu.dimensions.compute import DimensionsComputation
from streambench_tpu.dimensions.pubsub import PubSubServer
from streambench_tpu.dimensions.schema import DimensionalSchema, parse_schema
from streambench_tpu.dimensions.store import DurableDimensionStore
from streambench_tpu.encode.native_encoder import make_encoder
from streambench_tpu.utils.ids import now_ms

# the reference's test-fallback campaign id (DimensionTuple.java:32)
SENTINEL_CAMPAIGN = "1111111111111111111"

DEFAULT_SCHEMA = {
    "keys": [{"name": "campaignId", "type": "string"}],
    "timeBuckets": ["10s"],
    "values": [
        {"name": "clicks", "type": "long", "aggregators": ["SUM"]},
        {"name": "latency", "type": "long", "aggregators": ["MAX"]},
    ],
    "dimensions": [{"combination": ["campaignId"]}],
}


class DimensionApp:
    def __init__(self, schema: DimensionalSchema | dict | None,
                 ad_to_campaign: dict[str, str],
                 store_dir: str,
                 campaigns: list[str] | None = None,
                 include_join: bool = True,
                 filter_views: bool = True,
                 pubsub: PubSubServer | None = None,
                 pubsub_topic: str = "dimensions",
                 window_slots: int = 16,
                 lateness_ms: int = 60_000,
                 batch_size: int = 8192,
                 use_native_encoder: bool = True):
        if schema is None:
            schema = DEFAULT_SCHEMA
        if isinstance(schema, dict):
            schema = parse_schema(schema)
        self.schema = schema
        self.include_join = include_join
        # FilterTuples sits upstream of the converter in the DC DAG
        # (event_type == "view" only, FilterTuples.java:47-52)
        self.filter_views = filter_views
        self.pubsub = pubsub
        self.pubsub_topic = pubsub_topic
        self.batch_size = batch_size
        self.encoder = make_encoder(ad_to_campaign, campaigns,
                                    divisor_ms=schema.time_bucket_ms,
                                    lateness_ms=lateness_ms,
                                    use_native=use_native_encoder)
        # key space: campaigns (+ sentinel as the last index)
        self.key_names = list(self.encoder.campaigns) + [SENTINEL_CAMPAIGN]
        self.sentinel_idx = len(self.key_names) - 1
        self.compute = DimensionsComputation(
            schema, num_keys=len(self.key_names),
            window_slots=window_slots, lateness_ms=lateness_ms)
        self.state = self.compute.init_state()
        self.store = DurableDimensionStore(
            store_dir, bucket_ms=schema.time_bucket_ms)
        self.invalid_tuples = 0   # TupleToDimensionTupleConverter role
        self.events = 0

    # ------------------------------------------------------------------
    def process_lines(self, lines: list[bytes]) -> int:
        for off in range(0, len(lines), self.batch_size):
            chunk = lines[off:off + self.batch_size]
            if chunk:
                self._process_batch(chunk)
        return len(lines)

    def _process_batch(self, chunk: list[bytes]) -> None:
        batch = self.encoder.encode(chunk, self.batch_size)
        self.invalid_tuples += len(chunk) - batch.n
        if batch.n == 0:
            return
        base = batch.base_time_ms
        if self.include_join:
            key_idx = self.encoder.join_table[batch.ad_idx]
            # unjoinable ads -> sentinel campaign, NOT dropped
            # (DimensionTuple.fromTuple backfills, DimensionTuple.java:27-34)
            key_idx = np.where(key_idx < 0, self.sentinel_idx, key_idx)
        else:
            key_idx = np.full(batch.batch_size, self.sentinel_idx, np.int32)
        valid = batch.valid
        if self.filter_views:
            valid = valid & (batch.event_type == 0)  # "view" index
        # getLatency: now - event_time, vectorized in relative ms.  The
        # reference computes it in 64-bit; device arrays are int32, so
        # replayed historical events (latency = years) clamp at int32 max
        # rather than overflow — live-stream latencies are unaffected.
        now_rel = np.int64(now_ms()) - base
        latency = np.clip(now_rel - batch.event_time.astype(np.int64),
                          0, 2**31 - 2).astype(np.int32)
        clicks = np.ones(batch.batch_size, np.int32)  # clicks null -> 1
        self.state = self.compute.step(
            self.state, key_idx.astype(np.int32), batch.event_time,
            valid, {"clicks": clicks, "latency": latency})
        self.events += batch.n

    # ------------------------------------------------------------------
    def flush(self, drain: bool = False) -> int:
        rows, self.state = self.compute.flush_closed(self.state,
                                                     drain=drain)
        if not rows:
            return 0
        base = self.encoder.base_time_ms or 0
        named = [(self.key_names[k],
                  base + wid * self.schema.time_bucket_ms, aggs)
                 for k, wid, aggs in rows]
        written = self.store.put_rows(named)
        if self.pubsub is not None:
            self.pubsub.publish(self.pubsub_topic, [
                {"campaignId": key, "bucket": bucket, **aggs}
                for key, bucket, aggs in named])
        return written

    def close(self) -> str:
        """Final drain + store close; returns the latency decile report
        (the ProcessTimeAwareStore ``logFinalLatencies`` role)."""
        self.flush(drain=True)
        report = self.store.latency.report()
        self.store.close()
        return report

    @property
    def dropped(self) -> int:
        return int(self.state.dropped)
