"""The dimension-computation kernel: schema-driven keyed window aggregates.

The Apex reference computes its dimensional cube with a reflective POJO
aggregator (``DimensionsComputationFlexibleSingleSchemaPOJO``, keys
campaignId+time, aggregates SUM(clicks)/MAX(latency),
``ApplicationDimensionComputation.java:96-116``) partitioned by campaign
hash with a unifier merge (``:120,152-199``).  Here the cube is dense
arrays: each (value, aggregator) pair of the schema is one ``[K, W]``
int32 array over (key index, window-ring slot), and a batch folds in as a
masked scatter (add / max / min / count) — the keyed shuffle is an index,
the unifier is elementwise add/max/min, which also makes multi-device
merges psum/pmax-shaped for free.

Ring/watermark semantics are shared with the exact-count engine
(``ops.windowcount.assign_windows``): buckets close when the event-time
watermark passes their end plus allowed lateness; closed buckets are
emitted with their **final** aggregate values (the HDHT store holds final
aggregates per bucket, not deltas) and their slots reset to the
aggregator's identity.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.dimensions.schema import AGGREGATORS, DimensionalSchema
from streambench_tpu.ops.windowcount import assign_windows

# int32 identities (the schema layer's int64 identities clamp to int32)
_IDENT32 = {"add": 0, "count": 0, "max": -(2**31) + 1, "min": 2**31 - 1}


@dataclass
class DimensionState:
    """Device state for ONE key combination."""

    aggs: tuple[jax.Array, ...]   # one [K, W] int32 per (value, aggregator)
    presence: jax.Array           # [K, W] int32 events aggregated per cell
    window_ids: jax.Array         # [W] int32, -1 = free slot
    watermark: jax.Array          # [] int32 (relative ms)
    dropped: jax.Array            # [] int32


class KeyInterner:
    """Host-side key -> dense index with fixed device capacity.

    The synthetic generator defaults to 1M campaigns
    (``DimensionTupleGenerateOperator.java:16``); capacity is explicit so
    device arrays stay statically shaped.  Overflow keys map to -1; the
    kernel counts such rows in ``dropped`` (valid events the fixed
    key space lost)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.index: dict[str, int] = {}
        self.overflow = 0

    def intern_many(self, keys: list[str]) -> np.ndarray:
        out = np.empty(len(keys), np.int32)
        idx = self.index
        for i, k in enumerate(keys):
            v = idx.get(k)
            if v is None:
                if len(idx) >= self.capacity:
                    self.overflow += 1
                    v = -1
                else:
                    v = len(idx)
                    idx[k] = v
            out[i] = v
        return out

    def names(self) -> list[str]:
        return list(self.index)


@functools.partial(
    jax.jit, static_argnames=("kinds", "divisor_ms", "lateness_ms"))
def _fold(aggs, presence, window_ids, watermark, dropped,
          key_idx, event_time, valid, value_cols,
          *, kinds: tuple[str, ...], divisor_ms: int, lateness_ms: int):
    K, W = aggs[0].shape
    wid = event_time // divisor_ms
    wanted = valid & (key_idx >= 0)

    slot, mask, new_ids, new_wm = assign_windows(
        window_ids, watermark, wid, wanted, valid, event_time,
        divisor_ms=divisor_ms, lateness_ms=lateness_ms)

    flat = jnp.where(mask, key_idx * W + slot, K * W)  # OOB rows drop
    # exact participation counter: flush emits a (key, bucket) row iff
    # presence > 0, so identity-valued aggregates (a SUM of zeros) are
    # still reported
    new_presence = (presence.reshape(-1)
                    .at[flat].add(1, mode="drop").reshape(K, W))
    new_aggs = []
    for arr, col, kind in zip(aggs, value_cols, kinds):
        flatarr = arr.reshape(-1)
        if kind == "add":
            upd = flatarr.at[flat].add(jnp.where(mask, col, 0), mode="drop")
        elif kind == "count":
            upd = flatarr.at[flat].add(
                jnp.where(mask, 1, 0).astype(jnp.int32), mode="drop")
        elif kind == "max":
            upd = flatarr.at[flat].max(
                jnp.where(mask, col, _IDENT32["max"]), mode="drop")
        elif kind == "min":
            upd = flatarr.at[flat].min(
                jnp.where(mask, col, _IDENT32["min"]), mode="drop")
        else:
            raise ValueError(f"unknown aggregator kind {kind!r}")
        new_aggs.append(upd.reshape(K, W))

    # lost events: ring/lateness casualties + valid rows whose key fell
    # outside the fixed key space (KeyInterner overflow maps them to -1)
    new_dropped = dropped + (jnp.sum(wanted.astype(jnp.int32))
                             - jnp.sum(mask.astype(jnp.int32))
                             + jnp.sum((valid & (key_idx < 0))
                                       .astype(jnp.int32)))
    return tuple(new_aggs), new_presence, new_ids, new_wm, new_dropped


@functools.partial(
    jax.jit,
    static_argnames=("kinds", "divisor_ms", "lateness_ms", "drain"))
def _flush_closed(aggs, presence, window_ids, watermark,
                  *, kinds: tuple[str, ...], divisor_ms: int,
                  lateness_ms: int, drain: bool = False):
    if drain:  # job close: every occupied slot is final now
        closed = window_ids >= 0
    else:
        closed = ((window_ids >= 0) &
                  ((window_ids + 1) * divisor_ms + lateness_ms <= watermark))
    new_ids = jnp.where(closed, jnp.int32(-1), window_ids)
    new_presence = jnp.where(closed[None, :], jnp.int32(0), presence)
    new_aggs = []
    for arr, kind in zip(aggs, kinds):
        ident = jnp.int32(_IDENT32[kind])
        new_aggs.append(jnp.where(closed[None, :], ident, arr))
    return closed, tuple(new_aggs), new_presence, new_ids


class DimensionsComputation:
    """Schema-driven aggregation over one key combination."""

    def __init__(self, schema: DimensionalSchema, num_keys: int,
                 window_slots: int = 16, lateness_ms: int = 60_000,
                 combination: tuple[str, ...] | None = None):
        schema.validate()
        self.schema = schema
        self.combination = combination or schema.combinations[0]
        self.divisor_ms = schema.time_bucket_ms
        self.lateness_ms = lateness_ms
        self.K = num_keys
        self.W = window_slots
        self.slots = schema.aggregate_slots()   # [(value, agg)]
        self.kinds = tuple(AGGREGATORS[a][0] for _, a in self.slots)
        # value column order the kernel expects (one per slot; a value
        # aggregated two ways is passed twice — XLA dedups the operand)
        self.value_order = [v for v, _ in self.slots]

    def init_state(self) -> DimensionState:
        return DimensionState(
            aggs=tuple(jnp.full((self.K, self.W),
                                _IDENT32[k], jnp.int32)
                       for k in self.kinds),
            presence=jnp.zeros((self.K, self.W), jnp.int32),
            window_ids=jnp.full((self.W,), -1, jnp.int32),
            watermark=jnp.int32(0),
            dropped=jnp.int32(0),
        )

    def step(self, state: DimensionState, key_idx, event_time, valid,
             values: dict[str, np.ndarray]) -> DimensionState:
        """Fold one batch.  ``values`` maps value-field name -> [B] int32
        column (relative ms for time-like fields)."""
        cols = tuple(jnp.asarray(values[name]) for name in self.value_order)
        aggs, presence, ids, wm, dropped = _fold(
            state.aggs, state.presence, state.window_ids, state.watermark,
            state.dropped, jnp.asarray(key_idx), jnp.asarray(event_time),
            jnp.asarray(valid), cols,
            kinds=self.kinds, divisor_ms=self.divisor_ms,
            lateness_ms=self.lateness_ms)
        return DimensionState(aggs, presence, ids, wm, dropped)

    def flush_closed(self, state: DimensionState, drain: bool = False
                     ) -> tuple[list[tuple[int, int, dict[str, int]]],
                                DimensionState]:
        """Emit final aggregates of closed buckets and free their slots.

        Returns ``(rows, new_state)`` where each row is
        ``(key_index, window_id, {"<value>:<AGG>": final})`` for every key
        that actually aggregated something in that bucket.  ``drain=True``
        (job close) emits every occupied slot, open or not.
        """
        closed, new_aggs, new_presence, new_ids = _flush_closed(
            state.aggs, state.presence, state.window_ids, state.watermark,
            kinds=self.kinds, divisor_ms=self.divisor_ms,
            lateness_ms=self.lateness_ms, drain=drain)
        closed = np.asarray(closed)
        new_state = DimensionState(new_aggs, new_presence, new_ids,
                                   state.watermark, state.dropped)
        if not closed.any():
            return [], new_state
        old_ids = np.asarray(state.window_ids)
        olds = [np.asarray(a) for a in state.aggs]
        # exact participation: a (key, bucket) row exists iff any event
        # aggregated into it — identity-valued results (SUM of zeros)
        # still emit
        touched = np.asarray(state.presence) > 0
        rows: list[tuple[int, int, dict[str, int]]] = []
        names = [f"{v}:{a}" for v, a in self.slots]
        for s in np.flatnonzero(closed).tolist():
            for k in np.flatnonzero(touched[:, s]).tolist():
                rows.append((k, int(old_ids[s]),
                             {n: int(olds[i][k, s])
                              for i, n in enumerate(names)}))
        return rows, new_state

    @staticmethod
    def merge(a: DimensionState, b: DimensionState,
              kinds: tuple[str, ...]) -> DimensionState:
        """Unifier merge of two partials (the
        ``DimensionsComputationUnifierImpl`` role): elementwise add/max/min
        — associative, so it is also exactly what a cross-device
        psum/pmax would compute.

        Merge is only sound when both partials' ring slots hold the SAME
        windows — i.e. the partials were folded over the same batch
        cadence (as the unifier's upstream partitions are).  With divergent
        watermark progress, a slot could hold window ids w1 != w2 and the
        elementwise add would silently sum two different windows'
        aggregates under ``max(w1, w2)``.  That is checked here (one tiny
        host sync, ADVICE r1): empty slots (-1) merge freely with anything.
        """
        ia = np.asarray(a.window_ids)
        ib = np.asarray(b.window_ids)
        conflict = (ia >= 0) & (ib >= 0) & (ia != ib)
        if conflict.any():
            s = int(np.flatnonzero(conflict)[0])
            raise ValueError(
                f"cannot merge partials with divergent ring contents: slot "
                f"{s} holds window {int(ia[s])} in one partial and "
                f"{int(ib[s])} in the other; merge partials only across "
                "the same batch cadence (or flush both first)")
        merged = []
        for x, y, kind in zip(a.aggs, b.aggs, kinds):
            if kind in ("add", "count"):
                merged.append(x + y)
            elif kind == "max":
                merged.append(jnp.maximum(x, y))
            else:
                merged.append(jnp.minimum(x, y))
        return DimensionState(
            aggs=tuple(merged),
            presence=a.presence + b.presence,
            window_ids=jnp.maximum(a.window_ids, b.window_ids),
            watermark=jnp.maximum(a.watermark, b.watermark),
            dropped=a.dropped + b.dropped,
        )
