"""Dimensional schema: the ``eventSchema.json`` contract as a dataclass.

The reference declares its dimensional cube in JSON
(``apex-benchmarks/src/main/resources/eventSchema.json``): key fields, time
buckets ("10s"), value fields with aggregator lists (clicks:SUM,
latency:MAX), and key combinations (["campaignId"]).  The Apex engine
interprets it reflectively via POJO field expressions
(``ApplicationDimensionComputation.java:96-116``); here it compiles to
static shapes — each (combination, value, aggregator) triple becomes one
dense device array in ``DimensionState``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

# aggregator -> (scatter kind, identity element for int64 accumulation)
AGGREGATORS: dict[str, tuple[str, int]] = {
    "SUM": ("add", 0),
    "COUNT": ("count", 0),
    "MAX": ("max", -(2**62)),
    "MIN": ("min", 2**62),
}

_TIME_UNITS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000}


def parse_time_bucket(spec: str) -> int:
    """'10s' / '200ms' / '1m' -> milliseconds."""
    for unit in sorted(_TIME_UNITS, key=len, reverse=True):
        if spec.endswith(unit):
            head = spec[:-len(unit)]
            if head.isdigit():
                return int(head) * _TIME_UNITS[unit]
    raise ValueError(f"unparseable time bucket {spec!r}")


@dataclass(frozen=True)
class ValueSpec:
    name: str
    aggregators: tuple[str, ...]


@dataclass(frozen=True)
class DimensionalSchema:
    keys: tuple[str, ...]                    # all declared key fields
    time_bucket_ms: int                      # first (primary) time bucket
    values: tuple[ValueSpec, ...]
    combinations: tuple[tuple[str, ...], ...]  # key subsets to cube over

    def aggregate_slots(self) -> list[tuple[str, str]]:
        """The (value, aggregator) pairs, in declaration order — one state
        array per pair per combination."""
        return [(v.name, a) for v in self.values for a in v.aggregators]

    def validate(self) -> None:
        for v in self.values:
            for a in v.aggregators:
                if a not in AGGREGATORS:
                    raise ValueError(f"unsupported aggregator {a!r} "
                                     f"for value {v.name!r}")
        for combo in self.combinations:
            unknown = set(combo) - set(self.keys)
            if unknown:
                raise ValueError(f"combination {combo} uses undeclared "
                                 f"keys {sorted(unknown)}")


def parse_schema(src: str | dict) -> DimensionalSchema:
    """Parse an eventSchema.json-shaped document (string or dict).

    Tolerates trailing commas (the reference's own schema file has one
    after the campaignId key entry)."""
    if isinstance(src, str):
        src = json.loads(_strip_trailing_commas(src))
    keys = tuple(k["name"] for k in src.get("keys", []))
    buckets = src.get("timeBuckets") or ["10s"]  # absent OR empty -> 10s
    values = tuple(ValueSpec(v["name"], tuple(v.get("aggregators", ["SUM"])))
                   for v in src.get("values", []))
    combos = tuple(tuple(c["combination"])
                   for c in src.get("dimensions", [])) or (keys,)
    schema = DimensionalSchema(
        keys=keys,
        time_bucket_ms=parse_time_bucket(buckets[0]),
        values=values,
        combinations=combos,
    )
    schema.validate()
    return schema


def _strip_trailing_commas(text: str) -> str:
    out: list[str] = []
    in_str = False
    for i, ch in enumerate(text):
        if ch == '"' and (i == 0 or text[i - 1] != "\\"):
            in_str = not in_str
        if not in_str and ch in "]}":
            # drop a dangling comma before a closer
            j = len(out) - 1
            while j >= 0 and out[j] in " \t\r\n":
                j -= 1
            if j >= 0 and out[j] == ",":
                del out[j]
        out.append(ch)
    return "".join(out)
