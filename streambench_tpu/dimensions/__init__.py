"""Dimensional aggregation family (the Apex dimension-computation peer).

Re-expresses components #19-#23 of SURVEY.md §2 TPU-first: a declarative
dimensional schema (``eventSchema.json`` shape), a jitted multi-aggregate
window kernel (SUM/MAX/MIN/COUNT per key per time bucket), a durable
append-log store with the latency-aware decile report, and a JSON-lines
pub/sub query channel (the WebSocket gateway analog).
"""

from streambench_tpu.dimensions.app import (  # noqa: F401
    SENTINEL_CAMPAIGN,
    DimensionApp,
)
from streambench_tpu.dimensions.compute import (  # noqa: F401
    DimensionState,
    DimensionsComputation,
    KeyInterner,
)
from streambench_tpu.dimensions.pubsub import (  # noqa: F401
    PubSubClient,
    PubSubServer,
)
from streambench_tpu.dimensions.schema import (  # noqa: F401
    AGGREGATORS,
    DimensionalSchema,
    parse_schema,
)
from streambench_tpu.dimensions.store import DurableDimensionStore  # noqa: F401
