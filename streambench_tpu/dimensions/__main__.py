"""Dimension-computation app CLI.

Peer of the Apex app launches (``stream-bench.sh:268``: ``apex launch
-local``): either self-contained with an in-process JSON generator
(``ApplicationWithGenerator.java:22-58`` — seeds its own join table) or
consuming a broker topic produced by the generator CLI.  Optional pub/sub
query endpoint (the gateway analog; see ``dimensions.pubsub``).

    python -m streambench_tpu.dimensions --generate 100000 \
        --storeDir ./dim-store [--pubsubPort 8890] [--schema schema.json]
    python -m streambench_tpu.dimensions --confPath conf.yaml \
        --workdir RUN_DIR --brokerDir DIR --storeDir ./dim-store
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from streambench_tpu.utils.platform import pin_jax_platform

pin_jax_platform()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="streambench-dimensions")
    p.add_argument("--confPath", default=None)
    p.add_argument("--workdir", default=".")
    p.add_argument("--brokerDir", default=None)
    p.add_argument("--storeDir", required=True)
    p.add_argument("--schema", default=None,
                   help="eventSchema.json-shaped file (default: built-in "
                        "campaignId / clicks:SUM / latency:MAX)")
    p.add_argument("--generate", type=int, default=None,
                   help="self-contained mode: generate N events in-process "
                        "instead of reading a broker topic")
    p.add_argument("--numCampaigns", type=int, default=100)
    p.add_argument("--adsPerCampaign", type=int, default=10)
    p.add_argument("--pubsubPort", type=int, default=None)
    p.add_argument("--noJoin", action="store_true",
                   help="sentinel-campaign mode (includeRedisJoin=false)")
    args = p.parse_args(argv)

    import random

    from streambench_tpu.datagen import gen
    from streambench_tpu.dimensions import DimensionApp, PubSubServer
    from streambench_tpu.dimensions.schema import parse_schema
    from streambench_tpu.utils.ids import now_ms

    schema = None
    if args.schema:
        schema = parse_schema(open(args.schema).read())

    pubsub = None
    if args.pubsubPort is not None:
        pubsub = PubSubServer(port=args.pubsubPort).start()
        print(f"pubsub listening on {pubsub.address[0]}:{pubsub.address[1]}",
              flush=True)

    if args.generate is not None:
        # ApplicationWithGenerator mode: build our own join table
        rng = random.Random(77)
        campaigns = gen.make_ids(args.numCampaigns, rng)
        ads = gen.make_ids(args.numCampaigns * args.adsPerCampaign, rng)
        mapping = {a: campaigns[i % len(campaigns)]
                   for i, a in enumerate(ads)}
        src = gen.EventSource(ads=ads, user_ids=gen.make_ids(100, rng),
                              page_ids=gen.make_ids(100, rng), rng=rng)
        app = DimensionApp(schema, mapping, args.storeDir,
                           campaigns=campaigns,
                           include_join=not args.noJoin, pubsub=pubsub)
        start = now_ms()
        chunk = 8192
        done = 0
        while done < args.generate:
            n = min(chunk, args.generate - done)
            lines = [e.encode() for e in src.events_at(
                start + 10 * (done + i) for i in range(n))]
            app.process_lines(lines)
            app.flush()
            done += n
    else:
        from streambench_tpu.config import find_and_read_config_file
        from streambench_tpu.io.journal import FileBroker

        if not args.confPath:
            print("error: --confPath required without --generate",
                  file=sys.stderr)
            return 2
        cfg = find_and_read_config_file(args.confPath)
        mapping = gen.load_ad_mapping_file(
            cfg.ad_to_campaign_path
            or os.path.join(args.workdir, gen.AD_TO_CAMPAIGN_FILE))
        ids = gen.load_ids(args.workdir)
        campaigns = ids[0] if ids else None
        app = DimensionApp(schema, mapping, args.storeDir,
                           campaigns=campaigns,
                           include_join=not args.noJoin, pubsub=pubsub)
        broker = FileBroker(args.brokerDir
                            or os.path.join(args.workdir, "broker"))
        with broker.multi_reader(cfg.kafka_topic) as reader:
            while True:
                lines = reader.poll(max_records=8192)
                if not lines:
                    break
                app.process_lines(lines)
                app.flush()

    report = app.close()
    print(report, file=sys.stderr, flush=True)
    print(json.dumps({
        "events": app.events, "invalid": app.invalid_tuples,
        "dropped": app.dropped, "stored_rows": len(app.store.index),
    }), flush=True)
    if pubsub is not None:
        pubsub.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
