"""HyperLogLog distinct counting as a windowed TPU aggregation kernel.

BASELINE config #2: the YSB topology with distinct-user-per-campaign in
place of the exact view count.  Per event the update is a scatter-max of
the rank (leading-zero count) into a register array — exactly the shape of
the exact-count scatter-add, so it shares ``assign_windows`` and the same
ring/watermark semantics, and the cross-device merge is ``pmax`` (register
max is associative/commutative, so sharded merge is exact, SURVEY.md §2
"Reduce/unifier" row).

Registers are uint8 ``[C, W, R]`` with R a power of two: a register
holds ``1 + leading-zero-count`` of a 32-bit hash's top bits — at most
``33 - log2(R) <= 26`` for any R >= 64 — so a byte plane is lossless
and packs 4x the registers per byte of the original int32 plane
(ROADMAP item 2a; the fold casts the int32 rank at the scatter, so a
legacy int32 plane restored from an old snapshot still folds
bit-identically).  The hash is splitmix32 over the interned user index
(dense ids hash as well as UUIDs once mixed).  Estimation runs on
device: the classic alpha_m bias-corrected harmonic mean with
linear-counting small-range correction.

Unlike exact counts (flushed as HINCRBY-able deltas), HLL registers are
NOT deltas: the flush snapshots estimates for occupied slots and zeroes
only *closed* slots; the Redis writeback overwrites (HSET) instead of
accumulating.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from streambench_tpu.ops.windowcount import assign_windows


class HLLState(NamedTuple):
    """registers: [C, W, R] uint8 (ranks <= 26 fit a byte; legacy int32
    planes from old snapshots still fold); ring metadata as in
    WindowState."""

    registers: jax.Array
    window_ids: jax.Array
    watermark: jax.Array
    dropped: jax.Array


def init_state(num_campaigns: int, window_slots: int,
               num_registers: int = 256) -> HLLState:
    if num_registers & (num_registers - 1):
        raise ValueError("num_registers must be a power of two")
    return HLLState(
        registers=jnp.zeros((num_campaigns, window_slots, num_registers),
                            jnp.uint8),
        window_ids=jnp.full((window_slots,), -1, jnp.int32),
        watermark=jnp.int32(0),
        dropped=jnp.int32(0),
    )


def splitmix32(x: jax.Array) -> jax.Array:
    """32-bit splitmix finalizer (public-domain constant schedule)."""
    x = x.astype(jnp.uint32)
    x = (x + jnp.uint32(0x9E3779B9)).astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x21F0AAAD)
    x = (x ^ (x >> 15)) * jnp.uint32(0x735A2D97)
    x = x ^ (x >> 15)
    return x


def _rank(h: jax.Array, p: int) -> jax.Array:
    """1 + leading-zero count of the top (32-p) hash bits, in [1, 33-p].

    Computed via float32 frexp bit-length; exact because w < 2^(32-p)
    <= 2^24 for p >= 8 (init_state enforces R=2^p with p <= 14 in
    practice; callers should keep p >= 8 for exactness, or accept
    float32-rounding slack above that).
    """
    bits = 32 - p
    w = (h >> jnp.uint32(p)).astype(jnp.int32)
    _, exp = jnp.frexp(w.astype(jnp.float32))
    bitlen = jnp.where(w > 0, exp, 0)
    return (bits - bitlen + 1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("divisor_ms", "lateness_ms", "view_type"))
def step(state: HLLState, join_table: jax.Array,
         ad_idx: jax.Array, user_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, divisor_ms: int = 10_000, lateness_ms: int = 60_000,
         view_type: int = 0) -> HLLState:
    """Fold one micro-batch: registers[campaign, slot, j] = max(., rank)."""
    C, W, R = state.registers.shape
    p = R.bit_length() - 1

    campaign = join_table[ad_idx]
    wid = event_time // divisor_ms
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    slot, count_mask, window_ids, watermark = assign_windows(
        state.window_ids, state.watermark, wid, wanted, valid, event_time,
        divisor_ms=divisor_ms, lateness_ms=lateness_ms)

    h = splitmix32(user_idx)
    j = (h & jnp.uint32(R - 1)).astype(jnp.int32)
    rank = _rank(h, p)

    flat = jnp.where(count_mask, (campaign * W + slot) * R + j, C * W * R)
    registers = (state.registers.reshape(-1)
                 .at[flat].max(rank.astype(state.registers.dtype),
                               mode="drop")
                 .reshape(C, W, R))

    dropped = state.dropped + (
        jnp.sum(wanted.astype(jnp.int32))
        - jnp.sum(count_mask.astype(jnp.int32)))
    return HLLState(registers, window_ids, watermark, dropped)


@jax.jit
def estimate(registers: jax.Array) -> jax.Array:
    """Distinct-count estimates, any leading batch dims over last axis R.

    alpha_m * R^2 / sum(2^-M) with linear counting below 2.5R when empty
    registers remain (Flajolet et al. 2007 operating points).
    """
    R = registers.shape[-1]
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(
        R, 0.7213 / (1 + 1.079 / R))
    inv = jnp.sum(jnp.exp2(-registers.astype(jnp.float32)), axis=-1)
    raw = alpha * R * R / inv
    zeros = jnp.sum((registers == 0).astype(jnp.float32), axis=-1)
    linear = R * jnp.log(jnp.where(zeros > 0, R / jnp.maximum(zeros, 1.0),
                                   1.0))
    return jnp.where((raw <= 2.5 * R) & (zeros > 0), linear, raw)


def merge(a: HLLState, b: HLLState) -> HLLState:
    """Partial-state union: elementwise register max (associative,
    commutative, idempotent — the pmax the sharded engines rely on).

    Geometry is validated up front and a mismatch names both shapes —
    a [C, W, R] drift used to broadcast into garbage registers or die
    in XLA with an unhelpful shape error.  PRECONDITION: both partials
    share one window-ring assignment (shard splits of a single stream,
    where every shard ran the same ``assign_windows`` sequence) — slot
    ids are positional, so merging rings with different assignments is
    meaningless and the ids are taken from ``a``.
    """
    if (a.registers.shape != b.registers.shape
            or a.registers.dtype != b.registers.dtype):
        raise ValueError(
            f"hll.merge: geometry mismatch — a.registers "
            f"{a.registers.shape}/{a.registers.dtype} vs b.registers "
            f"{b.registers.shape}/{b.registers.dtype}")
    if a.window_ids.shape != b.window_ids.shape:
        raise ValueError(
            f"hll.merge: window-ring mismatch — a.window_ids "
            f"{a.window_ids.shape} vs b.window_ids {b.window_ids.shape}")
    return HLLState(
        registers=jnp.maximum(a.registers, b.registers),
        window_ids=a.window_ids,
        watermark=jnp.maximum(a.watermark, b.watermark),
        dropped=a.dropped + b.dropped)


@functools.partial(jax.jit, static_argnames=("divisor_ms", "lateness_ms"))
def flush(state: HLLState, *, divisor_ms: int = 10_000,
          lateness_ms: int = 60_000):
    """Snapshot estimates ``[C, W]`` + window ids; zero registers of
    *closed* slots (watermark past end + lateness) and free their slots.
    Open slots keep their registers — estimates are absolute, not deltas.
    """
    est = estimate(state.registers)
    closed = ((state.window_ids + 1) * divisor_ms + lateness_ms
              <= state.watermark)
    freed = closed | (state.window_ids < 0)
    new_ids = jnp.where(freed, jnp.int32(-1), state.window_ids)
    regs = jnp.where(freed[None, :, None], 0, state.registers)
    return est, state.window_ids, HLLState(
        regs, new_ids, state.watermark, state.dropped)


@functools.partial(
    jax.jit, static_argnames=("divisor_ms", "lateness_ms", "view_type"))
def scan_steps(state: HLLState, join_table: jax.Array,
               ad_idx: jax.Array, user_idx: jax.Array,
               event_type: jax.Array, event_time: jax.Array,
               valid: jax.Array,
               *, divisor_ms: int = 10_000, lateness_ms: int = 60_000,
               view_type: int = 0) -> HLLState:
    """Fold ``[N, B]`` stacked micro-batches via ``lax.scan`` — one
    dispatch per chunk, same amortization as
    ``ops.windowcount.scan_steps``."""

    def body(carry, xs):
        a, u, e, t, v = xs
        return step(carry, join_table, a, u, e, t, v,
                    divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                    view_type=view_type), None

    final, _ = jax.lax.scan(
        body, state, (ad_idx, user_idx, event_type, event_time, valid))
    return final


@functools.partial(
    jax.jit, static_argnames=("divisor_ms", "lateness_ms", "view_type"))
def scan_steps_packed(state: HLLState, join_table: jax.Array,
                      packed: jax.Array, user_idx: jax.Array,
                      event_time: jax.Array,
                      *, divisor_ms: int = 10_000,
                      lateness_ms: int = 60_000,
                      view_type: int = 0) -> HLLState:
    """``scan_steps`` over the packed wire word
    (``windowcount.pack_columns``) + user ids: 12 B/event on the wire
    instead of 17 B across five buffers."""
    from streambench_tpu.ops.windowcount import unpack_columns

    def body(carry, xs):
        p, u, t = xs
        a, e, v = unpack_columns(p)
        return step(carry, join_table, a, u, e, t, v,
                    divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                    view_type=view_type), None

    final, _ = jax.lax.scan(
        body, state, (packed, user_idx, event_time))
    return final
