"""Count-min sketch as a TPU scatter-add kernel.

BASELINE config #4's heavy-hitter structure: approximate per-key counts
(clicks per user) in ``D`` hash rows x ``Wd`` counters.  Update is a
masked scatter-add — same shape as the exact window count — and the
cross-device merge is ``psum`` (counter add is associative/commutative:
sharded merge exact, SURVEY.md §2 "Reduce/unifier" row).

Point query = min over rows; heavy-hitter candidates are maintained on the
host (classic CMS + candidate-set idiom) from the interned key universe.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from streambench_tpu.ops.hll import splitmix32


class CMSState(NamedTuple):
    table: jax.Array   # [D, Wd] int32
    total: jax.Array   # [] int32 — total weight folded in


# Distinct odd salts decorrelate the D rows of one splitmix stream.
_SALTS = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F,
          0x165667B1, 0xFC545C4F, 0x2545F491, 0x61C88647)


def init_state(depth: int = 4, width: int = 2048) -> CMSState:
    if width & (width - 1):
        raise ValueError("width must be a power of two")
    if depth > len(_SALTS):
        raise ValueError(f"depth <= {len(_SALTS)}")
    return CMSState(table=jnp.zeros((depth, width), jnp.int32),
                    total=jnp.int32(0))


def _row_cols(keys: jax.Array, depth: int, width: int) -> jax.Array:
    """[D, B] column index per row: salted splitmix32, low log2(Wd) bits."""
    cols = []
    for d in range(depth):
        h = splitmix32(keys.astype(jnp.uint32) ^ jnp.uint32(_SALTS[d]))
        cols.append((h & jnp.uint32(width - 1)).astype(jnp.int32))
    return jnp.stack(cols)


@jax.jit
def update(state: CMSState, keys: jax.Array, weights: jax.Array,
           mask: jax.Array) -> CMSState:
    """Add ``weights`` for ``keys`` (masked rows dropped)."""
    D, Wd = state.table.shape
    cols = _row_cols(keys, D, Wd)                       # [D, B]
    w = jnp.where(mask, weights, 0).astype(jnp.int32)   # [B]
    flat = (jnp.arange(D, dtype=jnp.int32)[:, None] * Wd + cols)
    flat = jnp.where(mask[None, :], flat, D * Wd)
    table = (state.table.reshape(-1)
             .at[flat.reshape(-1)]
             .add(jnp.broadcast_to(w, (D, w.shape[0])).reshape(-1),
                  mode="drop")
             .reshape(D, Wd))
    return CMSState(table, state.total + jnp.sum(w))


@jax.jit
def update_rowloop(state: CMSState, keys: jax.Array, weights: jax.Array,
                   mask: jax.Array) -> CMSState:
    """``update`` as D per-row scatter-adds instead of one flat scatter
    over the [D*Wd] plane — bit-identical; exists so the cms-family
    methodbench can measure which landing the backend prefers (XLA's
    flat scatter wins where row-concatenated indices fuse, the row loop
    where narrower scatters schedule better)."""
    D, Wd = state.table.shape
    cols = _row_cols(keys, D, Wd)
    w = jnp.where(mask, weights, 0).astype(jnp.int32)
    table = state.table
    for d in range(D):
        c = jnp.where(mask, cols[d], Wd)
        table = table.at[d].set(table[d].at[c].add(w, mode="drop"))
    return CMSState(table, state.total + jnp.sum(w))


@jax.jit
def query(state: CMSState, keys: jax.Array) -> jax.Array:
    """Point estimates (upper bounds) for ``keys``: min over rows."""
    D, Wd = state.table.shape
    cols = _row_cols(keys, D, Wd)
    rows = jnp.arange(D, dtype=jnp.int32)[:, None]
    return jnp.min(state.table[rows, cols], axis=0)


def merge(a: CMSState, b: CMSState) -> CMSState:
    """Sketch union: elementwise add.  Geometry is validated up front —
    a [D, Wd] mismatch used to broadcast into garbage (or die with a
    cryptic XLA shape error deep in the add); now it names both
    shapes."""
    if a.table.shape != b.table.shape or a.table.dtype != b.table.dtype:
        raise ValueError(
            f"cms.merge: geometry mismatch — a.table "
            f"{a.table.shape}/{a.table.dtype} vs b.table "
            f"{b.table.shape}/{b.table.dtype}")
    return CMSState(a.table + b.table, a.total + b.total)


# ----------------------------------------------------------------------
# SF-style two-stage sketch (ISSUE 13 / arXiv:1701.04148): a small
# query-side stage next to the fat update-side stage.
# ----------------------------------------------------------------------

class CMS2State(NamedTuple):
    """Two-stage count-min: ``fat`` is the ordinary update-linear
    [D, Wd] sketch (sharded merges psum IT — counter add stays linear);
    ``small [D, Ws]`` is the query-side stage, updated only when the
    fat stage's estimate for the touched key increases (a scatter-max
    of the post-update fat estimate).  Queries gather from the small
    plane — ~Wd/Ws fewer bytes per gather for the heavy-hitter paths
    (``fold_candidates``/``update_topk``) — and stay upper bounds: a
    key's true count is frozen at its last update, and the small cell
    only grows from estimates taken at update time.

    The small stage does NOT merge across shards (max of two shards'
    estimates can undercut the summed true count): ``merge`` on this
    state raises, and the sharded session engine refuses stages=2 —
    the fat stage is the distributed-merge surface, per the SF-sketch
    split."""

    fat: CMSState
    small: jax.Array   # [D, Ws] int32


def init_two_stage(depth: int = 4, width: int = 2048,
                   small_width: int | None = None) -> CMS2State:
    sw = small_width if small_width is not None else max(width // 8, 64)
    if sw & (sw - 1):
        raise ValueError("small_width must be a power of two")
    return CMS2State(fat=init_state(depth, width),
                     small=jnp.zeros((depth, sw), jnp.int32))


@jax.jit
def update2(state: CMS2State, keys: jax.Array, weights: jax.Array,
            mask: jax.Array) -> CMS2State:
    """Fat scatter-add, then refresh the small stage with the keys' NEW
    fat estimates (scatter-max, masked rows dropped)."""
    fat = update(state.fat, keys, weights, mask)
    est = query(fat, keys)                               # [B] upper bounds
    D, Ws = state.small.shape
    scols = _row_cols(keys, D, Ws)
    flat = jnp.arange(D, dtype=jnp.int32)[:, None] * Ws + scols
    flat = jnp.where(mask[None, :], flat, D * Ws)
    small = (state.small.reshape(-1)
             .at[flat.reshape(-1)]
             .max(jnp.broadcast_to(est, (D, est.shape[0])).reshape(-1),
                  mode="drop")
             .reshape(D, Ws))
    return CMS2State(fat, small)


@jax.jit
def query_small(state: CMS2State, keys: jax.Array) -> jax.Array:
    """Point estimates from the small stage: min over rows of the
    [D, Ws] plane (the SF-sketch read path)."""
    D, Ws = state.small.shape
    scols = _row_cols(keys, D, Ws)
    rows = jnp.arange(D, dtype=jnp.int32)[:, None]
    return jnp.min(state.small[rows, scols], axis=0)


def merge2(a: CMS2State, b: CMS2State) -> CMS2State:
    raise ValueError(
        "cms.CMS2State does not merge: max over small-stage estimates "
        "undercuts the summed true count (no longer an upper bound) — "
        "merge the fat stages (psum/cms.merge) and rebuild, or run "
        "two-stage single-device only")


# ----------------------------------------------------------------------
# family dispatch: the session engine's kernels run unchanged over the
# fixed, SALSA, and two-stage families through these two entry points
# (trace-time isinstance branches; the fixed path lowers to exactly the
# pre-existing programs, keeping the legacy arm byte-identical).
# ----------------------------------------------------------------------

def sk_update(state, keys: jax.Array, weights: jax.Array,
              mask: jax.Array):
    """Family-dispatching update (fixed / salsa / two-stage)."""
    if isinstance(state, CMSState):
        return update(state, keys, weights, mask)
    if isinstance(state, CMS2State):
        return update2(state, keys, weights, mask)
    from streambench_tpu.ops import salsa

    if isinstance(state, salsa.SalsaState):
        return salsa.update(state, keys, weights, mask)
    raise TypeError(f"not a sketch state: {type(state).__name__}")


def point_query(state, keys: jax.Array) -> jax.Array:
    """Family-dispatching point query.  Two-stage reads the SMALL
    stage (that is its point); SALSA reads the widest merged counter."""
    if isinstance(state, CMSState):
        return query(state, keys)
    if isinstance(state, CMS2State):
        return query_small(state, keys)
    from streambench_tpu.ops import salsa

    if isinstance(state, salsa.SalsaState):
        return salsa.query(state, keys)
    raise TypeError(f"not a sketch state: {type(state).__name__}")


def sk_total(state) -> jax.Array:
    """Total folded weight for any family."""
    return state.fat.total if isinstance(state, CMS2State) else state.total


@functools.partial(jax.jit, static_argnames=("k",))
def heavy_hitters(state, candidate_keys: jax.Array, *,
                  k: int = 16):
    """Top-k candidates by sketch estimate: (values, indices into
    candidates).  Works over any sketch family (``point_query``); the
    two-stage family reports from its small stage.

    Query cost is linear in the CANDIDATE set — callers must keep that
    bounded (see ``TopKState``); enumerating the whole interned key
    universe here defeats the sketch's sublinearity.
    """
    est = point_query(state, candidate_keys)
    return jax.lax.top_k(est, k)


class TopKState(NamedTuple):
    """Fixed-size device-resident heavy-hitter candidate ring.

    The classic CMS + candidate-set idiom with the candidate set ON
    DEVICE and bounded: ``keys [M]`` (int32 interned ids, -1 empty) with
    their last-queried estimates ``ests [M]``.  Every update batch's keys
    compete against the ring by estimate; a true heavy hitter keeps
    reappearing in the stream, so it re-enters with its ever-growing
    estimate even if it was evicted while still small.  Report cost is
    O(M), independent of the key universe.
    """

    keys: jax.Array   # [M] int32, -1 = empty slot
    ests: jax.Array   # [M] int32, -1 for empty slots


def init_topk(capacity: int = 128) -> TopKState:
    return TopKState(keys=jnp.full((capacity,), -1, jnp.int32),
                     ests=jnp.full((capacity,), -1, jnp.int32))


def init_candidates(capacity: int) -> tuple[jax.Array, jax.Array]:
    """Fresh chunk-local candidate table for ``fold_candidates``."""
    if capacity & (capacity - 1):
        raise ValueError("candidate capacity must be a power of two")
    return (jnp.full((capacity,), -1, jnp.int32),
            jnp.full((capacity,), -1, jnp.int32))


def fold_candidates(cand_keys: jax.Array, cand_ests: jax.Array,
                    keys: jax.Array, ests: jax.Array, mask: jax.Array,
                    salt: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Fold one batch into a hash-slotted candidate table: O(B), no sort.

    The exact-top-M ring update (``update_topk``) sorts ring+batch every
    call — 80%+ of the session engine's scanned device time.  Hot loops
    instead scatter candidates into this chunk-local table (each key
    competes for ONE salted slot; winner decided by (est, key) via two
    scatter-max passes, so ties are deterministic) and merge the table
    into the ring with a single ``update_topk`` call per chunk.

    A hash collision shadows the lighter key for this chunk only: the
    caller varies ``salt`` (a traced scalar, e.g. the chunk's dispatch
    stamp) so no pair of keys collides persistently, and a true heavy
    hitter keeps reappearing until an unshadowed chunk carries it into
    the ring, where ``update_topk``'s global-max semantics keep it.
    """
    M2 = cand_keys.shape[0]
    k = keys.astype(jnp.int32)
    h = splitmix32(k.astype(jnp.uint32)
                   ^ jnp.uint32(0xA5A5A5A5) ^ salt.astype(jnp.uint32))
    slot = (h & jnp.uint32(M2 - 1)).astype(jnp.int32)
    e = jnp.where(mask, ests, -1).astype(jnp.int32)
    slot_m = jnp.where(mask, slot, M2)
    best = cand_ests.at[slot_m].max(e, mode="drop")
    # keep the occupant's key where it still holds the slot max; ties
    # between occupant and batch (or within the batch) go to max key
    win = mask & (e >= best[jnp.clip(slot, 0, M2 - 1)])
    base = jnp.where(best == cand_ests, cand_keys, -1)
    new_keys = base.at[jnp.where(win, slot, M2)].max(
        jnp.where(win, k, -1), mode="drop")
    return new_keys, best


@jax.jit
def update_topk(state, topk: TopKState, keys: jax.Array,
                mask: jax.Array) -> TopKState:
    """Fold one batch of (masked) keys into the candidate ring.

    Concatenate ring + batch, dedupe by key keeping the max estimate
    (sort by a combined (key, -est) int64 rank; duplicates collapse to
    their first = largest entry), then keep the top-M by estimate.  All
    shapes static; one sort + one top_k on device.  ``state`` is any
    sketch family (``point_query`` — the two-stage ring reads the
    small stage, the SALSA ring the widest merged counter).
    """
    M = topk.keys.shape[0]
    est = jnp.where(mask, point_query(state, keys), -1).astype(jnp.int32)
    k_new = jnp.where(mask, keys.astype(jnp.int32), -1)
    allk = jnp.concatenate([topk.keys, k_new])
    alle = jnp.concatenate([topk.ests, est])
    # Group by key ascending with the largest estimate first within each
    # key (lexsort: last key is primary).  Stays in int32 — a packed
    # (key << 32 | est) int64 rank would silently truncate under JAX's
    # default x64-disabled mode and destroy the grouping.
    order = jnp.lexsort((-alle, allk))
    k_sorted = allk[order]
    e_sorted = alle[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), k_sorted[1:] != k_sorted[:-1]])
    keep = first & (k_sorted >= 0)
    e_uniq = jnp.where(keep, e_sorted, -1)
    vals, idx = jax.lax.top_k(e_uniq, M)
    return TopKState(keys=jnp.where(vals >= 0, k_sorted[idx], -1),
                     ests=vals)
