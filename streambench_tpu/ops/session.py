"""Session windows (gap-based), fully vectorized with carried user state.

BASELINE config #4: per-user click aggregation over 30 s-gap sessions.
Sessionization is inherently per-key-sequential; the TPU formulation makes
it data-parallel per micro-batch:

1. sort the batch by (user, time) — two stable argsorts, no dynamic shapes;
2. a session boundary is a user change or an intra-user gap > ``gap_ms``;
   segment ids come from a cumsum over boundary flags;
3. per-segment aggregates (start, end, clicks) via ``segment_sum``-style
   scatters with a static segment capacity of B;
4. the *last* segment per user merges into the carried state
   ``(last_time, sess_start, clicks)[user]``; earlier segments close and
   are emitted as fixed-shape ``[B]`` arrays with validity masks, as is a
   carried session whose user reappears after the gap.

Sessions also close by time: ``flush`` emits every carried session whose
``last_time + gap + lateness`` the watermark has passed (no event can
extend it anymore, since older events are dropped as late).

State capacity is static (``capacity`` users = interned ids); events whose
user index overflows it are dropped and counted, like ring eviction.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from streambench_tpu.ops.windowcount import NEG


class SessionState(NamedTuple):
    last_time: jax.Array   # [U] int32; -1 = no open session
    sess_start: jax.Array  # [U] int32
    clicks: jax.Array      # [U] int32
    watermark: jax.Array   # [] int32
    dropped: jax.Array     # [] int32


class ClosedSessions(NamedTuple):
    """Fixed-shape emission: one row per (potential) closed session."""

    user: jax.Array    # [N] int32
    start: jax.Array   # [N] int32
    end: jax.Array     # [N] int32
    clicks: jax.Array  # [N] int32
    valid: jax.Array   # [N] bool


def init_state(capacity: int) -> SessionState:
    return SessionState(
        last_time=jnp.full((capacity,), -1, jnp.int32),
        sess_start=jnp.zeros((capacity,), jnp.int32),
        clicks=jnp.zeros((capacity,), jnp.int32),
        watermark=jnp.int32(0),
        dropped=jnp.int32(0),
    )


@functools.partial(
    jax.jit, static_argnames=("gap_ms", "lateness_ms", "click_type"))
def step(state: SessionState, user_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, gap_ms: int = 30_000, lateness_ms: int = 60_000,
         click_type: int = 1
         ) -> tuple[SessionState, ClosedSessions, ClosedSessions]:
    """Fold one micro-batch; returns (state, closed_in_batch, closed_carry)."""
    U = state.last_time.shape[0]
    B = user_idx.shape[0]

    # Lateness vs watermark as of batch start (see ops.windowcount) plus
    # capacity overflow.
    min_t = state.watermark - lateness_ms
    mask = valid & (event_time >= min_t) & (user_idx >= 0) & (user_idx < U)
    batch_max = jnp.max(jnp.where(valid, event_time, NEG))
    new_wm = jnp.maximum(state.watermark, batch_max)
    dropped = state.dropped + (
        jnp.sum(valid.astype(jnp.int32)) - jnp.sum(mask.astype(jnp.int32)))

    # Sort by (user, time); masked rows sort to the end via user key U.
    ukey = jnp.where(mask, user_idx, U)
    order = jnp.argsort(event_time, stable=True)
    order = order[jnp.argsort(ukey[order], stable=True)]
    su = user_idx[order]
    st = event_time[order]
    sm = mask[order]
    sclick = (event_type[order] == click_type) & sm

    prev_su = jnp.concatenate([jnp.full((1,), -1, jnp.int32), su[:-1]])
    prev_st = jnp.concatenate([jnp.full((1,), 0, jnp.int32), st[:-1]])
    prev_sm = jnp.concatenate([jnp.zeros((1,), bool), sm[:-1]])
    same_user = sm & prev_sm & (su == prev_su)
    first_of_user = sm & ~same_user

    # Carried-session link.  A user's carry merges into their FIRST
    # in-batch segment iff the first event lies within ``gap_ms`` of the
    # carried span on EITHER side: at most gap after the last activity,
    # and at most gap before the carried session's start — a very late
    # event predating the span by more than the gap is its own session.
    # (If only a LATER in-batch event is near the carry, the merge is
    # missed — an accepted approximation: carry merges only at the first
    # segment.)
    cu = jnp.clip(su, 0, U - 1)
    user_first_t = jnp.full((U,), 2**31 - 1, jnp.int32).at[
        jnp.where(first_of_user, su, U)].min(st, mode="drop")
    ucont = ((state.last_time >= 0)
             & (user_first_t - state.last_time <= gap_ms)
             & (state.sess_start - user_first_t <= gap_ms))
    carry_last = state.last_time[cu]
    carry_open = first_of_user & (carry_last >= 0)
    cont_carry = first_of_user & ucont[cu]

    # Gap test: the session's last activity before row i is
    # max(previous in-batch time, carried last_time when the carry merges
    # into this user's first segment).  A late event can sort before the
    # carried last_time, so prev_st alone would split sessions spuriously.
    eff_prev = jnp.maximum(prev_st, jnp.where(ucont[cu], carry_last, NEG))
    boundary = first_of_user | (same_user & (st - eff_prev > gap_ms))
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1       # [B] segment id
    seg = jnp.where(sm, seg, B)                            # masked → pad seg

    ar = jnp.arange(B, dtype=jnp.int32)
    seg_clicks = jnp.zeros((B,), jnp.int32).at[seg].add(
        sclick.astype(jnp.int32), mode="drop")
    seg_start = jnp.full((B,), 2**31 - 1, jnp.int32).at[seg].min(
        jnp.where(sm, st, 2**31 - 1), mode="drop")
    seg_end = jnp.full((B,), NEG, jnp.int32).at[seg].max(
        jnp.where(sm, st, NEG), mode="drop")
    # per-segment metadata from its boundary row
    bseg = jnp.where(boundary, seg, B)
    seg_user = jnp.full((B,), -1, jnp.int32).at[bseg].set(su, mode="drop")
    seg_cont = jnp.zeros((B,), bool).at[bseg].set(
        cont_carry, mode="drop")
    seg_exists = jnp.zeros((B,), bool).at[bseg].set(True, mode="drop")

    # Merge carried session into each user's first segment when continuing.
    # The merged end must not regress below the carried last activity (the
    # whole batch may consist of late events older than it).
    cseg_user = jnp.clip(seg_user, 0, U - 1)
    seg_start = jnp.where(
        seg_cont, jnp.minimum(seg_start, state.sess_start[cseg_user]),
        seg_start)
    seg_end = jnp.where(
        seg_cont, jnp.maximum(seg_end, state.last_time[cseg_user]), seg_end)
    seg_clicks = seg_clicks + jnp.where(
        seg_cont, state.clicks[cseg_user], 0)

    # A segment closes if a later segment of the same user exists in the
    # batch — i.e. it is not its user's last segment.
    next_boundary_same = jnp.zeros((B,), bool).at[
        jnp.where(boundary & same_user, seg - 1, B)].set(True, mode="drop")
    seg_closed = seg_exists & next_boundary_same

    closed_in_batch = ClosedSessions(
        user=seg_user, start=seg_start, end=seg_end, clicks=seg_clicks,
        valid=seg_closed)

    # Carried sessions whose user reappeared after the gap close now.
    closed_carry = ClosedSessions(
        user=su,
        start=state.sess_start[cu],
        end=carry_last,
        clicks=state.clicks[cu],
        valid=carry_open & ~cont_carry)

    # Update carry from each user's LAST (open) segment.
    seg_open = seg_exists & ~seg_closed
    open_user = jnp.where(seg_open, seg_user, U)
    last_time = state.last_time.at[open_user].set(seg_end, mode="drop")
    sess_start = state.sess_start.at[open_user].set(seg_start, mode="drop")
    clicks = state.clicks.at[open_user].set(seg_clicks, mode="drop")

    new_state = SessionState(last_time, sess_start, clicks, new_wm, dropped)
    return new_state, closed_in_batch, closed_carry


@functools.partial(jax.jit,
                   static_argnames=("gap_ms", "lateness_ms", "force"))
def flush(state: SessionState, *, gap_ms: int = 30_000,
          lateness_ms: int = 60_000,
          force: bool = False) -> tuple[SessionState, ClosedSessions]:
    """Close sessions the watermark has passed (or all, when ``force``)."""
    U = state.last_time.shape[0]
    open_ = state.last_time >= 0
    expired = open_ & (state.watermark > state.last_time + gap_ms
                       + lateness_ms)
    if force:
        expired = open_
    closed = ClosedSessions(
        user=jnp.arange(U, dtype=jnp.int32),
        start=state.sess_start, end=state.last_time, clicks=state.clicks,
        valid=expired)
    last_time = jnp.where(expired, jnp.int32(-1), state.last_time)
    return SessionState(last_time, state.sess_start, state.clicks,
                        state.watermark, state.dropped), closed
