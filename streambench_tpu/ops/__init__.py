from streambench_tpu.ops.windowcount import (  # noqa: F401
    WindowState,
    flush_deltas,
    init_state,
    scan_steps,
    step,
)
