"""MinHash ∪ HyperLogLog reach sketches as one cumulative TPU state.

ROADMAP item 4 / PAPERS.md reach forecasting (arxiv 2502.14785): the ad
platform's hot query is *reach* — how many distinct devices does a
combination of campaigns cover?  The paper's construction composes two
sketches per campaign so any union/intersection/overlap query over
arbitrary campaign sets becomes a cheap merge of materialized state:

- a **k-hash-function MinHash signature** ``mins[C, k]``: slot ``j``
  of campaign ``c`` holds ``min over devices of h_j(device)``.  Updates
  are a sort-free running-min scatter (the register-max structure of
  ``ops/hll.py`` with ``min`` in place of ``max``), so a batch folds in
  one vectorized ``at[].min``; ``merge(a, b) = elementwise min`` is
  associative/commutative/idempotent, which makes sharded materialize
  trivially exact (tests/test_minhash.py pins the algebra).
- a **paired HLL register plane** ``registers[C, R]``: the same
  scatter-max as ``ops/hll.py`` but with no window axis — reach is
  cumulative audience, not a windowed aggregate.  ``merge = elementwise
  max``.

Query evaluation (``reach/query.py``) uses the classic identities: the
union's signature/registers are the elementwise min/max over the
selected campaigns; ``P(all selected campaigns share slot j's min) =
|∩| / |∪|`` (the slot's argmin device must belong to every selected
set), so the m-way Jaccard falls out of a collision fraction and
``|∩| ≈ |∪| · J``.

Hashes are 32-bit (this repo runs with jax x64 disabled — a uint64
plane would silently truncate; see ops/devdecode.py for the same
rule).  Device identity arrives as the encoder's stateless crc32 id
column (``HASHED_IDS``), then gets one splitmix32 mix for the HLL
plane and k salted splitmix32 mixes for the signature.  32-bit minima
tie only with probability ~n·2^-32 per slot — negligible at any
cardinality this harness reaches.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from streambench_tpu.ops.hll import _rank, splitmix32
from streambench_tpu.ops.windowcount import NEG

#: "no device seen" sentinel for a signature slot (uint32 max: any real
#: hash is smaller, so the running min absorbs it away on first touch)
EMPTY = 0xFFFFFFFF

#: salt-stream constant for the k per-slot hash functions (golden-ratio
#: increment, the standard splitmix stream schedule)
_SALT_GAMMA = 0x9E3779B9


class ReachState(NamedTuple):
    """mins: [C, k] uint32 signature; registers: [C, R] int32 HLL plane;
    watermark: max valid relative event time folded (host-mirrorable,
    same convention as ``WindowState``); dropped: always 0 — reach is
    cumulative, there is no ring and no lateness cutoff to drop for
    (kept for the engine-harness contract)."""

    mins: jax.Array
    registers: jax.Array
    watermark: jax.Array
    dropped: jax.Array


def salts(k: int) -> jax.Array:
    """The k slot salts, derived once from the splitmix stream; slot
    j's hash is ``splitmix32(splitmix32(id) ^ salts[j])``."""
    return splitmix32(jnp.arange(1, k + 1, dtype=jnp.uint32)
                      * jnp.uint32(_SALT_GAMMA))


def init_state(num_campaigns: int, k: int = 256,
               num_registers: int = 256) -> ReachState:
    if k <= 0:
        raise ValueError("k must be positive")
    if num_registers & (num_registers - 1) or num_registers < 16:
        raise ValueError("num_registers must be a power of two >= 16")
    if num_campaigns * max(k, num_registers) >= 2**31:
        raise ValueError("C*k / C*R must fit int32 flat indices")
    return ReachState(
        mins=jnp.full((num_campaigns, k), EMPTY, jnp.uint32),
        registers=jnp.zeros((num_campaigns, num_registers), jnp.int32),
        watermark=jnp.int32(NEG),
        dropped=jnp.int32(0),
    )


@functools.partial(jax.jit, static_argnames=("view_type",))
def step(state: ReachState, join_table: jax.Array,
         ad_idx: jax.Array, user_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, view_type: int = 0) -> ReachState:
    """Fold one micro-batch into both sketch planes.

    Per wanted row: ``mins[campaign, j] = min(., h_j(user))`` for all k
    slots (one [B, k] hash block + one flat scatter-min) and
    ``registers[campaign, h & (R-1)] = max(., rank)`` exactly as the
    windowed HLL step.  Invalid/non-view/join-miss rows scatter to the
    drop slot (``mode="drop"``).
    """
    C, k = state.mins.shape
    R = state.registers.shape[1]
    p = R.bit_length() - 1

    campaign = join_table[ad_idx]
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    h = splitmix32(user_idx)                         # [B] base mix
    hk = splitmix32(h[:, None] ^ salts(k)[None, :])  # [B, k] slot hashes
    slot = jnp.arange(k, dtype=jnp.int32)[None, :]
    flat = jnp.where(wanted[:, None], campaign[:, None] * k + slot, C * k)
    mins = (state.mins.reshape(-1)
            .at[flat].min(hk, mode="drop")
            .reshape(C, k))

    j = (h & jnp.uint32(R - 1)).astype(jnp.int32)
    rank = _rank(h, p)
    rflat = jnp.where(wanted, campaign * R + j, C * R)
    registers = (state.registers.reshape(-1)
                 .at[rflat].max(rank, mode="drop")
                 .reshape(C, R))

    watermark = jnp.maximum(
        state.watermark, jnp.max(jnp.where(valid, event_time, NEG)))
    return ReachState(mins, registers, watermark, state.dropped)


@functools.partial(jax.jit, static_argnames=("view_type",))
def scan_steps(state: ReachState, join_table: jax.Array,
               ad_idx: jax.Array, user_idx: jax.Array,
               event_type: jax.Array, event_time: jax.Array,
               valid: jax.Array, *, view_type: int = 0) -> ReachState:
    """Fold ``[N, B]`` stacked micro-batches via ``lax.scan`` — one
    dispatch per chunk, same amortization as ``hll.scan_steps``."""

    def body(carry, xs):
        a, u, e, t, v = xs
        return step(carry, join_table, a, u, e, t, v,
                    view_type=view_type), None

    final, _ = jax.lax.scan(
        body, state, (ad_idx, user_idx, event_type, event_time, valid))
    return final


@functools.partial(jax.jit, static_argnames=("view_type",))
def scan_steps_packed(state: ReachState, join_table: jax.Array,
                      packed: jax.Array, user_idx: jax.Array,
                      event_time: jax.Array,
                      *, view_type: int = 0) -> ReachState:
    """``scan_steps`` over the packed wire word
    (``windowcount.pack_columns``) + user ids — the same 12 B/event wire
    as the HLL engine's packed scan."""
    from streambench_tpu.ops.windowcount import unpack_columns

    def body(carry, xs):
        pk, u, t = xs
        a, e, v = unpack_columns(pk)
        return step(carry, join_table, a, u, e, t, v,
                    view_type=view_type), None

    final, _ = jax.lax.scan(body, state, (packed, user_idx, event_time))
    return final


def merge(a: ReachState, b: ReachState) -> ReachState:
    """Shard/partial-state merge: elementwise min over signatures, max
    over registers.  Commutative, associative, idempotent — the algebra
    tests/test_minhash.py sweeps over random shard splits.  Geometry is
    validated up front (a [C, k]/[C, R] mismatch used to broadcast into
    garbage or die with a cryptic XLA error); the merge itself stays
    jitted."""
    if a.mins.shape != b.mins.shape or a.mins.dtype != b.mins.dtype:
        raise ValueError(
            f"minhash.merge: signature mismatch — a.mins "
            f"{a.mins.shape}/{a.mins.dtype} vs b.mins "
            f"{b.mins.shape}/{b.mins.dtype}")
    if (a.registers.shape != b.registers.shape
            or a.registers.dtype != b.registers.dtype):
        raise ValueError(
            f"minhash.merge: register mismatch — a.registers "
            f"{a.registers.shape}/{a.registers.dtype} vs b.registers "
            f"{b.registers.shape}/{b.registers.dtype}")
    return _merge_jit(a, b)


@jax.jit
def _merge_jit(a: ReachState, b: ReachState) -> ReachState:
    return ReachState(
        mins=jnp.minimum(a.mins, b.mins),
        registers=jnp.maximum(a.registers, b.registers),
        watermark=jnp.maximum(a.watermark, b.watermark),
        dropped=a.dropped + b.dropped,
    )


def estimate(registers: jax.Array) -> jax.Array:
    """Per-campaign distinct-device estimates from the HLL plane (any
    leading batch dims; delegates to the windowed HLL's estimator —
    same alpha_m/linear-counting operating points)."""
    from streambench_tpu.ops import hll

    return hll.estimate(registers)
