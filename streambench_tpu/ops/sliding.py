"""Sliding (hopping) windows: size S, slide s, as S tumbling memberships.

BASELINE config #3's windowing (10 s window / 1 s slide).  An event at t
belongs to the S = size/slide windows whose ids end at floor(t/slide); the
ring machinery is reused with ``divisor = slide`` and an *effective
lateness* of ``lateness + size - slide`` so a window closes exactly when
the watermark passes ``start + size + lateness`` (the generic ring closes
at ``(wid+1)*divisor + lateness``; the widened lateness makes those equal).

The membership loop is a static Python ``for`` over S — under jit XLA
unrolls it into S masked scatters, no dynamic control flow.  State shape
is identical to the tumbling ``WindowState``; ``flush_deltas`` works
unchanged when called with the same effective lateness.  ``dropped``
counts lost *memberships* (an event has S of them), not events.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from streambench_tpu.ops.windowcount import WindowState, assign_windows


def effective_lateness(size_ms: int, slide_ms: int, lateness_ms: int) -> int:
    return lateness_ms + size_ms - slide_ms


@functools.partial(
    jax.jit,
    static_argnames=("size_ms", "slide_ms", "lateness_ms", "view_type"))
def step(state: WindowState, join_table: jax.Array,
         ad_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, size_ms: int = 10_000, slide_ms: int = 1_000,
         lateness_ms: int = 60_000, view_type: int = 0) -> WindowState:
    if size_ms % slide_ms:
        raise ValueError("size_ms must be a multiple of slide_ms")
    S = size_ms // slide_ms
    late_eff = effective_lateness(size_ms, slide_ms, lateness_ms)
    C, W = state.counts.shape

    campaign = join_table[ad_idx]
    base_wid = event_time // slide_ms
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    counts = state.counts
    ids = state.window_ids
    dropped = state.dropped
    watermark = state.watermark
    for k in range(S):
        wid = base_wid - k
        slot, count_mask, ids, wm = assign_windows(
            ids, state.watermark, wid, wanted, valid, event_time,
            divisor_ms=slide_ms, lateness_ms=late_eff)
        watermark = wm
        flat = jnp.where(count_mask, campaign * W + slot, C * W)
        counts = (counts.reshape(-1)
                  .at[flat].add(1, mode="drop")
                  .reshape(C, W))
        dropped = dropped + (
            jnp.sum(wanted.astype(jnp.int32))
            - jnp.sum(count_mask.astype(jnp.int32)))
    return WindowState(counts, ids, watermark, dropped)
