"""Sliding (hopping) windows: size S, slide s, as S tumbling memberships.

BASELINE config #3's windowing (10 s window / 1 s slide).  An event at t
belongs to the S = size/slide windows whose ids end at floor(t/slide); the
ring machinery is reused with ``divisor = slide`` and an *effective
lateness* of ``lateness + size - slide`` so a window closes exactly when
the watermark passes ``start + size + lateness`` (the generic ring closes
at ``(wid+1)*divisor + lateness``; the widened lateness makes those equal).

The membership loop is a static Python ``for`` over S — under jit XLA
unrolls it, no dynamic control flow.  Ring-slot *claims* stay per-k
(masked scatter-maxes over the [W] id vector — order matters and they
are cheap); how the S memberships become count updates is the
``method`` knob:

- ``"scatter"`` — the original unrolled form: S masked ``[C*W]``
  scatter-adds, one per membership (VERDICT item 8's complaint).
- ``"matmul"`` / ``"onehot"`` / ``"pallas"`` — the factored one-hot
  form: each k contributes a masked slot one-hot, summed into ONE
  ``[B, W]`` membership matrix (the ``[B, S*W]`` membership tensor with
  its S axis pre-folded — memberships of one event hit S *distinct*
  slots, so the sum stays 0/1), and a single
  ``campaign_onehot^T @ membership`` matmul lands all S memberships in
  one MXU pass instead of S scatters.  ``apply_count`` does the final
  dispatch, so the sliding step follows the same measured per-backend
  method choice (``ops.methodbench``) as the tumbling one.
  (``"pallas"``'s tiled kernel consumes single (campaign, slot) pairs,
  not membership rows — it routes to the same factored matmul here.)

All methods are bit-identical (tested).  State shape is identical to the
tumbling ``WindowState``; ``flush_deltas`` works unchanged when called
with the same effective lateness.  ``dropped`` counts lost *memberships*
(an event has S of them), not events.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from streambench_tpu.ops.windowcount import (
    WindowState,
    apply_count,
    assign_windows,
)


def effective_lateness(size_ms: int, slide_ms: int, lateness_ms: int) -> int:
    return lateness_ms + size_ms - slide_ms


@functools.partial(
    jax.jit,
    static_argnames=("size_ms", "slide_ms", "lateness_ms", "view_type",
                     "method"))
def step(state: WindowState, join_table: jax.Array,
         ad_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, size_ms: int = 10_000, slide_ms: int = 1_000,
         lateness_ms: int = 60_000, view_type: int = 0,
         method: str = "scatter") -> WindowState:
    if size_ms % slide_ms:
        raise ValueError("size_ms must be a multiple of slide_ms")
    S = size_ms // slide_ms
    late_eff = effective_lateness(size_ms, slide_ms, lateness_ms)
    C, W = state.counts.shape
    if S > W:
        # the factored membership sum (and slot claiming generally)
        # needs each event's S memberships on distinct ring slots
        raise ValueError(f"ring too small: {W} slots < {S} memberships")

    campaign = join_table[ad_idx]
    base_wid = event_time // slide_ms
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    counts = state.counts
    ids = state.window_ids
    dropped = state.dropped
    watermark = state.watermark
    factored = method != "scatter"
    membership = None
    for k in range(S):
        wid = base_wid - k
        slot, count_mask, ids, wm = assign_windows(
            ids, state.watermark, wid, wanted, valid, event_time,
            divisor_ms=slide_ms, lateness_ms=late_eff)
        watermark = wm
        if factored:
            oh = (slot[:, None] == jnp.arange(W, dtype=jnp.int32)
                  ) & count_mask[:, None]                        # [B, W]
            membership = oh if membership is None else membership | oh
        else:
            counts = apply_count(counts, campaign, slot, count_mask,
                                 "scatter")
        dropped = dropped + (
            jnp.sum(wanted.astype(jnp.int32))
            - jnp.sum(count_mask.astype(jnp.int32)))
    if factored:
        # one [B, C] x [B, W] MXU pass for all S memberships; masked
        # rows have campaign -1 -> an all-zero one-hot row.  f32
        # accumulation of 0/1 over B is exact to 2^24.
        camp_oh = (campaign[:, None] == jnp.arange(C, dtype=jnp.int32)
                   ).astype(jnp.float32)                         # [B, C]
        delta = jax.lax.dot_general(
            camp_oh, membership.astype(jnp.float32),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [C, W]
        counts = counts + delta.astype(jnp.int32)
    return WindowState(counts, ids, watermark, dropped)
