"""Sliding (hopping) windows: size S, slide s, as S tumbling memberships.

BASELINE config #3's windowing (10 s window / 1 s slide).  An event at t
belongs to the S = size/slide windows whose ids end at floor(t/slide); the
ring machinery is reused with ``divisor = slide`` and an *effective
lateness* of ``lateness + size - slide`` so a window closes exactly when
the watermark passes ``start + size + lateness`` (the generic ring closes
at ``(wid+1)*divisor + lateness``; the widened lateness makes those equal).

The membership loop is a static Python ``for`` over S — under jit XLA
unrolls it, no dynamic control flow.  Ring-slot *claims* stay per-k
(masked scatter-maxes over the [W] id vector — order matters and they
are cheap); how the S memberships become count updates is the
``method`` knob:

- ``"scatter"`` — the original unrolled form: S masked ``[C*W]``
  scatter-adds, one per membership (VERDICT item 8's complaint).
- ``"matmul"`` / ``"onehot"`` / ``"pallas"`` — the factored one-hot
  form: each k contributes a masked slot one-hot, summed into ONE
  ``[B, W]`` membership matrix (the ``[B, S*W]`` membership tensor with
  its S axis pre-folded — memberships of one event hit S *distinct*
  slots, so the sum stays 0/1), and a single
  ``campaign_onehot^T @ membership`` matmul lands all S memberships in
  one MXU pass instead of S scatters.  ``apply_count`` does the final
  dispatch, so the sliding step follows the same measured per-backend
  method choice (``ops.methodbench``) as the tumbling one.
  (``"pallas"``'s tiled kernel consumes single (campaign, slot) pairs,
  not membership rows — it routes to the same factored matmul here.)

All methods are bit-identical (tested).  State shape is identical to the
tumbling ``WindowState``; ``flush_deltas`` works unchanged when called
with the same effective lateness.  ``dropped`` counts lost *memberships*
(an event has S of them), not events.

Sliced fold (ISSUE 12)
----------------------
The unrolled forms above still pay S ring-claim passes per batch.  The
*sliced* fold (``step_sliced`` + ``flush_sliced``) is the classic
stream-slicing move (panes / Scotty / Flink slicing): count per-slide
**buckets** with ONE ``assign_windows`` claim (``divisor = slide``, the
same effective lateness) and ONE ``apply_count`` scatter, and only at
drain time materialize each window's count as the sum of its S live
buckets — a windowed prefix-sum over the ring.  The sliding fold
becomes a tumbling fold plus an O(C*S*W) drain.

Exactness under allowed lateness needs one refinement: an event can be
late for its *older* windows but on time for its bucket (legacy drops
the memberships into already-closed windows, judged against the
batch-start watermark).  The bucket plane therefore carries a third
axis of S **lateness classes**: an event lands in class
``d = clip(bucket - min_open_window, 0, S-1)`` — it is countable for
exactly its newest ``d+1`` windows — still one scatter, into
``[C, S, W]``.  ``flush_sliced`` takes a reversed cumulative sum over
the class axis, so window ``wid`` (= bucket ``wid+k`` at offset ``k``)
sums class-``>=k`` counts only.  Windows that closed before the
previous drain provably reconstruct to zero (every later event's class
excludes them), so the emitted rows are bit-identical to the legacy
flush wherever legacy itself is well-defined (live window-id span under
W — the span-guard regime; at ring wrap legacy misattributes evicted
slots equally).

``dropped`` conversion, exact: the sliced claim drops *events* (bucket
late or evicted) where legacy drops *memberships*.  The fold converts
at batch granularity — each accepted event counts ``d+1`` memberships,
each rejected wanted event drops all ``S`` — so
``dropped += S * wanted - sum(accepted ? d+1 : 0)`` reproduces the
legacy membership-granular counter bit for bit (in the same
no-eviction domain).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from streambench_tpu.ops.windowcount import (
    NEG,
    WindowState,
    _still_open,
    apply_count,
    assign_windows,
)


def effective_lateness(size_ms: int, slide_ms: int, lateness_ms: int) -> int:
    return lateness_ms + size_ms - slide_ms


@functools.partial(
    jax.jit,
    static_argnames=("size_ms", "slide_ms", "lateness_ms", "view_type",
                     "method"))
def step(state: WindowState, join_table: jax.Array,
         ad_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, size_ms: int = 10_000, slide_ms: int = 1_000,
         lateness_ms: int = 60_000, view_type: int = 0,
         method: str = "scatter") -> WindowState:
    if size_ms % slide_ms:
        raise ValueError("size_ms must be a multiple of slide_ms")
    S = size_ms // slide_ms
    late_eff = effective_lateness(size_ms, slide_ms, lateness_ms)
    C, W = state.counts.shape
    if S > W:
        # the factored membership sum (and slot claiming generally)
        # needs each event's S memberships on distinct ring slots
        raise ValueError(f"ring too small: {W} slots < {S} memberships")

    campaign = join_table[ad_idx]
    base_wid = event_time // slide_ms
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    counts = state.counts
    ids = state.window_ids
    dropped = state.dropped
    watermark = state.watermark
    factored = method != "scatter"
    membership = None
    for k in range(S):
        wid = base_wid - k
        slot, count_mask, ids, wm = assign_windows(
            ids, state.watermark, wid, wanted, valid, event_time,
            divisor_ms=slide_ms, lateness_ms=late_eff)
        watermark = wm
        if factored:
            oh = (slot[:, None] == jnp.arange(W, dtype=jnp.int32)
                  ) & count_mask[:, None]                        # [B, W]
            membership = oh if membership is None else membership | oh
        else:
            counts = apply_count(counts, campaign, slot, count_mask,
                                 "scatter")
        dropped = dropped + (
            jnp.sum(wanted.astype(jnp.int32))
            - jnp.sum(count_mask.astype(jnp.int32)))
    if factored:
        # one [B, C] x [B, W] MXU pass for all S memberships; masked
        # rows have campaign -1 -> an all-zero one-hot row.  f32
        # accumulation of 0/1 over B is exact to 2^24.
        camp_oh = (campaign[:, None] == jnp.arange(C, dtype=jnp.int32)
                   ).astype(jnp.float32)                         # [B, C]
        delta = jax.lax.dot_general(
            camp_oh, membership.astype(jnp.float32),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [C, W]
        counts = counts + delta.astype(jnp.int32)
    return WindowState(counts, ids, watermark, dropped)


# ----------------------------------------------------------------------
# Sliced fold: one claim + one scatter per batch, window sums at drain
# (module docstring "Sliced fold").
# ----------------------------------------------------------------------

class SlicedWindowState(NamedTuple):
    """Device-resident sliced sliding state (all int32).

    counts:     [C, S, W] per-slide bucket deltas since last flush,
                split by lateness class d (the event is countable for
                its newest d+1 windows; fully-on-time events land in
                class S-1)
    window_ids: [W]  absolute BUCKET id per ring slot; -1 empty.  The
                ring is claimed with ``divisor = slide`` and the
                effective lateness, so a bucket's slot frees exactly
                when the last window containing it closes.
    watermark:  []   max valid event_time seen (relative ms)
    dropped:    []   lost *memberships*, legacy-convention (see the
                module docstring's dropped conversion)
    """

    counts: jax.Array
    window_ids: jax.Array
    watermark: jax.Array
    dropped: jax.Array


def init_sliced(num_campaigns: int, window_slots: int,
                memberships: int) -> SlicedWindowState:
    return SlicedWindowState(
        counts=jnp.zeros((num_campaigns, memberships, window_slots),
                         jnp.int32),
        window_ids=jnp.full((window_slots,), -1, jnp.int32),
        watermark=jnp.int32(0),
        dropped=jnp.int32(0),
    )


def _sliced_geometry(state: SlicedWindowState, size_ms: int,
                     slide_ms: int) -> tuple[int, int, int]:
    if size_ms % slide_ms:
        raise ValueError("size_ms must be a multiple of slide_ms")
    S = size_ms // slide_ms
    C, Sp, W = state.counts.shape
    if Sp != S:
        raise ValueError(
            f"sliced plane carries {Sp} lateness classes, geometry "
            f"needs S={S}")
    if S > W:
        raise ValueError(f"ring too small: {W} slots < {S} memberships")
    return C, S, W


def step_sliced_core(state: SlicedWindowState, join_table: jax.Array,
                     ad_idx: jax.Array, event_type: jax.Array,
                     event_time: jax.Array, valid: jax.Array,
                     *, size_ms: int, slide_ms: int, lateness_ms: int,
                     view_type: int = 0,
                     method: str = "scatter") -> SlicedWindowState:
    """Traced body of ``step_sliced`` (reused by the fused engine scans
    and the sharded builders): ONE ring claim on per-slide buckets, ONE
    ``apply_count`` scatter into the ``[C, S, W]`` class plane."""
    C, S, W = _sliced_geometry(state, size_ms, slide_ms)
    late_eff = effective_lateness(size_ms, slide_ms, lateness_ms)

    campaign = join_table[ad_idx]
    bid = event_time // slide_ms
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    slot, count_mask, ids, watermark = assign_windows(
        state.window_ids, state.watermark, bid, wanted, valid, event_time,
        divisor_ms=slide_ms, lateness_ms=late_eff)

    # Lateness class: the event counts toward its newest d+1 windows
    # (min_open judged against the batch-start watermark, exactly the
    # per-membership mask of the unrolled forms).
    min_open = jnp.maximum((state.watermark - late_eff) // slide_ms, 0)
    d = jnp.clip(bid - min_open, 0, S - 1)

    # One scatter: the [C, S, W] plane flattened to [C*S, W] rows keeps
    # apply_count's measured method choice (scatter/matmul/...) intact.
    row = campaign * S + d
    counts = apply_count(state.counts.reshape(C * S, W), row, slot,
                         count_mask, method).reshape(C, S, W)

    # Membership-granular dropped, converted exactly (module docstring).
    counted = jnp.sum(jnp.where(count_mask, d + 1, 0))
    dropped = state.dropped + (
        S * jnp.sum(wanted.astype(jnp.int32)) - counted)
    return SlicedWindowState(counts, ids, watermark, dropped)


@functools.partial(
    jax.jit,
    static_argnames=("size_ms", "slide_ms", "lateness_ms", "view_type",
                     "method"))
def step_sliced(state: SlicedWindowState, join_table: jax.Array,
                ad_idx: jax.Array, event_type: jax.Array,
                event_time: jax.Array, valid: jax.Array,
                *, size_ms: int = 10_000, slide_ms: int = 1_000,
                lateness_ms: int = 60_000, view_type: int = 0,
                method: str = "scatter") -> SlicedWindowState:
    """Fold one micro-batch into the sliced bucket plane."""
    return step_sliced_core(state, join_table, ad_idx, event_type,
                            event_time, valid, size_ms=size_ms,
                            slide_ms=slide_ms, lateness_ms=lateness_ms,
                            view_type=view_type, method=method)


def flush_sliced_core(state: SlicedWindowState, *, size_ms: int,
                      slide_ms: int, lateness_ms: int):
    """Traced body of ``flush_sliced`` (reused by the sharded drain).

    Windowed prefix-sum over the ring: for the window anchored at slot
    ``s``, offset-k buckets live at slot ``(s+k) % W`` and contribute
    their lateness-class ``>= k`` counts (the reversed class cumsum).
    A slot's window id is the max consistent candidate
    ``bucket_id[(s+k)%W] - k`` — candidates from buckets outside the
    window (evicted or wrapped slots) are masked out, which is the
    "mask-aware of evicted slots" rule.
    """
    C, S, W = _sliced_geometry(state, size_ms, slide_ms)
    late_eff = effective_lateness(size_ms, slide_ms, lateness_ms)
    ids = state.window_ids

    # rcum[:, k, :] = counts of lateness class >= k (countable at
    # window offset k)
    rcum = jnp.cumsum(state.counts[:, ::-1, :], axis=1)[:, ::-1, :]

    sl = jnp.arange(W, dtype=jnp.int32)
    best = jnp.full((W,), NEG, jnp.int32)
    for k in range(S):
        bk = ids[(sl + k) % W]
        best = jnp.maximum(best, jnp.where(bk >= 0, bk - k, NEG))
    wid = jnp.where(best >= 0, best, -1)

    win = jnp.zeros((C, W), jnp.int32)
    for k in range(S):
        idx = (sl + k) % W
        bk = ids[idx]
        take = (bk >= 0) & (bk - k == wid) & (wid >= 0)
        win = win + jnp.where(take[None, :], rcum[:, k, idx], 0)

    new_state = SlicedWindowState(
        counts=jnp.zeros_like(state.counts),
        window_ids=_still_open(ids, state.watermark, slide_ms, late_eff),
        watermark=state.watermark,
        dropped=state.dropped,
    )
    return win, wid, new_state


@functools.partial(
    jax.jit, static_argnames=("size_ms", "slide_ms", "lateness_ms"))
def flush_sliced(state: SlicedWindowState, *, size_ms: int = 10_000,
                 slide_ms: int = 1_000, lateness_ms: int = 60_000):
    """Drain window deltas from the sliced bucket plane.

    Returns ``(delta_counts [C, W], window_ids [W], new_state)`` in the
    exact ``flush_deltas`` contract (window id per output slot, deltas
    per campaign, planes zeroed, closed bucket slots freed) — the host
    materialization path is shared verbatim with the legacy fold.
    Emitted rows are bit-identical to the legacy per-k fold's flush in
    the span-guard regime (module docstring).
    """
    return flush_sliced_core(state, size_ms=size_ms, slide_ms=slide_ms,
                             lateness_ms=lateness_ms)
