"""On-device event decode: raw journal bytes -> columns INSIDE the jitted step.

The round-5 device probe said the engine is an encoder with an
accelerator attached: host encode was 7.2 ms of an 8.9 ms pipelined
64K-event chunk while the device fold took ~1.7 ms (``BENCH_r05.json``),
for ~5% device occupancy.  The host was spending its one core turning
bytes into int32 columns the device consumes in microseconds — the exact
shape of the reference fork's mmap'd columnar-handoff experiment
(``WindowedArrowFormatBolter``): stop re-serializing on the host, hand
the compute engine raw bytes.

This module moves the decode into the compiled program.  The host ships
each journal block as ONE padded ``uint8`` buffer plus per-row
(start, len) vectors, and the jitted step does, fused with the window
fold it feeds:

- **fixed-schema field extraction** — the generator renders one byte
  skeleton (``core.clj:175-181`` / ``native/gen.cpp``), so the ad id is
  36 bytes at a fixed head offset and event type / event time sit at
  fixed END-relative offsets; extraction is pure gathers, no scanning;
- **``event_type == "view"`` filter** — a 4-byte tail compare;
- **ad -> campaign join** — FNV-1a over the 36 ad bytes probed against
  a device-resident open-addressed hash table (keys + campaign values
  built host-side once per engine, load factor <= 0.5, linear probing
  with a build-time probe bound) — the Redis join as device gathers;
- **event-time parse** — 13 tail-anchored ASCII digits folded to an
  int32 ms offset from ``base_time_ms`` (split at the 10^9 boundary so
  everything stays int32; x64 stays off);
- **window-count fold** — the same ``assign_windows`` +
  ``apply_count`` core every counting kernel uses.

What stays on the host is a *probe*, not an encode: one C pass
(``native/encoder.cpp:sb_probe_block``; numpy fallback below) that finds
record boundaries and VALIDATES the fixed layout byte-for-byte without
building any columns, and parses the times the host loop needs anyway
for the ring-span guard and the watermark mirror.  Rows that fail the
probe — malformed JSON, re-ordered keys, torn tails, non-13-digit
times — go back through the host encoder verbatim, so bad-line counting
and dead-letter behavior are IDENTICAL to the host arms (tested by the
oracle-equality sweep in ``tests/test_devdecode.py``).

Honesty note (1-core CPU host): the probe alone costs about what the
native encoder costs, so on this box the device arm does not win —
``jax.decode.device=auto`` gates on the measured A/B (``bench.py``
records it) and the committed artifact shows both arms.  The structural
claim stands regardless: with decode on, the host builds no columns.
"""

from __future__ import annotations

import ctypes
import functools

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.ops import windowcount as wc

# ----------------------------------------------------------------------
# Wire-format constants (the generator's fixed skeleton).  Byte positions
# are the contract also enforced by native/encoder.cpp:sb_probe_block —
# keep the two in lockstep (pinned by tests/test_devdecode.py).
UUID_LEN = 36
HEAD = b'{"user_id": "'                       # 13 @ 0
LIT_PAGE = b'", "page_id": "'                 # 15 @ 49
LIT_AD = b'", "ad_id": "'                     # 13 @ 100
LIT_ADTYPE = b'", "ad_type": "'               # 15 @ 149
LIT_ET = b'", "event_type": "'                # 18, end-relative
LIT_TM = b'", "event_time": "'                # 18 @ L-58
SUFFIX = b'", "ip_address": "1.2.3.4"}'       # 27 @ L-27
AD_OFF = 113                                  # ad id bytes [113, 149)
ADTYPE_OFF = 164
TIME_DIGITS = 13
# end-relative offsets
SUF_OFF = 27
DIG_OFF = SUF_OFF + TIME_DIGITS               # 40
TM_OFF = DIG_OFF + len(LIT_TM)                # 58
# fixed bytes head+tail (164 + 18+18+13+27 = 240) + >=1 ad_type + >=4 et
MIN_ROW = 245

_EVENT_TYPES = (b"view", b"click", b"purchase")

# FNV-1a 32-bit; the device kernel recomputes this hash with uint32 jnp
# ops, so host table build and device probe must wrap identically.
FNV_OFFSET = 2166136261
FNV_PRIME = 16777619


def fnv1a32(data: bytes) -> int:
    h = FNV_OFFSET
    for c in data:
        h = ((h ^ c) * FNV_PRIME) & 0xFFFFFFFF
    return h


# ----------------------------------------------------------------------
# Device-resident ad -> campaign join table
def build_ad_table(ads: list[bytes], campaign_idx: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray, int]:
    """Open-addressed (linear probe) hash table over 36-byte ad ids.

    Returns ``(keys [T, 36] uint8, vals [T] int32, max_probes)`` with
    ``T`` a power of two sized for load factor <= 0.5.  Empty slots hold
    val -1 and an all-zero key no uuid can equal, so a device probe that
    exhausts ``max_probes`` without a key match yields campaign -1 —
    exactly the host encoder's unknown-ad -> campaign -1 semantics.
    """
    if not ads:
        raise ValueError("device decode needs a non-empty ad table")
    if any(len(a) != UUID_LEN for a in ads):
        raise ValueError(
            "device decode requires fixed 36-byte ad ids (the generator's "
            "uuid wire format); got other lengths")
    T = 1 << max((2 * len(ads) - 1).bit_length(), 3)
    keys = np.zeros((T, UUID_LEN), np.uint8)
    vals = np.full(T, -1, np.int32)
    used = np.zeros(T, bool)
    max_probes = 1
    for ad, c in zip(ads, campaign_idx):
        h = fnv1a32(ad)
        p = 0
        while used[(h + p) & (T - 1)]:
            p += 1
        slot = (h + p) & (T - 1)
        used[slot] = True
        keys[slot] = np.frombuffer(ad, np.uint8)
        vals[slot] = int(c)
        max_probes = max(max_probes, p + 1)
    return keys, vals, max_probes


# ----------------------------------------------------------------------
# Host probe: record boundaries + full layout validation + times, no
# columns.  C fast path; numpy fallback keeps the feature alive (slower)
# when the native library is unavailable.
def _probe_native(lib, data, n_hint: int):
    starts_l, lens_l, times_l, ok_l = [], [], [], []
    cap = max(min(n_hint, 1 << 16), 1024)
    pos = 0
    i32p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    while pos < len(data):
        starts = np.empty(cap, np.int32)
        lens = np.empty(cap, np.int32)
        times = np.empty(cap, np.int64)
        ok = np.empty(cap, np.uint8)
        n = int(lib.sb_probe_block(
            data, len(data), pos, cap, i32p(starts), i32p(lens),
            times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))))
        if n == 0:
            break
        starts_l.append(starts[:n])
        lens_l.append(lens[:n])
        times_l.append(times[:n])
        ok_l.append(ok[:n])
        pos = int(starts[n - 1]) + int(lens[n - 1]) + 1
    if not starts_l:
        z = np.empty(0, np.int32)
        return z, z.copy(), np.empty(0, np.int64), np.empty(0, bool)
    cat = (lambda xs: xs[0] if len(xs) == 1 else np.concatenate(xs))
    return (cat(starts_l), cat(lens_l), cat(times_l),
            cat(ok_l).astype(bool))


def _tmpl_positions():
    """(positions, bytes) of every fixed HEAD byte, and the same for the
    end-relative tail (suffix + time literal)."""
    head = {}
    for off, lit in ((0, HEAD), (49, LIT_PAGE), (100, LIT_AD),
                     (149, LIT_ADTYPE)):
        for i, b in enumerate(lit):
            head[off + i] = b
    tail = {}
    for off, lit in ((-SUF_OFF, SUFFIX), (-TM_OFF, LIT_TM)):
        for i, b in enumerate(lit):
            tail[off + i] = b
    hp = np.asarray(sorted(head), np.int64)
    tp = np.asarray(sorted(tail), np.int64)
    return (hp, np.asarray([head[int(p)] for p in hp], np.uint8),
            tp, np.asarray([tail[int(p)] for p in tp], np.uint8))


_HP, _HB, _TP, _TB = _tmpl_positions()


def _probe_numpy(arr: np.ndarray):
    """Pure-numpy probe: the same accept predicate as ``sb_probe_block``
    (differential-tested).  ~10x slower than the C pass — the fallback
    when the native library is unavailable, and the reference the C
    probe is checked against."""
    nl = np.flatnonzero(arr == 10)
    if nl.size == 0:
        z = np.empty(0, np.int32)
        return z, z.copy(), np.empty(0, np.int64), np.empty(0, bool)
    starts = np.empty(nl.size, np.int64)
    starts[0] = 0
    starts[1:] = nl[:-1] + 1
    ends = nl
    lens = ends - starts
    ok = lens >= MIN_ROW
    s = np.where(ok, starts, 0)
    e = np.where(ok, ends, MIN_ROW)
    # pad so clamped gathers of not-ok rows stay in bounds
    if arr.size < MIN_ROW:
        arr = np.concatenate([arr, np.zeros(MIN_ROW, np.uint8)])
    ok &= (arr[s[:, None] + _HP[None, :]] == _HB).all(axis=1)
    ok &= (arr[e[:, None] + _TP[None, :]] == _TB).all(axis=1)
    # quote-free uuid fields (a quote inside a 36-byte span would make
    # the host token parser see a different structure)
    for off in (13, 64, AD_OFF):
        span = arr[s[:, None] + (off + np.arange(UUID_LEN))[None, :]]
        ok &= ~(span == ord('"')).any(axis=1)
    d = arr[e[:, None] + np.arange(-DIG_OFF, -SUF_OFF)[None, :]]
    digits_ok = ((d >= 48) & (d <= 57)).all(axis=1)
    ok &= digits_ok
    times = np.where(
        digits_ok,
        (d.astype(np.int64) - 48) @ (10 ** np.arange(12, -1, -1)), 0)
    # event type: full literal match, anchored at the end
    et_len = np.zeros(nl.size, np.int64)
    for name in _EVENT_TYPES:
        lit = LIT_ET + name
        p = np.arange(-TM_OFF - len(lit), -TM_OFF)
        m = (arr[e[:, None] + p[None, :]]
             == np.frombuffer(lit, np.uint8)).all(axis=1)
        et_len = np.where(m, len(name), et_len)
    ok &= et_len > 0
    # ad_type: non-empty and quote-free between the fixed head and tail
    at_len = lens - 240 - et_len
    ok &= at_len >= 1
    at_max = int(at_len[ok].max()) if ok.any() else 0
    if at_max > 0:
        span = arr[s[:, None] + (ADTYPE_OFF + np.arange(at_max))[None, :]]
        quote = (span == ord('"')) & (np.arange(at_max)[None, :]
                                      < at_len[:, None])
        ok &= ~quote.any(axis=1)
    return (starts.astype(np.int32), lens.astype(np.int32),
            np.where(ok, times, 0), ok)


def probe_block(data, *, native: bool | None = None):
    """``(starts, lens, times_abs, ok)`` for every complete record in
    ``data`` (an incomplete trailing record is not scanned).  ``native``
    forces the C/numpy implementation; default tries C first."""
    if isinstance(data, np.ndarray):
        buf = data.tobytes() if native is not False else None
        arr = data
    else:
        buf = data
        arr = None
    lib = None
    if native is not False:
        from streambench_tpu import native as _native

        lib = _native.load()
    if lib is not None and native is not False:
        if buf is None:
            buf = arr.tobytes()
        return _probe_native(lib, buf, len(buf) // MIN_ROW + 2)
    if arr is None:
        arr = np.frombuffer(data, np.uint8)
    return _probe_numpy(arr)


# ----------------------------------------------------------------------
# The jitted decode+fold step
def _decode_columns(buf, starts, lens, keys, vals, base_hi, base_lo,
                    probes: int):
    """bytes -> (campaign, is_view, rel_time, valid) for one [B] row
    group.  Rows with len 0 (padding) are invalid; every gather is
    clamped onto row 0 / MIN_ROW for them, so indices stay in bounds
    regardless of the garbage they decode to (masked downstream)."""
    valid = lens > 0
    s = jnp.where(valid, starts, 0)
    e = jnp.where(valid, starts + lens, MIN_ROW)

    # ad id bytes + FNV-1a hash (36 fused uint32 steps)
    ad = buf[s[:, None]
             + (AD_OFF + jnp.arange(UUID_LEN, dtype=jnp.int32))[None, :]]
    h = jnp.full(s.shape, np.uint32(FNV_OFFSET), jnp.uint32)
    for i in range(UUID_LEN):
        h = (h ^ ad[:, i].astype(jnp.uint32)) * jnp.uint32(FNV_PRIME)

    # linear-probe join against the device-resident table
    T = vals.shape[0]
    campaign = jnp.full(s.shape, -1, jnp.int32)
    found = jnp.zeros(s.shape, bool)
    for p in range(probes):
        slot = ((h + jnp.uint32(p)) & jnp.uint32(T - 1)).astype(jnp.int32)
        hit = jnp.all(keys[slot] == ad, axis=1) & ~found
        campaign = jnp.where(hit, vals[slot], campaign)
        found = found | hit

    # "view" filter: 4 bytes right before the event_time literal ('view'
    # is the only event type ending in those bytes — the probe already
    # pinned the value to one of the three known types)
    vt = buf[(e - (TM_OFF + 4))[:, None]
             + jnp.arange(4, dtype=jnp.int32)[None, :]]
    is_view = jnp.all(vt == jnp.asarray(np.frombuffer(b"view", np.uint8)),
                      axis=1)

    # 13 tail-anchored digits -> int32 ms relative to base, split at the
    # 10^9 boundary so no intermediate leaves int32 (x64 stays off):
    # t = hi * 1e9 + lo, rel = (hi - base_hi) * 1e9 + (lo - base_lo).
    d = (buf[(e - DIG_OFF)[:, None]
             + jnp.arange(TIME_DIGITS, dtype=jnp.int32)[None, :]]
         .astype(jnp.int32) - 48)
    hi = ((d[:, 0] * 10 + d[:, 1]) * 10 + d[:, 2]) * 10 + d[:, 3]
    lo = d[:, 4]
    for k in range(5, TIME_DIGITS):
        lo = lo * 10 + d[:, k]
    rel = (hi - base_hi) * np.int32(1_000_000_000) + (lo - base_lo)
    return campaign, is_view, rel, valid


@functools.partial(
    jax.jit,
    static_argnames=("divisor_ms", "lateness_ms", "method", "probes"))
def decode_fold_scan(state: wc.WindowState, buf, starts, lens, keys, vals,
                     base_hi, base_lo, *, divisor_ms: int,
                     lateness_ms: int, method: str,
                     probes: int) -> wc.WindowState:
    """Decode + filter + join + fold ``[K, B]`` row groups out of ONE
    shared byte buffer in a single compiled program — the whole YSB
    stage chain with the deserializer inside it."""

    def body(st, xs):
        s, l = xs
        campaign, is_view, rel, valid = _decode_columns(
            buf, s, l, keys, vals, base_hi, base_lo, probes)
        wid = rel // divisor_ms
        wanted = valid & is_view & (campaign >= 0)
        slot, count_mask, window_ids, watermark = wc.assign_windows(
            st.window_ids, st.watermark, wid, wanted, valid, rel,
            divisor_ms=divisor_ms, lateness_ms=lateness_ms)
        counts = wc.apply_count(st.counts, campaign, slot, count_mask,
                                method)
        dropped = st.dropped + (jnp.sum(wanted.astype(jnp.int32))
                                - jnp.sum(count_mask.astype(jnp.int32)))
        return wc.WindowState(counts, window_ids, watermark, dropped), None

    final, _ = jax.lax.scan(body, state, (starts, lens))
    return final


# ----------------------------------------------------------------------
class PreparedBlock:
    """One probed journal block, ready for device dispatch.

    Duck-types the ``EncodedBatch`` surface the host bookkeeping reads —
    ``n``, ``valid``, ``event_time`` (relative int32 ms of the probe-ok
    rows), ``base_time_ms``, plus the ``_lc_*`` attribution stamps — so
    the watermark mirror, span guard, and obs lifecycle treat it like
    any encoded batch.  What it does NOT carry is columns: the bytes
    ride to the device raw.
    """

    is_device_block = True

    def __init__(self, buf_dev, starts: np.ndarray, lens: np.ndarray,
                 rel_times: np.ndarray, base_time_ms: int,
                 batch_size: int):
        self.buf_dev = buf_dev
        self.starts = starts
        self.lens = lens
        self.event_time = rel_times
        self.base_time_ms = base_time_ms
        self.batch_size = batch_size
        self.n = int(starts.shape[0])
        self.valid = np.ones(self.n, bool)
        self._lc_read_ms = None
        self._lc_encode_ms = None

    def halves(self) -> tuple["PreparedBlock", "PreparedBlock"]:
        """Split for the span-guard recursion (``engine._fold``'s
        halving rule); the byte buffer is shared, only row vectors
        split."""
        mid = self.n // 2
        lo = PreparedBlock(self.buf_dev, self.starts[:mid],
                           self.lens[:mid], self.event_time[:mid],
                           self.base_time_ms, self.batch_size)
        hi = PreparedBlock(self.buf_dev, self.starts[mid:],
                           self.lens[mid:], self.event_time[mid:],
                           self.base_time_ms, self.batch_size)
        for part in (lo, hi):
            part._lc_read_ms = self._lc_read_ms
            part._lc_encode_ms = self._lc_encode_ms
        return lo, hi


class DeviceDecoder:
    """Per-engine device-decode driver: owns the device-resident join
    table and turns raw journal blocks into :class:`PreparedBlock`s plus
    the probe-rejected lines the engine re-encodes on the host."""

    def __init__(self, encoder, *, batch_size: int, scan_batches: int,
                 divisor_ms: int, lateness_ms: int,
                 native_probe: bool | None = None):
        keys, vals, probes = build_ad_table(
            [a.encode() for a in encoder.ads],
            encoder.join_table[:-1])
        self.keys = jnp.asarray(keys)
        self.vals = jnp.asarray(vals)
        self.probes = probes
        self.encoder = encoder
        self.batch_size = max(int(batch_size), 1)
        self.scan_batches = max(int(scan_batches), 1)
        self.divisor_ms = divisor_ms
        self.lateness_ms = lateness_ms
        self.native_probe = native_probe
        # telemetry (single-writer ints, GIL-safe)
        self.rows_decoded = 0
        self.rows_fallback = 0
        self.probe_ms_total = 0.0

    # ------------------------------------------------------------------
    def prepare(self, data: bytes
                ) -> tuple[list[PreparedBlock], list[bytes]]:
        """Probe one raw block: returns the device-ready blocks and the
        probe-rejected raw lines (host-encoder fallback, in journal
        order).  Establishes the encoder's ``base_time_ms`` from the
        first probe-ok row when unset (the same rebase rule the host
        encoder applies to its first parsed event)."""
        import time

        t0 = time.perf_counter()
        starts, lens, times, ok = probe_block(data,
                                              native=self.native_probe)
        bad_lines: list[bytes] = []
        blocks: list[PreparedBlock] = []
        if starts.size == 0:
            self.probe_ms_total += (time.perf_counter() - t0) * 1e3
            return blocks, bad_lines
        base = self.encoder.base_time_ms
        if base is None and bool(ok.any()):
            t_first = int(times[int(np.flatnonzero(ok)[0])])
            base = (t_first - (t_first % self.divisor_ms)
                    - self.lateness_ms)
            self.encoder.set_base_time(base)
        if base is not None and ok.any():
            rel = times - base
            # rebased time must fit the int32 column (the host fallback
            # applies the same rule); out-of-range rows fall back
            ok = ok & (rel >= -(1 << 31)) & (rel < (1 << 31))
        if not bool(ok.all()):
            for i in np.flatnonzero(~ok).tolist():
                s = int(starts[i])
                bad_lines.append(bytes(data[s:s + int(lens[i])]))
            self.rows_fallback += len(bad_lines)
        n_ok = int(ok.sum())
        if n_ok:
            # one padded device buffer shared by every group of the
            # block; pow2 bucketing bounds the compile-shape set.  The
            # pad tail is never read (gathers stay inside each row's
            # extent), so it is left unzeroed.
            cap = max(1 << (len(data) - 1).bit_length(), 1 << 12)
            padded = np.empty(cap, np.uint8)
            padded[:len(data)] = np.frombuffer(data, np.uint8)
            buf_dev = jnp.asarray(padded)
            s_ok = starts[ok]
            l_ok = lens[ok]
            rel32 = (times[ok] - base).astype(np.int32)
            per = self.batch_size * self.scan_batches
            for off in range(0, n_ok, per):
                blocks.append(PreparedBlock(
                    buf_dev, s_ok[off:off + per], l_ok[off:off + per],
                    rel32[off:off + per], base, self.batch_size))
            self.rows_decoded += n_ok
        self.probe_ms_total += (time.perf_counter() - t0) * 1e3
        return blocks, bad_lines

    # ------------------------------------------------------------------
    def fold(self, state: wc.WindowState, block: PreparedBlock, *,
             method: str) -> wc.WindowState:
        """Dispatch one prepared block: rows padded to a power-of-two
        ``[K, B]`` group shape (compiles once per bucket, like
        ``_fold_group``), one fused decode+fold scan per dispatch."""
        B = block.batch_size
        base = int(block.base_time_ms)
        base_hi = jnp.int32(base // 1_000_000_000)
        base_lo = jnp.int32(base % 1_000_000_000)
        R = block.n
        per = B * self.scan_batches
        for off in range(0, R, per):
            s = block.starts[off:off + per]
            l = block.lens[off:off + per]
            k = -(-s.shape[0] // B)
            kp = 1
            while kp < k:
                kp *= 2
            pad = kp * B - s.shape[0]
            if pad:
                s = np.concatenate([s, np.zeros(pad, np.int32)])
                l = np.concatenate([l, np.zeros(pad, np.int32)])
            state = decode_fold_scan(
                state, block.buf_dev, jnp.asarray(s.reshape(kp, B)),
                jnp.asarray(l.reshape(kp, B)), self.keys, self.vals,
                base_hi, base_lo, divisor_ms=self.divisor_ms,
                lateness_ms=self.lateness_ms, method=method,
                probes=self.probes)
        return state

    def telemetry(self) -> dict:
        return {
            "rows_decoded": self.rows_decoded,
            "rows_fallback": self.rows_fallback,
            "probe_ms_total": round(self.probe_ms_total, 3),
        }


# ----------------------------------------------------------------------
# auto gating: the measured A/B (bench.py records it through
# ops.methodbench's shared cache) decides; without a measurement the
# device arm is assumed to pay only where the host is not the
# bottleneck's owner (accelerator backends).
def auto_enabled(backend: str | None = None) -> bool:
    if backend is None:
        backend = jax.default_backend()
    try:
        from streambench_tpu.ops import methodbench

        winner = methodbench.cached_value(f"{backend}/devdecode")
        if winner is not None:
            return winner.get("winner") == "device"
    except Exception:
        pass
    return backend not in ("cpu",)
