"""Measured kernel-method selection: scatter vs matmul vs pallas, per backend.

``windowcount.step`` ships four bit-identical counting strategies and
``engine.pipeline.default_method`` picked between them by a hand-written
heuristic (scatter on CPU, matmul on TPU under a campaign bound) that
was never measured — VERDICT item 7.  This module times the ACTUAL
compiled step per method at a given geometry and caches the winner, so
``default_method`` becomes a measured decision with the heuristic as
fallback.

The cache is one JSON file (``$STREAMBENCH_METHOD_CACHE``, default
``~/.cache/streambench_tpu/method_bench.json``) keyed by
``<backend>/C<pow2-bucket>``; ``bench.py``'s device section writes it on
every run and records the full per-method ns/event table in the
committed artifact.  The same file carries the device-decode A/B winner
under ``<backend>/devdecode`` (``ops.devdecode.auto_enabled``).

``python -m streambench_tpu.ops.methodbench --smoke`` runs a tiny-size
measurement end to end (CI exercises the measured path this way).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

METHODS = ("scatter", "matmul", "pallas")
# Sliding-family arms (ISSUE 12): the unrolled per-k fold with its
# scatter or factored-matmul membership landing, vs the sliced fold
# (one claim + one scatter into the [C, S, W] bucket plane).  Keyed per
# (backend, S-bucket) — S = size/slide drives the unrolled forms' cost.
SLIDING_METHODS = ("scatter", "matmul", "sliced")
# CMS-family arms (ISSUE 13): the fixed plane's flat scatter vs its
# per-row loop landing, the SF two-stage update (fat add + small
# refresh), and the SALSA merge-on-overflow update (decode + scatter +
# settle + encode).  Keyed per (backend, width) — the settle pass is
# O(Wd) per batch, so the crossover moves with width.
CMS_METHODS = ("flat", "rowloop", "twostage", "salsa")
_DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "streambench_tpu",
    "method_bench.json")

# in-process memo: (path, mtime) -> parsed cache
_memo: tuple[str, float, dict] | None = None


def cache_path() -> str:
    return os.environ.get("STREAMBENCH_METHOD_CACHE", _DEFAULT_CACHE)


def _load_cache() -> dict:
    global _memo
    path = cache_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}
    if _memo is not None and _memo[0] == path and _memo[1] == mtime:
        return _memo[2]
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    _memo = (path, mtime, data)
    return data


def record(key: str, value: dict) -> None:
    """Merge one measurement under ``key`` (atomic rewrite)."""
    global _memo
    path = cache_path()
    data = dict(_load_cache())
    data[key] = value
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    _memo = None


def cached_value(key: str) -> dict | None:
    v = _load_cache().get(key)
    return v if isinstance(v, dict) else None


def bucket(num_campaigns: int) -> int:
    """Pow2 bucket a geometry's campaign axis (the method trade-off's
    driver: the matmul's [B, C] operand scales with C)."""
    return 1 << max((max(int(num_campaigns), 1) - 1).bit_length(), 0)


def method_key(backend: str, num_campaigns: int) -> str:
    return f"{backend}/C{bucket(num_campaigns)}"


def cached_winner(backend: str, num_campaigns: int | None) -> str | None:
    """The measured winner for this backend + campaign bucket, or None
    when nothing comparable was ever measured (callers fall back to the
    heuristic).  Only an exact bucket hit is trusted: the scatter/matmul
    crossover moves with C, so a winner measured at C=128 says nothing
    about C=1e6."""
    if num_campaigns is None:
        return None
    entry = cached_value(method_key(backend, int(num_campaigns)))
    if entry is None:
        return None
    winner = entry.get("winner")
    return winner if winner in METHODS else None


# ----------------------------------------------------------------------
def measure_methods(num_campaigns: int = 100, window_slots: int = 16,
                    batch_size: int = 8192, iters: int = 20,
                    methods: tuple = METHODS, scan_batches: int = 1,
                    time_budget_s: float = 5.0, seed: int = 0) -> dict:
    """Time the compiled window step per counting method.

    Synthetic uniform batch (every row a counted view — the worst case
    for all methods equally), blocking sample like bench.py's device
    section: warm once, then ``iters`` timed dispatches with one
    trailing block.  A method whose single warm call already exceeds
    ``time_budget_s / len(methods)`` is sampled just once (pallas in
    interpret mode on CPU is orders slower; the table should record
    that, not burn the bench budget proving it).  Returns the artifact
    table: per-method ns/event (or error), the winner, geometry.
    """
    import jax

    from streambench_tpu.ops import windowcount as wc

    rng = np.random.default_rng(seed)
    C, W, B = int(num_campaigns), int(window_slots), int(batch_size)
    ad_per = 1
    join = np.arange(C * ad_per, dtype=np.int32) % C
    join_table = np.concatenate([join, np.array([-1], np.int32)])
    ad_idx = rng.integers(0, C * ad_per, B).astype(np.int32)
    event_type = np.zeros(B, np.int32)           # all views
    event_time = (rng.integers(0, W // 2 + 1, B).astype(np.int32)
                  * np.int32(10_000))
    valid = np.ones(B, bool)
    jt = jax.numpy.asarray(join_table)
    np_cols = (ad_idx, event_type, event_time, valid)
    if scan_batches > 1:
        np_cols = tuple(np.stack([c] * scan_batches) for c in np_cols)
    cols = [jax.numpy.asarray(c) for c in np_cols]

    out: dict = {
        "backend": jax.default_backend(),
        "num_campaigns": C, "window_slots": W, "batch_size": B,
        "scan_batches": int(scan_batches), "iters": int(iters),
        "methods": {},
    }
    per_budget = time_budget_s / max(len(methods), 1)
    events = B * max(scan_batches, 1)
    for method in methods:
        state = wc.init_state(C, W)

        def run(st):
            if scan_batches > 1:
                return wc.scan_steps(st, jt, *cols, method=method)
            return wc.step(st, jt, *cols, method=method)

        try:
            st = run(state)
            jax.block_until_ready(st.counts)      # compile + warm
            t0 = time.perf_counter()
            st = run(state)
            jax.block_until_ready(st.counts)
            warm_s = time.perf_counter() - t0
            n = (1 if warm_s > per_budget
                 else max(1, min(iters, int(per_budget / max(warm_s,
                                                             1e-7)))))
            t0 = time.perf_counter()
            for _ in range(n):
                st = run(st)
            jax.block_until_ready(st.counts)
            per_call = (time.perf_counter() - t0) / n
            out["methods"][method] = {
                "ns_per_event": round(per_call * 1e9 / events, 2),
                "ms_per_step": round(per_call * 1e3, 4),
                "timed_iters": n,
            }
        except Exception as e:  # a broken method must not kill the table
            out["methods"][method] = {"error": repr(e)}
    ranked = sorted(
        (m for m, v in out["methods"].items() if "ns_per_event" in v),
        key=lambda m: out["methods"][m]["ns_per_event"])
    out["winner"] = ranked[0] if ranked else None
    return out


def measure_and_record(num_campaigns: int = 100, window_slots: int = 16,
                       batch_size: int = 8192, **kw) -> dict:
    """Measure + persist under the backend/C-bucket key.  The entry
    ``default_method`` consults; re-measuring overwrites."""
    res = measure_methods(num_campaigns=num_campaigns,
                          window_slots=window_slots,
                          batch_size=batch_size, **kw)
    if res.get("winner"):
        record(method_key(res["backend"], num_campaigns), res)
    return res


# ----------------------------------------------------------------------
# Sliding family (ISSUE 12): the real compiled sliding step per arm.
# ----------------------------------------------------------------------

def sliding_key(backend: str, memberships: int) -> str:
    return f"{backend}/sliding/S{int(memberships)}"


def sliding_winner(backend: str, memberships: int) -> str | None:
    """Measured sliding-family winner for this backend + S-bucket, or
    None when nothing was measured (``jax.sliding.sliced=auto`` then
    falls back to its fits-the-plane heuristic)."""
    entry = cached_value(sliding_key(backend, memberships))
    if entry is None:
        return None
    winner = entry.get("winner")
    return winner if winner in SLIDING_METHODS else None


def measure_sliding(num_campaigns: int = 100, window_slots: int = 128,
                    batch_size: int = 8192, size_ms: int = 10_000,
                    slide_ms: int = 1_000, iters: int = 20,
                    methods: tuple = SLIDING_METHODS,
                    time_budget_s: float = 5.0, seed: int = 0) -> dict:
    """Time the compiled SLIDING step per arm at a given geometry.

    Arms: ``scatter``/``matmul`` run the unrolled per-k fold with that
    membership landing; ``sliced`` runs the one-claim-one-scatter fold
    (its bucket scatter uses the tumbling-family measured winner where
    one exists, else scatter).  Same sampling discipline as
    ``measure_methods``.
    """
    import jax

    from streambench_tpu.ops import sliding
    from streambench_tpu.ops import windowcount as wc

    rng = np.random.default_rng(seed)
    C, W, B = int(num_campaigns), int(window_slots), int(batch_size)
    S = int(size_ms) // int(slide_ms)
    join_table = np.concatenate(
        [np.arange(C, dtype=np.int32), np.array([-1], np.int32)])
    ad_idx = rng.integers(0, C, B).astype(np.int32)
    event_type = np.zeros(B, np.int32)
    event_time = np.sort(rng.integers(
        0, max(W - S, 1), B).astype(np.int32) * np.int32(slide_ms))
    valid = np.ones(B, bool)
    jt = jax.numpy.asarray(join_table)
    cols = [jax.numpy.asarray(c)
            for c in (ad_idx, event_type, event_time, valid)]
    bucket_method = cached_winner(jax.default_backend(), C) or "scatter"
    if bucket_method == "pallas":
        bucket_method = "scatter"   # pallas tiles consume pairs, not rows

    out: dict = {
        "backend": jax.default_backend(),
        "num_campaigns": C, "window_slots": W, "batch_size": B,
        "size_ms": int(size_ms), "slide_ms": int(slide_ms),
        "memberships": S, "iters": int(iters), "methods": {},
    }
    per_budget = time_budget_s / max(len(methods), 1)
    for method in methods:
        def run(st, method=method):
            if method == "sliced":
                return sliding.step_sliced(
                    st, jt, *cols, size_ms=size_ms, slide_ms=slide_ms,
                    method=bucket_method)
            return sliding.step(st, jt, *cols, size_ms=size_ms,
                                slide_ms=slide_ms, method=method)

        try:
            state = (sliding.init_sliced(C, W, S) if method == "sliced"
                     else wc.init_state(C, W))
            st = run(state)
            jax.block_until_ready(st.counts)      # compile + warm
            t0 = time.perf_counter()
            st = run(state)
            jax.block_until_ready(st.counts)
            warm_s = time.perf_counter() - t0
            n = (1 if warm_s > per_budget
                 else max(1, min(iters, int(per_budget / max(warm_s,
                                                             1e-7)))))
            t0 = time.perf_counter()
            for _ in range(n):
                st = run(st)
            jax.block_until_ready(st.counts)
            per_call = (time.perf_counter() - t0) / n
            out["methods"][method] = {
                "ns_per_event": round(per_call * 1e9 / B, 2),
                "ms_per_step": round(per_call * 1e3, 4),
                "timed_iters": n,
            }
        except Exception as e:  # a broken arm must not kill the table
            out["methods"][method] = {"error": repr(e)}
    ranked = sorted(
        (m for m, v in out["methods"].items() if "ns_per_event" in v),
        key=lambda m: out["methods"][m]["ns_per_event"])
    out["winner"] = ranked[0] if ranked else None
    return out


def measure_and_record_sliding(num_campaigns: int = 100,
                               window_slots: int = 128,
                               batch_size: int = 8192,
                               size_ms: int = 10_000,
                               slide_ms: int = 1_000, **kw) -> dict:
    """Measure + persist under the backend/sliding/S-bucket key the
    ``jax.sliding.sliced=auto`` resolution consults."""
    res = measure_sliding(num_campaigns=num_campaigns,
                          window_slots=window_slots,
                          batch_size=batch_size, size_ms=size_ms,
                          slide_ms=slide_ms, **kw)
    if res.get("winner"):
        record(sliding_key(res["backend"], res["memberships"]), res)
    return res


# ----------------------------------------------------------------------
# CMS family (ISSUE 13): the real compiled sketch update per arm.
# ----------------------------------------------------------------------

def cms_key(backend: str, width: int) -> str:
    return f"{backend}/cms/W{int(width)}"


def cms_winner(backend: str, width: int) -> str | None:
    """Measured cms-family winner for this backend + width, or None
    when nothing was measured (``jax.cms.mode=auto`` then resolves
    fixed)."""
    entry = cached_value(cms_key(backend, width))
    if entry is None:
        return None
    winner = entry.get("winner")
    return winner if winner in CMS_METHODS else None


def measure_cms(width: int = 2048, depth: int = 4,
                batch_size: int = 8192, iters: int = 20,
                methods: tuple = CMS_METHODS,
                time_budget_s: float = 5.0, seed: int = 0) -> dict:
    """Time the compiled sketch update per arm at a given geometry.

    Synthetic Zipf-skewed keys with unit-ish weights (the heavy-hitter
    shape the session engine feeds the sketch), same sampling
    discipline as ``measure_methods``: warm once, budget-bounded timed
    iterations, one trailing block.
    """
    import jax

    from streambench_tpu.ops import cms as cms_ops
    from streambench_tpu.ops import salsa as salsa_ops

    rng = np.random.default_rng(seed)
    B = int(batch_size)
    keys = np.minimum(rng.zipf(1.1, B), 2**28).astype(np.int32)
    weights = rng.integers(1, 8, B).astype(np.int32)
    mask = np.ones(B, bool)
    cols = [jax.numpy.asarray(c) for c in (keys, weights, mask)]

    def make(method):
        if method == "salsa":
            return (salsa_ops.init_state(depth, width),
                    salsa_ops.update)
        if method == "twostage":
            return (cms_ops.init_two_stage(depth, width),
                    cms_ops.update2)
        if method == "rowloop":
            return (cms_ops.init_state(depth, width),
                    cms_ops.update_rowloop)
        return (cms_ops.init_state(depth, width), cms_ops.update)

    out: dict = {
        "backend": jax.default_backend(),
        "depth": int(depth), "width": int(width), "batch_size": B,
        "iters": int(iters), "methods": {},
    }
    per_budget = time_budget_s / max(len(methods), 1)
    for method in methods:
        try:
            state, fn = make(method)
            st = fn(state, *cols)
            jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
            t0 = time.perf_counter()
            st = fn(state, *cols)
            jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
            warm_s = time.perf_counter() - t0
            n = (1 if warm_s > per_budget
                 else max(1, min(iters, int(per_budget / max(warm_s,
                                                             1e-7)))))
            t0 = time.perf_counter()
            for _ in range(n):
                st = fn(st, *cols)
            jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
            per_call = (time.perf_counter() - t0) / n
            out["methods"][method] = {
                "ns_per_event": round(per_call * 1e9 / B, 2),
                "ms_per_step": round(per_call * 1e3, 4),
                "timed_iters": n,
            }
        except Exception as e:  # a broken arm must not kill the table
            out["methods"][method] = {"error": repr(e)}
    ranked = sorted(
        (m for m, v in out["methods"].items() if "ns_per_event" in v),
        key=lambda m: out["methods"][m]["ns_per_event"])
    out["winner"] = ranked[0] if ranked else None
    return out


def measure_and_record_cms(width: int = 2048, depth: int = 4,
                           batch_size: int = 8192, **kw) -> dict:
    """Measure + persist under the backend/cms/W key the
    ``jax.cms.mode=auto`` resolution consults."""
    res = measure_cms(width=width, depth=depth, batch_size=batch_size,
                      **kw)
    if res.get("winner"):
        record(cms_key(res["backend"], width), res)
    return res


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="micro-bench the window-count kernel methods")
    ap.add_argument("--campaigns", type=int, default=100)
    ap.add_argument("--window-slots", type=int, default=16)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--scan-batches", type=int, default=1)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes, 2 iters (CI: exercise the "
                         "measured path end to end)")
    ap.add_argument("--no-record", action="store_true",
                    help="print the table without touching the cache")
    ap.add_argument("--family", default="all",
                    choices=("count", "sliding", "cms", "all"),
                    help="which kernel family to measure")
    args = ap.parse_args(argv)
    if args.smoke:
        args.campaigns, args.window_slots = 8, 4
        args.batch, args.iters = 128, 2
    res = {}
    if args.family in ("count", "all"):
        fn = measure_methods if args.no_record else measure_and_record
        res["count"] = fn(num_campaigns=args.campaigns,
                          window_slots=args.window_slots,
                          batch_size=args.batch, iters=args.iters,
                          scan_batches=args.scan_batches)
    if args.family in ("sliding", "all"):
        # the sliding ring must hold S memberships; the smoke's tiny
        # W=4 ring can't, so size the sliding geometry independently
        fn = (measure_sliding if args.no_record
              else measure_and_record_sliding)
        res["sliding"] = fn(
            num_campaigns=args.campaigns,
            window_slots=max(args.window_slots, 128),
            batch_size=args.batch, iters=args.iters)
    if args.family in ("cms", "all"):
        # ISSUE 13: the sketch-update arms (flat/rowloop/twostage/
        # salsa); smoke uses a narrow plane (the settle pass is O(Wd))
        fn = measure_cms if args.no_record else measure_and_record_cms
        res["cms"] = fn(width=(256 if args.smoke else 2048),
                        batch_size=args.batch, iters=args.iters)
    print(json.dumps(res, indent=1, sort_keys=True))
    return 0 if all(v.get("winner") for v in res.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
