"""The hot op: masked per-(campaign, window) counting on device.

In array terms the whole YSB pipeline stage chain
(filter -> project -> join -> keyed window count;
``AdvertisingTopology.java:228-233``) is, per micro-batch::

    campaign = join_table[ad_idx]            # the Redis-join, as a gather
    wid      = event_time // divisor         # 10 s tumbling window id
    mask     = valid & (event_type == VIEW) & (campaign >= 0) & not-too-late
    counts[campaign, wid % W] += mask        # keyed count, as a scatter-add

State lives on device as a **ring of W open windows** (the reference keeps a
10-window LRU per processor, ``CampaignProcessorCommon.java:37,110-146``):
``window_ids[slot]`` tags which absolute window occupies each ring slot, and
newer windows claim slots from older ones (a masked scatter-max).  Events
whose window lost its slot — i.e. events later than the ring's span — are
counted in ``dropped``, the analog of the reference LRU's silent eviction.

Counts are **deltas since the last flush**: the flusher zeroes them and the
Redis writeback accumulates with HINCRBY, exactly the reference's
partial-flush semantics (``AdvertisingSpark.scala:203``,
``CampaignProcessorCommon.java:91-98``).

Four counting strategies are provided (``method=``):

- ``"scatter"`` — a flat ``.at[].add`` scatter-add; masked rows get index -1
  which JAX scatters drop.
- ``"onehot"``  — a one-hot f32 reduction over the flattened [C*W] cell
  space; materializes a [B, C*W] intermediate, so only viable while C*W is
  small.
- ``"matmul"``  — the factored MXU formulation: the [C, W] count delta is
  ``campaign_onehot[B,C]^T @ slot_onehot[B,W]``, a real f32 matmul on the
  systolic array.  Intermediates are [B,C] + [B,W] (not [B,C*W]), so it
  scales in C and W independently; f32 accumulation of 0/1 over B stays
  exact to 2^24, far above any batch size.
- ``"pallas"``  — the same factored matmul as a hand-fused Pallas kernel
  (``ops.pallas_count``): one-hots and the [C, W] accumulator live in
  VMEM only, streamed over batch tiles.

``bench.py`` picks per backend; all methods are bit-identical (tested).

All times are int32 ms relative to the encoder's ``base_time_ms``; window
ids are int32.  Nothing here uses dynamic shapes or Python control flow, so
the step jits once and scans cleanly.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# "minus infinity" for int32 maxes.  A plain Python int (weak-typed, stays
# int32 next to int32 operands): a module-level jnp scalar would initialize
# the JAX backend at import time, before a CLI entry point can pin the
# platform (the image's sitecustomize force-selects the hardware plugin).
NEG = -2_000_000_000


class WindowState(NamedTuple):
    """Device-resident window state (all int32).

    counts:     [C, W] view-count deltas since last flush
    window_ids: [W]    absolute(relative-base) window id per ring slot; -1 empty
    watermark:  []     max valid event_time seen (relative ms)
    dropped:    []     events lost to lateness / ring eviction
    """

    counts: jax.Array
    window_ids: jax.Array
    watermark: jax.Array
    dropped: jax.Array


def init_state(num_campaigns: int, window_slots: int) -> WindowState:
    return WindowState(
        counts=jnp.zeros((num_campaigns, window_slots), jnp.int32),
        window_ids=jnp.full((window_slots,), -1, jnp.int32),
        watermark=jnp.int32(0),
        dropped=jnp.int32(0),
    )


def assign_windows(window_ids: jax.Array, watermark: jax.Array,
                   wid: jax.Array, wanted: jax.Array, valid: jax.Array,
                   event_time: jax.Array, *, divisor_ms: int,
                   lateness_ms: int):
    """The shared windowing core: lateness mask, ring-slot claim, ownership.

    Every windowed aggregator (exact count, HLL, count-min, t-digest) uses
    this identically; only the state update differs.  Returns
    ``(slot, count_mask, new_window_ids, new_watermark)`` where
    ``count_mask`` marks events whose window owns its ring slot.
    """
    W = window_ids.shape[0]

    # Event-time watermark over the *valid* rows (not just counted ones).
    batch_max = jnp.max(jnp.where(valid, event_time, NEG))
    new_watermark = jnp.maximum(watermark, batch_max)

    # Allowed lateness (generator can emit events up to 60 s late,
    # core.clj:170-173); older events are dropped, not miscounted.
    # Lateness is judged against the watermark AS OF BATCH START
    # (the passed-in watermark, not the post-batch one): watermarks flow
    # between batches, so events can never be late relative to peers in
    # their own batch — otherwise a catchup batch spanning >lateness of
    # event time would drop its own oldest events.
    # wid < 0 (events before the encoder's base window) must also be
    # dropped: wid == -1 would alias the empty-slot sentinel and count
    # into a phantom slot.  The encoder rebases base_time_ms a full
    # lateness span early, so in practice this only fires for events
    # beyond allowed lateness anyway.
    min_wid = (watermark - lateness_ms) // divisor_ms
    mask = wanted & (wid >= min_wid) & (wid >= 0)

    # Claim ring slots: newer window ids win (masked scatter-max; masked
    # rows scatter to index W which the padded buffer absorbs).
    slot = wid % W
    slot_or_pad = jnp.where(mask, slot, W)
    padded_ids = jnp.concatenate([window_ids, jnp.full((1,), -1, jnp.int32)])
    padded_ids = padded_ids.at[slot_or_pad].max(wid)
    new_window_ids = padded_ids[:W]

    # Aggregate only events whose window owns its slot after claiming;
    # events evicted by a newer window within the ring span are dropped.
    owns = new_window_ids[slot] == wid
    count_mask = mask & owns
    return slot, count_mask, new_window_ids, new_watermark


def apply_count(counts: jax.Array, campaign: jax.Array, slot: jax.Array,
                count_mask: jax.Array, method: str) -> jax.Array:
    """``counts[campaign, slot] += 1`` for masked rows, by strategy.

    The ONE copy of the four counting strategies (module docstring):
    every counting kernel — the tumbling step here, the sliding-window
    membership fold, the device-decode fused step — routes its masked
    (campaign, slot) pairs through this dispatch, so the per-backend
    method choice (``engine.pipeline.default_method``, measured by
    ``ops.methodbench``) applies uniformly.  Traced code; all methods
    are bit-identical (tested).
    """
    C, W = counts.shape
    if method == "scatter":
        # Masked rows get index C*W: out-of-bounds on the high side,
        # which scatter mode="drop" discards (negative indices *wrap*).
        flat = jnp.where(count_mask, campaign * W + slot, C * W)
        return (counts.reshape(-1)
                .at[flat].add(1, mode="drop")
                .reshape(C, W))
    if method == "onehot":
        flat = jnp.where(count_mask, campaign * W + slot, C * W)
        onehot = (flat[:, None] == jnp.arange(C * W, dtype=jnp.int32)[None, :])
        return counts + jnp.sum(
            onehot.astype(jnp.float32), axis=0).astype(jnp.int32).reshape(C, W)
    if method == "matmul":
        # Masked rows have campaign -1 / arbitrary slot; zeroing their
        # campaign one-hot row zeroes their whole outer-product contribution.
        camp_oh = ((campaign[:, None] == jnp.arange(C, dtype=jnp.int32))
                   & count_mask[:, None]).astype(jnp.float32)      # [B, C]
        slot_oh = (slot[:, None] == jnp.arange(W, dtype=jnp.int32)
                   ).astype(jnp.float32)                           # [B, W]
        delta = jax.lax.dot_general(
            camp_oh, slot_oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                    # [C, W]
        return counts + delta.astype(jnp.int32)
    if method == "pallas":
        from streambench_tpu.ops.pallas_count import count_tiles

        return count_tiles(counts, campaign, slot, count_mask)
    raise ValueError(f"unknown method {method!r}")


@functools.partial(
    jax.jit,
    static_argnames=("divisor_ms", "lateness_ms", "view_type", "method"))
def step(state: WindowState, join_table: jax.Array,
         ad_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, divisor_ms: int = 10_000, lateness_ms: int = 60_000,
         view_type: int = 0, method: str = "scatter") -> WindowState:
    """Fold one micro-batch into the window state.  Pure; jits once."""
    campaign = join_table[ad_idx]                      # [B] gather-join
    wid = event_time // divisor_ms                     # [B]
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    slot, count_mask, window_ids, watermark = assign_windows(
        state.window_ids, state.watermark, wid, wanted, valid, event_time,
        divisor_ms=divisor_ms, lateness_ms=lateness_ms)

    counts = apply_count(state.counts, campaign, slot, count_mask, method)

    dropped = state.dropped + (
        jnp.sum(wanted.astype(jnp.int32)) - jnp.sum(count_mask.astype(jnp.int32)))
    return WindowState(counts, window_ids, watermark, dropped)


def _still_open(window_ids: jax.Array, watermark: jax.Array,
                divisor_ms: int, lateness_ms: int) -> jax.Array:
    """Free ring slots of closed windows (watermark passed end+lateness)
    — the ONE copy of the close rule every drain variant shares."""
    closed = (window_ids + 1) * divisor_ms + lateness_ms <= watermark
    return jnp.where(closed | (window_ids < 0), jnp.int32(-1), window_ids)


@functools.partial(jax.jit, static_argnames=("divisor_ms", "lateness_ms"))
def flush_deltas(state: WindowState, *, divisor_ms: int = 10_000,
                 lateness_ms: int = 60_000
                 ) -> tuple[jax.Array, jax.Array, WindowState]:
    """Drain count deltas for the host flusher.

    Returns ``(delta_counts [C,W], window_ids [W], new_state)``; the new
    state has all counts zeroed (they were handed to the host) and ring
    slots of *closed* windows freed.  A window is closed once the watermark
    passes its end plus allowed lateness — the event-time analog of the 10 s
    window falling out of the reference's LRU.
    """
    new_state = WindowState(
        counts=jnp.zeros_like(state.counts),
        window_ids=_still_open(state.window_ids, state.watermark,
                               divisor_ms, lateness_ms),
        watermark=state.watermark,
        dropped=state.dropped,
    )
    return state.counts, state.window_ids, new_state


@functools.partial(
    jax.jit, static_argnames=("cap", "divisor_ms", "lateness_ms"))
def flush_deltas_compact(state: WindowState, *, cap: int,
                         divisor_ms: int = 10_000,
                         lateness_ms: int = 60_000):
    """``flush_deltas`` with the nonzero cells compacted ON DEVICE.

    The dense ``[C, W]`` delta block is mostly zeros at large key
    spaces, but the host pays its full transfer per drain — 256 MB at
    C=1e6, W=64, which over a tunneled accelerator link is seconds.
    Here the device compacts to at most ``cap`` (flat_idx, count) pairs
    (static shapes: ``jnp.nonzero(..., size=cap)``), so a typical drain
    moves a few MB.  Returns
    ``(flat_idx [cap], counts [cap], nnz, dense, window_ids, new_state)``
    where ``flat_idx = campaign * W + slot``; entries past ``nnz`` are
    padding.  When ``nnz > cap`` the compaction is incomplete — the
    caller must read ``dense`` instead (it is the ORIGINAL device
    counts handle: no transfer happens unless it is materialized).
    """
    flat = state.counts.reshape(-1)
    nnz = jnp.count_nonzero(flat)
    (idx,) = jnp.nonzero(flat > 0, size=cap, fill_value=0)
    vals = flat[idx]
    _, wids, new_state = flush_deltas(
        state, divisor_ms=divisor_ms, lateness_ms=lateness_ms)
    return (idx.astype(jnp.int32), vals, nnz, state.counts, wids,
            new_state)


@functools.partial(
    jax.jit, static_argnames=("cap", "divisor_ms", "lateness_ms"),
    donate_argnums=(0,))
def flush_deltas_rows_compact(state: WindowState, rows: jax.Array,
                              nrow: jax.Array, *,
                              cap: int, divisor_ms: int = 10_000,
                              lateness_ms: int = 60_000):
    """Touched-rows drain with ON-DEVICE nonzero compaction.

    The alternatives each have a cost that does not scale with the live
    data on a tunneled accelerator: transferring the CAP-padded
    ``[R, W]`` row block costs 33 MB at the 131072-row cap with W=64
    (measured ~70% of config5's TPU catchup wall — the retired
    ``flush_deltas_rows``), and ``flush_deltas_compact`` scans all
    ``C x W`` cells on device (64M at C=1e6).  This op gathers just the
    touched rows (device-internal, no transfer), compacts THEIR
    ``R x W`` cells (8.4M at the cap — 8x less device work), and hands
    the host only ``(flat_idx, count)`` pairs.  ``flat_idx`` indexes the GATHERED
    block: ``campaign = rows[flat_idx // W]``, ``slot = flat_idx % W``.
    Entries past ``nnz`` are padding; ``nnz > cap`` means incomplete
    compaction and the caller must read ``sub`` (the gathered block
    handle — no transfer unless materialized).  Only the touched rows
    are zeroed (in place via donation).  Returns
    ``(idx [cap], vals [cap], nnz, sub [R, W], window_ids, new_state)``.
    """
    sub = state.counts[rows]
    # ``rows`` is zero-padded past ``nrow``: the padding re-gathers
    # campaign row 0, and compacting those duplicates would multiply
    # row 0's counts.  Mask them out (static shape, dynamic count).
    keep = jnp.arange(rows.shape[0], dtype=jnp.int32)[:, None] < nrow
    flat = jnp.where(keep, sub, 0).reshape(-1)
    nnz = jnp.count_nonzero(flat)
    (idx,) = jnp.nonzero(flat > 0, size=cap, fill_value=0)
    vals = flat[idx]
    _, wids, new_state = _zero_rows(state, rows, divisor_ms, lateness_ms)
    return idx.astype(jnp.int32), vals, nnz, sub, wids, new_state


def _zero_rows(state: WindowState, rows: jax.Array,
               divisor_ms: int, lateness_ms: int):
    new_state = WindowState(
        counts=state.counts.at[rows].set(0),
        window_ids=_still_open(state.window_ids, state.watermark,
                               divisor_ms, lateness_ms),
        watermark=state.watermark,
        dropped=state.dropped,
    )
    return None, state.window_ids, new_state


@functools.partial(jax.jit, static_argnames=("divisor_ms", "lateness_ms"),
                   donate_argnums=(0,))
def flush_free_slots(state: WindowState, *, divisor_ms: int = 10_000,
                     lateness_ms: int = 60_000) -> WindowState:
    """Slot-free-only drain: nothing was written since the last drain,
    so counts are already all-zero — only closed ring slots need
    freeing.  With the state donated the counts buffer passes through
    untouched (``flush_deltas`` here would copy AND memset the whole
    [C, W] block just to say "empty": ~650 ms at C=1e6 on CPU)."""
    return WindowState(state.counts,
                       _still_open(state.window_ids, state.watermark,
                                   divisor_ms, lateness_ms),
                       state.watermark, state.dropped)


@functools.partial(jax.jit, static_argnames=("divisor_ms", "lateness_ms"),
                   donate_argnums=(0,))
def flush_rows_zero(state: WindowState, rows: jax.Array, *,
                    divisor_ms: int = 10_000, lateness_ms: int = 60_000):
    """The zero-and-free half of a touched-rows drain, for callers that
    already copied the touched rows out host-side.  On CPU backends the
    count block is host memory: ``np.asarray`` is a zero-copy view and a
    numpy fancy-index reads the touched rows ~13x faster than XLA's row
    gather (measured 14 ms vs 200 ms for 49k rows at C=1e6), so the
    only device work left is this in-place scatter-zero.  Returns
    ``(window_ids, new_state)``."""
    _, wids, new_state = _zero_rows(state, rows, divisor_ms, lateness_ms)
    return wids, new_state


# ----------------------------------------------------------------------
# Packed transfer format.  Against a tunneled accelerator the host->device
# link is the throughput ceiling (measured on the v5e tunnel: ~60-140 ms
# fixed cost per synchronous transfer, ~10-40 MB/s streamed), so the three
# narrow columns (ad_idx, event_type, valid) travel as ONE int32 word per
# event — 8 B/event total with event_time instead of 13 B in four buffers —
# and are unpacked inside the jitted step (shifts/masks, fused for free).
# Layout: bits 0..27 ad_idx (< 2^28 ads), bits 28..29 event_type + 1
# (domain {-1, 0, 1, 2}, ``encode/encoder.py:64``), bit 30 valid.
PACK_AD_BITS = 28
PACK_AD_MAX = 1 << PACK_AD_BITS


def pack_columns(ad_idx: np.ndarray, event_type: np.ndarray,
                 valid: np.ndarray) -> np.ndarray:
    """Host-side (numpy) packing; inverse of ``unpack_columns``.

    Domain-checked: an ``ad_idx`` outside [0, PACK_AD_MAX) or an
    ``event_type`` outside {-1..2} would silently bleed into the
    neighboring bit fields and corrupt every decoded row.  Engine call
    sites are already gated (``_pack_ok``; the unknown-ad sentinel is
    ``len(ads)``, never -1), but the op is public — external callers get
    an error, not corruption.  Numpy reductions off the jitted path:
    ~µs per 8k batch.
    """
    if ad_idx.size:
        if int(ad_idx.min()) < 0 or int(ad_idx.max()) >= PACK_AD_MAX:
            raise ValueError(
                f"pack_columns: ad_idx outside [0, {PACK_AD_MAX}): "
                f"[{int(ad_idx.min())}, {int(ad_idx.max())}]")
        if int(event_type.min()) < -1 or int(event_type.max()) > 2:
            raise ValueError(
                "pack_columns: event_type outside [-1, 2]: "
                f"[{int(event_type.min())}, {int(event_type.max())}]")
    return (ad_idx.astype(np.int32)
            | ((event_type.astype(np.int32) + 1) << PACK_AD_BITS)
            | (valid.astype(np.int32) << (PACK_AD_BITS + 2)))


def unpack_columns(packed: jax.Array):
    """Traced unpack: ``(ad_idx, event_type, valid)`` bit-identical to
    what ``pack_columns`` consumed (given the documented domains)."""
    ad = packed & (PACK_AD_MAX - 1)
    etype = ((packed >> PACK_AD_BITS) & 3) - 1
    valid = ((packed >> (PACK_AD_BITS + 2)) & 1).astype(bool)
    return ad, etype, valid


@functools.partial(
    jax.jit,
    static_argnames=("divisor_ms", "lateness_ms", "view_type", "method"))
def step_packed(state: WindowState, join_table: jax.Array,
                packed: jax.Array, event_time: jax.Array,
                *, divisor_ms: int = 10_000, lateness_ms: int = 60_000,
                view_type: int = 0, method: str = "scatter") -> WindowState:
    """``step`` consuming the packed wire word (see ``pack_columns``)."""
    ad_idx, event_type, valid = unpack_columns(packed)
    return step(state, join_table, ad_idx, event_type, event_time, valid,
                divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                view_type=view_type, method=method)


@functools.partial(
    jax.jit,
    static_argnames=("divisor_ms", "lateness_ms", "view_type", "method"))
def scan_steps_packed(state: WindowState, join_table: jax.Array,
                      packed: jax.Array, event_time: jax.Array,
                      *, divisor_ms: int = 10_000,
                      lateness_ms: int = 60_000, view_type: int = 0,
                      method: str = "scatter") -> WindowState:
    """``scan_steps`` over ``[N, B]`` packed words + event times."""

    def body(carry, xs):
        p, t = xs
        return step_packed(carry, join_table, p, t,
                           divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                           view_type=view_type, method=method), None

    final, _ = jax.lax.scan(body, state, (packed, event_time))
    return final


@functools.partial(
    jax.jit,
    static_argnames=("divisor_ms", "lateness_ms", "view_type", "method"))
def scan_steps(state: WindowState, join_table: jax.Array,
               ad_idx: jax.Array, event_type: jax.Array,
               event_time: jax.Array, valid: jax.Array,
               *, divisor_ms: int = 10_000, lateness_ms: int = 60_000,
               view_type: int = 0, method: str = "scatter") -> WindowState:
    """Fold ``[N, B]`` stacked micro-batches via ``lax.scan``.

    One compiled program processes N batches with the carry on device —
    the streaming-scan idiom from SURVEY.md section 5.7 (the unbounded
    stream, chunked; XLA sees a single loop, no per-batch dispatch).
    """

    def body(carry, xs):
        a, e, t, v = xs
        return step(carry, join_table, a, e, t, v,
                    divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                    view_type=view_type, method=method), None

    final, _ = jax.lax.scan(body, state, (ad_idx, event_type, event_time, valid))
    return final
