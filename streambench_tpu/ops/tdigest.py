"""t-digest quantile sketches as fixed-shape TPU kernels.

BASELINE config #3's latency-quantile structure.  Classic t-digest keeps a
variable-length centroid list per key — hostile to XLA.  This formulation
is fixed-shape throughout, keyed over ``N`` digests (the caller maps
(campaign, window-slot) -> key):

- state: ``means [N, K]``, ``weights [N, K]`` (weight 0 = empty centroid);
- batch fold: sort events by (key, value); within-key ranks by a
  segment-cumsum; each event lands in centroid
  ``floor(K * k1(q))`` where ``q`` is its within-key mid-rank quantile and
  ``k1(q) = asin(2q-1)/pi + 1/2`` is t-digest's tail-accurate scale
  function (Dunning & Ertl); scatter-add (weight, weight*value);
- merge: concat old and new centroids to ``[N, 2K]``, sort by mean,
  re-bucket by cumulative-weight mid-quantile through the same scale, and
  scatter back to ``[N, K]``.  Merge is associative *approximately* — the
  usual t-digest property — and weight totals are conserved exactly.

Quantile query sorts centroids by mean and linearly interpolates on the
cumulative-weight midpoints.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class TDigestState(NamedTuple):
    means: jax.Array    # [N, K] float32
    weights: jax.Array  # [N, K] float32


def init_state(num_keys: int, compression: int = 64) -> TDigestState:
    return TDigestState(
        means=jnp.zeros((num_keys, compression), jnp.float32),
        weights=jnp.zeros((num_keys, compression), jnp.float32),
    )


def _k1_bucket(q: jax.Array, K: int) -> jax.Array:
    """Scale-function bucketing: tails get narrow centroids."""
    q = jnp.clip(q, 0.0, 1.0)
    k = (jnp.arcsin(2.0 * q - 1.0) / jnp.pi + 0.5) * K
    return jnp.clip(k.astype(jnp.int32), 0, K - 1)


def _fold(key, value, w, N: int, K: int):
    """Batch-local digest: scatter (w, w*value) into fresh ``[N, K]``
    buffers, bucketed by within-key mid-rank quantile."""
    B = key.shape[0]
    # sort by (key, value): stable value sort, then stable key sort
    order = jnp.argsort(value, stable=True)
    order = order[jnp.argsort(key[order], stable=True)]
    sk = key[order]
    sv = value[order]
    sw = w[order]

    # within-key cumulative weight (exclusive) via global cumsum minus the
    # key's starting cumsum, taken from the first row of each key run
    csum = jnp.cumsum(sw) - sw                      # exclusive prefix
    first = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    # broadcast each run's starting csum to its rows: running max of
    # (csum at run starts), since csum is nondecreasing
    run_base = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, csum, 0.0))
    within = csum - run_base
    total = jnp.zeros((N,), jnp.float32).at[sk].add(sw, mode="drop")
    tot_here = total[jnp.clip(sk, 0, N - 1)]
    q = (within + sw * 0.5) / jnp.maximum(tot_here, 1e-9)
    bucket = _k1_bucket(q, K)

    flat = jnp.where(sw > 0, sk * K + bucket, N * K)
    weights = (jnp.zeros((N * K,), jnp.float32)
               .at[flat].add(sw, mode="drop").reshape(N, K))
    means_num = (jnp.zeros((N * K,), jnp.float32)
                 .at[flat].add(sw * sv, mode="drop").reshape(N, K))
    return means_num, weights


@jax.jit
def update(state: TDigestState, key: jax.Array, value: jax.Array,
           mask: jax.Array) -> TDigestState:
    """Fold one batch of (key, value) points, then compress back to K."""
    N, K = state.means.shape
    w = jnp.where(mask, 1.0, 0.0).astype(jnp.float32)
    key = jnp.where(mask, key, N)

    new_num, new_w = _fold(key, value.astype(jnp.float32), w, N, K)
    new_mean = new_num / jnp.maximum(new_w, 1e-9)
    return _compress(
        jnp.concatenate([state.means, new_mean], axis=1),
        jnp.concatenate([state.weights, new_w], axis=1), K)


def _compress(m2: jax.Array, w2: jax.Array, K: int) -> TDigestState:
    """Re-bucket ``[N, M]`` centroids down to ``[N, K]`` via the k1 scale."""
    N = m2.shape[0]
    order = jnp.argsort(jnp.where(w2 > 0, m2, jnp.inf), axis=1)
    m2 = jnp.take_along_axis(m2, order, axis=1)
    w2 = jnp.take_along_axis(w2, order, axis=1)
    csum = jnp.cumsum(w2, axis=1) - w2
    tot = jnp.sum(w2, axis=1, keepdims=True)
    q = (csum + 0.5 * w2) / jnp.maximum(tot, 1e-9)
    bucket = _k1_bucket(q, K)
    rows = jnp.broadcast_to(jnp.arange(N)[:, None], bucket.shape)
    flat = jnp.where(w2 > 0, rows * K + bucket, N * K)
    weights = (jnp.zeros((N * K,), jnp.float32)
               .at[flat.reshape(-1)].add(w2.reshape(-1), mode="drop")
               .reshape(N, K))
    nums = (jnp.zeros((N * K,), jnp.float32)
            .at[flat.reshape(-1)].add((w2 * m2).reshape(-1), mode="drop")
            .reshape(N, K))
    means = nums / jnp.maximum(weights, 1e-9)
    return TDigestState(means, weights)


@jax.jit
def quantile(state: TDigestState, qs: jax.Array) -> jax.Array:
    """Per-key quantiles: returns ``[N, len(qs)]``.

    Linear interpolation between centroid means at cumulative-weight
    midpoints; empty digests return 0.
    """
    N, K = state.means.shape
    order = jnp.argsort(jnp.where(state.weights > 0, state.means, jnp.inf),
                        axis=1)
    m = jnp.take_along_axis(state.means, order, axis=1)
    w = jnp.take_along_axis(state.weights, order, axis=1)
    tot = jnp.sum(w, axis=1, keepdims=True)            # [N, 1]
    mid = (jnp.cumsum(w, axis=1) - 0.5 * w) / jnp.maximum(tot, 1e-9)
    # Empty centroids sort last with mean 0; mask their midpoints to +inf
    # and clamp interpolation to the last OCCUPIED centroid, else any q
    # above the last occupied midpoint interpolates toward 0.
    mid = jnp.where(w > 0, mid, jnp.inf)
    last = jnp.maximum(jnp.sum((w > 0).astype(jnp.int32), axis=1) - 1, 0)

    def one_key(mids, mns, total, last_i):
        def one_q(q):
            idx = jnp.searchsorted(mids, q)
            lo = jnp.clip(idx - 1, 0, last_i)
            hi = jnp.clip(idx, 0, last_i)
            t = jnp.where(
                mids[hi] > mids[lo],
                (q - mids[lo]) / jnp.maximum(mids[hi] - mids[lo], 1e-9),
                0.0)
            v = mns[lo] + t * (mns[hi] - mns[lo])
            return jnp.where(total[0] > 0, v, 0.0)
        return jax.vmap(one_q)(qs)

    return jax.vmap(one_key)(mid, m, tot, last)


@jax.jit
def merge(a: TDigestState, b: TDigestState) -> TDigestState:
    """Digest union (e.g. cross-device): exact in total weight."""
    K = a.means.shape[1]
    return _compress(
        jnp.concatenate([a.means, b.means], axis=1),
        jnp.concatenate([a.weights, b.weights], axis=1), K)
