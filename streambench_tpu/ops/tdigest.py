"""t-digest quantile sketches as fixed-shape TPU kernels.

BASELINE config #3's latency-quantile structure.  Classic t-digest keeps a
variable-length centroid list per key — hostile to XLA.  This formulation
is fixed-shape throughout, keyed over ``N`` digests (the caller maps
(campaign, window-slot) -> key):

- state: ``means [N, K]``, ``weights [N, K]`` (weight 0 = empty centroid);
- batch fold: value-bucketed pre-clustering.  Events scatter-add
  ``(w, w*value)`` into a ``[N, HIST_BINS]`` histogram whose bins are the
  top exponent+mantissa bits of float32(value) — log-spaced, *monotone in
  value*, ~3% relative width — so the histogram is value-sorted by
  construction and the within-key ranks a t-digest needs fall out of a
  row cumsum, no per-event sort.  (The first formulation sorted every
  batch by (key, value); two argsorts over B per batch were 60% of
  config #3's device time.  The histogram fold is two O(B) scatters.)
- compress: concat centroids to ``[N, M]``, sort by mean, re-bucket by
  cumulative-weight mid-quantile through t-digest's tail-accurate scale
  ``k1(q) = asin(2q-1)/pi + 1/2`` (Dunning & Ertl), scatter back to
  ``[N, K]``.  Compression is associative *approximately* — the usual
  t-digest property — and weight totals are conserved exactly: bin means
  are exact averages of their members, so total weight and grand mean
  survive any compress cadence.
- scan folding: callers on a hot loop accumulate the histogram across a
  whole chunk (``fold_hist`` per batch, O(B) each) and ``absorb_hist``
  once per chunk — one compress amortized over K batches.

The value bucketing floors resolution at one part in 2^MANT (~3%
relative): values below 1.0 collapse into bin 0 and negatives clamp to
0.  Built for nonnegative metrics (latency in ms); for signed data,
shift before folding.

Quantile query sorts centroids by mean and linearly interpolates on the
cumulative-weight midpoints.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class TDigestState(NamedTuple):
    means: jax.Array    # [N, K] float32
    weights: jax.Array  # [N, K] float32


def init_state(num_keys: int, compression: int = 64) -> TDigestState:
    return TDigestState(
        means=jnp.zeros((num_keys, compression), jnp.float32),
        weights=jnp.zeros((num_keys, compression), jnp.float32),
    )


def _k1_bucket(q: jax.Array, K: int) -> jax.Array:
    """Scale-function bucketing: tails get narrow centroids."""
    q = jnp.clip(q, 0.0, 1.0)
    k = (jnp.arcsin(2.0 * q - 1.0) / jnp.pi + 0.5) * K
    return jnp.clip(k.astype(jnp.int32), 0, K - 1)


# Value-bucketed pre-cluster geometry: bins are float32(value)'s top
# exponent + MANT mantissa bits, shifted so value 1.0 lands in bin 0.
# Monotone in value for value >= 0 (positive-float bit patterns are
# order-preserving), 2^MANT bins per octave over [1, 2^31) -> 992 live
# bins; bin 0 additionally absorbs [0, 1).
HIST_MANT = 5
HIST_BINS = 1024
_HIST_SHIFT = 23 - HIST_MANT
_HIST_OFFSET = 127 << HIST_MANT  # bucket of value 1.0 before shifting


def _value_bucket(value: jax.Array) -> jax.Array:
    f = jnp.maximum(value, 0.0).astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(f, jnp.int32)
    return jnp.clip((bits >> _HIST_SHIFT) - _HIST_OFFSET, 0, HIST_BINS - 1)


def hist_init(num_keys: int) -> tuple[jax.Array, jax.Array]:
    """Fresh (value-sum, weight) accumulator for ``fold_hist``."""
    z = jnp.zeros((num_keys, HIST_BINS), jnp.float32)
    return z, z


def fold_hist(hist_num: jax.Array, hist_w: jax.Array, key: jax.Array,
              value: jax.Array, w: jax.Array, num_keys: int
              ) -> tuple[jax.Array, jax.Array]:
    """Fold one batch into the histogram: two O(B) scatter-adds.

    Rows with ``w == 0`` or out-of-range keys drop.  The key range must
    be masked explicitly: JAX normalizes negative scatter indices
    NumPy-style BEFORE the ``mode="drop"`` bounds check, so a negative
    flat index would wrap into the last key's row, not drop.  Values
    clamp to 0 before accumulating (bin 0's sum must match its bucket).
    """
    value = jnp.maximum(value.astype(jnp.float32), 0.0)
    ok = (w > 0) & (key >= 0) & (key < num_keys)
    flat = jnp.where(ok, key * HIST_BINS + _value_bucket(value),
                     num_keys * HIST_BINS)
    hist_w = (hist_w.reshape(-1).at[flat].add(w, mode="drop")
              .reshape(num_keys, HIST_BINS))
    hist_num = (hist_num.reshape(-1).at[flat].add(w * value, mode="drop")
                .reshape(num_keys, HIST_BINS))
    return hist_num, hist_w


def absorb_hist(state: TDigestState, hist_num: jax.Array,
                hist_w: jax.Array) -> TDigestState:
    """Compress an accumulated histogram into the digest.

    Two stages, both cheap: the histogram is value-sorted by
    construction, so it compresses to K centroids sort-free; the state's
    centroids are mean-ordered after any ``_compress`` (k1 buckets are
    quantile-ordered), so the merge only sorts ``[N, 2K]`` — never the
    ``[N, HIST_BINS]`` block."""
    K = state.means.shape[1]
    hist_mean = hist_num / jnp.maximum(hist_w, 1e-9)
    hd = _compress_sorted(hist_mean, hist_w, K)
    return _compress(
        jnp.concatenate([state.means, hd.means], axis=1),
        jnp.concatenate([state.weights, hd.weights], axis=1), K)


def _fold(key, value, w, N: int, K: int):
    """Step-form batch fold: scatter (w, w*value) into fresh ``[N, K]``
    buffers, bucketed by exact within-key mid-rank quantile.

    This is the sort-based formulation — O(B log B) time but O(N*K)
    memory, so the per-batch ``update`` stays viable at large key
    counts where a ``fold_hist`` transient (``[N, HIST_BINS]`` floats
    per call) would dwarf the digest state.  Hot loops should prefer
    ``fold_hist`` + ``absorb_hist`` once per chunk instead."""
    # sort by (key, value): stable value sort, then stable key sort
    order = jnp.argsort(value, stable=True)
    order = order[jnp.argsort(key[order], stable=True)]
    sk = key[order]
    sv = value[order]
    sw = w[order]

    # within-key cumulative weight (exclusive) via global cumsum minus the
    # key's starting cumsum, taken from the first row of each key run
    csum = jnp.cumsum(sw) - sw                      # exclusive prefix
    first = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    # broadcast each run's starting csum to its rows: running max of
    # (csum at run starts), since csum is nondecreasing
    run_base = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, csum, 0.0))
    within = csum - run_base
    total = jnp.zeros((N,), jnp.float32).at[sk].add(sw, mode="drop")
    tot_here = total[jnp.clip(sk, 0, N - 1)]
    q = (within + sw * 0.5) / jnp.maximum(tot_here, 1e-9)
    bucket = _k1_bucket(q, K)

    flat = jnp.where(sw > 0, sk * K + bucket, N * K)
    weights = (jnp.zeros((N * K,), jnp.float32)
               .at[flat].add(sw, mode="drop").reshape(N, K))
    means_num = (jnp.zeros((N * K,), jnp.float32)
                 .at[flat].add(sw * sv, mode="drop").reshape(N, K))
    return means_num, weights


@jax.jit
def update(state: TDigestState, key: jax.Array, value: jax.Array,
           mask: jax.Array) -> TDigestState:
    """Fold one batch of (key, value) points, then compress back to K."""
    N, K = state.means.shape
    w = jnp.where(mask, 1.0, 0.0).astype(jnp.float32)
    # match fold_hist's domain exactly: nonneg values, in-range keys
    value = jnp.maximum(value.astype(jnp.float32), 0.0)
    key = jnp.where(mask & (key >= 0) & (key < N), key, N)

    new_num, new_w = _fold(key, value, w, N, K)
    new_mean = new_num / jnp.maximum(new_w, 1e-9)
    return _compress(
        jnp.concatenate([state.means, new_mean], axis=1),
        jnp.concatenate([state.weights, new_w], axis=1), K)


def _compress_sorted(m2: jax.Array, w2: jax.Array, K: int) -> TDigestState:
    """Re-bucket value-ORDERED ``[N, M]`` centroids down to ``[N, K]``
    via the k1 scale — no sort.  Zero-weight columns contribute nothing
    to the cumsum and drop out of the scatter, so they may sit anywhere
    in the order."""
    N = m2.shape[0]
    csum = jnp.cumsum(w2, axis=1) - w2
    tot = jnp.sum(w2, axis=1, keepdims=True)
    q = (csum + 0.5 * w2) / jnp.maximum(tot, 1e-9)
    bucket = _k1_bucket(q, K)
    rows = jnp.broadcast_to(jnp.arange(N)[:, None], bucket.shape)
    flat = jnp.where(w2 > 0, rows * K + bucket, N * K)
    weights = (jnp.zeros((N * K,), jnp.float32)
               .at[flat.reshape(-1)].add(w2.reshape(-1), mode="drop")
               .reshape(N, K))
    nums = (jnp.zeros((N * K,), jnp.float32)
            .at[flat.reshape(-1)].add((w2 * m2).reshape(-1), mode="drop")
            .reshape(N, K))
    means = nums / jnp.maximum(weights, 1e-9)
    return TDigestState(means, weights)


def _compress(m2: jax.Array, w2: jax.Array, K: int) -> TDigestState:
    """Re-bucket ``[N, M]`` centroids down to ``[N, K]`` via the k1 scale."""
    order = jnp.argsort(jnp.where(w2 > 0, m2, jnp.inf), axis=1)
    m2 = jnp.take_along_axis(m2, order, axis=1)
    w2 = jnp.take_along_axis(w2, order, axis=1)
    return _compress_sorted(m2, w2, K)


@jax.jit
def quantile(state: TDigestState, qs: jax.Array) -> jax.Array:
    """Per-key quantiles: returns ``[N, len(qs)]``.

    Linear interpolation between centroid means at cumulative-weight
    midpoints; empty digests return 0.
    """
    N, K = state.means.shape
    order = jnp.argsort(jnp.where(state.weights > 0, state.means, jnp.inf),
                        axis=1)
    m = jnp.take_along_axis(state.means, order, axis=1)
    w = jnp.take_along_axis(state.weights, order, axis=1)
    tot = jnp.sum(w, axis=1, keepdims=True)            # [N, 1]
    mid = (jnp.cumsum(w, axis=1) - 0.5 * w) / jnp.maximum(tot, 1e-9)
    # Empty centroids sort last with mean 0; mask their midpoints to +inf
    # and clamp interpolation to the last OCCUPIED centroid, else any q
    # above the last occupied midpoint interpolates toward 0.
    mid = jnp.where(w > 0, mid, jnp.inf)
    last = jnp.maximum(jnp.sum((w > 0).astype(jnp.int32), axis=1) - 1, 0)

    def one_key(mids, mns, total, last_i):
        def one_q(q):
            idx = jnp.searchsorted(mids, q)
            lo = jnp.clip(idx - 1, 0, last_i)
            hi = jnp.clip(idx, 0, last_i)
            t = jnp.where(
                mids[hi] > mids[lo],
                (q - mids[lo]) / jnp.maximum(mids[hi] - mids[lo], 1e-9),
                0.0)
            v = mns[lo] + t * (mns[hi] - mns[lo])
            return jnp.where(total[0] > 0, v, 0.0)
        return jax.vmap(one_q)(qs)

    return jax.vmap(one_key)(mid, m, tot, last)


@jax.jit
def merge(a: TDigestState, b: TDigestState) -> TDigestState:
    """Digest union (e.g. cross-device): exact in total weight."""
    K = a.means.shape[1]
    return _compress(
        jnp.concatenate([a.means, b.means], axis=1),
        jnp.concatenate([a.weights, b.weights], axis=1), K)
