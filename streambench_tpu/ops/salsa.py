"""SALSA-style count-min sketch: 8-bit cells that merge on overflow.

ROADMAP open item 2 / PAPERS.md arXiv:2102.12531 (SALSA: self-adjusting
lean streaming analytics): at production cardinality the fixed-width
``ops/cms.py`` plane is exactly wrong — a [D, Wd] int32 table spends 4
bytes on every counter when the overwhelming majority of cells hold
tiny values, so at a fixed device-memory budget the sketch is 4x
narrower than it could be and its collision error 4x higher.  SALSA
starts every counter at 8 bits and **widens only where traffic lands**:
a cell that overflows merges with its sibling into a 16-bit pair, an
overflowing pair merges into a 32-bit quad.  Width goes where the heavy
keys are; everywhere else a counter costs one byte.

State is three planes plus a scalar, all static-shaped:

- ``table [D, Wd] uint8`` — the cell bytes.  A merged group stores its
  value little-endian across its member bytes.
- ``m1 [D, Wd//16] uint8`` — packed bitmap, one bit per PAIR: bit ``p``
  set means cells ``(2p, 2p+1)`` form one 16-bit counter.
- ``m2 [D, Wd//32] uint8`` — packed bitmap, one bit per QUAD: bit ``q``
  set means cells ``4q..4q+3`` form one 32-bit counter (implies both
  pair bits).
- ``total [] int32`` — total folded weight (same contract as CMSState).

Bitmap overhead is 3/32 byte per cell, so the plane costs ~1.094
bytes/cell vs the fixed sketch's 4 — 3.66x the counters in the same
device bytes (``obs.devmem.state_nbytes`` measures it; bench_sketch.py
commits the numbers).

**The transition is a multiset homomorphism.**  Three deliberate
choices make the whole state a pure function of the exact per-cell
totals, independent of batching, event order, and shard split:

1. overflow is detected on the EXACT int32 accumulated value (the
   update decodes, adds, then settles — increments are never lost to a
   saturating 8-bit add);
2. merging SUMS the sibling counters (SALSA's max-on-merge is slightly
   tighter but max does not distribute over the cross-shard sum, which
   would break merge-order invariance; sum keeps every estimate an
   upper bound and keeps the algebra linear);
3. merge bits only ever turn on, and they turn on exactly when a
   group's running total first exceeds its width (totals are monotone,
   so the final bitmap depends only on the final totals).

Consequences, all pinned by tests/test_salsa.py: per-batch fold, scan
fold, and any sharded split + arbitrary merge order produce
bit-identical planes, and the numpy oracle can compute the expected
state in closed form from exact totals (``oracle_encode_np``) without
replaying the transition at all.

``merge(a, b)`` = OR the bitmaps, sum the decoded value planes, settle
(a union group can itself overflow), re-encode — associative,
commutative, and idempotence-free like any counter sum.  No psum: the
sharded session engine all_gathers closed rows (already gathered for
the candidate ring) and updates the replicated plane, so the SALSA
mode costs zero extra collectives (parallel/sketches.py).

Query semantics match ``ops/cms.py`` exactly while every touched group
is still solo (same ``_row_cols`` hash, same min-over-rows), so at
equal width a run without overflows reports bit-identical estimates —
the A/B oracle the CI session leg uses.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.ops.cms import _SALTS, _row_cols

#: width caps per merge level: solo byte, 16-bit pair, 32-bit quad
#: (the quad cap is int31 — this repo runs x64-off, decoded values and
#: totals live in int32; quads saturate there, order-invariantly,
#: instead of wrapping)
CAP0 = 255
CAP1 = 65_535
CAP2 = 2**31 - 1


class SalsaState(NamedTuple):
    table: jax.Array   # [D, Wd] uint8 cell bytes
    m1: jax.Array      # [D, Wd//16] uint8 packed pair-merge bits
    m2: jax.Array      # [D, Wd//32] uint8 packed quad-merge bits
    total: jax.Array   # [] int32 total folded weight


def init_state(depth: int = 4, width: int = 2048,
               cell_bits: int = 8) -> SalsaState:
    """Fresh plane.  ``cell_bits=16`` starts with every pair pre-merged
    (16-bit counters everywhere, quads still form on overflow) — the
    ``jax.cms.cell.bits`` knob."""
    if width & (width - 1) or width < 32:
        raise ValueError("width must be a power of two >= 32")
    if depth > len(_SALTS):
        raise ValueError(f"depth <= {len(_SALTS)}")
    if cell_bits not in (8, 16):
        raise ValueError(f"cell_bits must be 8 or 16, got {cell_bits}")
    m1_fill = 0xFF if cell_bits == 16 else 0
    return SalsaState(
        table=jnp.zeros((depth, width), jnp.uint8),
        m1=jnp.full((depth, width // 16), m1_fill, jnp.uint8),
        m2=jnp.zeros((depth, width // 32), jnp.uint8),
        total=jnp.int32(0))


# ----------------------------------------------------------------------
# bitmap + value-plane plumbing (shared by update / query / merge)
# ----------------------------------------------------------------------

def _expand_bits(packed: jax.Array, n: int) -> jax.Array:
    """[D, n//8] packed uint8 -> [D, n] int32 in {0, 1} (bit k of byte
    i is group 8i+k)."""
    D = packed.shape[0]
    bits = (packed[:, :, None].astype(jnp.int32)
            >> jnp.arange(8, dtype=jnp.int32)[None, None, :]) & 1
    return bits.reshape(D, n)


def _pack_bits(bits: jax.Array) -> jax.Array:
    """[D, n] {0,1} -> [D, n//8] packed uint8 (inverse of _expand_bits)."""
    D, n = bits.shape
    b = bits.reshape(D, n // 8, 8).astype(jnp.int32)
    w = (1 << jnp.arange(8, dtype=jnp.int32))[None, None, :]
    return jnp.sum(b * w, axis=-1).astype(jnp.uint8)


def _decode(state: SalsaState):
    """Base-placed value plane: ``v [D, Wd] int32`` holds each group's
    value at the group's FIRST cell, zero at the other member cells
    (so any coarser group-sum is a plain strided reshape-sum).  Also
    returns the expanded pair/quad bit planes."""
    D, Wd = state.table.shape
    b = state.table.astype(jnp.int32)
    pair = b[:, 0::2] + (b[:, 1::2] << 8)            # [D, Wd/2] raw LE16
    quad = pair[:, 0::2] + (pair[:, 1::2] << 16)     # [D, Wd/4] raw LE32
    m1b = _expand_bits(state.m1, Wd // 2)            # [D, Wd/2]
    m2b = _expand_bits(state.m2, Wd // 4)            # [D, Wd/4]
    idx = jnp.arange(Wd, dtype=jnp.int32)
    pair_base = (idx % 2 == 0)[None, :]
    quad_base = (idx % 4 == 0)[None, :]
    m1_cell = jnp.repeat(m1b, 2, axis=1)
    m2_cell = jnp.repeat(m2b, 4, axis=1)
    pair_exp = jnp.repeat(pair, 2, axis=1)
    quad_exp = jnp.repeat(quad, 4, axis=1)
    v = jnp.where(
        m2_cell == 1,
        jnp.where(quad_base, quad_exp, 0),
        jnp.where(m1_cell == 1,
                  jnp.where(pair_base, pair_exp, 0),
                  b))
    return v, m1b, m2b


def _settle(v: jax.Array, m1b: jax.Array, m2b: jax.Array) -> SalsaState:
    """Overflow pass + re-encode.  ``v`` is a base-placed int32 value
    plane whose groups may exceed their width; merge bits turn on where
    they do (solo > 255 -> pair, pair > 65535 -> quad, quad saturates
    at CAP2), values re-base at the new geometry, bytes re-encode."""
    D, Wd = v.shape
    # group-sums at each granularity (non-base member cells hold 0, so
    # the strided sums ARE the group totals regardless of current level)
    pair_tot = v[:, 0::2] + v[:, 1::2]               # [D, Wd/2]
    quad_tot = pair_tot[:, 0::2] + pair_tot[:, 1::2]  # [D, Wd/4]
    # a pair merges when any member SOLO value outgrew a byte (merged
    # pairs/quads are already excluded: their bit is set)
    cell_hi = jnp.maximum(v[:, 0::2], v[:, 1::2])
    m1b = jnp.maximum(m1b, (cell_hi > CAP0).astype(jnp.int32))
    # a quad merges when a MERGED pair's value outgrew 16 bits (an
    # unmerged pair is <= 510, so the m1b guard is belt only)
    pair_over = (m1b == 1) & (pair_tot > CAP1)
    quad_over = pair_over[:, 0::2] | pair_over[:, 1::2]
    m2b = jnp.maximum(m2b, quad_over.astype(jnp.int32))
    # quad merge implies both pair bits
    m1b = jnp.maximum(m1b, jnp.repeat(m2b, 2, axis=1))
    quad_tot = jnp.minimum(quad_tot, CAP2)
    # re-encode at the (possibly widened) final geometry
    idx = jnp.arange(Wd, dtype=jnp.int32)
    m1_cell = jnp.repeat(m1b, 2, axis=1)
    m2_cell = jnp.repeat(m2b, 4, axis=1)
    # bytes: each cell extracts its lane of the owning group's value
    # (solo: byte 0 of its own value; pair: byte idx%2; quad: idx%4)
    group_val = jnp.where(
        m2_cell == 1, jnp.repeat(quad_tot, 4, axis=1),
        jnp.where(m1_cell == 1, jnp.repeat(pair_tot, 2, axis=1), v))
    lane = jnp.where(m2_cell == 1, idx[None, :] % 4,
                     jnp.where(m1_cell == 1, idx[None, :] % 2, 0))
    table = ((group_val >> (lane * 8)) & 0xFF).astype(jnp.uint8)
    return table, _pack_bits(m1b), _pack_bits(m2b)


def _bit_at(packed: jax.Array, group: jax.Array) -> jax.Array:
    """Gather bit ``group`` of each row's packed bitmap: packed is
    [D, G//8], group is [D, B] int32 -> [D, B] int32 in {0, 1}."""
    byte = jnp.take_along_axis(packed, (group >> 3).astype(jnp.int32),
                               axis=1).astype(jnp.int32)
    return (byte >> (group & 7)) & 1


# ----------------------------------------------------------------------
# the three transitions
# ----------------------------------------------------------------------

@jax.jit
def update(state: SalsaState, keys: jax.Array, weights: jax.Array,
           mask: jax.Array) -> SalsaState:
    """Add ``weights`` for ``keys`` (masked rows dropped): decode to the
    exact value plane, scatter each key's weight at its CURRENT group
    base, settle overflow, re-encode.  Same ``_row_cols`` hash as the
    fixed-width sketch, so both arms touch the same cells."""
    D, Wd = state.table.shape
    cols = _row_cols(keys, D, Wd)                        # [D, B]
    w = jnp.where(mask, weights, 0).astype(jnp.int32)    # [B]
    v, m1b, m2b = _decode(state)
    m1_at = _bit_at(state.m1, cols >> 1)
    m2_at = _bit_at(state.m2, cols >> 2)
    base = jnp.where(m2_at == 1, (cols >> 2) << 2,
                     jnp.where(m1_at == 1, (cols >> 1) << 1, cols))
    flat = jnp.arange(D, dtype=jnp.int32)[:, None] * Wd + base
    flat = jnp.where(mask[None, :], flat, D * Wd)
    v = (v.reshape(-1)
         .at[flat.reshape(-1)]
         .add(jnp.broadcast_to(w, (D, w.shape[0])).reshape(-1),
              mode="drop")
         .reshape(D, Wd))
    table, m1, m2 = _settle(v, m1b, m2b)
    return SalsaState(table, m1, m2, state.total + jnp.sum(w))


@jax.jit
def query(state: SalsaState, keys: jax.Array) -> jax.Array:
    """Point estimates (upper bounds): the widest merged counter
    covering each key's cell, min over the D rows."""
    D, Wd = state.table.shape
    cols = _row_cols(keys, D, Wd)
    m1_at = _bit_at(state.m1, cols >> 1)
    m2_at = _bit_at(state.m2, cols >> 2)
    t = state.table.astype(jnp.int32)

    def at(off_base, k):
        return jnp.take_along_axis(t, off_base + k, axis=1)

    solo = jnp.take_along_axis(t, cols, axis=1)
    p0 = (cols >> 1) << 1
    pairv = at(p0, 0) + (at(p0, 1) << 8)
    q0 = (cols >> 2) << 2
    quadv = (at(q0, 0) + (at(q0, 1) << 8)
             + (at(q0, 2) << 16) + (at(q0, 3) << 24))
    val = jnp.where(m2_at == 1, quadv,
                    jnp.where(m1_at == 1, pairv, solo))
    return jnp.min(val, axis=0)


def merge(a: SalsaState, b: SalsaState) -> SalsaState:
    """Shard union: OR bitmaps, sum the decoded value planes, settle
    (a union group can itself overflow), re-encode.  Commutative and
    associative bit-for-bit — tests/test_salsa.py sweeps random shard
    splits and merge orders."""
    if (a.table.shape != b.table.shape
            or a.table.dtype != b.table.dtype):
        raise ValueError(
            f"salsa.merge: geometry mismatch — a.table "
            f"{a.table.shape}/{a.table.dtype} vs b.table "
            f"{b.table.shape}/{b.table.dtype}")
    va, m1a, m2a = _decode(a)
    vb, m1b, m2b = _decode(b)
    table, m1, m2 = _settle(va + vb, jnp.maximum(m1a, m1b),
                            jnp.maximum(m2a, m2b))
    return SalsaState(table, m1, m2, a.total + b.total)


@functools.partial(jax.jit, static_argnames=("k",))
def heavy_hitters(state: SalsaState, candidate_keys: jax.Array, *,
                  k: int = 16):
    """Top-k candidates by SALSA estimate (peer of cms.heavy_hitters)."""
    est = query(state, candidate_keys)
    return jax.lax.top_k(est, k)


def stats(state: SalsaState) -> dict:
    """Host-side merge census (bench/report honesty: a SALSA rung that
    never merged proves nothing about overflow handling)."""
    Wd = state.table.shape[1]
    m1 = np.unpackbits(np.asarray(state.m1), axis=1,
                       count=Wd // 2, bitorder="little")
    m2 = np.unpackbits(np.asarray(state.m2), axis=1,
                       count=Wd // 4, bitorder="little")
    return {"cells": int(state.table.size),
            "merged_pairs": int(m1.sum()),
            "merged_quads": int(m2.sum()),
            "total": int(state.total)}


# ----------------------------------------------------------------------
# numpy differential oracle
# ----------------------------------------------------------------------
# The homomorphism property (module docstring) means the expected state
# is a CLOSED FORM of the exact per-cell totals — the oracle never
# replays the batched transition, so it cannot share a bug with it.

def oracle_cols_np(keys: np.ndarray, depth: int, width: int) -> np.ndarray:
    """numpy mirror of cms._row_cols ([D, B] column per row)."""
    from streambench_tpu.reach.oracle import splitmix32_np

    cols = []
    for d in range(depth):
        h = splitmix32_np(
            (keys.astype(np.uint32) ^ np.uint32(_SALTS[d])).astype(np.int64)
            .astype(np.int32))
        cols.append((h & np.uint32(width - 1)).astype(np.int32))
    return np.stack(cols)


def oracle_totals_np(batches, depth: int, width: int) -> np.ndarray:
    """Exact per-cell totals [D, Wd] int64 from (keys, weights, mask)
    batch triples — the ground truth every transition must encode."""
    tot = np.zeros((depth, width), np.int64)
    for keys, weights, mask in batches:
        cols = oracle_cols_np(np.asarray(keys), depth, width)
        w = np.where(mask, weights, 0).astype(np.int64)
        for d in range(depth):
            np.add.at(tot[d], cols[d], w)
    return tot


def oracle_encode_np(totals: np.ndarray, cell_bits: int = 8):
    """Closed-form expected state from exact per-cell totals: a pair is
    merged iff a member's total ever exceeded 255 (totals are monotone,
    so "ever" = "finally"); a quad iff a pair total exceeded 65535;
    values are group-sums clipped at CAP2; bytes little-endian per
    group.  Returns (table uint8, m1 packed, m2 packed)."""
    D, Wd = totals.shape
    t = totals
    pair_tot = t[:, 0::2] + t[:, 1::2]
    m1 = np.maximum(t[:, 0::2], t[:, 1::2]) > CAP0
    if cell_bits == 16:
        m1 = np.ones_like(m1)
    m2 = ((m1 & (pair_tot > CAP1))[:, 0::2]
          | (m1 & (pair_tot > CAP1))[:, 1::2])
    m1 = m1 | np.repeat(m2, 2, axis=1)
    quad_tot = np.minimum(pair_tot[:, 0::2] + pair_tot[:, 1::2], CAP2)
    m1c = np.repeat(m1, 2, axis=1)
    m2c = np.repeat(m2, 4, axis=1)
    group = np.where(m2c, np.repeat(quad_tot, 4, axis=1),
                     np.where(m1c, np.repeat(pair_tot, 2, axis=1), t))
    idx = np.arange(Wd)
    lane = np.where(m2c, idx % 4, np.where(m1c, idx % 2, 0))
    table = ((group >> (lane * 8)) & 0xFF).astype(np.uint8)
    pm1 = np.packbits(m1.astype(np.uint8), axis=1, bitorder="little")
    pm2 = np.packbits(m2.astype(np.uint8), axis=1, bitorder="little")
    return table, pm1, pm2


def oracle_query_np(totals: np.ndarray, keys: np.ndarray,
                    cell_bits: int = 8) -> np.ndarray:
    """Expected point estimates from exact totals at the final merge
    geometry (what ``query`` must return bit-for-bit)."""
    D, Wd = totals.shape
    table, pm1, pm2 = oracle_encode_np(totals, cell_bits)
    m1 = np.unpackbits(pm1, axis=1, count=Wd // 2, bitorder="little")
    m2 = np.unpackbits(pm2, axis=1, count=Wd // 4, bitorder="little")
    pair_tot = totals[:, 0::2] + totals[:, 1::2]
    quad_tot = np.minimum(pair_tot[:, 0::2] + pair_tot[:, 1::2], CAP2)
    cols = oracle_cols_np(np.asarray(keys), D, Wd)
    out = np.empty((D, cols.shape[1]), np.int64)
    for d in range(D):
        c = cols[d]
        solo = totals[d, c]
        pv = pair_tot[d, c >> 1]
        qv = quad_tot[d, c >> 2]
        out[d] = np.where(m2[d, c >> 2] == 1, qv,
                          np.where(m1[d, c >> 1] == 1, pv, solo))
    return out.min(axis=0)
