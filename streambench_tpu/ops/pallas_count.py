"""Pallas TPU kernel for the hot op: masked (campaign, slot) counting.

The MXU formulation of the window count (``ops.windowcount.step`` with
``method="matmul"``) computes ``campaign_onehot^T @ slot_onehot`` through
XLA, which may materialize the ``[B, C]``/``[B, W]`` one-hot operands in
HBM between fusions.  This kernel fuses one-hot construction and the
matmul accumulation inside VMEM: the batch streams through in tiles, the
``[C, W]`` accumulator never leaves VMEM, and each tile's one-hots exist
only as kernel-local values (pallas_guide.md: grid + BlockSpec
accumulation pattern).

Optional by design: ``method="pallas"`` in ``windowcount.step`` selects
it; the default remains XLA's fusion (``matmul``/``scatter``), which this
kernel is bit-identical to (tested in interpret mode, which also makes it
runnable on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(camp_ref, slot_ref, mask_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    T = camp_ref.shape[1]
    C, W = out_ref.shape
    camp = camp_ref[0, :]
    slot = slot_ref[0, :]
    mask = mask_ref[0, :] != 0
    camp_oh = ((camp[:, None]
                == jax.lax.broadcasted_iota(jnp.int32, (T, C), 1))
               & mask[:, None]).astype(jnp.float32)
    slot_oh = (slot[:, None]
               == jax.lax.broadcasted_iota(jnp.int32, (T, W), 1)
               ).astype(jnp.float32)
    out_ref[...] += jax.lax.dot_general(
        camp_oh, slot_oh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def count_tiles(counts: jax.Array, campaign: jax.Array, slot: jax.Array,
                count_mask: jax.Array, *, tile: int = 512,
                interpret: bool | None = None) -> jax.Array:
    """``counts[c, w] += #{masked rows with campaign c, slot w}``.

    ``campaign``/``slot`` are int32 ``[B]``; masked-out rows may hold any
    values.  ``B`` is padded to a tile multiple internally.  ``interpret``
    defaults to True off-TPU so tests exercise identical semantics on the
    CPU mesh.
    """
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    B = campaign.shape[0]
    C, W = counts.shape
    nb = -(-B // tile)
    pad = nb * tile - B
    mask_i = count_mask.astype(jnp.int32)
    if pad:
        campaign = jnp.pad(campaign, (0, pad))
        slot = jnp.pad(slot, (0, pad))
        mask_i = jnp.pad(mask_i, (0, pad))
    camp2 = campaign.reshape(nb, tile)
    slot2 = slot.reshape(nb, tile)
    mask2 = mask_i.reshape(nb, tile)
    delta = pl.pallas_call(
        _kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec((1, tile), lambda i: (i, 0))
                  for _ in range(3)],
        out_specs=pl.BlockSpec((C, W), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((C, W), jnp.int32),
        interpret=interpret,
    )(camp2, slot2, mask2)
    return counts + delta
