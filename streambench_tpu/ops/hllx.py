"""HLL "hyper extended": frequency statistics mined from register planes.

ROADMAP open item 2 / PAPERS.md arXiv:1607.06517 (Cohen, "HyperLogLog
Hyper Extended: Sketches for Concave Sublinear Frequency Statistics"):
a plain distinct-count HLL plane answers exactly one question.  The
hyper-extended construction answers a LADDER of concave sublinear
frequency statistics from the same register structure by hashing, per
arrival, the pair ``(key, token mod T)`` instead of the bare key: the
distinct count of that derived stream is

    D_T  =  sum over keys x of  T * (1 - (1 - 1/T)^c_x)

— a smooth cap of the key's count ``c_x`` at scale ``T`` (≈ c_x for
c_x << T, -> T for c_x >> T).  One register plane per rung of a
geometric cap ladder ``T_g = 2^g`` turns a single scatter-max per
batch into distinct count (g=0: the token is constant, so the plane IS
the plain user HLL, bit-identical to ``ops/hll.py``'s hash), the
soft-capped counts at every ``T_g``, and a log-count moment

    sum_x log2(1 + c_x)  ≈  sum_g D_g / T_g

(each term ``D_g/T_g ≈ sum_x (1 - e^{-c_x/T_g})`` contributes ~1 for
rungs below ``c_x`` and ~0 above — the telescoped octave count; the
estimator is validated against exact numpy counts in
tests/test_hllx.py and its bias for counts outside [1, 2^(G-1)] is
stated, not hidden).  F1 (total views) rides along exactly in an int32
counter.

State is cumulative per campaign — ``[C, G, R]`` registers, no window
ring (the windowed variant is the existing HLL engine; hllx trades the
ring axis for the cap ladder at the same bytes-per-campaign budget).
The per-arrival token must differ between arrivals of the same key:
it is mixed from the event timestamp, so an exact duplicate (same
user, same ms) contributes no new token — which makes at-least-once
REPLAY idempotent for free, and undercounts only keys emitting several
events in one millisecond (the generator spaces events 10 ms apart).

Merge = elementwise register max + counter add: associative,
commutative, register-idempotent — the same shard-order-invariant
algebra as ``ops/minhash.py``, swept in tests/test_hllx.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from streambench_tpu.ops.hll import _rank, splitmix32
from streambench_tpu.ops.windowcount import NEG

#: salt-stream constant for the per-rung hash functions (golden-ratio
#: schedule, the minhash.salts convention)
_SALT_GAMMA = 0x9E3779B9

#: per-key additive bias of the octave telescope sum_g (1-(1-1/T_g)^c)
#: over log2(1+c), averaged over c in [1, 2^(G-1)] (exact arithmetic;
#: see ``moments``) — subtracted as BIAS * distinct
LOG_MOMENT_BIAS = 1.07


class HLLXState(NamedTuple):
    """registers: [C, G, R] uint8 (rung g caps at T_g = 2^g; ranks
    <= 26 fit a byte — 4x the register density of the original int32
    plane, ROADMAP item 2a; legacy int32 planes from old snapshots
    still fold, the scatter casts to the plane's dtype); totals: [C]
    int32 exact wanted-event counts (F1); watermark/dropped as in
    ReachState (cumulative: nothing ever drops)."""

    registers: jax.Array
    totals: jax.Array
    watermark: jax.Array
    dropped: jax.Array


def caps(groups: int) -> jnp.ndarray:
    """The cap ladder [G]: T_g = 2^g."""
    return jnp.asarray([1 << g for g in range(groups)], jnp.int32)


def salts(groups: int) -> jax.Array:
    """Per-rung hash salts (rung 0's is unused — its hash is the bare
    user mix so the distinct plane matches ops/hll.py bit-for-bit)."""
    return splitmix32(jnp.arange(1, groups + 1, dtype=jnp.uint32)
                      * jnp.uint32(_SALT_GAMMA))


def init_state(num_campaigns: int, groups: int = 8,
               num_registers: int = 128) -> HLLXState:
    if groups < 1 or groups > 24:
        raise ValueError("groups must be in [1, 24]")
    if num_registers & (num_registers - 1) or num_registers < 16:
        raise ValueError("num_registers must be a power of two >= 16")
    if num_campaigns * groups * num_registers >= 2**31:
        raise ValueError("C*G*R must fit int32 flat indices")
    return HLLXState(
        registers=jnp.zeros((num_campaigns, groups, num_registers),
                            jnp.uint8),
        totals=jnp.zeros((num_campaigns,), jnp.int32),
        watermark=jnp.int32(NEG),
        dropped=jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("view_type",))
def step(state: HLLXState, join_table: jax.Array,
         ad_idx: jax.Array, user_idx: jax.Array, event_type: jax.Array,
         event_time: jax.Array, valid: jax.Array,
         *, view_type: int = 0) -> HLLXState:
    """Fold one micro-batch into every rung: one [B, G] hash block, one
    flat scatter-max — the same dispatch shape as a plain HLL step, so
    the frequency ladder costs no extra ingest dispatches."""
    C, G, R = state.registers.shape
    p = R.bit_length() - 1

    campaign = join_table[ad_idx]
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    hu = splitmix32(user_idx)                         # [B] key mix
    he = splitmix32(hu ^ splitmix32(event_time))      # [B] arrival mix
    tg = he[:, None] & (caps(G).astype(jnp.uint32) - 1)[None, :]  # [B, G]
    hg = splitmix32(hu[:, None] ^ salts(G)[None, :] ^ tg)
    # rung 0 is the bare key: bit-identical to the ops/hll.py hash
    h = jnp.concatenate([hu[:, None], hg[:, 1:]], axis=1) if G > 1 \
        else hu[:, None]

    j = (h & jnp.uint32(R - 1)).astype(jnp.int32)
    rank = _rank(h, p)
    g = jnp.arange(G, dtype=jnp.int32)[None, :]
    flat = jnp.where(wanted[:, None],
                     (campaign[:, None] * G + g) * R + j, C * G * R)
    registers = (state.registers.reshape(-1)
                 .at[flat.reshape(-1)].max(
                     rank.reshape(-1).astype(state.registers.dtype),
                     mode="drop")
                 .reshape(C, G, R))

    totals = state.totals.at[jnp.where(wanted, campaign, C)].add(
        1, mode="drop")
    watermark = jnp.maximum(
        state.watermark, jnp.max(jnp.where(valid, event_time, NEG)))
    return HLLXState(registers, totals, watermark, state.dropped)


@functools.partial(jax.jit, static_argnames=("view_type",))
def scan_steps(state: HLLXState, join_table: jax.Array,
               ad_idx: jax.Array, user_idx: jax.Array,
               event_type: jax.Array, event_time: jax.Array,
               valid: jax.Array, *, view_type: int = 0) -> HLLXState:
    """Fold ``[N, B]`` stacked micro-batches via ``lax.scan`` — one
    dispatch per chunk, same amortization as ``hll.scan_steps``."""

    def body(carry, xs):
        a, u, e, t, v = xs
        return step(carry, join_table, a, u, e, t, v,
                    view_type=view_type), None

    final, _ = jax.lax.scan(
        body, state, (ad_idx, user_idx, event_type, event_time, valid))
    return final


@functools.partial(jax.jit, static_argnames=("view_type",))
def scan_steps_packed(state: HLLXState, join_table: jax.Array,
                      packed: jax.Array, user_idx: jax.Array,
                      event_time: jax.Array,
                      *, view_type: int = 0) -> HLLXState:
    """``scan_steps`` over the packed wire word + user ids — the same
    12 B/event wire as the HLL/reach packed scans."""
    from streambench_tpu.ops.windowcount import unpack_columns

    def body(carry, xs):
        pk, u, t = xs
        a, e, v = unpack_columns(pk)
        return step(carry, join_table, a, u, e, t, v,
                    view_type=view_type), None

    final, _ = jax.lax.scan(body, state, (packed, user_idx, event_time))
    return final


def merge(a: HLLXState, b: HLLXState) -> HLLXState:
    """Shard/partial union: register max + exact counter add.
    Geometry validated up front, mismatches name both shapes."""
    if (a.registers.shape != b.registers.shape
            or a.registers.dtype != b.registers.dtype):
        raise ValueError(
            f"hllx.merge: geometry mismatch — a.registers "
            f"{a.registers.shape}/{a.registers.dtype} vs b.registers "
            f"{b.registers.shape}/{b.registers.dtype}")
    return HLLXState(
        registers=jnp.maximum(a.registers, b.registers),
        totals=a.totals + b.totals,
        watermark=jnp.maximum(a.watermark, b.watermark),
        dropped=a.dropped + b.dropped)


@jax.jit
def moments(state: HLLXState):
    """Every answer the ladder holds, one device program:

    - ``distinct [C]`` — rung-0 estimate (the plain HLL number);
    - ``softcap [C, G]`` — the concave sublinear capped counts
      ``sum_x T_g(1-(1-1/T_g)^c_x)`` per rung;
    - ``log_moment [C]`` — ``sum_x log2(1+c_x)`` via the octave
      telescope ``sum_g D_g/T_g - LOG_MOMENT_BIAS * D_0`` (each rung
      contributes ~1 per key whose count exceeds it; the telescope
      carries a per-key additive bias of 1.07 +- 0.12 for counts in
      [1, 2^(G-1)], computed exactly from the soft-cap form and
      subtracted here; counts ABOVE the ladder truncate toward the
      G*distinct ceiling — size G to the workload's count range);
    - ``totals [C]`` — exact F1 (wanted events).
    """
    from streambench_tpu.ops import hll

    G = state.registers.shape[1]
    d = hll.estimate(state.registers)                  # [C, G]
    inv_t = 1.0 / caps(G).astype(jnp.float32)
    log_raw = jnp.sum(d * inv_t[None, :], axis=1)
    return {
        "distinct": d[:, 0],
        "softcap": d,
        "log_moment": jnp.maximum(
            log_raw - LOG_MOMENT_BIAS * d[:, 0], 0.0),
        "totals": state.totals,
    }
