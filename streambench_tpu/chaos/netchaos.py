"""Seeded network chaos for the pub/sub query plane (ISSUE 16).

:class:`ChaosPubSub` is a TCP proxy that sits between pub/sub clients
(queries, the reach router) and a ``dimensions.pubsub`` server,
injecting the plan's scheduled message faults into BOTH directions of
the JSON-lines transport:

- ``drop``  — the message vanishes (also every message inside a
  ``partition_windows`` index window: a full partition);
- ``delay`` — the message (and, realistically, everything queued
  behind it on that connection) is held ``net_delay_ms``;
- ``dup``   — the message is forwarded twice — the duplicated-reply /
  retried-request case the server-side request-id dedup and the
  client's id-matched receive loop must absorb;
- ``torn``  — the frame is damaged in flight: the line's tail is
  NUL-smashed with the newline kept, so the receiver sees exactly one
  undecodable line (the message is lost WITHOUT desyncing the framing
  — a receiver that drops garbage lines resyncs on the next message).

Faults are drawn from the shared :class:`FaultInjector`'s GLOBAL
message index (``net_fault()``), so one seeded plan spans every proxied
replica in a fleet and supervised restarts continue the plan rather
than replaying it.  A proxy built without an injector (or over an empty
plan) is a byte-exact pass-through — pinned by the tier-1 test.

Scope: the JSON-lines transport only (``PubSubClient``).  The
WebSocket transport frames messages in binary and would need
frame-aware splitting; every fleet component routes through
JSON lines, so the proxy meets the chaos layer where the traffic is.
"""

from __future__ import annotations

import socket
import threading
import time

#: how much of a torn line survives (the rest is NUL-smashed)
_TORN_KEEP = 0.5


class ChaosPubSub:
    """Fault-injecting TCP proxy in front of one pub/sub endpoint.

    ``upstream`` is ``(host, port)`` of the real server; the proxy
    listens on ``host:port`` (port 0 = ephemeral) and ``address`` is
    what clients should dial.  One proxy per replica endpoint; share
    one injector across the fleet so the plan's message index is
    global.
    """

    def __init__(self, upstream: tuple, injector=None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 name: str = ""):
        self.upstream = (str(upstream[0]), int(upstream[1]))
        self.injector = injector
        self.name = name
        self.stats = {"msgs": 0, "dropped": 0, "delayed": 0,
                      "dupped": 0, "torn": 0, "conns": 0}
        self._lock = threading.Lock()
        self._conns: set = set()
        self._closed = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(32)
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True,
                                        name=f"chaos-pubsub{name}")

    @property
    def address(self) -> tuple:
        return self._srv.getsockname()[:2]

    def start(self) -> "ChaosPubSub":
        self._thread.start()
        return self

    # -- wiring --------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                client, _ = self._srv.accept()
            except OSError:
                return
            try:
                up = socket.create_connection(self.upstream,
                                              timeout=10.0)
                up.settimeout(None)
            except OSError:
                client.close()
                continue
            with self._lock:
                if self._closed:
                    client.close()
                    up.close()
                    return
                self._conns.update((client, up))
                self.stats["conns"] += 1
            for src, dst in ((client, up), (up, client)):
                threading.Thread(target=self._pump, args=(src, dst),
                                 daemon=True,
                                 name=f"chaos-pump{self.name}").start()

    def _pump(self, src: socket.socket, dst: socket.socket) -> None:
        """One direction: split the byte stream into newline-framed
        messages and forward each through the fault draw.  A partial
        line at EOF is discarded (the peer died mid-frame)."""
        buf = b""
        try:
            while True:
                try:
                    chunk = src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                buf += chunk
                while True:
                    line, sep, rest = buf.partition(b"\n")
                    if not sep:
                        break
                    buf = rest
                    if not self._forward(line + b"\n", dst):
                        return
        finally:
            self._drop_conn(src)
            self._drop_conn(dst)

    def _forward(self, data: bytes, dst: socket.socket) -> bool:
        self.stats["msgs"] += 1
        kind = (self.injector.net_fault()
                if self.injector is not None else None)
        if kind == "drop":
            self.stats["dropped"] += 1
            return True
        if kind == "delay":
            self.stats["delayed"] += 1
            time.sleep(self.injector.net_delay_s)
        elif kind == "torn":
            self.stats["torn"] += 1
            keep = max(int((len(data) - 1) * _TORN_KEEP), 1)
            data = (data[:keep]
                    + b"\x00" * (len(data) - keep - 1) + b"\n")
        try:
            dst.sendall(data)
            if kind == "dup":
                self.stats["dupped"] += 1
                dst.sendall(data)
        except OSError:
            return False
        return True

    def _drop_conn(self, sock: socket.socket) -> None:
        with self._lock:
            self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass

    def drop_conns(self) -> int:
        """Sever every live proxied connection WITHOUT closing the
        listener — the wire-level view of a replica dying: established
        clients see EOF/reset and must re-dial, and whether the re-dial
        lands depends on whether anything answers upstream.  Returns
        the number of sockets severed."""
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
        return len(conns)

    # -- lifecycle -----------------------------------------------------
    def summary(self) -> dict:
        out = dict(self.stats)
        out["upstream"] = "%s:%d" % self.upstream
        return out

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
