"""Oracle-verified at-least-once accounting: the executable contract.

``checkpoint.py`` documents the recovery guarantee prose-style: *"the
replay window is bounded by the snapshot cadence"*.  This module turns
that sentence into an assertable invariant.  For a supervised chaos run
(``chaos.supervisor``) over a journaled topic, every per-window Redis
count must satisfy::

    oracle(w)  <=  count(w)  <=  oracle(w) + bound(w)

where ``oracle`` is the golden model's exact view count
(``datagen.gen.dostats``, the peer of ``check-correct`` in
``core.clj:215-237``) and ``bound`` is the sum of the two legal
over-count sources the supervisor recorded:

- the *replay segments*: for each crash, the view events in the journal
  byte range ``[resume_offset, crash_offset)`` — events that may have
  been flushed before the crash and re-folded after the resume;
- the *carried pending*: snapshot-carried deltas (reclaimed failed
  writes) that may already have landed before the crash and are
  re-flushed after restore.

Anything outside those bounds is a real bug: a count below the oracle is
lost data (the at-least-once side), a count above the bound is
double-counting the documented contract does not allow.

With ``jax.sink.exactly_once`` on, :func:`check_exactly_once` drops the
bound entirely: the fence protocol (ROBUSTNESS.md "Exactly-once")
reconciles replay segments and carried pending, so ``count(w) ==
oracle(w)`` must hold for every window.

Fleet invariants (ISSUE 16): a fleet chaos run — network faults on the
query plane, ship-log faults on the replica feed, crash-faulted
replicas behind the router — must additionally satisfy, by
:class:`FleetVerdict`:

- **shed-or-answer accounting**: every request id sent gets EXACTLY one
  terminal reply — an answer or an honest shed — so ``sent == answered
  + shed`` with no duplicates and no silent drops
  (:func:`check_fleet_accounting`);
- **staleness honesty**: every ANSWER's ``plane_epoch`` is at least the
  epoch that was durable in the ship log one staleness bound before the
  query was submitted — i.e. no reply silently served planes staler
  than the bound the replica advertises
  (:func:`check_staleness_bound`, over the ship log's epoch timeline);
- **post-heal convergence**: once faults stop and a final forced ship
  lands, every surviving replica reaches the writer's final epoch, and
  the close-time reach record is bit-identical to the fault-free arm's
  (:func:`check_fleet_convergence`) — chaos may delay, it may never
  corrupt.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from streambench_tpu.datagen import gen
from streambench_tpu.io.redis_schema import read_seen_counts


def segment_view_counts(topic_path: str, segments,
                        mapping: dict[str, str],
                        divisor_ms: int = 10_000) -> dict:
    """Per-window view counts over journal byte ranges.

    ``segments`` are ``(lo, hi)`` byte offsets into the topic file (the
    unit the supervisor records); multi-partition offset vectors are not
    supported (the chaos harness drives single-partition topics).
    Returns ``(campaign, abs_window_ts) -> count`` summed over segments;
    overlapping segments intentionally double-count (each crash is an
    independent replay opportunity).
    """
    out: dict[tuple[str, int], int] = {}
    with open(topic_path, "rb") as f:
        for lo, hi in segments:
            if isinstance(lo, list) or isinstance(hi, list):
                raise ValueError(
                    "segment offsets must be scalars (single-partition "
                    f"topics only): ({lo!r}, {hi!r})")
            if hi <= lo:
                continue
            f.seek(lo)
            blob = f.read(hi - lo)
            for line in blob.split(b"\n"):
                if not line.strip() or b"\x00" in line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # segment edge cut a record in half
                if ev.get("event_type") != "view":
                    continue
                campaign = mapping.get(ev.get("ad_id"))
                if campaign is None:
                    continue
                wts = (int(ev["event_time"]) // divisor_ms) * divisor_ms
                out[(campaign, wts)] = out.get((campaign, wts), 0) + 1
    return out


@dataclass
class ChaosVerdict:
    """The bound check's full report (``ok`` is the headline)."""

    ok: bool
    windows: int = 0
    exact: int = 0               # count == oracle
    within_bound: int = 0        # oracle < count <= oracle + bound
    undercounts: list = field(default_factory=list)
    overcounts: list = field(default_factory=list)
    max_overcount: int = 0
    # one-paste repro for a red run (see replay_note): appended to
    # summary() so every sweep assertion message carries it
    repro: str | None = None

    def summary(self) -> str:
        s = (f"chaos verdict: ok={self.ok} windows={self.windows} "
             f"exact={self.exact} within_bound={self.within_bound} "
             f"under={len(self.undercounts)} over={len(self.overcounts)} "
             f"max_overcount={self.max_overcount}")
        if self.repro:
            s += "\n" + self.repro
        return s


def replay_note(*, seed, topic_path: str,
                overrides: dict | None = None) -> str:
    """One-paste repro line for a failing seeded chaos run.

    Fault plans are fully determined by their seed, so a red sweep
    replays bit-identically from (test node, seed, config overrides,
    topic).  Inside pytest the exact node id comes from
    ``PYTEST_CURRENT_TEST``; the seed/topic/overrides ride along for
    harnesses that drive plans directly.
    """
    node = os.environ.get("PYTEST_CURRENT_TEST", "").split(" ")[0]
    cmd = (f"python -m pytest '{node}' -q" if node
           else "python -m pytest tests/ -q -m chaos")
    parts = [f"seed={seed}", f"topic={topic_path}"]
    if overrides:
        parts.append("overrides[" + " ".join(
            f"{k}={v}" for k, v in sorted(overrides.items())) + "]")
    return f"replay: {cmd}   # {' '.join(parts)}"


def _read_oracle(workdir: str, divisor_ms: int) -> dict:
    """(campaign, abs_window_ts) -> exact view count, from the golden
    model (``datagen.gen.dostats``, the peer of ``check-correct``)."""
    oracle_buckets = gen.dostats(workdir, time_divisor_ms=divisor_ms)
    return {(c, b * divisor_ms): n
            for c, per in oracle_buckets.items()
            for b, n in per.items()}


def _read_actual(redis) -> dict:
    actual_nested = read_seen_counts(redis)
    return {(c, ts): n
            for c, per in actual_nested.items()
            for ts, n in per.items()}


def check_exactly_once(redis, workdir: str,
                       divisor_ms: int = 10_000,
                       repro: str | None = None) -> ChaosVerdict:
    """Assert the exactly-once contract: for EVERY (campaign, window),
    ``redis_count(w) == oracle(w)`` — no bound, no slack.  The
    acceptance check for chaos runs with ``jax.sink.exactly_once`` on
    (ROBUSTNESS.md "Exactly-once"): replay segments and carried pending
    are reconciled by the fence protocol, so any deviation in either
    direction is a real bug.  ``repro`` (see :func:`replay_note`) is
    carried into the verdict so a red sweep's assertion message is one
    paste away from a bit-identical local replay."""
    oracle = _read_oracle(workdir, divisor_ms)
    actual = _read_actual(redis)
    v = ChaosVerdict(ok=True, repro=repro)
    for key in sorted(set(oracle) | set(actual)):
        want = oracle.get(key, 0)
        have = actual.get(key, 0)
        v.windows += 1
        if have == want:
            v.exact += 1
        elif have < want:
            v.ok = False
            v.undercounts.append((key, have, want))
        else:
            v.ok = False
            v.overcounts.append((key, have, want, 0))
            v.max_overcount = max(v.max_overcount, have - want)
    return v


def check_at_least_once(redis, workdir: str, topic_path: str,
                        replay_segments=(), carried=None,
                        divisor_ms: int = 10_000,
                        repro: str | None = None) -> ChaosVerdict:
    """Assert the at-least-once contract against a finished chaos run.

    ``redis`` holds the engine's writes; ``workdir`` holds the
    generator's ``kafka-json.txt`` + ad mapping (the oracle inputs);
    ``topic_path`` is the single-partition topic file whose byte offsets
    the supervisor's ``replay_segments`` index; ``carried`` is the
    supervisor's snapshot-carried pending map.  Violations are collected,
    not raised — tests assert on ``verdict.ok`` and print ``summary()``.
    """
    mapping = gen.load_ad_mapping_file(
        os.path.join(workdir, gen.AD_TO_CAMPAIGN_FILE))
    oracle = _read_oracle(workdir, divisor_ms)
    bound = segment_view_counts(topic_path, replay_segments, mapping,
                                divisor_ms)
    for key, n in (carried or {}).items():
        bound[key] = bound.get(key, 0) + n
    actual = _read_actual(redis)

    v = ChaosVerdict(ok=True, repro=repro)
    for key in sorted(set(oracle) | set(actual)):
        want = oracle.get(key, 0)
        have = actual.get(key, 0)
        slack = bound.get(key, 0)
        v.windows += 1
        if have == want:
            v.exact += 1
        elif want < have <= want + slack:
            v.within_bound += 1
            v.max_overcount = max(v.max_overcount, have - want)
        elif have < want:
            v.ok = False
            v.undercounts.append((key, have, want))
        else:
            v.ok = False
            v.overcounts.append((key, have, want, slack))
            v.max_overcount = max(v.max_overcount, have - want)
    return v


# ---------------------------------------------------------------------
# fleet invariants (ISSUE 16)
# ---------------------------------------------------------------------

@dataclass
class FleetVerdict:
    """The fleet chaos run's full report (``ok`` is the headline)."""

    ok: bool
    sent: int = 0
    answered: int = 0
    shed: int = 0
    # accounting violations: ids answered/shed more than once, ids that
    # got no terminal reply at all, reply ids nobody sent
    duplicate_ids: list = field(default_factory=list)
    missing_ids: list = field(default_factory=list)
    unexpected_ids: list = field(default_factory=list)
    # staleness violations: (id, plane_epoch, floor_epoch, submit_ms)
    stale_violations: list = field(default_factory=list)
    # convergence: replicas that never reached the writer's final epoch
    # ((idx, replica_epoch, writer_epoch)); divergent = the close-time
    # reach record differs bit-for-bit from the fault-free arm's
    lagging_replicas: list = field(default_factory=list)
    divergent: bool = False
    writer_epoch: int | None = None
    repro: str | None = None

    def summary(self) -> str:
        s = (f"fleet verdict: ok={self.ok} sent={self.sent} "
             f"answered={self.answered} shed={self.shed} "
             f"dup={len(self.duplicate_ids)} "
             f"missing={len(self.missing_ids)} "
             f"unexpected={len(self.unexpected_ids)} "
             f"stale_violations={len(self.stale_violations)} "
             f"lagging={len(self.lagging_replicas)} "
             f"divergent={self.divergent}")
        if self.repro:
            s += "\n" + self.repro
        return s


def check_fleet_accounting(sent_ids, replies,
                           repro: str | None = None) -> FleetVerdict:
    """Assert ``sent == answered + shed`` EXACTLY, by request id.

    ``sent_ids`` is every id the driver submitted; ``replies`` is every
    terminal reply payload it received (answers, sheds, error replies —
    an error IS an answer: the client heard back).  Each sent id must
    appear exactly once; a duplicate means the dedup/dup-fault machinery
    double-answered, a missing id means a query was silently dropped
    (the one thing the router contract forbids), an unexpected id means
    a stale retry leaked through the client's discard set.
    """
    v = FleetVerdict(ok=True, repro=repro)
    sent = list(sent_ids)
    v.sent = len(sent)
    sent_set = set(sent)
    seen: dict = {}
    for rep in replies:
        rid = rep.get("id")
        if rid not in sent_set:
            v.ok = False
            v.unexpected_ids.append(rid)
            continue
        if rid in seen:
            v.ok = False
            v.duplicate_ids.append(rid)
            continue
        seen[rid] = rep
        if rep.get("shed"):
            v.shed += 1
        else:
            v.answered += 1
    for rid in sent:
        if rid not in seen:
            v.ok = False
            v.missing_ids.append(rid)
    return v


def ship_epoch_timeline(ship_path: str) -> list:
    """``(stamp_ms, epoch)`` per decodable reach-sketch record in the
    ship log, append order.  The stamp is the writer's submit stamp
    (``sm``) falling back to the record stamp (``t``) — the moment the
    record became durable, which is what the staleness bound is
    measured against."""
    out = []
    try:
        f = open(ship_path, "rb")
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line or b'"reach_sketch"' not in line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn/corrupted by the chaos layer: not durable
            if rec.get("kind") != "reach_sketch":
                continue
            stamp = rec.get("sm", rec.get("t", 0))
            out.append((int(stamp), int(rec.get("epoch", 0))))
    return out


def durable_epoch_at(timeline, stamp_ms: int) -> int | None:
    """Newest epoch durable at ``stamp_ms`` (None: nothing was)."""
    epoch = None
    for t, e in timeline:
        if t <= stamp_ms:
            epoch = e if epoch is None else max(epoch, e)
    return epoch


def check_staleness_bound(queries, timeline, max_staleness_ms: int,
                          verdict: FleetVerdict | None = None,
                          slack_ms: int = 0) -> FleetVerdict:
    """Assert no answer served planes staler than the bound.

    ``queries`` is ``(submit_ms, reply)`` per request the driver made
    (driver-clock submit stamp; single-host runs share the clock with
    the ship log's stamps).  For every ANSWERED reply carrying a
    ``plane_epoch``, the epoch must be at least the newest epoch that
    was durable at ``submit_ms - max_staleness_ms`` — a reply below
    that floor means some replica silently served beyond-bound planes
    instead of shedding or being failed over.  Sheds and error replies
    are exempt (they are the honest path).  ``slack_ms`` absorbs stamp
    granularity at the window edge.
    """
    v = verdict if verdict is not None else FleetVerdict(ok=True)
    for submit_ms, rep in queries:
        if rep is None or rep.get("shed") or rep.get("error"):
            continue
        epoch = rep.get("plane_epoch", rep.get("epoch"))
        if epoch is None:
            continue
        floor = durable_epoch_at(
            timeline, int(submit_ms) - int(max_staleness_ms) - slack_ms)
        if floor is not None and int(epoch) < floor:
            v.ok = False
            v.stale_violations.append(
                (rep.get("id"), int(epoch), floor, int(submit_ms)))
    return v


def final_reach_record(ship_path: str) -> dict | None:
    """The last decodable reach-sketch record in a ship log, raw (the
    base64 plane fields uncompared-decoded — bit-identity is judged on
    the encoded bytes)."""
    newest = None
    try:
        f = open(ship_path, "rb")
    except OSError:
        return None
    with f:
        for line in f:
            line = line.strip()
            if not line or b'"reach_sketch"' not in line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "reach_sketch" and "mins" in rec:
                newest = rec
    return newest


def check_fleet_convergence(ship_path: str, replica_epochs,
                            clean_ship_path: str | None = None,
                            verdict: FleetVerdict | None = None
                            ) -> FleetVerdict:
    """Assert post-heal convergence.

    ``replica_epochs`` is each surviving replica's final loaded
    ``plane_epoch`` (index order).  Every one must equal the writer's
    final shipped epoch — after faults stop, the forced close-time ship
    lands intact and one poll later the fleet agrees.  With
    ``clean_ship_path`` (the fault-free arm's ship log), the close-time
    reach record must match it bit-for-bit on the plane payloads —
    chaos may delay convergence, it must never change what is converged
    TO.
    """
    v = verdict if verdict is not None else FleetVerdict(ok=True)
    final = final_reach_record(ship_path)
    if final is None:
        v.ok = False
        v.divergent = True
        return v
    v.writer_epoch = int(final.get("epoch", 0))
    for idx, epoch in enumerate(replica_epochs):
        if epoch is None or int(epoch) != v.writer_epoch:
            v.ok = False
            v.lagging_replicas.append((idx, epoch, v.writer_epoch))
    if clean_ship_path is not None:
        clean = final_reach_record(clean_ship_path)
        same = (clean is not None and
                all(final.get(k) == clean.get(k)
                    for k in ("mins", "regs", "c", "k", "r", "epoch")))
        if not same:
            v.ok = False
            v.divergent = True
    return v


# ---------------------------------------------------------------------
# broker-edge invariants (ISSUE 20)
# ---------------------------------------------------------------------

@dataclass
class KafkaEdgeVerdict:
    """The broker-edge delivery ledger, balanced or not.

    The accounting identity a faulted broker run must satisfy::

        consumed == delivered + redelivered      (no uncounted duplicate,
                                                  no silent drop at the
                                                  consumer)
        delivered == sent                        (every acked produce
                                                  reached the engine
                                                  exactly once)

    where ``sent`` is the producers' acked-record count
    (``kafka_produced``), ``consumed`` every record the broker handed
    up, ``delivered`` the unique records returned to the engine, and
    ``redelivered`` the reconnect duplicates the reader counted and
    filtered.  ``windows`` optionally folds in an oracle window-count
    verdict (:func:`check_at_least_once` / :func:`check_exactly_once`)
    so one ``ok`` covers socket-to-Redis.
    """

    ok: bool
    sent: int = 0
    delivered: int = 0
    redelivered: int = 0
    consumed: int = 0
    produce_retries: int = 0
    consume_retries: int = 0
    broker_down_ms: int = 0
    violations: list = field(default_factory=list)
    windows: "ChaosVerdict | None" = None
    repro: str | None = None

    def summary(self) -> str:
        s = (f"kafka edge verdict: ok={self.ok} sent={self.sent} "
             f"delivered={self.delivered} redelivered={self.redelivered} "
             f"consumed={self.consumed} "
             f"produce_retries={self.produce_retries} "
             f"consume_retries={self.consume_retries} "
             f"broker_down_ms={self.broker_down_ms} "
             f"violations={self.violations}")
        if self.windows is not None:
            s += "\n" + self.windows.summary()
        if self.repro:
            s += "\n" + self.repro
        return s


def check_kafka_edge(counters, *, sent: int | None = None,
                     require_redeliveries: bool = False,
                     windows: "ChaosVerdict | None" = None,
                     repro: str | None = None) -> KafkaEdgeVerdict:
    """Assert the broker edge's delivery accounting from one counter
    snapshot (the ``KafkaBroker``-shared :class:`~streambench_tpu.
    metrics.FaultCounters`, or a plain snapshot dict).

    ``sent`` overrides the producer-acked count when the ground truth
    comes from elsewhere (the broker log length, the generator's event
    count); ``require_redeliveries`` makes a faulted sweep prove its
    conn-drop faults actually exercised the redelivery path.  Pass the
    run's oracle window verdict as ``windows`` to fold end-to-end count
    correctness into the same ``ok``.
    """
    snap = counters.snapshot() if hasattr(counters, "snapshot") \
        else dict(counters)
    v = KafkaEdgeVerdict(
        ok=True,
        sent=int(snap.get("kafka_produced", 0) if sent is None else sent),
        delivered=int(snap.get("kafka_delivered", 0)),
        redelivered=int(snap.get("kafka_redeliveries", 0)),
        consumed=int(snap.get("kafka_consumed", 0)),
        produce_retries=int(snap.get("kafka_produce_retries", 0)),
        consume_retries=int(snap.get("kafka_consume_retries", 0)),
        broker_down_ms=int(snap.get("kafka_broker_down_ms", 0)),
        windows=windows, repro=repro)
    if v.consumed != v.delivered + v.redelivered:
        v.ok = False
        v.violations.append(
            f"consumed({v.consumed}) != delivered({v.delivered}) "
            f"+ redelivered({v.redelivered})")
    if v.delivered != v.sent:
        v.ok = False
        v.violations.append(
            f"delivered({v.delivered}) != sent({v.sent})")
    if require_redeliveries and v.redelivered <= 0:
        v.ok = False
        v.violations.append(
            "redeliveries required but none observed (the conn-drop "
            "faults never exercised the redelivery path)")
    if windows is not None and not windows.ok:
        v.ok = False
        v.violations.append("oracle window-count check failed")
    return v
