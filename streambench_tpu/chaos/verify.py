"""Oracle-verified at-least-once accounting: the executable contract.

``checkpoint.py`` documents the recovery guarantee prose-style: *"the
replay window is bounded by the snapshot cadence"*.  This module turns
that sentence into an assertable invariant.  For a supervised chaos run
(``chaos.supervisor``) over a journaled topic, every per-window Redis
count must satisfy::

    oracle(w)  <=  count(w)  <=  oracle(w) + bound(w)

where ``oracle`` is the golden model's exact view count
(``datagen.gen.dostats``, the peer of ``check-correct`` in
``core.clj:215-237``) and ``bound`` is the sum of the two legal
over-count sources the supervisor recorded:

- the *replay segments*: for each crash, the view events in the journal
  byte range ``[resume_offset, crash_offset)`` — events that may have
  been flushed before the crash and re-folded after the resume;
- the *carried pending*: snapshot-carried deltas (reclaimed failed
  writes) that may already have landed before the crash and are
  re-flushed after restore.

Anything outside those bounds is a real bug: a count below the oracle is
lost data (the at-least-once side), a count above the bound is
double-counting the documented contract does not allow.

With ``jax.sink.exactly_once`` on, :func:`check_exactly_once` drops the
bound entirely: the fence protocol (ROBUSTNESS.md "Exactly-once")
reconciles replay segments and carried pending, so ``count(w) ==
oracle(w)`` must hold for every window.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from streambench_tpu.datagen import gen
from streambench_tpu.io.redis_schema import read_seen_counts


def segment_view_counts(topic_path: str, segments,
                        mapping: dict[str, str],
                        divisor_ms: int = 10_000) -> dict:
    """Per-window view counts over journal byte ranges.

    ``segments`` are ``(lo, hi)`` byte offsets into the topic file (the
    unit the supervisor records); multi-partition offset vectors are not
    supported (the chaos harness drives single-partition topics).
    Returns ``(campaign, abs_window_ts) -> count`` summed over segments;
    overlapping segments intentionally double-count (each crash is an
    independent replay opportunity).
    """
    out: dict[tuple[str, int], int] = {}
    with open(topic_path, "rb") as f:
        for lo, hi in segments:
            if isinstance(lo, list) or isinstance(hi, list):
                raise ValueError(
                    "segment offsets must be scalars (single-partition "
                    f"topics only): ({lo!r}, {hi!r})")
            if hi <= lo:
                continue
            f.seek(lo)
            blob = f.read(hi - lo)
            for line in blob.split(b"\n"):
                if not line.strip() or b"\x00" in line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # segment edge cut a record in half
                if ev.get("event_type") != "view":
                    continue
                campaign = mapping.get(ev.get("ad_id"))
                if campaign is None:
                    continue
                wts = (int(ev["event_time"]) // divisor_ms) * divisor_ms
                out[(campaign, wts)] = out.get((campaign, wts), 0) + 1
    return out


@dataclass
class ChaosVerdict:
    """The bound check's full report (``ok`` is the headline)."""

    ok: bool
    windows: int = 0
    exact: int = 0               # count == oracle
    within_bound: int = 0        # oracle < count <= oracle + bound
    undercounts: list = field(default_factory=list)
    overcounts: list = field(default_factory=list)
    max_overcount: int = 0
    # one-paste repro for a red run (see replay_note): appended to
    # summary() so every sweep assertion message carries it
    repro: str | None = None

    def summary(self) -> str:
        s = (f"chaos verdict: ok={self.ok} windows={self.windows} "
             f"exact={self.exact} within_bound={self.within_bound} "
             f"under={len(self.undercounts)} over={len(self.overcounts)} "
             f"max_overcount={self.max_overcount}")
        if self.repro:
            s += "\n" + self.repro
        return s


def replay_note(*, seed, topic_path: str,
                overrides: dict | None = None) -> str:
    """One-paste repro line for a failing seeded chaos run.

    Fault plans are fully determined by their seed, so a red sweep
    replays bit-identically from (test node, seed, config overrides,
    topic).  Inside pytest the exact node id comes from
    ``PYTEST_CURRENT_TEST``; the seed/topic/overrides ride along for
    harnesses that drive plans directly.
    """
    node = os.environ.get("PYTEST_CURRENT_TEST", "").split(" ")[0]
    cmd = (f"python -m pytest '{node}' -q" if node
           else "python -m pytest tests/ -q -m chaos")
    parts = [f"seed={seed}", f"topic={topic_path}"]
    if overrides:
        parts.append("overrides[" + " ".join(
            f"{k}={v}" for k, v in sorted(overrides.items())) + "]")
    return f"replay: {cmd}   # {' '.join(parts)}"


def _read_oracle(workdir: str, divisor_ms: int) -> dict:
    """(campaign, abs_window_ts) -> exact view count, from the golden
    model (``datagen.gen.dostats``, the peer of ``check-correct``)."""
    oracle_buckets = gen.dostats(workdir, time_divisor_ms=divisor_ms)
    return {(c, b * divisor_ms): n
            for c, per in oracle_buckets.items()
            for b, n in per.items()}


def _read_actual(redis) -> dict:
    actual_nested = read_seen_counts(redis)
    return {(c, ts): n
            for c, per in actual_nested.items()
            for ts, n in per.items()}


def check_exactly_once(redis, workdir: str,
                       divisor_ms: int = 10_000,
                       repro: str | None = None) -> ChaosVerdict:
    """Assert the exactly-once contract: for EVERY (campaign, window),
    ``redis_count(w) == oracle(w)`` — no bound, no slack.  The
    acceptance check for chaos runs with ``jax.sink.exactly_once`` on
    (ROBUSTNESS.md "Exactly-once"): replay segments and carried pending
    are reconciled by the fence protocol, so any deviation in either
    direction is a real bug.  ``repro`` (see :func:`replay_note`) is
    carried into the verdict so a red sweep's assertion message is one
    paste away from a bit-identical local replay."""
    oracle = _read_oracle(workdir, divisor_ms)
    actual = _read_actual(redis)
    v = ChaosVerdict(ok=True, repro=repro)
    for key in sorted(set(oracle) | set(actual)):
        want = oracle.get(key, 0)
        have = actual.get(key, 0)
        v.windows += 1
        if have == want:
            v.exact += 1
        elif have < want:
            v.ok = False
            v.undercounts.append((key, have, want))
        else:
            v.ok = False
            v.overcounts.append((key, have, want, 0))
            v.max_overcount = max(v.max_overcount, have - want)
    return v


def check_at_least_once(redis, workdir: str, topic_path: str,
                        replay_segments=(), carried=None,
                        divisor_ms: int = 10_000,
                        repro: str | None = None) -> ChaosVerdict:
    """Assert the at-least-once contract against a finished chaos run.

    ``redis`` holds the engine's writes; ``workdir`` holds the
    generator's ``kafka-json.txt`` + ad mapping (the oracle inputs);
    ``topic_path`` is the single-partition topic file whose byte offsets
    the supervisor's ``replay_segments`` index; ``carried`` is the
    supervisor's snapshot-carried pending map.  Violations are collected,
    not raised — tests assert on ``verdict.ok`` and print ``summary()``.
    """
    mapping = gen.load_ad_mapping_file(
        os.path.join(workdir, gen.AD_TO_CAMPAIGN_FILE))
    oracle = _read_oracle(workdir, divisor_ms)
    bound = segment_view_counts(topic_path, replay_segments, mapping,
                                divisor_ms)
    for key, n in (carried or {}).items():
        bound[key] = bound.get(key, 0) + n
    actual = _read_actual(redis)

    v = ChaosVerdict(ok=True, repro=repro)
    for key in sorted(set(oracle) | set(actual)):
        want = oracle.get(key, 0)
        have = actual.get(key, 0)
        slack = bound.get(key, 0)
        v.windows += 1
        if have == want:
            v.exact += 1
        elif want < have <= want + slack:
            v.within_bound += 1
            v.max_overcount = max(v.max_overcount, have - want)
        elif have < want:
            v.ok = False
            v.undercounts.append((key, have, want))
        else:
            v.ok = False
            v.overcounts.append((key, have, want, slack))
            v.max_overcount = max(v.max_overcount, have - want)
    return v
