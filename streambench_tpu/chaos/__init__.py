"""Chaos layer: deterministic fault injection + supervised recovery.

See ROBUSTNESS.md for the failure model.  The pieces:

- ``plan``       — seeded :class:`FaultPlan`, :class:`CrashScheduler`,
  the simulated :class:`EngineCrash`
- ``inject``     — :class:`FaultInjector` and its surface wrappers
  (:class:`ChaosRedis`, :class:`ChaosJournalReader`)
- ``supervisor`` — :class:`Supervisor` restart loop with capped
  exponential backoff and no-progress give-up
- ``verify``     — the executable at-least-once bound
  (:func:`check_at_least_once`) and the strict exactly-once check
  (:func:`check_exactly_once`, ``jax.sink.exactly_once`` runs)
"""

from streambench_tpu.chaos.inject import (  # noqa: F401
    ChaosJournalReader,
    ChaosRedis,
    FaultInjector,
)
from streambench_tpu.chaos.plan import (  # noqa: F401
    CrashScheduler,
    EngineCrash,
    FaultPlan,
)
from streambench_tpu.chaos.supervisor import (  # noqa: F401
    Supervisor,
    SupervisorStats,
)
from streambench_tpu.chaos.verify import (  # noqa: F401
    ChaosVerdict,
    check_at_least_once,
    check_exactly_once,
    replay_note,
    segment_view_counts,
)
