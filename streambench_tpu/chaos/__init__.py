"""Chaos layer: deterministic fault injection + supervised recovery.

See ROBUSTNESS.md for the failure model.  The pieces:

- ``plan``       — seeded :class:`FaultPlan`, :class:`CrashScheduler`,
  the simulated :class:`EngineCrash`
- ``inject``     — :class:`FaultInjector` and its surface wrappers
  (:class:`ChaosRedis`, :class:`ChaosJournalReader`,
  :class:`ShipChaosFilter`)
- ``netchaos``   — :class:`ChaosPubSub`, the fault-injecting TCP proxy
  over the pub/sub query plane (drops, delays, dups, torn frames,
  partitions)
- ``supervisor`` — :class:`Supervisor` restart loop with capped
  exponential backoff and no-progress give-up
- ``fleet_supervisor`` — :class:`FleetSupervisor`, the same semantics
  at the replica-process level (crash-kill, backoff restart, give-up)
- ``verify``     — the executable at-least-once bound
  (:func:`check_at_least_once`), the strict exactly-once check
  (:func:`check_exactly_once`, ``jax.sink.exactly_once`` runs), the
  fleet invariants (:func:`check_fleet_accounting`,
  :func:`check_staleness_bound`, :func:`check_fleet_convergence`),
  and the broker-edge delivery ledger (:func:`check_kafka_edge`:
  ``consumed == delivered + redelivered``, ``delivered == sent``)
"""

from streambench_tpu.chaos.fleet_supervisor import (  # noqa: F401
    FleetSupervisor,
    ReplicaSlot,
)
from streambench_tpu.chaos.inject import (  # noqa: F401
    ChaosJournalReader,
    ChaosRedis,
    FaultInjector,
    ShipChaosFilter,
)
from streambench_tpu.chaos.netchaos import ChaosPubSub  # noqa: F401
from streambench_tpu.chaos.plan import (  # noqa: F401
    CrashScheduler,
    EngineCrash,
    FaultPlan,
)
from streambench_tpu.chaos.supervisor import (  # noqa: F401
    Supervisor,
    SupervisorStats,
)
from streambench_tpu.chaos.verify import (  # noqa: F401
    ChaosVerdict,
    FleetVerdict,
    KafkaEdgeVerdict,
    check_at_least_once,
    check_exactly_once,
    check_fleet_accounting,
    check_kafka_edge,
    check_fleet_convergence,
    check_staleness_bound,
    durable_epoch_at,
    final_reach_record,
    replay_note,
    segment_view_counts,
    ship_epoch_timeline,
)
