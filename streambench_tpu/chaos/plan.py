"""Deterministic fault plans: the seeded script a chaos run executes.

The reference fork has no fault story at all — ``enableCheckpointing`` is
commented out (``AdvertisingTopologyNative.java:81-84``) and a Redis
outage is a Jedis stack trace; nothing ever *exercises* recovery.  A
``FaultPlan`` makes adversity reproducible: every injected fault (sink
error, journal read damage, simulated crash) is scheduled up front from
one RNG seed, so a failing chaos run replays bit-identically under the
same seed — the property the oracle-verified recovery tests depend on.

Fault surfaces (see ``chaos.inject`` for the wrappers):

- **sink** — per store-operation index: ``refused`` (connection refused),
  ``timeout`` (socket timeout), ``resp`` (transient server-side RESP
  error, e.g. ``LOADING``).  Faults are injected *before* the command is
  forwarded, i.e. atomically: a faulted operation applies nothing.  This
  matches a refused connection exactly and models timeouts
  conservatively (a real timeout can land after a partial pipeline; the
  at-least-once bound in ROBUSTNESS.md assumes atomic failure).
- **journal** — per reader-poll index: ``truncated`` (short read),
  ``torn`` (a NUL zero-page tail, what a crashed writer's partial page
  looks like), ``corrupt`` (a NUL-damaged copy of the next record).
  All three are *transient*: the damaged bytes are re-delivered intact
  on the next poll, so no event is ever lost to injection — required
  for the oracle lower bound to hold.
- **crash** — ordered ``(boundary, count)`` points consumed one at a
  time by the :class:`CrashScheduler`; boundary kinds are ``batch``,
  ``flush``, ``checkpoint`` (the hooks in ``StreamRunner``).

Fleet surfaces (ISSUE 16; see ``chaos.netchaos`` for the proxy and
``chaos.inject`` for the ship-log filter):

- **net** — per pub/sub-message index through a :class:`ChaosPubSub`
  proxy: ``drop`` (the message vanishes), ``delay`` (held
  ``net_delay_ms`` before forwarding), ``dup`` (forwarded twice —
  the duplicated-reply/retried-request case the request-id dedup must
  absorb), ``torn`` (the frame is damaged in flight: the line's tail
  is NUL-smashed, so the peer sees one undecodable line and the
  message is lost WITHOUT desyncing the stream).
  ``partition_windows`` additionally drops EVERY message whose global
  index falls in a ``(start, length)`` window — a full partition, the
  index-based peer of ``sink_outage``.
- **ship** — per ``put_reach_sketches`` append index: ``torn`` (a
  prefix with no newline; the next append concatenates into one
  garbage line the tailer must skip), ``corrupt`` (NUL-damaged tail,
  newline intact), ``delayed`` (the record is held and appended in
  front of the NEXT ship — late, out of order).  Beyond ``ship_ops``
  the surface runs clean, so the writer's close-time forced ship is
  always delivered intact and post-heal convergence is provable.

Broker surface (ISSUE 20; see ``io.fakekafka`` for the cluster that
executes these draws):

- **kafka** — per broker-operation index (appends and fetches share one
  op counter, so fault placement is a pure function of the plan and the
  op sequence): ``produce`` (transient produce error, record rejected),
  ``consume`` (transient fetch error after any delivered records),
  ``dr_fail`` (the record is rejected and the producer learns it from a
  FAILED delivery report, not an exception), ``conn_drop`` (the broker
  drops the consumer's connection — the reconnect resumes from the last
  *returned* batch, so un-checkpointed records arrive twice:
  redelivery, Kafka's honest at-least-once shape).  ``kafka_down``
  windows additionally fail EVERY broker op in an index range — the
  broker-down outage, ``sink_outage``'s peer.
"""

from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass, field

from streambench_tpu.metrics import FaultCounters

SINK_KINDS = ("refused", "timeout", "resp")
# The non-atomic sink fault (a timeout that lands a PREFIX of the
# pipeline before raising).  Deliberately NOT in SINK_KINDS: the
# at-least-once bound assumes atomic sink failure (ROBUSTNESS.md), so
# plain sweeps never roll it — only exactly-once sweeps opt in via
# ``generate(..., sink_partial_rate=...)``.
SINK_PARTIAL = "partial"
JOURNAL_KINDS = ("truncated", "torn", "corrupt")
CRASH_KINDS = ("batch", "flush", "checkpoint")
# Fleet surfaces (ISSUE 16): pub/sub transport + ship-log append.
NET_KINDS = ("drop", "delay", "dup", "torn")
SHIP_FAULT_KINDS = ("torn", "corrupt", "delayed")
# Broker surface (ISSUE 20): the fake Kafka cluster's per-op faults.
# "down" is not drawn per-op — it comes from kafka_down windows.
KAFKA_KINDS = ("produce", "consume", "dr_fail", "conn_drop")


class EngineCrash(RuntimeError):
    """A simulated process crash raised at a runner boundary.

    Semantically the injected peer of ``kill -9``: the engine object is
    abandoned exactly where it stood (device state, parked drains,
    queued writebacks — all lost), and recovery must come entirely from
    the checkpoint + journal replay path, never from cleanup code."""


@dataclass(frozen=True)
class FaultPlan:
    """One immutable, fully-enumerated fault schedule.

    ``sink_faults``/``journal_faults`` map an operation index (counted by
    the injecting wrapper from its construction) to a fault kind;
    ``crashes`` is the ordered crash script.  An empty plan
    (:meth:`zeros`) injects nothing — wrappers built from it are exact
    pass-throughs, pinned by the byte-identical test.
    """

    seed: int = 0
    sink_faults: dict = field(default_factory=dict)      # op idx -> kind
    journal_faults: dict = field(default_factory=dict)   # poll idx -> kind
    crashes: tuple = ()                                  # ((kind, n), ...)
    # fleet surfaces (ISSUE 16); empty on every pre-fleet plan, so
    # old plans stay bit-identical under the same seed
    net_faults: dict = field(default_factory=dict)       # msg idx -> kind
    net_delay_ms: int = 0                                # "delay" hold time
    partition_windows: tuple = ()                        # ((start, len), ...)
    ship_faults: dict = field(default_factory=dict)      # ship idx -> kind
    # broker surface (ISSUE 20); empty on every pre-kafka plan, so old
    # plans stay bit-identical under the same seed
    kafka_faults: dict = field(default_factory=dict)     # op idx -> kind
    kafka_down: tuple = ()                               # ((start, end), ...)

    @classmethod
    def zeros(cls) -> "FaultPlan":
        """The no-fault plan (chaos layer present, adversity absent)."""
        return cls()

    @classmethod
    def generate(cls, seed: int, *,
                 sink_rate: float = 0.0,
                 sink_ops: int = 0,
                 sink_outage: tuple[int, int] | None = None,
                 sink_partial_rate: float = 0.0,
                 journal_rate: float = 0.0,
                 journal_polls: int = 0,
                 crashes: int = 0,
                 crash_span: int = 8,
                 net_drop_rate: float = 0.0,
                 net_delay_rate: float = 0.0,
                 net_delay_ms: int = 25,
                 net_dup_rate: float = 0.0,
                 net_torn_rate: float = 0.0,
                 net_msgs: int = 0,
                 partition_windows: tuple = (),
                 ship_rate: float = 0.0,
                 ship_ops: int = 0,
                 kafka_produce_rate: float = 0.0,
                 kafka_consume_rate: float = 0.0,
                 kafka_dr_fail_rate: float = 0.0,
                 kafka_conn_drop_rate: float = 0.0,
                 kafka_ops: int = 0,
                 kafka_down: tuple = ()) -> "FaultPlan":
        """Roll a deterministic plan from ``seed``.

        ``sink_rate``/``journal_rate`` are per-operation fault
        probabilities over the first ``sink_ops``/``journal_polls``
        operations (beyond those indices the surface runs clean, which
        guarantees retries eventually succeed).  ``sink_outage=(start,
        length)`` additionally fails every sink op in that index range —
        a hard outage window.  ``sink_partial_rate`` rolls the
        non-atomic ``partial`` fault on top (same index space, same
        single RNG draw, so plans with the rate at 0 are bit-identical
        to pre-partial plans under the same seed) — exactly-once sweeps
        only, see :data:`SINK_PARTIAL`.  ``crashes`` schedules that many
        crash points, each at a random boundary kind within the first
        ``crash_span`` boundaries of an attempt.

        Fleet surfaces (ISSUE 16, all default-off): the ``net_*_rate``
        knobs roll one fault decision per pub/sub message over the
        first ``net_msgs`` messages through a ``ChaosPubSub`` proxy
        (one RNG draw per index, cumulative thresholds — a rate at 0
        leaves the other kinds' schedule unchanged); ``net_delay_ms``
        is the hold a ``delay`` fault imposes.
        ``partition_windows=((start, length), ...)`` drops every
        message in those global-index windows outright.  ``ship_rate``
        rolls torn/corrupt/delayed append damage over the first
        ``ship_ops`` ship-log appends.  All fleet draws happen AFTER
        the legacy surfaces' draws, so plans with the fleet knobs at
        their defaults are bit-identical to pre-fleet plans under the
        same seed (the ``sink_partial_rate`` precedent).

        Broker surface (ISSUE 20, all default-off): the ``kafka_*_rate``
        knobs roll one fault decision per broker op over the first
        ``kafka_ops`` ops (cumulative thresholds, same guarantees as the
        net draws); ``kafka_down=((start, end), ...)`` fails every
        broker op whose index falls in a window.  Kafka draws happen
        LAST, after the fleet draws, so plans with the kafka knobs at
        their defaults are bit-identical to pre-kafka plans.
        """
        rng = random.Random(seed)
        sink: dict[int, str] = {}
        for i in range(sink_ops):
            roll = rng.random()
            if roll < sink_rate:
                sink[i] = rng.choice(SINK_KINDS)
            elif sink_partial_rate and roll < sink_rate + sink_partial_rate:
                sink[i] = SINK_PARTIAL
        if sink_outage is not None:
            start, length = sink_outage
            for i in range(start, start + length):
                sink[i] = "refused"
        journal: dict[int, str] = {}
        for i in range(journal_polls):
            if rng.random() < journal_rate:
                journal[i] = rng.choice(JOURNAL_KINDS)
        # Batch boundaries are plentiful; flush/checkpoint boundaries are
        # scarce in catchup mode (one final flush + one final checkpoint
        # per attempt, plus the 1 Hz periodic ones a fast drain may never
        # reach) — cap their scheduled ordinal at 2 so the armed head of
        # the script is always reachable and never wedges the whole plan.
        crash_script = []
        for _ in range(crashes):
            kind = rng.choice(CRASH_KINDS)
            hi = crash_span if kind == "batch" else min(crash_span, 2)
            crash_script.append((kind, rng.randrange(1, hi + 1)))
        crash_script = tuple(crash_script)
        # fleet draws LAST (bit-identity for pre-fleet plans): one roll
        # per message index, kinds picked by cumulative rate thresholds
        # so turning one kind on never reshuffles another kind's draws
        net: dict[int, str] = {}
        rates = (("drop", net_drop_rate), ("delay", net_delay_rate),
                 ("dup", net_dup_rate), ("torn", net_torn_rate))
        for i in range(net_msgs):
            roll = rng.random()
            lo = 0.0
            for kind, rate in rates:
                if rate and roll < lo + rate:
                    net[i] = kind
                    break
                lo += rate
        ship: dict[int, str] = {}
        for i in range(ship_ops):
            if rng.random() < ship_rate:
                ship[i] = rng.choice(SHIP_FAULT_KINDS)
        windows = tuple((int(s), int(n)) for s, n in partition_windows)
        # broker draws LAST (bit-identity for pre-kafka plans): same
        # cumulative-threshold scheme as the net draws
        kafka: dict[int, str] = {}
        krates = (("produce", kafka_produce_rate),
                  ("consume", kafka_consume_rate),
                  ("dr_fail", kafka_dr_fail_rate),
                  ("conn_drop", kafka_conn_drop_rate))
        for i in range(kafka_ops):
            roll = rng.random()
            lo = 0.0
            for kind, rate in krates:
                if rate and roll < lo + rate:
                    kafka[i] = kind
                    break
                lo += rate
        kdown = tuple((int(s), int(e)) for s, e in kafka_down)
        return cls(seed=seed, sink_faults=sink, journal_faults=journal,
                   crashes=crash_script, net_faults=net,
                   net_delay_ms=int(net_delay_ms),
                   partition_windows=windows, ship_faults=ship,
                   kafka_faults=kafka, kafka_down=kdown)

    @property
    def is_zero(self) -> bool:
        return not (self.sink_faults or self.journal_faults
                    or self.crashes or self.net_faults
                    or self.partition_windows or self.ship_faults
                    or self.kafka_faults or self.kafka_down)


class CrashScheduler:
    """Raises :class:`EngineCrash` at scripted runner boundaries.

    Holds the plan's ordered crash script; only the HEAD entry is armed.
    Boundary counts are per-attempt (``reset()`` at every supervised
    restart), so ``("flush", 3)`` means "the 3rd flush of the current
    attempt", which keeps crash points reachable no matter where the
    previous crash left the stream.  Exhausted schedulers never raise —
    the run is guaranteed to finish once the script is consumed.
    """

    def __init__(self, crashes, counters: FaultCounters | None = None):
        for kind, n in crashes:
            if kind not in CRASH_KINDS or n < 1:
                raise ValueError(f"bad crash point ({kind!r}, {n})")
        self._pending = deque(crashes)
        self.counters = counters if counters is not None else FaultCounters()
        self._counts: dict[str, int] = {}

    def reset(self) -> None:
        """New run attempt: boundary counts restart at zero."""
        self._counts = {}

    @property
    def exhausted(self) -> bool:
        return not self._pending

    @property
    def remaining(self) -> int:
        return len(self._pending)

    def point(self, kind: str) -> None:
        """One boundary of ``kind`` passed; crash here if scripted."""
        self._counts[kind] = c = self._counts.get(kind, 0) + 1
        if not self._pending:
            return
        want_kind, want_n = self._pending[0]
        if kind == want_kind and c >= want_n:
            self._pending.popleft()
            self.counters.inc("crashes_injected")
            raise EngineCrash(f"injected crash at {kind} #{c}")
