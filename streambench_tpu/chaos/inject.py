"""Fault-injecting wrappers for the three failure surfaces.

One :class:`FaultInjector` owns a :class:`~streambench_tpu.chaos.plan.FaultPlan`
plus the *global* operation counters, and hands out wrappers:

- :meth:`FaultInjector.wrap_redis` — a sink proxy injecting connection
  refusals, timeouts, and transient RESP errors into the window-writeback
  path (raised *before* forwarding: a faulted op applies nothing);
- :meth:`FaultInjector.wrap_reader` — a journal-reader wrapper injecting
  torn tails, truncated reads, and corrupt records, all transient (the
  damaged bytes are rewound and re-delivered intact on the next poll, so
  injection can never lose an event);
- :attr:`FaultInjector.scheduler` — the crash scheduler a
  ``StreamRunner`` takes as ``crash_points``;
- :meth:`FaultInjector.net_fault` — the per-message draw the
  :class:`~streambench_tpu.chaos.netchaos.ChaosPubSub` proxy consumes
  (partition windows outrank the rolled kind);
- :meth:`FaultInjector.attach_ship_chaos` — installs a
  ``ship_fault_hook`` on a ``DurableDimensionStore`` so
  ``put_reach_sketches`` appends are damaged per the plan's ship
  schedule (torn / corrupt / delayed), proving the replica tailer's
  skip-and-resync.

Operation indices are owned by the injector, NOT the wrappers, so a
supervised restart (which re-wraps fresh engine/reader objects) continues
the plan where the crashed attempt left it instead of replaying the same
faults forever.
"""

from __future__ import annotations

import threading

from streambench_tpu.chaos.plan import CrashScheduler, FaultPlan
from streambench_tpu.io.resp import RespError
from streambench_tpu.metrics import FaultCounters

# The NUL zero-page a crashed writer's torn append leaves behind
# (filesystems zero-fill the unwritten tail of a dirtied page).
TORN_PAGE = b"\x00" * 64


class ChaosRedis:
    """RedisLike proxy that injects scheduled sink faults.

    Faults are atomic — raised before the command is forwarded — so a
    faulted write applies nothing (see ``chaos.plan`` for why the
    at-least-once bound needs this).  One fault decision per
    ``execute``/``pipeline_execute`` call: the writeback path submits
    whole flush batches, so per-call granularity is per-batch
    granularity, matching how a real connection fails.

    The one deliberate exception is the ``partial`` fault (exactly-once
    sweeps only): a timeout that forwards a PREFIX of the pipeline
    before raising — the non-atomic failure a real socket timeout can
    leave behind, which the at-least-once model explicitly cannot
    represent (ROBUSTNESS.md "modeling choices") and only the epoch/seq
    fence protocol survives.  On a single ``execute`` it applies the
    command fully and then raises (the response-loss flavor).

    Underscore attributes are deliberately NOT forwarded: the engine
    probes ``redis._store`` to pick its in-C bulk writeback, which would
    bypass this proxy entirely — hiding it forces every flush through
    the faultable path.
    """

    def __init__(self, target, injector: "FaultInjector"):
        self._target = target
        self._injector = injector

    def _maybe_fault(self) -> str | None:
        """Raise the scheduled atomic fault, or return "partial" for the
        caller to enact (it needs the command list)."""
        kind = self._injector.sink_fault()
        if kind == "refused":
            raise ConnectionRefusedError("chaos: connection refused")
        if kind == "timeout":
            raise TimeoutError("chaos: sink operation timed out")
        if kind == "resp":
            raise RespError(
                "LOADING chaos: Redis is loading the dataset in memory")
        return kind

    def execute(self, *args):
        kind = self._maybe_fault()
        if kind == "partial":
            # single command: fully applied, response lost
            self._target.execute(*args)
            raise TimeoutError("chaos: sink timed out after apply")
        return self._target.execute(*args)

    def pipeline_execute(self, commands):
        kind = self._maybe_fault()
        if kind == "partial":
            cmds = list(commands)
            k = max(len(cmds) // 2, 1)
            self._target.pipeline_execute(cmds[:k])
            raise TimeoutError(
                f"chaos: sink timed out after partial pipeline apply "
                f"({k}/{len(cmds)} commands landed)")
        return self._target.pipeline_execute(commands)

    def reconnect(self) -> None:
        """Connection management, never faulted (a refused reconnect is
        modeled as the NEXT op faulting, which the plan already covers)."""
        reconnect = getattr(self._target, "reconnect", None)
        if reconnect is not None:
            reconnect()

    def close(self) -> None:
        self._target.close()

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._target, name)


class ChaosJournalReader:
    """JournalReader wrapper injecting transient read damage.

    Wraps a single-partition ``JournalReader`` (``MultiReader`` is not
    supported: rewind bookkeeping needs one byte offset).  Fault kinds:

    - ``truncated`` — a short read: only a prefix (cut at a record
      boundary) is delivered, the rest rewound;
    - ``torn``      — a torn tail: a prefix plus a NUL zero-page
      pseudo-record (``TORN_PAGE``), the real records rewound;
    - ``corrupt``   — the record after the cut is delivered as a
      NUL-damaged copy and rewound for intact re-delivery.

    Every fault preserves the journal's byte-exactness: ``offset`` never
    covers damaged bytes, so checkpoints taken through this wrapper
    resume correctly.  Damaged pseudo-records always contain NULs and
    can never parse as events (the encoder rejects them), so injection
    shows up as ``bad_lines``, never as count drift.
    """

    def __init__(self, delegate, injector: "FaultInjector"):
        self._delegate = delegate
        self._injector = injector
        self.fault_counters = injector.counters

    # -- checkpoint surface (forwarded byte-exactly) -------------------
    @property
    def offset(self) -> int:
        return self._delegate.offset

    def seek(self, offset: int) -> None:
        self._delegate.seek(offset)

    def close(self) -> None:
        self._delegate.close()

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._delegate, name)

    # -- faulted reads -------------------------------------------------
    def poll(self, max_records: int = 65536) -> list[bytes]:
        before = self._delegate.offset
        lines = self._delegate.poll(max_records)
        if not lines:
            return lines
        kind = self._injector.journal_fault()
        if kind is None:
            return lines
        cut = len(lines) // 2
        keep = lines[:cut]
        self._delegate.seek(before + sum(len(l) + 1 for l in keep))
        if kind == "truncated":
            return keep
        if kind == "torn":
            return keep + [TORN_PAGE]
        victim = lines[cut]
        half = max(len(victim) // 2, 1)
        return keep + [victim[:half] + b"\x00" * (len(victim) - half)]

    def poll_block(self, max_bytes: int | None = None) -> bytes:
        before = self._delegate.offset
        data = self._delegate.poll_block(max_bytes)
        if not data:
            return data
        kind = self._injector.journal_fault()
        if kind is None:
            return data
        # cut at the record boundary nearest the middle of the block
        pos = data.rfind(b"\n", 0, max(len(data) // 2, 1)) + 1
        self._delegate.seek(before + pos)
        keep = data[:pos]
        if kind == "truncated":
            return keep
        if kind == "torn":
            return keep + TORN_PAGE + b"\n"
        end = data.find(b"\n", pos)
        victim = data[pos:end if end >= 0 else len(data)]
        half = max(len(victim) // 2, 1)
        return (keep + victim[:half]
                + b"\x00" * (len(victim) - half) + b"\n")


class ShipChaosFilter:
    """The ship-log append filter ``attach_ship_chaos`` installs.

    Called by ``DurableDimensionStore.put_reach_sketches`` with the
    serialized record line (newline included); returns ``(data,
    intact)`` where ``data`` is what actually hits the file and
    ``intact`` says whether the store may absorb the record into its
    in-memory index (a damaged append must not leave the writer's OWN
    view ahead of what it durably wrote).

    - ``torn``    — a prefix with NO newline: the next append
      concatenates into one undecodable garbage line (the tailer's
      ``_carry`` holds the stub until that newline lands, then the
      combined line fails to parse and is skipped);
    - ``corrupt`` — the line's tail is NUL-smashed, newline intact:
      one self-contained garbage line;
    - ``delayed`` — the record is held and prepended to the NEXT
      append: late and out of order, which the tailer's
      newest-decodable rule must absorb.
    """

    def __init__(self, injector: "FaultInjector"):
        self._injector = injector
        self._held = ""

    def __call__(self, data: str) -> tuple[str, bool]:
        kind = self._injector.ship_fault()
        held, self._held = self._held, ""
        if kind is None:
            return held + data, True
        if kind == "torn":
            return held + data[: max(len(data) // 2, 1)], False
        if kind == "corrupt":
            half = max(len(data) // 2, 1)
            return (held + data[:half]
                    + "\x00" * (len(data) - half - 1) + "\n"), False
        # delayed: hold the record for the next append (nothing written
        # now beyond any previously-held record)
        self._held = data
        return held, False


class FaultInjector:
    """The plan's executor: wraps surfaces, owns global fault indices.

    One injector per chaos run.  Wrap fresh engine/reader objects at
    every supervised restart; the injector's counters make the plan
    progress monotonically across attempts.
    """

    def __init__(self, plan: FaultPlan,
                 counters: FaultCounters | None = None):
        self.plan = plan
        self.counters = counters if counters is not None else FaultCounters()
        self.scheduler = CrashScheduler(plan.crashes, self.counters)
        self._lock = threading.Lock()
        self._sink_idx = 0
        self._journal_idx = 0
        self._net_idx = 0
        self._ship_idx = 0
        self._kafka_idx = 0

    def sink_fault(self) -> str | None:
        with self._lock:
            i = self._sink_idx
            self._sink_idx += 1
        kind = self.plan.sink_faults.get(i)
        if kind is not None:
            self.counters.inc("chaos_sink_faults")
        return kind

    def journal_fault(self) -> str | None:
        with self._lock:
            i = self._journal_idx
            self._journal_idx += 1
        kind = self.plan.journal_faults.get(i)
        if kind is not None:
            self.counters.inc("journal_faults")
        return kind

    # -- fleet surfaces (ISSUE 16) -------------------------------------
    def net_fault(self) -> str | None:
        """One per-message draw for the ChaosPubSub proxy.  Partition
        windows outrank the rolled kind: a message inside one is
        dropped no matter what the rate draw said (a partition is not
        a probability)."""
        with self._lock:
            i = self._net_idx
            self._net_idx += 1
        for start, length in self.plan.partition_windows:
            if start <= i < start + length:
                self.counters.inc("net_faults")
                self.counters.inc("net_partition_drops")
                return "drop"
        kind = self.plan.net_faults.get(i)
        if kind is not None:
            self.counters.inc("net_faults")
            self.counters.inc(f"net_{kind}")
        return kind

    def ship_fault(self) -> str | None:
        with self._lock:
            i = self._ship_idx
            self._ship_idx += 1
        kind = self.plan.ship_faults.get(i)
        if kind is not None:
            self.counters.inc("ship_faults")
        return kind

    # -- broker surface (ISSUE 20) -------------------------------------
    def kafka_fault(self) -> str | None:
        """One per-broker-op draw for the fake Kafka cluster.  Down
        windows outrank the rolled kind (an outage is not a
        probability, the partition-window precedent)."""
        with self._lock:
            i = self._kafka_idx
            self._kafka_idx += 1
        for start, end in self.plan.kafka_down:
            if start <= i < end:
                self.counters.inc("chaos_kafka_faults")
                self.counters.inc("chaos_kafka_down")
                return "down"
        kind = self.plan.kafka_faults.get(i)
        if kind is not None:
            self.counters.inc("chaos_kafka_faults")
            self.counters.inc(f"chaos_kafka_{kind}")
        return kind

    @property
    def net_delay_s(self) -> float:
        return max(self.plan.net_delay_ms, 0) / 1000.0

    def wrap_redis(self, target) -> ChaosRedis:
        return ChaosRedis(target, self)

    def wrap_reader(self, delegate) -> ChaosJournalReader:
        if not hasattr(delegate, "offset") or not hasattr(delegate, "seek"):
            raise TypeError(
                "ChaosJournalReader wraps a single-partition "
                "JournalReader (MultiReader has no scalar offset)")
        return ChaosJournalReader(delegate, self)

    def attach_ship_chaos(self, store) -> ShipChaosFilter:
        """Install the ship-log append filter on ``store`` (a
        ``DurableDimensionStore``); returns the filter for tests."""
        filt = ShipChaosFilter(self)
        store.ship_fault_hook = filt
        return filt
