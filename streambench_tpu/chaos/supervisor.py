"""Supervised crash-recovery: restart the runner until the stream is done.

The reference's only recovery story is operator-driven: restart the
topology and recount from the earliest Kafka offset
(``setStartFromEarliest``, ``AdvertisingTopologyNative.java:92``).  The
:class:`Supervisor` is the in-process peer of a process supervisor
(systemd / the Storm nimbus restart loop): it runs a ``StreamRunner``
attempt, and on a crash builds a FRESH runner (the crashed engine is
abandoned exactly as a dead process would leave it), resumes it from the
newest checkpoint, and retries under capped exponential backoff with
jitter.  It gives up cleanly after N consecutive restarts that made no
progress — the checkpoint offset did not advance — so a poisoned stream
or a permanently-down dependency cannot restart-loop forever.

Recovery bookkeeping for the oracle (``chaos.verify``): each crash
contributes one *replay segment* ``[resume_offset, crash_offset)`` — the
journal byte range whose events may be double-applied (flushed before
the crash AND re-folded after the resume) — and each resume records the
restored snapshot's *carried pending* deltas, which may likewise be
double-applied when the pre-crash attempt had already written them.
Together these are exactly the at-least-once over-count bound documented
in ``checkpoint.py``.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from streambench_tpu.chaos.plan import EngineCrash
from streambench_tpu.engine.runner import RunStats
from streambench_tpu.metrics import FaultCounters


@dataclass
class SupervisorStats:
    """One supervised run, summarized."""

    attempts: int = 0
    crashes: int = 0
    restarts: int = 0
    gave_up: bool = False
    backoff_ms_total: float = 0.0
    # journal byte ranges whose events may be double-counted, one per
    # crash: (resume_offset_of_the_following_attempt, crash_offset)
    replay_segments: list = field(default_factory=list)
    # snapshot-carried pending deltas observed at each resume:
    # (campaign_name, abs_window_ts) -> summed count.  Reclaimed failed
    # writes a snapshot carries may already have landed before the
    # crash; re-flushing them after restore is the second (and only
    # other) legal over-count source.
    carried: dict = field(default_factory=dict)
    stats: RunStats | None = None     # the successful attempt's stats
    errors: list = field(default_factory=list)  # repr per crash

    @property
    def completed(self) -> bool:
        return self.stats is not None


class Supervisor:
    """Runs ``make_runner()`` attempts until one completes or progress dies.

    ``make_runner`` must return a FRESH ``StreamRunner`` each call (new
    engine, new reader, same checkpointer directory) — reusing a crashed
    engine would let host state survive a "crash", which is exactly what
    the chaos layer exists to rule out.  If the runner carries a
    ``crash_points`` scheduler, its per-attempt boundary counts are
    reset on every restart.

    ``catch`` is the crash surface: the simulated :class:`EngineCrash`
    plus the connection-shaped errors a real dependency failure raises
    out of the run loop.  Anything else (assertion, schema mismatch) is
    a bug and propagates.
    """

    def __init__(self, make_runner, *,
                 max_no_progress_restarts: int = 3,
                 backoff_base_ms: float = 50.0,
                 backoff_cap_ms: float = 2000.0,
                 seed: int = 0,
                 catch: tuple = (EngineCrash, ConnectionError, TimeoutError),
                 sleep=time.sleep,
                 counters: FaultCounters | None = None,
                 sampler=None,
                 flightrec=None):
        self.make_runner = make_runner
        # Telemetry annotation hook (obs.MetricsSampler or anything with
        # ``annotate(event, **fields)``): crash/restart/give-up events
        # land in the metrics.jsonl stream between snapshots, so a
        # supervised run's time series shows WHEN each restart happened
        # against the throughput/backlog curves, not just how many.
        self.sampler = sampler
        # Crash flight recorder (obs.flightrec or None): crash/restart
        # annotations land in the postmortem ring next to the runners'
        # tick records (share ONE recorder with make_runner's runners so
        # the sequence numbers interleave in true order), and a give-up
        # dumps ``flight_give_up.jsonl`` with the terminal fault last —
        # the black box of a chaos sweep that died for good.
        self.flightrec = flightrec
        self.max_no_progress_restarts = max(int(max_no_progress_restarts), 1)
        self.backoff_base_ms = max(float(backoff_base_ms), 0.0)
        self.backoff_cap_ms = max(float(backoff_cap_ms), self.backoff_base_ms)
        self.catch = catch
        self._sleep = sleep
        self._rng = random.Random(seed)
        self.counters = counters if counters is not None else FaultCounters()
        self.stats = SupervisorStats()
        self.runner = None          # the last (on success: final) runner

    # ------------------------------------------------------------------
    def _backoff(self, consecutive_crashes: int) -> float:
        """Capped exponential backoff with jitter (ms).  Full jitter on
        the upper half: deterministic under ``seed``, but two supervisors
        sharing a dependency don't thundering-herd its recovery."""
        n = min(consecutive_crashes, 16)
        base = min(self.backoff_base_ms * (1 << max(n - 1, 0)),
                   self.backoff_cap_ms)
        return base * (0.5 + 0.5 * self._rng.random())

    @staticmethod
    def _progress_key(position) -> int:
        """Scalar progress from a reader position (sum of the vector for
        multi-partition readers: any partition advancing is progress)."""
        return sum(position) if isinstance(position, list) else int(position)

    def _durable_progress(self, runner) -> int:
        """Where the NEXT attempt will resume: the newest checkpoint's
        offset (0 when none exists).  Evaluated at crash time so an
        attempt that saved a snapshot and THEN crashed — e.g. a crash
        injected right at the checkpoint boundary — counts as progress
        immediately, not one restart later."""
        ck = getattr(runner, "checkpointer", None)
        snap = ck.load() if ck is not None else None
        return self._progress_key(snap.offset) if snap is not None else 0

    def _record_resume(self, runner, prev_crash_offset) -> None:
        """Log the replay segment + carried pending for this resume."""
        resume_pos = runner._reader_position()
        if prev_crash_offset is not None:
            self.stats.replay_segments.append(
                (resume_pos, prev_crash_offset))
        campaigns = runner.engine.encoder.campaigns
        for (ci, ts), n in runner.engine.pending_counts().items():
            key = (campaigns[ci], int(ts))
            self.stats.carried[key] = self.stats.carried.get(key, 0) + n
        # exactly-once runs: the restored fence baseline the next flush's
        # sink read will be judged against — on the telemetry/postmortem
        # streams so a reconcile decision can be traced back to its input
        fence = getattr(runner.engine, "_xo_baseline", None)
        xo = getattr(runner.engine, "_xo", False)
        if xo and fence is not None:
            if self.sampler is not None:
                self.sampler.annotate(
                    "resume", resume_offset=resume_pos,
                    fence_epoch=fence[0], fence_seq=fence[1])
            if self.flightrec is not None:
                self.flightrec.record(
                    "supervisor", event="resume",
                    resume_offset=resume_pos,
                    fence_epoch=fence[0], fence_seq=fence[1])

    # ------------------------------------------------------------------
    def run(self, *, catchup: bool = False, **run_kwargs) -> SupervisorStats:
        """Drive attempts to completion.  ``run_kwargs`` go to every
        attempt's ``runner.run``/``run_catchup`` unchanged."""
        st = self.stats
        consecutive_crashes = 0
        no_progress = 0
        last_durable_progress: int | None = None
        prev_crash_offset = None
        while True:
            runner = self.runner = self.make_runner()
            st.attempts += 1
            resumed = runner.resume()
            if resumed:
                self._record_resume(runner, prev_crash_offset)
            elif prev_crash_offset is not None:
                # crashed before the first checkpoint: the whole prefix
                # up to the crash replays from offset zero
                zero = ([0] * len(prev_crash_offset)
                        if isinstance(prev_crash_offset, list) else 0)
                st.replay_segments.append((zero, prev_crash_offset))
            sched = getattr(runner, "crash_points", None)
            if sched is not None:
                sched.reset()
            try:
                st.stats = (runner.run_catchup(**run_kwargs) if catchup
                            else runner.run(**run_kwargs))
                return st
            except self.catch as e:
                st.crashes += 1
                st.errors.append(repr(e))
                prev_crash_offset = runner._reader_position()
                if self.sampler is not None:
                    self.sampler.annotate(
                        "crash", attempt=st.attempts, error=repr(e),
                        crash_offset=prev_crash_offset)
                if self.flightrec is not None:
                    self.flightrec.record(
                        "supervisor", event="crash",
                        attempt=st.attempts, error=repr(e),
                        crash_offset=prev_crash_offset)
                # DURABLE progress only: the checkpoint the next attempt
                # will resume from.  Work a crashed attempt did but never
                # snapshotted is not progress — counting it would let a
                # crash-before-first-checkpoint loop restart forever
                # while recovering nothing.
                progress = self._durable_progress(runner)
                if (last_durable_progress is not None
                        and progress <= last_durable_progress):
                    no_progress += 1
                else:
                    no_progress = 0
                last_durable_progress = progress
                if no_progress >= self.max_no_progress_restarts:
                    st.gave_up = True
                    if self.sampler is not None:
                        self.sampler.annotate(
                            "give_up", attempts=st.attempts,
                            crashes=st.crashes, no_progress=no_progress)
                    if self.flightrec is not None:
                        self.flightrec.dump("give_up", terminal={
                            "kind": "fault", "event": "give_up",
                            "error": st.errors[-1] if st.errors else None,
                            "attempts": st.attempts,
                            "crashes": st.crashes,
                            "no_progress": no_progress,
                            "durable_progress": progress})
                    return st
                consecutive_crashes += 1
                back = self._backoff(consecutive_crashes)
                st.backoff_ms_total += back
                st.restarts += 1
                self.counters.inc("restarts")
                if self.sampler is not None:
                    self.sampler.annotate(
                        "restart", restarts=st.restarts,
                        backoff_ms=round(back, 1),
                        durable_progress=progress)
                if self.flightrec is not None:
                    self.flightrec.record(
                        "supervisor", event="restart",
                        restarts=st.restarts, backoff_ms=round(back, 1),
                        durable_progress=progress)
                if back > 0:
                    self._sleep(back / 1000.0)
