"""Fleet supervisor: crash-fault, restart, and give up on replica
processes (ISSUE 16 tentpole (c)).

PR 1's :class:`~streambench_tpu.chaos.supervisor.Supervisor` proved the
recovery semantics for the single writer: fresh attempt per crash,
capped exponential backoff with seeded jitter, give-up when restarts
stop making progress.  The :class:`FleetSupervisor` lifts the same
semantics to the PROCESS level for reach read replicas:

- it spawns N replica slots through an injectable ``spawn(idx, attempt)
  -> handle`` (a subprocess.Popen, or an in-process stand-in in tests —
  anything with ``pid`` / ``poll()`` / ``terminate()`` / ``kill()``);
- :meth:`kill` is the chaos driver's crash fault — SIGKILL by default,
  so the replica gets no chance to shed gracefully or release its
  pidfile (the pidfile's recycled-pid check is what makes that safe);
- :meth:`step` notices deaths, schedules a respawn after the SAME
  capped-backoff-with-jitter formula as PR 1 (seeded: a sweep replays
  bit-identically), and respawns when the backoff elapses;
- a slot whose process keeps dying *young* — uptime under
  ``healthy_after_s`` on ``max_restarts`` consecutive deaths — is given
  up on, exactly PR 1's no-progress rule with uptime as the progress
  proxy (a replica that served for a while and then was crash-faulted
  earns its restart counter back);
- ``on_restart(idx, attempt)`` is the PR 15 restart-path hook: the
  bench wires it to the writer shipper's forced ship
  (``note_state(..., force=True)``), so a freshly restarted replica
  finds a RECENT record to load instead of sitting shed-stale until the
  next cadence tick.

Crash/restart/give-up events are annotated onto the shared telemetry
stream and flight recorder under the ``fleet_supervisor`` key, and the
``restarts`` counter feeds the ``obs fleet`` table.
"""

from __future__ import annotations

import random
import signal as _signal
import time

from streambench_tpu.metrics import FaultCounters


class ReplicaSlot:
    """One supervised replica seat: the live handle plus its ledger."""

    __slots__ = ("idx", "handle", "attempt", "restarts",
                 "consecutive_young_deaths", "gave_up", "retired",
                 "spawned_at", "restart_at", "exit_codes", "kills")

    def __init__(self, idx: int):
        self.idx = idx
        self.handle = None
        self.attempt = 0
        self.restarts = 0
        self.consecutive_young_deaths = 0
        self.gave_up = False
        self.retired = False           # graceful scale-down, no respawn
        self.spawned_at = 0.0          # monotonic
        self.restart_at: float | None = None  # backoff deadline
        self.exit_codes: list = []
        self.kills = 0

    def summary(self) -> dict:
        return {"idx": self.idx,
                "pid": getattr(self.handle, "pid", None),
                "attempt": self.attempt, "restarts": self.restarts,
                "kills": self.kills, "gave_up": self.gave_up,
                "retired": self.retired,
                "exit_codes": list(self.exit_codes)}


class FleetSupervisor:
    """Spawn/kill/restart N replica slots under capped backoff.

    ``spawn(idx, attempt)`` must return a fresh process handle each
    call; the supervisor never reuses a dead handle (a crashed replica
    is abandoned exactly as PR 1 abandons a crashed engine).  Drive it
    with :meth:`watch` (poll loop) or :meth:`step` directly from a
    test's own clock.
    """

    def __init__(self, spawn, n: int, *,
                 backoff_base_ms: float = 50.0,
                 backoff_cap_ms: float = 2000.0,
                 max_restarts: int = 5,
                 healthy_after_s: float = 5.0,
                 seed: int = 0,
                 on_restart=None,
                 counters: FaultCounters | None = None,
                 sampler=None, flightrec=None,
                 sleep=time.sleep, clock=time.monotonic):
        self.spawn_fn = spawn
        self.slots = [ReplicaSlot(i) for i in range(int(n))]
        self.backoff_base_ms = max(float(backoff_base_ms), 0.0)
        self.backoff_cap_ms = max(float(backoff_cap_ms),
                                  self.backoff_base_ms)
        self.max_restarts = max(int(max_restarts), 1)
        self.healthy_after_s = float(healthy_after_s)
        self.on_restart = on_restart
        self.counters = counters if counters is not None else FaultCounters()
        self.sampler = sampler
        self.flightrec = flightrec
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock
        self._stopping = False

    # -- PR 1's backoff, verbatim semantics ----------------------------
    def _backoff(self, consecutive: int) -> float:
        """Capped exponential backoff with jitter (ms)."""
        n = min(consecutive, 16)
        base = min(self.backoff_base_ms * (1 << max(n - 1, 0)),
                   self.backoff_cap_ms)
        return base * (0.5 + 0.5 * self._rng.random())

    def _annotate(self, event: str, **fields) -> None:
        if self.sampler is not None:
            self.sampler.annotate(event, **fields)
        if self.flightrec is not None:
            self.flightrec.record("fleet_supervisor", event=event,
                                  **fields)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetSupervisor":
        for slot in self.slots:
            self._spawn(slot)
        return self

    def _spawn(self, slot: ReplicaSlot) -> None:
        slot.attempt += 1
        slot.handle = self.spawn_fn(slot.idx, slot.attempt)
        slot.spawned_at = self._clock()
        slot.restart_at = None

    # -- elastic surface (ISSUE 17): the autoscaler's grow/shrink ------
    def spawn(self) -> int:
        """Grow the fleet by one supervised slot and spawn it now.
        Returns the new slot index (also its spawn-callable idx)."""
        if self._stopping:
            raise RuntimeError("supervisor is stopping")
        slot = ReplicaSlot(len(self.slots))
        self.slots.append(slot)
        self._spawn(slot)
        self.counters.inc("spawns")
        self._annotate("replica_spawn", idx=slot.idx,
                       pid=getattr(slot.handle, "pid", None))
        return slot.idx

    def retire(self, idx: int, *, deregister=None,
               drain_s: float = 0.25, grace_s: float = 5.0) -> bool:
        """Graceful scale-down: deregister -> drain -> stop.

        ``deregister(idx)`` (e.g. ``router.remove_replica``) runs FIRST
        so no new queries route here; then the replica drains in-flight
        work for ``drain_s`` before SIGTERM (SIGKILL after ``grace_s``).
        A retired slot is never respawned, and ``retires`` is counted
        separately from crash ``kills`` — scale-down is an intended
        state change, not a fault.  Returns False when the slot is
        already retired/given-up."""
        slot = self.slots[idx]
        if slot.retired or slot.gave_up:
            return False
        if deregister is not None:
            deregister(idx)
        slot.retired = True   # before the stop: step() must not respawn
        h = slot.handle
        pid = getattr(h, "pid", None)
        if h is not None and h.poll() is None:
            if drain_s > 0:
                self._sleep(drain_s)
            try:
                h.terminate()
            except OSError:
                pass
            deadline = self._clock() + float(grace_s)
            while h.poll() is None and self._clock() < deadline:
                self._sleep(0.02)
            if h.poll() is None:
                try:
                    h.kill()
                except OSError:
                    pass
                h.poll()
        self.counters.inc("retires")
        self._annotate("replica_retire", idx=idx, pid=pid,
                       drain_s=drain_s)
        return True

    def alive(self, idx: int) -> bool:
        h = self.slots[idx].handle
        return h is not None and h.poll() is None

    def kill(self, idx: int, *, hard: bool = True) -> bool:
        """Crash-fault one replica.  ``hard`` (default) is SIGKILL —
        no graceful shed, no pidfile release; False is SIGTERM.
        Returns False when the slot has no live process to kill."""
        slot = self.slots[idx]
        h = slot.handle
        if h is None or h.poll() is not None:
            return False
        (h.kill if hard else h.terminate)()
        slot.kills += 1
        self.counters.inc("crash_kills")
        self._annotate("replica_kill", idx=idx,
                       pid=getattr(h, "pid", None), hard=hard)
        return True

    def step(self, now: float | None = None) -> int:
        """One supervision pass: notice deaths, schedule backoffs,
        respawn slots whose backoff elapsed.  Returns restarts
        performed this pass."""
        if self._stopping:
            return 0
        now = self._clock() if now is None else now
        restarted = 0
        for slot in self.slots:
            if slot.gave_up or slot.retired:
                continue
            if slot.restart_at is None:
                h = slot.handle
                code = h.poll() if h is not None else 0
                if code is None:
                    continue
                # death observed: ledger it, decide give-up vs backoff
                slot.exit_codes.append(code)
                uptime = now - slot.spawned_at
                if uptime >= self.healthy_after_s:
                    slot.consecutive_young_deaths = 0
                else:
                    slot.consecutive_young_deaths += 1
                self._annotate("replica_crash", idx=slot.idx,
                               exit_code=code,
                               uptime_s=round(uptime, 3))
                if slot.consecutive_young_deaths >= self.max_restarts:
                    slot.gave_up = True
                    self.counters.inc("give_ups")
                    self._annotate("replica_give_up", idx=slot.idx,
                                   attempts=slot.attempt,
                                   young_deaths=
                                   slot.consecutive_young_deaths)
                    continue
                back_ms = self._backoff(
                    max(slot.consecutive_young_deaths, 1))
                slot.restart_at = now + back_ms / 1000.0
            if slot.restart_at is not None and now >= slot.restart_at:
                self._spawn(slot)
                slot.restarts += 1
                restarted += 1
                self.counters.inc("restarts")
                self._annotate("replica_restart", idx=slot.idx,
                               attempt=slot.attempt,
                               pid=getattr(slot.handle, "pid", None))
                if self.on_restart is not None:
                    self.on_restart(slot.idx, slot.attempt)
        return restarted

    def watch(self, duration_s: float, poll_s: float = 0.05) -> int:
        """Poll loop for ``duration_s``; returns total restarts."""
        deadline = self._clock() + float(duration_s)
        total = 0
        while self._clock() < deadline and not self._stopping:
            total += self.step()
            self._sleep(poll_s)
        return total

    def stop(self, *, grace_s: float = 5.0) -> None:
        """Terminate every live replica (SIGTERM, escalate to SIGKILL
        after ``grace_s``) and stop restarting."""
        self._stopping = True
        live = [s for s in self.slots
                if s.handle is not None and s.handle.poll() is None]
        for slot in live:
            try:
                slot.handle.terminate()
            except OSError:
                pass
        deadline = self._clock() + float(grace_s)
        for slot in live:
            while (slot.handle.poll() is None
                   and self._clock() < deadline):
                self._sleep(0.02)
            if slot.handle.poll() is None:
                try:
                    slot.handle.kill()
                except OSError:
                    pass
                slot.handle.poll()

    def summary(self) -> dict:
        return {"replicas": [s.summary() for s in self.slots],
                "restarts": sum(s.restarts for s in self.slots),
                "kills": sum(s.kills for s in self.slots),
                "gave_up": sum(1 for s in self.slots if s.gave_up),
                "retired": sum(1 for s in self.slots if s.retired),
                "active": sum(1 for s in self.slots
                              if not s.retired and not s.gave_up
                              and s.handle is not None
                              and s.handle.poll() is None)}


def cli_spawn(ship_path: str, workdir: str, *,
              host: str = "127.0.0.1", ports=None,
              max_staleness_ms: int | None = None,
              poll_ms: int | None = None, fleet: bool = False,
              metrics: bool = False, extra_args=()):
    """A ``spawn`` callable running the real replica CLI per slot:
    ``python -m streambench_tpu.reach.replica --ship ... --pid-file
    pids/replica_<idx>`` with stdout teed to
    ``<workdir>/replica_<idx>.out`` (the harness parses the ready
    line from it).  ``ports[idx]`` pins each slot's pub/sub port so a
    restarted replica comes back at the SAME address — the router's
    replica list stays valid across restarts."""
    import os
    import subprocess
    import sys

    os.makedirs(workdir, exist_ok=True)

    def spawn(idx: int, attempt: int):
        cmd = [sys.executable, "-m", "streambench_tpu.reach.replica",
               "--ship", ship_path, "--host", host,
               "--port", str(ports[idx] if ports else 0),
               "--pid-file",
               os.path.join(workdir, "pids", f"replica_{idx}")]
        if max_staleness_ms is not None:
            cmd += ["--max-staleness-ms", str(max_staleness_ms)]
        if poll_ms is not None:
            cmd += ["--poll-ms", str(poll_ms)]
        if fleet:
            cmd.append("--fleet")
        if metrics:
            d = os.path.join(workdir, f"replica_{idx}")
            os.makedirs(d, exist_ok=True)
            cmd += ["--metrics-dir", d]
        cmd += list(extra_args)
        out = open(os.path.join(workdir, f"replica_{idx}.out"), "ab")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(cmd, stdout=out, stderr=out, env=env)
        out.close()
        return proc

    return spawn


def wait_ready(out_path: str, *, timeout_s: float = 30.0,
               marker: str = "replica: pubsub=") -> tuple[str, int]:
    """Parse a spawned replica's ready line from its teed stdout;
    returns (host, port).  Raises TimeoutError when the line never
    lands (the spawn died before serving)."""
    import os

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(out_path):
            with open(out_path, encoding="utf-8",
                      errors="replace") as f:
                for line in f:
                    if marker in line:
                        addr = line.split(marker, 1)[1].split()[0]
                        host, port = addr.rsplit(":", 1)
                        return host, int(port)
        time.sleep(0.05)
    raise TimeoutError(f"no ready line in {out_path}")


# re-exported so chaos drivers need one import for the kill signal set
SIGKILL = _signal.SIGKILL
SIGTERM = _signal.SIGTERM
