"""Stamped-timestamp tracing for the host loop + device profiler hook.

The reference has no dedicated tracer; profiling is ad-hoc stopwatch
timestamps woven into the dataflow (SURVEY.md §5.1): ``DeserializeBolt``
stamps arrival time into each tuple (``AdvertisingTopologyNative.java:264,
273``), the windowed bolts capture per-window (receive, row->col, col->row)
stamps (``:316-353``), and per-window wall time is printed
(``:425-426``).  This module makes that idiom first-class: named
monotonic-clock spans per pipeline stage, aggregated into per-stage
totals/counts, cheap enough to leave on (two ``perf_counter_ns`` calls and
a dict update per span).

``device_trace`` wraps ``jax.profiler`` so a run can also capture an XLA
trace (TensorBoard format) of the device side — the TPU analog of the
reference's JVM GC logging (``META-INF/properties.xml:10-12``).
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field


@dataclass
class StageStats:
    calls: int = 0
    total_ns: int = 0
    max_ns: int = 0

    @property
    def total_ms(self) -> float:
        return self.total_ns / 1e6

    @property
    def mean_ms(self) -> float:
        return self.total_ns / 1e6 / max(self.calls, 1)


@dataclass
class Tracer:
    """Per-stage span accounting.  ``with tracer.span("encode"): ...``

    Thread-safe: the Redis flusher thread records ``redis_flush`` spans
    concurrently with the host loop's ``encode``/``device_step`` spans
    (and the telemetry sampler reads the table mid-run), so the
    ``StageStats`` read-modify-write happens under one lock.  The span
    overhead stays ~two ``perf_counter_ns`` calls plus the locked dict
    update — timing runs outside the lock.
    """

    stages: dict[str, StageStats] = field(default_factory=dict)
    enabled: bool = True
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    # Optional per-span forwarder ``sink(stage, start_ns, dur_ns)``
    # (obs.spans.SpanTracer wires itself here): None by default, so the
    # uninstrumented path pays one attribute check per span — the same
    # price as the lifecycle/flightrec hooks.  Called OUTSIDE the lock,
    # on the thread that ran the span (span tracing is thread-aware).
    sink: "object | None" = field(default=None, repr=False,
                                  compare=False)

    def _record(self, stage: str, duration_ns: int) -> None:
        with self._lock:
            st = self.stages.get(stage)
            if st is None:
                st = self.stages[stage] = StageStats()
            st.calls += 1
            st.total_ns += duration_ns
            st.max_ns = max(st.max_ns, duration_ns)

    @contextlib.contextmanager
    def span(self, stage: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            dur = time.perf_counter_ns() - t0
            self._record(stage, dur)
            if self.sink is not None:
                self.sink(stage, t0, dur)

    def add(self, stage: str, duration_ns: int) -> None:
        self._record(stage, duration_ns)

    def snapshot(self) -> dict[str, tuple[int, int, int]]:
        """Consistent ``{stage: (calls, total_ns, max_ns)}`` copy — the
        delta source for the telemetry sampler."""
        with self._lock:
            return {name: (st.calls, st.total_ns, st.max_ns)
                    for name, st in self.stages.items()}

    def report(self) -> str:
        snap = self.snapshot()
        if not snap:
            return "trace: no spans recorded"
        width = max(len(s) for s in snap)
        lines = ["trace (stage: calls total_ms mean_ms max_ms):"]
        for name, (calls, total_ns, max_ns) in sorted(
                snap.items(), key=lambda kv: -kv[1][1]):
            lines.append(
                f"  {name:<{width}}  {calls:>8}  {total_ns / 1e6:>10.1f}  "
                f"{total_ns / 1e6 / max(calls, 1):>8.3f}  "
                f"{max_ns / 1e6:>8.3f}")
        return "\n".join(lines)

    def as_dict(self) -> dict[str, dict[str, float]]:
        return {name: {"calls": calls, "total_ms": total_ns / 1e6,
                       "mean_ms": total_ns / 1e6 / max(calls, 1),
                       "max_ms": max_ns / 1e6}
                for name, (calls, total_ns, max_ns)
                in self.snapshot().items()}


@contextlib.contextmanager
def device_trace(logdir: str | None):
    """Capture a ``jax.profiler`` trace under ``logdir`` (no-op if None).

    Delegates to ``obs.capture.profiler_window`` — the ONE profiler
    start/stop path in the repo, shared with the triggered-capture
    manager (``jax.profiler`` is process-global; two entry points with
    their own state could double-start and crash the run).  If a
    triggered capture already owns the profiler, this trace is skipped
    rather than raised."""
    from streambench_tpu.obs.capture import profiler_window

    with profiler_window(logdir):
        yield
