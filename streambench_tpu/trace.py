"""Stamped-timestamp tracing for the host loop + device profiler hook.

The reference has no dedicated tracer; profiling is ad-hoc stopwatch
timestamps woven into the dataflow (SURVEY.md §5.1): ``DeserializeBolt``
stamps arrival time into each tuple (``AdvertisingTopologyNative.java:264,
273``), the windowed bolts capture per-window (receive, row->col, col->row)
stamps (``:316-353``), and per-window wall time is printed
(``:425-426``).  This module makes that idiom first-class: named
monotonic-clock spans per pipeline stage, aggregated into per-stage
totals/counts, cheap enough to leave on (two ``perf_counter_ns`` calls and
a dict update per span).

``device_trace`` wraps ``jax.profiler`` so a run can also capture an XLA
trace (TensorBoard format) of the device side — the TPU analog of the
reference's JVM GC logging (``META-INF/properties.xml:10-12``).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@dataclass
class StageStats:
    calls: int = 0
    total_ns: int = 0
    max_ns: int = 0

    @property
    def total_ms(self) -> float:
        return self.total_ns / 1e6

    @property
    def mean_ms(self) -> float:
        return self.total_ns / 1e6 / max(self.calls, 1)


@dataclass
class Tracer:
    """Per-stage span accounting.  ``with tracer.span("encode"): ...``"""

    stages: dict[str, StageStats] = field(default_factory=dict)
    enabled: bool = True

    @contextlib.contextmanager
    def span(self, stage: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            dt = time.perf_counter_ns() - t0
            st = self.stages.get(stage)
            if st is None:
                st = self.stages[stage] = StageStats()
            st.calls += 1
            st.total_ns += dt
            st.max_ns = max(st.max_ns, dt)

    def add(self, stage: str, duration_ns: int) -> None:
        st = self.stages.get(stage)
        if st is None:
            st = self.stages[stage] = StageStats()
        st.calls += 1
        st.total_ns += duration_ns
        st.max_ns = max(st.max_ns, duration_ns)

    def report(self) -> str:
        if not self.stages:
            return "trace: no spans recorded"
        width = max(len(s) for s in self.stages)
        lines = ["trace (stage: calls total_ms mean_ms max_ms):"]
        for name, st in sorted(self.stages.items(),
                               key=lambda kv: -kv[1].total_ns):
            lines.append(
                f"  {name:<{width}}  {st.calls:>8}  {st.total_ms:>10.1f}  "
                f"{st.mean_ms:>8.3f}  {st.max_ns / 1e6:>8.3f}")
        return "\n".join(lines)

    def as_dict(self) -> dict[str, dict[str, float]]:
        return {name: {"calls": st.calls, "total_ms": st.total_ms,
                       "mean_ms": st.mean_ms, "max_ms": st.max_ns / 1e6}
                for name, st in self.stages.items()}


@contextlib.contextmanager
def device_trace(logdir: str | None):
    """Capture a ``jax.profiler`` trace under ``logdir`` (no-op if None)."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
