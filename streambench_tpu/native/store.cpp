// Native in-process Redis-compatible store.
//
// The results sink of the reference is a real (C) redis-server
// (stream-bench.sh:180-187); the framework's in-process stand-in was pure
// Python, which put a ~1.4 us/row dict loop on the canonical window
// writeback (AdvertisingSpark.scala:184-208) — the largest host cost left
// in the catchup pipeline after the native encoder.  This store keeps the
// same command surface and RESP reply format (one implementation of reply
// encoding, shared by the in-process adapter and the TCP server), plus a
// bulk window-writeback entry point that performs the whole canonical
// schema update (probe -> create ids -> LPUSH -> HINCRBY/HSET) in native
// code at ~100 ns/row.
//
// Threading: one mutex per store; every entry point takes it.
// Replies: RESP2 bytes into a caller-owned buffer; when the buffer is too
// small the required size is returned as -(needed) and the caller retries.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_set>
#include <random>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

using std::string;
using std::string_view;

struct Reply {
  char* out;
  int64_t cap;
  int64_t len = 0;  // bytes needed (written only while len <= cap)

  void raw(const char* p, size_t n) {
    if (len + (int64_t)n <= cap) std::memcpy(out + len, p, n);
    len += (int64_t)n;
  }
  void lit(const char* s) { raw(s, std::strlen(s)); }
  void num(int64_t v) {
    char tmp[24];
    int n = std::snprintf(tmp, sizeof tmp, "%lld", (long long)v);
    raw(tmp, (size_t)n);
  }
  void integer(int64_t v) { lit(":"); num(v); lit("\r\n"); }
  void simple(const char* s) { lit("+"); lit(s); lit("\r\n"); }
  void nil() { lit("$-1\r\n"); }
  void bulk(string_view s) {
    lit("$");
    num((int64_t)s.size());
    lit("\r\n");
    raw(s.data(), s.size());
    lit("\r\n");
  }
  void error(const char* msg) { lit("-"); lit(msg); lit("\r\n"); }
  void array_header(int64_t n) { lit("*"); num(n); lit("\r\n"); }
};

// Transparent (heterogeneous) hashing: probes take string_view into
// caller buffers with no per-probe std::string allocation (same idiom as
// the encoder's interner).
struct SvHash {
  using is_transparent = void;
  // single overload: std::string and char literals convert to
  // string_view, and two overloads would make literal keys ambiguous
  size_t operator()(string_view sv) const {
    return std::hash<string_view>{}(sv);
  }
};
struct SvEq {
  using is_transparent = void;
  bool operator()(string_view a, string_view b) const { return a == b; }
};
#if defined(__cpp_lib_generic_unordered_lookup) && \
    __cpp_lib_generic_unordered_lookup >= 201811L
template <typename V>
using SvMap = std::unordered_map<string, V, SvHash, SvEq>;
using SvSet = std::unordered_set<string, SvHash, SvEq>;
#else
// Pre-C++20-library toolchains (GCC 10's libstdc++ has no heterogeneous
// unordered lookup): emulate find/count(string_view) with a key copy on
// the probe.  One short-string allocation per probe, identical
// semantics; newer toolchains keep the alloc-free path above.  The
// const char* overloads keep literal keys (e.g. find("windows"))
// unambiguous between the string and string_view conversions.
template <typename V>
struct SvMap : std::unordered_map<string, V, SvHash, SvEq> {
  using Base = std::unordered_map<string, V, SvHash, SvEq>;
  using Base::count;
  using Base::find;
  typename Base::iterator find(string_view k) {
    return Base::find(string(k));
  }
  typename Base::const_iterator find(string_view k) const {
    return Base::find(string(k));
  }
  typename Base::iterator find(const char* k) {
    return Base::find(string(k));
  }
  size_t count(string_view k) const { return Base::count(string(k)); }
};
struct SvSet : std::unordered_set<string, SvHash, SvEq> {
  using Base = std::unordered_set<string, SvHash, SvEq>;
  using Base::count;
  using Base::find;
  Base::iterator find(string_view k) { return Base::find(string(k)); }
  size_t count(string_view k) const { return Base::count(string(k)); }
};
#endif

// Specialized value for window hashes — every row the bulk writeback
// creates is exactly {seen_count: int, time_updated: ms-string}, and the
// generic two-node inner map costs ~3x as much to build.  Any write that
// doesn't fit this shape DEMOTES the entry into the generic `hashes` map
// (see demote_window), so the observable command surface is identical.
struct WinVal {
  int64_t seen;
  string updated;
};

inline bool parse_i64(string_view s, int64_t* out) {
  if (s.empty()) return false;
  int64_t v = 0;
  size_t i = 0;
  bool neg = s[0] == '-';
  if (neg) i = 1;
  if (i >= s.size()) return false;
  for (; i < s.size(); i++) {
    if (s[i] < '0' || s[i] > '9') return false;
    v = v * 10 + (s[i] - '0');
  }
  *out = neg ? -v : v;
  return true;
}

struct Store {
  SvMap<string> strings;
  SvMap<SvMap<string>> hashes;
  SvMap<WinVal> windows;  // hash-kind, specialized (see WinVal)
  SvMap<SvSet> sets;
  SvMap<std::deque<string>> lists;
  std::mutex mu;
  // native id generation for the bulk writeback
  char id_prefix[17];
  uint64_t id_counter = 0;

  Store() {
    // 4 hex chars: with the counter the id stays unique per store, and
    // the whole "%s-%010llx" id fits std::string's 15-char SSO buffer
    // — the bulk writeback otherwise pays two heap allocations per
    // fresh window row just for the id and its map-key copy.
    std::random_device rd;
    std::snprintf(id_prefix, sizeof id_prefix, "%04x", rd() & 0xffff);
  }

  // WRONGTYPE guard identical to the Python impl's _check_type.
  // `windows` is hash-kind: it conflicts with everything except hashes.
  template <typename Owner>
  bool wrongtype(string_view key, const Owner& owner) const {
    bool owner_is_hash = (const void*)&owner == (const void*)&hashes;
    if ((const void*)&owner != (const void*)&strings &&
        strings.count(key))
      return true;
    if (!owner_is_hash && hashes.count(key)) return true;
    if (!owner_is_hash && windows.count(key)) return true;
    if ((const void*)&owner != (const void*)&sets && sets.count(key))
      return true;
    if ((const void*)&owner != (const void*)&lists && lists.count(key))
      return true;
    return false;
  }

  // Move a specialized window entry into the generic hash map (an
  // off-schema write is about to land); returns the generic hash.
  SvMap<string>& demote_window(string_view key) {
    auto wit = windows.find(key);
    auto& h = hashes[string(key)];
    if (wit != windows.end()) {
      char tmp[24];
      int n = std::snprintf(tmp, sizeof tmp, "%lld",
                            (long long)wit->second.seen);
      h.emplace("seen_count", string(tmp, (size_t)n));
      h.emplace("time_updated", std::move(wit->second.updated));
      windows.erase(wit);
    }
    return h;
  }

  string fresh_id() {
    char buf[40];
    std::snprintf(buf, sizeof buf, "%s-%010llx", id_prefix,
                  (unsigned long long)id_counter++);
    return string(buf);
  }

  // One window row's value update, shared by both bulk writers: the
  // specialized WinVal path unless the uuid already lives as a generic
  // hash (created through the per-command path).
  void bump_window(const string& wuuid, int64_t count,
                   const string& stamp_s, bool absolute) {
    auto ghit = hashes.find(string_view(wuuid));
    if (ghit != hashes.end()) {
      auto& wh = ghit->second;
      char tmp[24];
      int tmp_len;
      auto sit = wh.find(string_view("seen_count"));
      if (absolute) {
        tmp_len = std::snprintf(tmp, sizeof tmp, "%lld", (long long)count);
      } else {
        int64_t cur = 0;
        if (sit != wh.end()) parse_i64(sit->second, &cur);
        cur += count;
        tmp_len = std::snprintf(tmp, sizeof tmp, "%lld", (long long)cur);
      }
      if (sit == wh.end())
        wh.emplace("seen_count", string(tmp, (size_t)tmp_len));
      else
        sit->second.assign(tmp, (size_t)tmp_len);
      auto uit = wh.find(string_view("time_updated"));
      if (uit == wh.end())
        wh.emplace("time_updated", stamp_s);
      else
        uit->second = stamp_s;
      return;
    }
    auto wvit = windows.find(string_view(wuuid));
    if (wvit == windows.end()) {
      windows.emplace(wuuid, WinVal{count, stamp_s});
    } else {
      if (absolute)
        wvit->second.seen = count;
      else
        wvit->second.seen += count;
      wvit->second.updated = stamp_s;
    }
  }
};

const char* kWrongType =
    "WRONGTYPE Operation against a key holding the wrong kind of value";

inline bool ieq(string_view a, const char* b) {
  size_t n = std::strlen(b);
  if (a.size() != n) return false;
  for (size_t i = 0; i < n; i++) {
    char c = a[i];
    if (c >= 'a' && c <= 'z') c = (char)(c - 32);
    if (c != b[i]) return false;
  }
  return true;
}


void run_cmd(Store& st, int32_t argc, string_view* a, Reply& r) {
  if (argc < 1) {
    r.error("ERR empty command");
    return;
  }
  string_view name = a[0];
  if (ieq(name, "PING")) {
    r.simple("PONG");
  } else if (ieq(name, "FLUSHALL")) {
    st.strings.clear();
    st.hashes.clear();
    st.windows.clear();
    st.sets.clear();
    st.lists.clear();
    r.simple("OK");
  } else if (ieq(name, "SET")) {
    if (argc != 3) return r.error("ERR wrong number of arguments for 'set'");
    string key(a[1]);
    if (st.wrongtype(key, st.strings)) return r.error(kWrongType);
    st.strings[key] = string(a[2]);
    r.simple("OK");
  } else if (ieq(name, "GET")) {
    if (argc != 2) return r.error("ERR wrong number of arguments for 'get'");
    string key(a[1]);
    if (st.wrongtype(key, st.strings)) return r.error(kWrongType);
    auto it = st.strings.find(key);
    if (it == st.strings.end()) return r.nil();
    r.bulk(it->second);
  } else if (ieq(name, "SADD")) {
    if (argc < 3) return r.error("ERR wrong number of arguments for 'sadd'");
    string key(a[1]);
    if (st.wrongtype(key, st.sets)) return r.error(kWrongType);
    auto& s = st.sets[key];
    int64_t added = 0;
    for (int32_t i = 2; i < argc; i++) {
      if (s.emplace(a[i]).second) added++;
    }
    r.integer(added);
  } else if (ieq(name, "SMEMBERS")) {
    if (argc != 2)
      return r.error("ERR wrong number of arguments for 'smembers'");
    string key(a[1]);
    if (st.wrongtype(key, st.sets)) return r.error(kWrongType);
    auto it = st.sets.find(key);
    std::vector<string> members;
    if (it != st.sets.end())
      members.assign(it->second.begin(), it->second.end());
    std::sort(members.begin(), members.end());  // Python impl sorts
    r.array_header((int64_t)members.size());
    for (const auto& m : members) r.bulk(m);
  } else if (ieq(name, "HSET")) {
    if (argc < 4 || (argc - 2) % 2)
      return r.error("ERR wrong number of arguments for 'hset'");
    string key(a[1]);
    if (st.wrongtype(key, st.hashes)) return r.error(kWrongType);
    // generic writes to a specialized window entry demote it first
    auto& h = st.windows.count(a[1]) ? st.demote_window(a[1])
                                     : st.hashes[key];
    int64_t added = 0;
    for (int32_t i = 2; i + 1 < argc; i += 2) {
      string f(a[i]);
      if (!h.count(f)) added++;
      h[std::move(f)] = string(a[i + 1]);
    }
    r.integer(added);
  } else if (ieq(name, "HGET")) {
    if (argc != 3) return r.error("ERR wrong number of arguments for 'hget'");
    string key(a[1]);
    if (st.wrongtype(key, st.hashes)) return r.error(kWrongType);
    auto wv = st.windows.find(a[1]);
    if (wv != st.windows.end()) {
      if (a[2] == string_view("seen_count")) {
        char tmp[24];
        int tl = std::snprintf(tmp, sizeof tmp, "%lld",
                               (long long)wv->second.seen);
        return r.bulk(string_view(tmp, (size_t)tl));
      }
      if (a[2] == string_view("time_updated"))
        return r.bulk(wv->second.updated);
      return r.nil();
    }
    auto it = st.hashes.find(key);
    if (it == st.hashes.end()) return r.nil();
    auto f = it->second.find(string(a[2]));
    if (f == it->second.end()) return r.nil();
    r.bulk(f->second);
  } else if (ieq(name, "HDEL")) {
    if (argc < 3) return r.error("ERR wrong number of arguments for 'hdel'");
    string key(a[1]);
    if (st.wrongtype(key, st.hashes)) return r.error(kWrongType);
    if (st.windows.count(a[1])) {
      bool touches_schema = false;
      for (int32_t i = 2; i < argc; i++)
        if (a[i] == string_view("seen_count") ||
            a[i] == string_view("time_updated"))
          touches_schema = true;
      // deleting only absent fields must not cost the specialization
      if (touches_schema) st.demote_window(a[1]);
    }
    auto it = st.hashes.find(key);
    int64_t removed = 0;
    if (it != st.hashes.end()) {
      for (int32_t i = 2; i < argc; i++) removed += it->second.erase(string(a[i]));
      if (it->second.empty()) st.hashes.erase(it);
    }
    r.integer(removed);
  } else if (ieq(name, "HGETALL")) {
    if (argc != 2)
      return r.error("ERR wrong number of arguments for 'hgetall'");
    string key(a[1]);
    if (st.wrongtype(key, st.hashes)) return r.error(kWrongType);
    auto wv = st.windows.find(a[1]);
    if (wv != st.windows.end()) {
      char tmp[24];
      int tl = std::snprintf(tmp, sizeof tmp, "%lld",
                             (long long)wv->second.seen);
      r.array_header(4);
      r.bulk("seen_count");
      r.bulk(string_view(tmp, (size_t)tl));
      r.bulk("time_updated");
      r.bulk(wv->second.updated);
      return;
    }
    auto it = st.hashes.find(key);
    if (it == st.hashes.end()) return r.array_header(0);
    r.array_header((int64_t)it->second.size() * 2);
    for (const auto& kv : it->second) {
      r.bulk(kv.first);
      r.bulk(kv.second);
    }
  } else if (ieq(name, "HINCRBY")) {
    if (argc != 4)
      return r.error("ERR wrong number of arguments for 'hincrby'");
    string key(a[1]);
    if (st.wrongtype(key, st.hashes)) return r.error(kWrongType);
    int64_t amount;
    if (!parse_i64(a[3], &amount))
      return r.error("ERR value is not an integer or out of range");
    auto wv = st.windows.find(a[1]);
    if (wv != st.windows.end()) {
      if (a[2] == string_view("seen_count")) {
        wv->second.seen += amount;
        return r.integer(wv->second.seen);
      }
      if (a[2] == string_view("time_updated")) {
        int64_t cur;
        if (!parse_i64(wv->second.updated, &cur))
          return r.error("ERR hash value is not an integer");
        cur += amount;
        char tmp[24];
        int tl = std::snprintf(tmp, sizeof tmp, "%lld", (long long)cur);
        wv->second.updated.assign(tmp, (size_t)tl);
        return r.integer(cur);
      }
      // off-schema field: fall back to a generic hash
      st.demote_window(a[1]);
    }
    auto& h = st.hashes[key];
    string f(a[2]);
    int64_t cur = 0;
    auto it = h.find(f);
    if (it != h.end() && !parse_i64(it->second, &cur))
      return r.error("ERR hash value is not an integer");
    cur += amount;
    char tmp[24];
    std::snprintf(tmp, sizeof tmp, "%lld", (long long)cur);
    h[std::move(f)] = tmp;
    r.integer(cur);
  } else if (ieq(name, "LPUSH")) {
    if (argc < 3) return r.error("ERR wrong number of arguments for 'lpush'");
    string key(a[1]);
    if (st.wrongtype(key, st.lists)) return r.error(kWrongType);
    auto& l = st.lists[key];
    for (int32_t i = 2; i < argc; i++) l.push_front(string(a[i]));
    r.integer((int64_t)l.size());
  } else if (ieq(name, "LLEN")) {
    if (argc != 2) return r.error("ERR wrong number of arguments for 'llen'");
    string key(a[1]);
    if (st.wrongtype(key, st.lists)) return r.error(kWrongType);
    auto it = st.lists.find(key);
    r.integer(it == st.lists.end() ? 0 : (int64_t)it->second.size());
  } else if (ieq(name, "LRANGE")) {
    if (argc != 4)
      return r.error("ERR wrong number of arguments for 'lrange'");
    string key(a[1]);
    if (st.wrongtype(key, st.lists)) return r.error(kWrongType);
    int64_t i, j;
    if (!parse_i64(a[2], &i) || !parse_i64(a[3], &j))
      return r.error("ERR value is not an integer or out of range");
    auto it = st.lists.find(key);
    int64_t n = it == st.lists.end() ? 0 : (int64_t)it->second.size();
    if (i < 0) i += n;
    if (j < 0) j += n;
    if (i < 0) i = 0;
    if (j > n - 1) j = n - 1;
    if (i > j || n == 0) return r.array_header(0);
    r.array_header(j - i + 1);
    for (int64_t k = i; k <= j; k++) r.bulk(it->second[(size_t)k]);
  } else {
    string msg = "ERR unknown command '" + string(name) + "'";
    r.error(msg.c_str());
  }
}

}  // namespace

extern "C" {

void* sbr_new() { return new Store(); }
void sbr_free(void* s) { delete static_cast<Store*>(s); }

// Execute one command; returns reply bytes written into out, or
// -(needed) when out_cap is too small.  On overflow the caller re-issues
// the command with a larger buffer — safe because every WRITE command
// has a small fixed-size reply (+OK / :N), so only read-only commands
// (SMEMBERS / HGETALL / LRANGE / GET / HGET) can ever overflow.
//
// That safety is enforced structurally, not assumed: a mutating command
// is refused (without executing) unless the buffer already has at least
// kMinMutatingCap bytes, so the overflow->re-issue path can only ever
// re-run read-only commands.  Any future write command whose reply could
// exceed kMinMutatingCap must raise the constant, and the invariant
// check below makes a violation loud instead of a silent double-apply.
constexpr int64_t kMinMutatingCap = 4096;

inline bool is_mutating(string_view name) {
  return ieq(name, "SET") || ieq(name, "SADD") || ieq(name, "HSET") ||
         ieq(name, "HDEL") || ieq(name, "HINCRBY") ||
         ieq(name, "LPUSH") || ieq(name, "FLUSHALL");
}

int64_t sbr_cmd(void* store, int32_t argc, const char** argv,
                const int64_t* lens, char* out, int64_t out_cap) {
  auto* st = static_cast<Store*>(store);
  std::vector<string_view> a((size_t)argc);
  for (int32_t i = 0; i < argc; i++)
    a[(size_t)i] = string_view(argv[i], (size_t)lens[i]);
  bool mutating = argc > 0 && is_mutating(a[0]);
  if (mutating && out_cap < kMinMutatingCap)
    return -kMinMutatingCap;  // refused BEFORE executing; retry is safe
  Reply r{out, out_cap};
  std::lock_guard<std::mutex> g(st->mu);
  run_cmd(*st, argc, a.data(), r);
  if (mutating && r.len > kMinMutatingCap) std::abort();  // invariant broken
  return r.len <= out_cap ? r.len : -r.len;
}

// Canonical window writeback (AdvertisingSpark.scala:184-208) for n rows
// of (campaign, window_ts, count), entirely in native code:
//   campaign hash probe -> create window/list ids on miss -> LPUSH ts ->
//   HINCRBY seen_count (or HSET when absolute) -> HSET time_updated.
// Blobs are concatenated strings described by offset arrays (n+1 each).
// Returns the number of rows applied.  A WRONGTYPE campaign key skips
// that row (matching the pipelined RESP path, where every command of the
// row errors in-list and the rest of the batch proceeds) — aborting
// mid-batch would make the caller's retained-batch retry double-apply
// the rows before the conflict.
int64_t sbr_write_windows(void* store, int64_t n, const char* camp_blob,
                          const int64_t* camp_off, const char* ts_blob,
                          const int64_t* ts_off, const int64_t* counts,
                          const char* stamp, int64_t stamp_len,
                          int32_t absolute) {
  auto* st = static_cast<Store*>(store);
  string stamp_s(stamp, (size_t)stamp_len);
  std::lock_guard<std::mutex> g(st->mu);
  int64_t applied = 0;
  for (int64_t i = 0; i < n; i++) {
    string camp(camp_blob + camp_off[i],
                (size_t)(camp_off[i + 1] - camp_off[i]));
    string wts(ts_blob + ts_off[i], (size_t)(ts_off[i + 1] - ts_off[i]));
    if (st->wrongtype(camp, st->hashes)) continue;
    // a campaign key sitting in `windows` (possible only if a caller
    // reuses a window uuid as a campaign name) must merge, not shadow
    if (st->windows.count(string_view(camp))) st->demote_window(camp);
    auto& ch = st->hashes[camp];
    auto wit = ch.find(wts);
    string wuuid;
    if (wit == ch.end()) {
      wuuid = st->fresh_id();
      string luuid;
      auto lit_ = ch.find("windows");
      if (lit_ == ch.end()) {
        luuid = st->fresh_id();
        ch["windows"] = luuid;
      } else {
        luuid = lit_->second;
      }
      ch[wts] = wuuid;
      st->lists[luuid].push_front(wts);
    } else {
      wuuid = wit->second;
    }
    st->bump_window(wuuid, counts[i], stamp_s, absolute != 0);
    applied++;
  }
  return applied;
}

// Index-form bulk writeback: campaign NAMES are passed once as a table
// (blob + offsets) and each row is (campaign_index, window_ts_ms, count)
// from plain int arrays — no per-row Python string handling anywhere.
// This is the engine flush path: its pending deltas already live as
// numpy (index, ts, count) triples.  Returns rows applied (WRONGTYPE
// campaign keys skip their rows, like sbr_write_windows), or -2 on an
// out-of-range campaign index (caller bug, not data state — abort).
int64_t sbr_write_windows_idx(void* store, int64_t n,
                              const char* names_blob,
                              const int64_t* names_off, int64_t n_names,
                              const int32_t* ci, const int64_t* ts,
                              const int64_t* counts, const char* stamp,
                              int64_t stamp_len, int32_t absolute) {
  auto* st = static_cast<Store*>(store);
  string stamp_s(stamp, (size_t)stamp_len);
  std::lock_guard<std::mutex> g(st->mu);
  // Mostly-fresh batches (the sliding family writes ~1 row per event at
  // slide granularity) otherwise rehash the window map a dozen times
  // mid-call; bucket reservation is cheap when rows are dup-heavy.
  st->windows.reserve(st->windows.size() + (size_t)n);
  // Per-campaign row counts in one O(n) int pass, so each campaign's
  // hash reserves its growth ONCE instead of rehashing ~15k-node maps
  // mid-stream (rows arrive campaign-grouped; measured ~15% of the
  // bulk write at sliding row volumes).
  std::vector<int64_t> per_campaign((size_t)n_names, 0);
  for (int64_t i = 0; i < n; i++) {
    if (ci[i] >= 0 && ci[i] < n_names) per_campaign[(size_t)ci[i]]++;
  }
  // Resolve each distinct campaign's hash once: rows arrive grouped by
  // drain order (np.nonzero is row-major over the campaign axis), so a
  // one-slot memo removes most outer-map lookups — including the
  // campaign's window LIST deque (deque + mapped-node references are
  // stable across later inserts).  All probes are transparent
  // string_view finds — std::string is constructed only on inserts.
  int32_t last_ci = -1;
  SvMap<string>* ch = nullptr;
  std::deque<string>* clist = nullptr;
  constexpr string_view kWindows = "windows";
  int64_t applied = 0;
  for (int64_t i = 0; i < n; i++) {
    int32_t c = ci[i];
    if (c < 0 || c >= n_names) return -2;
    if (c != last_ci) {
      string_view camp(names_blob + names_off[c],
                       (size_t)(names_off[c + 1] - names_off[c]));
      last_ci = c;
      if (st->wrongtype(camp, st->hashes)) {
        ch = nullptr;  // skip this campaign's rows (see sbr_write_windows)
        continue;
      }
      if (st->windows.count(camp)) st->demote_window(camp);
      auto hit = st->hashes.find(camp);
      if (hit == st->hashes.end())
        hit = st->hashes.emplace(string(camp), SvMap<string>()).first;
      ch = &hit->second;
      ch->reserve(ch->size() + (size_t)per_campaign[(size_t)c]);
      clist = nullptr;
    }
    if (ch == nullptr) continue;
    char wts_buf[24];
    int wts_len =
        std::snprintf(wts_buf, sizeof wts_buf, "%lld", (long long)ts[i]);
    string_view wts(wts_buf, (size_t)wts_len);
    auto wit = ch->find(wts);
    if (wit == ch->end()) {
      // Fresh window: register it (list entry + wts->uuid mapping) and
      // write its WinVal DIRECTLY — a just-minted uuid cannot already
      // exist in `hashes` or `windows`, so the two big-map probes
      // bump_window would pay are provably misses.
      if (clist == nullptr) {
        auto lit_ = ch->find(kWindows);
        if (lit_ == ch->end())
          lit_ = ch->emplace(string(kWindows), st->fresh_id()).first;
        clist = &st->lists[lit_->second];
      }
      clist->emplace_front(wts);
      const string& fresh =
          ch->emplace(string(wts), st->fresh_id()).first->second;
      st->windows.emplace(fresh, WinVal{counts[i], stamp_s});
    } else {
      st->bump_window(wit->second, counts[i], stamp_s, absolute != 0);
    }
    applied++;
  }
  return applied;
}

}  // extern "C"
