// Native event formatter: the load generator's hot loop.
//
// The reference's generator spends its per-event budget building a JSON
// string on the JVM (make-kafka-event-at, data/src/setup/core.clj:163-181).
// The Python peer (datagen/gen.py EventSource) does the same at ~3 us/event,
// which is fine on a many-core host but starves the co-located engine on a
// single-core one: the paced producer and the engine share that core, so
// every producer-side microsecond is stolen from the pipeline under test.
// This formatter renders the identical wire format at ~50 ns/event so the
// producer's share of the core rounds to zero.
//
// Plain C ABI (loaded via ctypes, same discipline as encoder.cpp): all
// buffers are caller-owned; the RNG state is caller-held and updated in
// place so successive calls continue one deterministic stream.

#include <cstdint>
#include <cstring>

namespace {

// splitmix64 — tiny, well-distributed, and stateless per step.  Chosen over
// reproducing Python's Mersenne Twister: the wire format carries no RNG
// contract (the oracle replays the journal), only the *distributions*
// matter (uniform id choice, the reference's skew odds, core.clj:166-174).
inline uint64_t next_u64(uint64_t &state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// Unbiased-enough bounded draw (128-bit multiply; bias < 2^-32 for the
// small bounds used here).
inline uint64_t bounded(uint64_t &state, uint64_t n) {
  return (uint64_t)(((__uint128_t)next_u64(state) * n) >> 64);
}

inline char *put(char *p, const char *s, size_t n) {
  std::memcpy(p, s, n);
  return p + n;
}

inline char *put_i64(char *p, int64_t v) {
  if (v < 0) {
    *p++ = '-';
    v = -v;
  }
  char tmp[20];
  int n = 0;
  do {
    tmp[n++] = (char)('0' + v % 10);
    v /= 10;
  } while (v);
  while (n) *p++ = tmp[--n];
  return p;
}

}  // namespace

extern "C" {

// Renders n_events wire-format ad events (newline-terminated JSON lines,
// field order and spacing identical to datagen/gen.py::EventSource) into
// `out`.  Ids are fixed-stride blobs (UUID strings are uniform width);
// ad/event types are concatenated variable-length strings described by a
// length array.  Returns bytes written, or -1 when out_cap could not hold
// the worst case (caller sizes via sb_format_events_cap).
int64_t sb_format_events(
    const char *users, int32_t user_len, int32_t n_users,
    const char *pages, int32_t page_len, int32_t n_pages,
    const char *ads, int32_t ad_len, int32_t n_ads,
    const char *ad_types, const int32_t *ad_type_len, int32_t n_ad_types,
    const char *ev_types, const int32_t *ev_type_len, int32_t n_ev_types,
    const int64_t *ts_ms, int64_t n_events,
    uint64_t *rng_state, int32_t with_skew,
    char *out, int64_t out_cap) {
  if (n_users <= 0 || n_pages <= 0 || n_ads <= 0 || n_ad_types <= 0 ||
      n_ev_types <= 0)
    return -1;
  // Precompute type-string offsets + the worst-case line length.
  int32_t ad_off[65], ev_off[65];  // prefix sums write index n_types
  if (n_ad_types > 64 || n_ev_types > 64) return -1;
  int32_t max_ad_t = 0, max_ev_t = 0;
  ad_off[0] = 0;
  for (int i = 0; i < n_ad_types; i++) {
    ad_off[i + 1] = ad_off[i] + ad_type_len[i];
    if (ad_type_len[i] > max_ad_t) max_ad_t = ad_type_len[i];
  }
  ev_off[0] = 0;
  for (int i = 0; i < n_ev_types; i++) {
    ev_off[i + 1] = ev_off[i] + ev_type_len[i];
    if (ev_type_len[i] > max_ev_t) max_ev_t = ev_type_len[i];
  }
  static const char k_user[] = "{\"user_id\": \"";
  static const char k_page[] = "\", \"page_id\": \"";
  static const char k_ad[] = "\", \"ad_id\": \"";
  static const char k_adt[] = "\", \"ad_type\": \"";
  static const char k_evt[] = "\", \"event_type\": \"";
  static const char k_time[] = "\", \"event_time\": \"";
  static const char k_tail[] = "\", \"ip_address\": \"1.2.3.4\"}\n";
  const int64_t fixed = (sizeof(k_user) - 1) + (sizeof(k_page) - 1) +
                        (sizeof(k_ad) - 1) + (sizeof(k_adt) - 1) +
                        (sizeof(k_evt) - 1) + (sizeof(k_time) - 1) +
                        (sizeof(k_tail) - 1);
  const int64_t worst =
      fixed + user_len + page_len + ad_len + max_ad_t + max_ev_t + 21;
  if (n_events * worst > out_cap) return -1;

  uint64_t st = *rng_state;
  char *p = out;
  for (int64_t i = 0; i < n_events; i++) {
    int64_t t = ts_ms[i];
    if (with_skew) {
      // +-50 ms skew; 1/100,000 events late by up to 60 s (core.clj:166-174)
      t += 50 - (int64_t)bounded(st, 100);
      if (bounded(st, 100000) == 0) t -= (int64_t)bounded(st, 60000);
    }
    p = put(p, k_user, sizeof(k_user) - 1);
    p = put(p, users + bounded(st, n_users) * user_len, user_len);
    p = put(p, k_page, sizeof(k_page) - 1);
    p = put(p, pages + bounded(st, n_pages) * page_len, page_len);
    p = put(p, k_ad, sizeof(k_ad) - 1);
    p = put(p, ads + bounded(st, n_ads) * ad_len, ad_len);
    p = put(p, k_adt, sizeof(k_adt) - 1);
    uint64_t a = bounded(st, n_ad_types);
    p = put(p, ad_types + ad_off[a], ad_type_len[a]);
    p = put(p, k_evt, sizeof(k_evt) - 1);
    uint64_t e = bounded(st, n_ev_types);
    p = put(p, ev_types + ev_off[e], ev_type_len[e]);
    p = put(p, k_time, sizeof(k_time) - 1);
    p = put_i64(p, t);
    p = put(p, k_tail, sizeof(k_tail) - 1);
  }
  *rng_state = st;
  return p - out;
}

// Worst-case output bytes per event for the given id/type geometry, so the
// caller can size `out` exactly once.
int64_t sb_format_events_cap(int32_t user_len, int32_t page_len,
                             int32_t ad_len, const int32_t *ad_type_len,
                             int32_t n_ad_types, const int32_t *ev_type_len,
                             int32_t n_ev_types) {
  int32_t max_ad_t = 0, max_ev_t = 0;
  for (int i = 0; i < n_ad_types; i++)
    if (ad_type_len[i] > max_ad_t) max_ad_t = ad_type_len[i];
  for (int i = 0; i < n_ev_types; i++)
    if (ev_type_len[i] > max_ev_t) max_ev_t = ev_type_len[i];
  return 128 + user_len + page_len + ad_len + max_ad_t + max_ev_t + 21;
}

}  // extern "C"
