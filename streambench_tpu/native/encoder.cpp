// Native columnar event encoder: the host-side deserialize stage at line rate.
//
// TPU-native peer of the JVM engines' deserialize bolts
// (storm-benchmarks/.../AdvertisingTopology.java:44-70): parses the
// generator's fixed-field-order JSON wire format
// (make-kafka-event-at, data/src/setup/core.clj:175-181) straight into
// int32 column buffers that the XLA window step gathers/scatters on.
// Strings (ad/user/page UUIDs) are interned to dense indices here, in C++,
// so nothing string-shaped crosses into Python or onto the device.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
// Lines whose layout the fast scan rejects get status=2 and are re-parsed
// by the Python json.loads fallback; hard-bad lines get status=0.

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

// Transparent (heterogeneous) hashing: lets the hot loop probe the maps
// with a string_view into the input buffer — NO std::string temporary,
// no heap allocation per row (C++20 unordered heterogeneous lookup).
struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view sv) const {
    return std::hash<std::string_view>{}(sv);
  }
  size_t operator()(const std::string& s) const {
    return std::hash<std::string_view>{}(std::string_view(s));
  }
};
struct SvEq {
  using is_transparent = void;
  bool operator()(std::string_view a, std::string_view b) const {
    return a == b;
  }
};
#if defined(__cpp_lib_generic_unordered_lookup) && \
    __cpp_lib_generic_unordered_lookup >= 201811L
using SvMap = std::unordered_map<std::string, int32_t, SvHash, SvEq>;
#else
// Pre-C++20-library toolchains (GCC 10's libstdc++ has no heterogeneous
// unordered lookup): emulate find(string_view) with a key copy on the
// probe.  The hit path pays one short-string allocation; semantics are
// identical, and newer toolchains keep the alloc-free path above.
struct SvMap : std::unordered_map<std::string, int32_t, SvHash, SvEq> {
  using Base = std::unordered_map<std::string, int32_t, SvHash, SvEq>;
  using Base::find;
  Base::iterator find(std::string_view k) {
    return Base::find(std::string(k));
  }
  Base::const_iterator find(std::string_view k) const {
    return Base::find(std::string(k));
  }
};
#endif

struct StringInterner {
  SvMap map;
  int32_t next = 0;

  int32_t intern(const char* s, size_t len) {
    std::string_view sv(s, len);
    auto it = map.find(sv);           // no alloc on the hit path
    if (it != map.end()) return it->second;
    auto r = map.emplace(std::string(sv), next);
    ++next;
    return r.first->second;
  }

  // Total key bytes (for sizing a dump buffer).
  int64_t total_bytes() const {
    int64_t n = 0;
    for (const auto& kv : map) n += static_cast<int64_t>(kv.first.size());
    return n;
  }

  // Write keys concatenated in INDEX ORDER into buf; offsets[next+1]
  // gets the cumulative byte offsets.  Index order is what lets a
  // restore re-intern the keys and land on identical indices — the
  // checkpoint/resume contract for sketch state keyed by interned ids.
  void dump(char* buf, int64_t* offsets) const {
    std::vector<const std::string*> by_idx(static_cast<size_t>(next));
    for (const auto& kv : map) by_idx[static_cast<size_t>(kv.second)] =
        &kv.first;
    int64_t off = 0;
    offsets[0] = 0;
    for (int32_t i = 0; i < next; ++i) {
      const std::string& s = *by_idx[static_cast<size_t>(i)];
      std::memcpy(buf + off, s.data(), s.size());
      off += static_cast<int64_t>(s.size());
      offsets[i + 1] = off;
    }
  }
};

// "Unset" sentinel for base_time_ms.  NOT -1 / "< 0": a legitimately
// negative base is routine for small synthetic event times (base =
// t - t%divisor - lateness goes negative whenever t < divisor+lateness),
// and conflating it with "unset" silently re-rebased every batch.
constexpr int64_t kBaseUnset = INT64_MIN;

// Stateless 32-bit id hash: standard CRC-32 (IEEE reflected), chosen to
// be bit-identical to Python's zlib.crc32 — the differential tests pin
// the two implementations against each other.
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
static const Crc32Table kCrc;

static inline int32_t crc32b(const char* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    c = kCrc.t[(c ^ (uint8_t)p[i]) & 0xFF] ^ (c >> 8);
  return (int32_t)(c ^ 0xFFFFFFFFu);
}

struct Encoder {
  SvMap ad_index;
  StringInterner users;
  StringInterner pages;
  // When false, user/page ids are NOT interned (columns get 0): the
  // exact-count kernels never read them, and the two hash probes per
  // row are the single largest per-event cost after tokenization.
  bool intern_ids = true;
  // When true (wins over intern_ids), user/page columns carry crc32 of
  // the id bytes: stateless, so independent encoders (pool workers,
  // micro-batch partitions) and restarted processes agree without any
  // intern-table snapshot.  For hash-consuming kernels (HLL) only.
  bool hash_ids = false;
  int64_t base_time_ms = kBaseUnset;
  int64_t divisor_ms = 10000;
  int64_t lateness_ms = 60000;
  int32_t unknown_ad = 0;
  // Adaptive value-length hints for the skeleton fast path (ids are
  // fixed-width UUIDs in practice; learned from the first line so other
  // id shapes still get the one-probe hit path).
  size_t hint_user = 36, hint_page = 36, hint_ad = 36;
};

// token positions when splitting the generator's line on '"':
//  1:user_id 3:<u> 5:page_id 7:<p> 9:ad_id 11:<ad> 13:ad_type 15:<at>
// 17:event_type 19:<et> 21:event_time 23:<t>
struct Tok {
  const char* p;
  size_t len;
};

inline bool tok_eq(const Tok& t, const char* lit, size_t n) {
  return t.len == n && std::memcmp(t.p, lit, n) == 0;
}

// ad_type table (encode/encoder.py AD_TYPES) and event_type table
// (EVENT_TYPES); event "view" == 0 is the device-side filter constant.
inline int32_t ad_type_code(const Tok& t) {
  switch (t.len) {
    case 6:
      if (tok_eq(t, "banner", 6)) return 0;
      if (tok_eq(t, "mobile", 6)) return 4;
      return -1;
    case 5:  return tok_eq(t, "modal", 5) ? 1 : -1;
    case 16: return tok_eq(t, "sponsored-search", 16) ? 2 : -1;
    case 4:  return tok_eq(t, "mail", 4) ? 3 : -1;
    default: return -1;
  }
}

inline int32_t event_type_code(const Tok& t) {
  if (tok_eq(t, "view", 4)) return 0;
  if (tok_eq(t, "click", 5)) return 1;
  if (tok_eq(t, "purchase", 8)) return 2;
  return -1;
}

}  // namespace

namespace {

// --- skeleton fast path -------------------------------------------------
// The generator renders one fixed skeleton (gen.cpp / EventSource.event_at):
//   {"user_id": "U", "page_id": "P", ..., "event_time": "T", ...
// so instead of tokenizing on quotes we memcmp the literal skeleton and
// probe each value's closing quote at its learned length (one branch per
// value instead of a memchr).  Any mismatch falls back to the quote-token
// parser below, which tolerates arbitrary spacing.

inline bool rel_time_fits(int64_t t, int64_t base) {
  const int64_t rel = t - base;
  return rel >= INT32_MIN && rel <= INT32_MAX;
}

inline bool skel(const char*& p, const char* end, const char* lit,
                 size_t n) {
  if (static_cast<size_t>(end - p) < n || std::memcmp(p, lit, n) != 0)
    return false;
  p += n;
  return true;
}

inline bool skel_value(const char*& p, const char* end, size_t& hint,
                       Tok& out) {
  if (hint > 0 && p + hint < end && p[hint] == '"') {
    out.p = p;
    out.len = hint;
    p += hint + 1;
    return true;
  }
  const char* q = static_cast<const char*>(
      std::memchr(p, '"', static_cast<size_t>(end - p)));
  if (q == nullptr) return false;
  out.p = p;
  out.len = static_cast<size_t>(q - p);
  hint = out.len;
  p = q + 1;
  return true;
}

// Returns 1 on success (row i filled, status 1); 0 = not this layout
// (caller tries the tolerant parser; nothing written).
inline int parse_skeleton(Encoder* enc, const char* p, const char* end,
                          int64_t i, int32_t* ad_idx, int32_t* etype,
                          int32_t* etime, int32_t* user_idx,
                          int32_t* page_idx, int32_t* ad_type,
                          uint8_t* status) {
  Tok user, page, ad, at, et;
  if (!skel(p, end, "{\"user_id\": \"", 13) ||
      !skel_value(p, end, enc->hint_user, user) ||
      !skel(p, end, ", \"page_id\": \"", 14) ||
      !skel_value(p, end, enc->hint_page, page) ||
      !skel(p, end, ", \"ad_id\": \"", 12) ||
      !skel_value(p, end, enc->hint_ad, ad))
    return 0;
  // type values vary per event, so no stable length hint: the throwaway
  // hint makes skel_value a plain closing-quote memchr
  size_t no_hint = 0;
  if (!skel(p, end, ", \"ad_type\": \"", 14) ||
      !skel_value(p, end, (no_hint = 0), at))
    return 0;
  if (!skel(p, end, ", \"event_type\": \"", 17) ||
      !skel_value(p, end, (no_hint = 0), et))
    return 0;
  if (!skel(p, end, ", \"event_time\": \"", 17)) return 0;
  int64_t t = 0;
  size_t nd = 0;
  while (p + nd < end && nd < 15) {  // same 15-digit cap as parse_tokens
    char c = p[nd];
    if (c == '"') break;
    if (c < '0' || c > '9') return 0;
    t = t * 10 + (c - '0');
    ++nd;
  }
  if (nd == 0 || p + nd >= end || p[nd] != '"') return 0;
  p += nd + 1;
  // Tail check: a truncated record must fall through to the tolerant
  // parser (whose 24-token requirement rejects it to the Python
  // fallback), not be silently accepted as a valid event.
  if (!skel(p, end, ", \"ip_address\"", 14)) return 0;

  if (enc->base_time_ms == kBaseUnset) {
    enc->base_time_ms = t - (t % enc->divisor_ms) - enc->lateness_ms;
  }
  if (!rel_time_fits(t, enc->base_time_ms)) {
    status[i] = 2;  // python fallback re-applies the int32-fit rule and
    return 0;       // rejects — never a silent int32 wrap
  }
  auto ad_it = enc->ad_index.find(std::string_view(ad.p, ad.len));
  ad_idx[i] = ad_it == enc->ad_index.end() ? enc->unknown_ad
                                           : ad_it->second;
  etype[i] = event_type_code(et);
  etime[i] = static_cast<int32_t>(t - enc->base_time_ms);
  if (enc->hash_ids) {
    user_idx[i] = crc32b(user.p, user.len);
    page_idx[i] = crc32b(page.p, page.len);
  } else if (enc->intern_ids) {
    user_idx[i] = enc->users.intern(user.p, user.len);
    page_idx[i] = enc->pages.intern(page.p, page.len);
  } else {
    user_idx[i] = 0;
    page_idx[i] = 0;
  }
  ad_type[i] = ad_type_code(at);
  status[i] = 1;
  return 1;
}

// Parse one wire-format line [p, end) into row i of the column buffers.
// status[i]: 1 = parsed, 2 = layout mismatch (python fallback), 0 = bad.
// Returns 1 on success, 0 otherwise.
inline int parse_tokens(Encoder* enc, const char* p, const char* end,
                        int64_t i, int32_t* ad_idx, int32_t* etype,
                        int32_t* etime, int32_t* user_idx, int32_t* page_idx,
                        int32_t* ad_type, uint8_t* status) {
  // split on '"' into the first 24 tokens (memchr: SIMD-accelerated)
  Tok toks[24];
  int nt = 0;
  const char* start = p;
  while (nt < 24) {
    const char* q = static_cast<const char*>(
        std::memchr(start, '"', static_cast<size_t>(end - start)));
    if (q == nullptr) break;
    toks[nt].p = start;
    toks[nt].len = static_cast<size_t>(q - start);
    ++nt;
    start = q + 1;
  }
  if (nt < 24 || !tok_eq(toks[1], "user_id", 7) ||
      !tok_eq(toks[5], "page_id", 7) || !tok_eq(toks[9], "ad_id", 5) ||
      !tok_eq(toks[13], "ad_type", 7) ||
      !tok_eq(toks[17], "event_type", 10) ||
      !tok_eq(toks[21], "event_time", 10)) {
    status[i] = 2;
    return 0;
  }
  // event_time digits
  int64_t t = 0;
  bool tok_ok = toks[23].len > 0 && toks[23].len <= 15;
  if (tok_ok) {
    for (size_t k = 0; k < toks[23].len; ++k) {
      char c = toks[23].p[k];
      if (c < '0' || c > '9') { tok_ok = false; break; }
      t = t * 10 + (c - '0');
    }
  }
  if (!tok_ok) {
    status[i] = 2;
    return 0;
  }
  if (enc->base_time_ms == kBaseUnset) {
    enc->base_time_ms = t - (t % enc->divisor_ms) - enc->lateness_ms;
  }
  if (!rel_time_fits(t, enc->base_time_ms)) {
    status[i] = 2;
    return 0;
  }
  auto ad_it = enc->ad_index.find(std::string_view(toks[11].p,
                                                   toks[11].len));
  ad_idx[i] = ad_it == enc->ad_index.end() ? enc->unknown_ad
                                           : ad_it->second;
  etype[i] = event_type_code(toks[19]);
  etime[i] = static_cast<int32_t>(t - enc->base_time_ms);
  if (enc->hash_ids) {
    user_idx[i] = crc32b(toks[3].p, toks[3].len);
    page_idx[i] = crc32b(toks[7].p, toks[7].len);
  } else if (enc->intern_ids) {
    user_idx[i] = enc->users.intern(toks[3].p, toks[3].len);
    page_idx[i] = enc->pages.intern(toks[7].p, toks[7].len);
  } else {
    user_idx[i] = 0;
    page_idx[i] = 0;
  }
  ad_type[i] = ad_type_code(toks[15]);
  status[i] = 1;
  return 1;
}

inline int parse_one(Encoder* enc, const char* p, const char* end,
                     int64_t i, int32_t* ad_idx, int32_t* etype,
                     int32_t* etime, int32_t* user_idx, int32_t* page_idx,
                     int32_t* ad_type, uint8_t* status) {
  if (parse_skeleton(enc, p, end, i, ad_idx, etype, etime, user_idx,
                     page_idx, ad_type, status))
    return 1;
  return parse_tokens(enc, p, end, i, ad_idx, etype, etime, user_idx,
                      page_idx, ad_type, status);
}

}  // namespace

extern "C" {

void* sb_encoder_new(const char* ads_buf, const int64_t* ad_offsets,
                     int32_t n_ads, int64_t divisor_ms, int64_t lateness_ms) {
  auto* e = new Encoder();
  e->ad_index.reserve(static_cast<size_t>(n_ads) * 2);
  for (int32_t i = 0; i < n_ads; ++i) {
    const char* s = ads_buf + ad_offsets[i];
    size_t len = static_cast<size_t>(ad_offsets[i + 1] - ad_offsets[i]);
    e->ad_index.emplace(std::string(s, len), i);
  }
  e->unknown_ad = n_ads;
  e->divisor_ms = divisor_ms;
  e->lateness_ms = lateness_ms;
  return e;
}

void sb_encoder_free(void* enc) { delete static_cast<Encoder*>(enc); }

int64_t sb_encoder_base_time(void* enc) {
  return static_cast<Encoder*>(enc)->base_time_ms;
}

void sb_encoder_set_base_time(void* enc, int64_t base) {
  static_cast<Encoder*>(enc)->base_time_ms = base;
}

// 0 disables user/page interning (columns become 0) for engines whose
// kernels never read those columns; 1 (default) re-enables it.
void sb_encoder_set_intern_ids(void* enc, int32_t on) {
  static_cast<Encoder*>(enc)->intern_ids = on != 0;
}

// 1 switches user/page columns to stateless crc32 of the id bytes
// (consistent across encoders/restarts; for hash-consuming kernels).
void sb_encoder_set_hash_ids(void* enc, int32_t on) {
  static_cast<Encoder*>(enc)->hash_ids = on != 0;
}

int64_t sb_encoder_n_users(void* enc) {
  return static_cast<Encoder*>(enc)->users.next;
}

int64_t sb_encoder_n_pages(void* enc) {
  return static_cast<Encoder*>(enc)->pages.next;
}

int64_t sb_encoder_users_bytes(void* enc) {
  return static_cast<Encoder*>(enc)->users.total_bytes();
}

int64_t sb_encoder_pages_bytes(void* enc) {
  return static_cast<Encoder*>(enc)->pages.total_bytes();
}

// Dump intern tables in index order (see StringInterner::dump): buf must
// hold *_bytes() bytes, offsets must hold n_*+1 int64s.
void sb_encoder_dump_users(void* enc, char* buf, int64_t* offsets) {
  static_cast<Encoder*>(enc)->users.dump(buf, offsets);
}

void sb_encoder_dump_pages(void* enc, char* buf, int64_t* offsets) {
  static_cast<Encoder*>(enc)->pages.dump(buf, offsets);
}

// Intern one id through the same maps the fast path uses, so Python
// fallback-parsed lines stay index-consistent.
int32_t sb_intern_user(void* enc, const char* s, int64_t len) {
  return static_cast<Encoder*>(enc)->users.intern(
      s, static_cast<size_t>(len));
}

int32_t sb_intern_page(void* enc, const char* s, int64_t len) {
  return static_cast<Encoder*>(enc)->pages.intern(
      s, static_cast<size_t>(len));
}

// Parse n_lines lines (buf + line_offsets, offsets[n] = end) into columns.
// status[i]: 1 = parsed, 2 = layout mismatch (python fallback), 0 = bad.
// Returns the number of status==1 rows.
int64_t sb_encode_json(void* enc_, const char* buf,
                       const int64_t* line_offsets, int32_t n_lines,
                       int32_t* ad_idx, int32_t* etype, int32_t* etime,
                       int32_t* user_idx, int32_t* page_idx,
                       int32_t* ad_type, uint8_t* status) {
  auto* enc = static_cast<Encoder*>(enc_);
  int64_t ok = 0;
  for (int32_t i = 0; i < n_lines; ++i) {
    ok += parse_one(enc, buf + line_offsets[i], buf + line_offsets[i + 1],
                    i, ad_idx, etype, etime, user_idx, page_idx, ad_type,
                    status);
  }
  return ok;
}

// Scan up to max_records NEWLINE-DELIMITED records straight out of a raw
// journal block and parse them in the same pass — no per-line buffers or
// offset arrays cross the FFI (the fork's mmap'd columnar handoff taken
// to its conclusion: bytes in, columns out).  Scanning starts at
// buf[start]; rec_offsets[i] records each record's start (for the rare
// Python fallback on layout-mismatch rows) and rec_offsets[n] the total
// consumed length, excluding any incomplete trailing record.
int64_t sb_encode_block(void* enc_, const char* buf, int64_t len,
                        int64_t start, int64_t max_records,
                        int32_t* ad_idx, int32_t* etype, int32_t* etime,
                        int32_t* user_idx, int32_t* page_idx,
                        int32_t* ad_type, uint8_t* status,
                        int64_t* rec_offsets) {
  auto* enc = static_cast<Encoder*>(enc_);
  int64_t n = 0;
  int64_t pos = start;
  while (n < max_records && pos < len) {
    const char* nl = static_cast<const char*>(
        std::memchr(buf + pos, '\n', static_cast<size_t>(len - pos)));
    if (nl == nullptr) break;  // incomplete trailing record: not consumed
    rec_offsets[n] = pos;
    parse_one(enc, buf + pos, nl, n, ad_idx, etype, etime, user_idx,
              page_idx, ad_type, status);
    pos = (nl - buf) + 1;
    ++n;
  }
  rec_offsets[n] = pos;
  return n;
}

// Device-decode probe (ops/devdecode.py): scan newline-delimited records
// and VALIDATE the generator's fixed byte layout without building any
// columns — the decode itself (field extraction, ad join, window fold)
// happens inside the jitted device step.  A record passes (ok=1) iff
// every byte the device kernel will read sits exactly where the fixed
// schema puts it:
//
//   {"user_id": "<36>", "page_id": "<36>", "ad_id": "<36>",
//    "ad_type": "<1..n, no quotes>", "event_type": "<view|click|purchase>",
//    "event_time": "<exactly 13 digits>", "ip_address": "1.2.3.4"}
//
// head literals are anchored at the record START (uuid fields are
// quote-free, so their 36-byte spans cannot hide early terminators the
// host's token parser would split on), tail literals at the record END.
// Rows that fail go back to the host encoder verbatim, which keeps
// bad-line counting and dead-letter behavior identical to the host
// arms.  times[i] holds the parsed absolute ms stamp for ok rows (the
// span-guard/watermark input the host loop needs before dispatching).
int64_t sb_probe_block(const char* buf, int64_t len, int64_t start,
                       int32_t max_rows, int32_t* starts, int32_t* lens,
                       int64_t* times, uint8_t* ok) {
  static const char kHead[] = "{\"user_id\": \"";          // 13 @ 0
  static const char kPage[] = "\", \"page_id\": \"";       // 15 @ 49
  static const char kAd[] = "\", \"ad_id\": \"";           // 13 @ 100
  static const char kAdType[] = "\", \"ad_type\": \"";     // 15 @ 149
  static const char kTime[] = "\", \"event_time\": \"";    // 18 @ L-58
  static const char kSuffix[] = "\", \"ip_address\": \"1.2.3.4\"}";  // 27
  static const char kView[] = "\", \"event_type\": \"view";        // 22
  static const char kClick[] = "\", \"event_type\": \"click";      // 23
  static const char kPurchase[] = "\", \"event_type\": \"purchase";  // 26
  int64_t n = 0;
  int64_t pos = start;
  while (n < max_rows && pos < len) {
    const char* nl = static_cast<const char*>(
        std::memchr(buf + pos, '\n', static_cast<size_t>(len - pos)));
    if (nl == nullptr) break;  // incomplete trailing record: not consumed
    const char* p = buf + pos;
    const int64_t L = nl - p;
    starts[n] = static_cast<int32_t>(pos);
    lens[n] = static_cast<int32_t>(L);
    int good = 0;
    int64_t t = 0;
    // 245 = 164-byte fixed head + 1-byte ad_type floor + 80-byte fixed
    // tail (event_type "view" is the shortest).
    if (L >= 245 && std::memcmp(p, kHead, 13) == 0 &&
        std::memchr(p + 13, '"', 36) == nullptr &&
        std::memcmp(p + 49, kPage, 15) == 0 &&
        std::memchr(p + 64, '"', 36) == nullptr &&
        std::memcmp(p + 100, kAd, 13) == 0 &&
        std::memchr(p + 113, '"', 36) == nullptr &&
        std::memcmp(p + 149, kAdType, 15) == 0 &&
        std::memcmp(p + L - 27, kSuffix, 27) == 0 &&
        std::memcmp(p + L - 58, kTime, 18) == 0) {
      good = 1;
      for (int k = 0; k < 13; ++k) {
        char c = p[L - 40 + k];
        if (c < '0' || c > '9') { good = 0; break; }
        t = t * 10 + (c - '0');
      }
      if (good) {
        int64_t et_len;
        if (std::memcmp(p + L - 80, kView, 22) == 0) et_len = 4;
        else if (std::memcmp(p + L - 81, kClick, 23) == 0) et_len = 5;
        else if (std::memcmp(p + L - 84, kPurchase, 26) == 0) et_len = 8;
        else et_len = -1;
        // ad_type value: whatever sits between the fixed head and the
        // event_type literal; must be non-empty and quote-free or the
        // host token parser would see a different structure.
        const int64_t at_len = L - 240 - et_len;
        good = (et_len > 0 && at_len >= 1 &&
                std::memchr(p + 164, '"',
                            static_cast<size_t>(at_len)) == nullptr)
                   ? 1
                   : 0;
      }
    }
    ok[n] = static_cast<uint8_t>(good);
    times[n] = good ? t : 0;
    pos = (nl - buf) + 1;
    ++n;
  }
  return n;
}

}  // extern "C"
