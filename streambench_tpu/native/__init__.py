"""ctypes binding for the native encoder, with build-on-demand.

``load()`` returns the shared library handle or None (no toolchain / build
failure) — callers fall back to the pure-Python encoder.  The .so is built
next to this file by ``make`` on first use; pybind11 isn't in this image,
so the ABI is plain C and all buffers are numpy arrays passed by pointer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libsbnative.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_i64 = ctypes.c_int64
    c_p = ctypes.c_void_p
    lib.sb_encoder_new.restype = c_p
    lib.sb_encoder_new.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(c_i64), ctypes.c_int32, c_i64, c_i64]
    lib.sb_encoder_free.argtypes = [c_p]
    lib.sb_encoder_base_time.restype = c_i64
    lib.sb_encoder_base_time.argtypes = [c_p]
    lib.sb_encoder_set_base_time.argtypes = [c_p, c_i64]
    lib.sb_encoder_set_intern_ids.argtypes = [c_p, ctypes.c_int32]
    lib.sb_encoder_set_hash_ids.argtypes = [c_p, ctypes.c_int32]
    lib.sb_encoder_n_users.restype = c_i64
    lib.sb_encoder_n_users.argtypes = [c_p]
    lib.sb_encoder_n_pages.restype = c_i64
    lib.sb_encoder_n_pages.argtypes = [c_p]
    lib.sb_encoder_users_bytes.restype = c_i64
    lib.sb_encoder_users_bytes.argtypes = [c_p]
    lib.sb_encoder_pages_bytes.restype = c_i64
    lib.sb_encoder_pages_bytes.argtypes = [c_p]
    lib.sb_encoder_dump_users.argtypes = [
        c_p, ctypes.c_char_p, ctypes.POINTER(c_i64)]
    lib.sb_encoder_dump_pages.argtypes = [
        c_p, ctypes.c_char_p, ctypes.POINTER(c_i64)]
    lib.sb_intern_user.restype = ctypes.c_int32
    lib.sb_intern_user.argtypes = [c_p, ctypes.c_char_p, c_i64]
    lib.sb_intern_page.restype = ctypes.c_int32
    lib.sb_intern_page.argtypes = [c_p, ctypes.c_char_p, c_i64]
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.sb_encode_json.restype = c_i64
    lib.sb_encode_json.argtypes = [
        c_p, ctypes.c_char_p, ctypes.POINTER(c_i64), ctypes.c_int32,
        i32p, i32p, i32p, i32p, i32p, i32p,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.sb_encode_block.restype = c_i64
    lib.sb_encode_block.argtypes = [
        c_p, ctypes.c_char_p, c_i64, c_i64, c_i64,
        i32p, i32p, i32p, i32p, i32p, i32p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(c_i64)]
    lib.sb_probe_block.restype = c_i64
    lib.sb_probe_block.argtypes = [
        ctypes.c_char_p, c_i64, c_i64, ctypes.c_int32,
        i32p, i32p, ctypes.POINTER(c_i64),
        ctypes.POINTER(ctypes.c_uint8)]
    c_i32 = ctypes.c_int32
    lib.sb_format_events.restype = c_i64
    lib.sb_format_events.argtypes = [
        ctypes.c_char_p, c_i32, c_i32,          # users
        ctypes.c_char_p, c_i32, c_i32,          # pages
        ctypes.c_char_p, c_i32, c_i32,          # ads
        ctypes.c_char_p, i32p, c_i32,           # ad types
        ctypes.c_char_p, i32p, c_i32,           # event types
        ctypes.POINTER(c_i64), c_i64,           # timestamps
        ctypes.POINTER(ctypes.c_uint64), c_i32,  # rng state, with_skew
        ctypes.c_char_p, c_i64]                 # out, cap
    lib.sb_format_events_cap.restype = c_i64
    lib.sb_format_events_cap.argtypes = [
        c_i32, c_i32, c_i32, i32p, c_i32, i32p, c_i32]
    i64p = ctypes.POINTER(c_i64)
    lib.sbr_new.restype = c_p
    lib.sbr_new.argtypes = []
    lib.sbr_free.argtypes = [c_p]
    lib.sbr_cmd.restype = c_i64
    lib.sbr_cmd.argtypes = [
        c_p, c_i32, ctypes.POINTER(ctypes.c_char_p), i64p,
        ctypes.c_char_p, c_i64]
    lib.sbr_write_windows.restype = c_i64
    lib.sbr_write_windows.argtypes = [
        c_p, c_i64, ctypes.c_char_p, i64p, ctypes.c_char_p, i64p,
        i64p, ctypes.c_char_p, c_i64, c_i32]
    lib.sbr_write_windows_idx.restype = c_i64
    lib.sbr_write_windows_idx.argtypes = [
        c_p, c_i64, ctypes.c_char_p, i64p, c_i64, i32p, i64p, i64p,
        ctypes.c_char_p, c_i64, c_i32]
    return lib


def load(rebuild: bool = False) -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None and not rebuild:
            return _lib
        if _tried and not rebuild:
            return _lib
        _tried = True
        srcs = [os.path.join(_HERE, "encoder.cpp"),
                os.path.join(_HERE, "gen.cpp"),
                os.path.join(_HERE, "store.cpp")]
        try:
            if rebuild or not os.path.exists(_SO) or any(
                    os.path.getmtime(_SO) < os.path.getmtime(s)
                    for s in srcs):
                subprocess.run(["make", "-C", _HERE], check=True,
                               capture_output=True, timeout=120)
            _lib = _configure(ctypes.CDLL(_SO))
        except (OSError, subprocess.SubprocessError, AttributeError):
            # AttributeError = a stale .so missing a newer symbol; treat
            # it like any other unusable library rather than crashing the
            # import path (callers fall back to pure Python).
            _lib = None
        return _lib
