from streambench_tpu.io.resp import RespClient, RespError  # noqa: F401
from streambench_tpu.io.fakeredis import FakeRedisStore, FakeRedisServer  # noqa: F401
