"""The canonical YSB Redis schema, plus the fork's latency-hash format.

Schema (codified by the reader at ``data/src/setup/core.clj:130-149`` and the
writer at ``AdvertisingSpark.scala:184-208``):

- ``campaigns`` : SET of campaign ids (seeded by ``do-new-setup``,
  ``core.clj:206-213``)
- ``<ad_id>`` : STRING -> campaign id (join side-table, seeded per
  ``RedisHelper.java:64-78`` / ``gen-ads`` ``core.clj:151-161``)
- ``<campaign>`` : HASH { <window_ts> -> <windowUUID>, "windows" -> <listUUID> }
- ``<listUUID>`` : LIST of window_ts strings
- ``<windowUUID>`` : HASH { "seen_count" -> int, "time_updated" -> ms }

Fork latency hash (``AdvertisingTopologyNative.java:521-532``): one HASH named
by ``redis.hashtable`` holding ``thread_idx``, ``running_time:<idx>`` and
``<event_ts>:<idx> -> latency_ms`` entries.

All functions take either a ``RespClient`` or a ``FakeRedisStore`` (adapted
in-process) so engine code and tests share one code path.
"""

from __future__ import annotations

import uuid
from typing import Any, Iterable, Mapping

from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.resp import RespClient, RespError
from streambench_tpu.utils.ids import now_ms


class StoreAdapter:
    """RespClient-shaped convenience API over an in-process FakeRedisStore."""

    def __init__(self, store: FakeRedisStore):
        self._store = store

    def execute(self, *args: Any) -> Any:
        return self._store.dispatch(list(args))

    def pipeline_execute(self, commands: Iterable[tuple]) -> list[Any]:
        # Match RespClient semantics: per-command errors are returned
        # in-list, not raised, and never abort the rest of the batch.
        out: list[Any] = []
        for c in commands:
            try:
                out.append(self._store.dispatch(list(c)))
            except RespError as e:
                out.append(e)
        return out

    def close(self) -> None:
        pass

    def __getattr__(self, name: str):
        # ping/get/set/hget/... share names with FakeRedisStore methods.
        attr = getattr(self._store, name)
        if name == "hgetall":
            def hgetall(key: str) -> dict[str, str]:
                flat = attr(key)
                return dict(zip(flat[0::2], flat[1::2]))
            return hgetall
        return attr


RedisLike = RespClient | StoreAdapter


def as_redis(obj: RespClient | StoreAdapter | FakeRedisStore) -> RedisLike:
    if isinstance(obj, FakeRedisStore):
        return StoreAdapter(obj)
    return obj


# ----------------------------------------------------------------------
# Seeding (generator -n mode / RedisHelper.prepareRedis)
# ----------------------------------------------------------------------

def seed_campaigns(r: RedisLike, campaigns: Iterable[str],
                   flush: bool = True) -> None:
    """``do-new-setup`` (``core.clj:206-213``): FLUSHALL + SADD campaigns."""
    if flush:
        r.execute("FLUSHALL")
    for c in campaigns:
        r.execute("SADD", "campaigns", c)


def seed_ad_mapping(r: RedisLike, ad_to_campaign: Mapping[str, str]) -> None:
    """Join side-table: ``SET <ad_id> <campaign>`` (``RedisHelper.java:73-77``)."""
    r.pipeline_execute([("SET", ad, camp) for ad, camp in ad_to_campaign.items()])


def load_ad_mapping(r: RedisLike, ad_ids: Iterable[str]) -> dict[str, str]:
    """Bulk ``GET`` of the join table (RedisAdCampaignCache warm-up path)."""
    ads = list(ad_ids)
    vals = r.pipeline_execute([("GET", a) for a in ads])
    return {a: v for a, v in zip(ads, vals) if isinstance(v, str)}


# ----------------------------------------------------------------------
# Canonical window writeback (AdvertisingSpark.scala:184-208)
# ----------------------------------------------------------------------

def write_window(r: RedisLike, campaign: str, window_ts: int | str,
                 seen_count: int, time_updated: int | None = None) -> None:
    """One window's writeback, exactly the Spark ``writeWindow`` algorithm.

    HINCRBY on ``seen_count`` (not SET) so partial flushes of a still-open
    window accumulate, matching the reference semantics.
    """
    wts = str(window_ts)
    window_uuid = r.execute("HGET", campaign, wts)
    if window_uuid is None:
        window_uuid = str(uuid.uuid4())
        r.execute("HSET", campaign, wts, window_uuid)
        window_list_uuid = r.execute("HGET", campaign, "windows")
        if window_list_uuid is None:
            window_list_uuid = str(uuid.uuid4())
            r.execute("HSET", campaign, "windows", window_list_uuid)
        r.execute("LPUSH", window_list_uuid, wts)
    r.execute("HINCRBY", window_uuid, "seen_count", int(seen_count))
    r.execute("HSET", window_uuid, "time_updated",
              str(now_ms() if time_updated is None else int(time_updated)))


def write_windows_pipelined(r: RedisLike,
                            entries: Iterable[tuple[str, int, int]],
                            time_updated: int | None = None,
                            absolute: bool = False) -> int:
    """Flush many ``(campaign, window_ts, count)`` rows efficiently.

    Same observable schema as ``write_window``, but the existence probes for
    all rows ride one pipeline and the mutations another — two round trips
    per flush instead of the reference's 5+ per window
    (``AdvertisingSpark.scala:189-205``).  Returns the number of rows written.

    ``absolute=True`` HSETs ``seen_count`` instead of HINCRBY — for
    aggregators whose flushed value is an absolute snapshot rather than a
    delta (HLL distinct estimates: re-flushing a still-open window must
    replace, not accumulate).
    """
    rows = [(c, str(w), int(n)) for c, w, n in entries]
    if not rows:
        return 0
    stamp = str(now_ms() if time_updated is None else int(time_updated))

    probes = r.pipeline_execute(
        [("HGET", c, w) for c, w, _ in rows]
        + [("HGET", c, "windows") for c, w, _ in rows]
    )
    win_uuids = probes[: len(rows)]
    list_uuids = probes[len(rows):]

    # Assign UUIDs for missing structures; campaigns and even whole rows may
    # repeat within one flush, so keep a local view of what we've created.
    new_lists: dict[str, str] = {}
    new_windows: dict[tuple[str, str], str] = {}
    muts: list[tuple] = []
    for i, (campaign, wts, count) in enumerate(rows):
        wuuid = win_uuids[i] or new_windows.get((campaign, wts))
        if wuuid is None:
            wuuid = str(uuid.uuid4())
            new_windows[(campaign, wts)] = wuuid
            muts.append(("HSET", campaign, wts, wuuid))
            luuid = list_uuids[i] or new_lists.get(campaign)
            if luuid is None:
                luuid = str(uuid.uuid4())
                new_lists[campaign] = luuid
                muts.append(("HSET", campaign, "windows", luuid))
            muts.append(("LPUSH", luuid, wts))
        if absolute:
            muts.append(("HSET", wuuid, "seen_count", count))
        else:
            muts.append(("HINCRBY", wuuid, "seen_count", count))
        muts.append(("HSET", wuuid, "time_updated", stamp))
    r.pipeline_execute(muts)
    return len(rows)


# ----------------------------------------------------------------------
# Stats reader (core.clj:130-149 `get-stats`)
# ----------------------------------------------------------------------

def walk_windows(r: RedisLike):
    """The canonical schema walk (``get-stats``, ``core.clj:130-149``):
    campaigns set → per-campaign "windows" list → per-window UUID hash.
    Yields ``(campaign, window_ts_str, window_key)`` — the single source
    of truth every reader builds on."""
    for campaign in r.execute("SMEMBERS", "campaigns"):
        windows_key = r.execute("HGET", campaign, "windows")
        if windows_key is None:
            continue
        n = r.execute("LLEN", windows_key)
        for window_ts in r.execute("LRANGE", windows_key, 0, n):
            window_key = r.execute("HGET", campaign, window_ts)
            if window_key is not None:
                yield campaign, window_ts, window_key


def read_stats(r: RedisLike) -> list[tuple[int, int]]:
    """All ``(seen_count, latency_ms)`` pairs, latency = time_updated −
    window_ts, one row per (campaign, window) — ``get-stats``'s view."""
    out: list[tuple[int, int]] = []
    for _, window_ts, window_key in walk_windows(r):
        seen = r.execute("HGET", window_key, "seen_count")
        updated = r.execute("HGET", window_key, "time_updated")
        if seen is None or updated is None:
            continue
        out.append((int(seen), int(updated) - int(window_ts)))
    return out


def read_window_latencies(r: RedisLike) -> dict[int, int]:
    """Per UNIQUE window: ``window_ts -> final writeback latency`` (ms).

    The canonical walk yields one row per (campaign, window); percentile
    reports over those rows overweight windows with many campaigns and
    collapse to a handful of distinct values (every campaign in a window
    shares its stamps).  The honest latency distribution — what
    ``README.markdown:36-37`` defines — has one sample per window: the
    LAST ``time_updated`` that touched it, minus the window timestamp.
    """
    out: dict[int, int] = {}
    for _, window_ts, window_key in walk_windows(r):
        updated = r.execute("HGET", window_key, "time_updated")
        if updated is None:
            continue
        ts = int(window_ts)
        lat = int(updated) - ts
        if ts not in out or lat > out[ts]:
            out[ts] = lat
    return out


def read_seen_counts(r: RedisLike) -> dict[str, dict[int, int]]:
    """campaign -> {window_ts -> seen_count}; the oracle's comparison view
    (``check-correct``, ``core.clj:215-237``)."""
    out: dict[str, dict[int, int]] = {}
    for campaign in r.execute("SMEMBERS", "campaigns"):
        out.setdefault(campaign, {})
    for campaign, window_ts, window_key in walk_windows(r):
        seen = r.execute("HGET", window_key, "seen_count")
        if seen is not None:
            out[campaign][int(window_ts)] = int(seen)
    return out


# ----------------------------------------------------------------------
# Fork latency hash (AdvertisingTopologyNative.java:521-532)
# ----------------------------------------------------------------------

def dump_latency_hash(r: RedisLike, hashtable: str,
                      latencies: Mapping[int, int], running_time_ms: int) -> int:
    """Per-worker latency dump; returns this worker's 1-based index."""
    idx = r.execute("HINCRBY", hashtable, "thread_idx", 1)
    cmds: list[tuple] = [("HSET", hashtable, f"running_time:{idx}",
                          str(int(running_time_ms)))]
    cmds += [("HSET", hashtable, f"{ts}:{idx}", str(int(lat)))
             for ts, lat in latencies.items()]
    r.pipeline_execute(cmds)
    return idx


def read_latency_hash(r: RedisLike, hashtable: str
                      ) -> tuple[dict[int, int], dict[int, dict[int, int]]]:
    """Inverse of ``dump_latency_hash``.

    Returns ``(running_time_by_idx, {idx: {event_ts: latency_ms}})``.
    """
    flat = r.hgetall(hashtable) if hasattr(r, "hgetall") else {}
    running: dict[int, int] = {}
    per_idx: dict[int, dict[int, int]] = {}
    for field, value in flat.items():
        if field == "thread_idx":
            continue
        name, _, idx_s = field.rpartition(":")
        idx = int(idx_s)
        if name == "running_time":
            running[idx] = int(value)
        else:
            per_idx.setdefault(idx, {})[int(name)] = int(value)
    return running, per_idx
