"""The canonical YSB Redis schema, plus the fork's latency-hash format.

Schema (codified by the reader at ``data/src/setup/core.clj:130-149`` and the
writer at ``AdvertisingSpark.scala:184-208``):

- ``campaigns`` : SET of campaign ids (seeded by ``do-new-setup``,
  ``core.clj:206-213``)
- ``<ad_id>`` : STRING -> campaign id (join side-table, seeded per
  ``RedisHelper.java:64-78`` / ``gen-ads`` ``core.clj:151-161``)
- ``<campaign>`` : HASH { <window_ts> -> <windowUUID>, "windows" -> <listUUID> }
- ``<listUUID>`` : LIST of window_ts strings
- ``<windowUUID>`` : HASH { "seen_count" -> int, "time_updated" -> ms }

Fork latency hash (``AdvertisingTopologyNative.java:521-532``): one HASH named
by ``redis.hashtable`` holding ``thread_idx``, ``running_time:<idx>`` and
``<event_ts>:<idx> -> latency_ms`` entries.

All functions take either a ``RespClient`` or a ``FakeRedisStore`` (adapted
in-process) so engine code and tests share one code path.
"""

from __future__ import annotations

import itertools
import os
from typing import Any, Iterable, Mapping

from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.resp import RespClient, RespError
from streambench_tpu.utils.ids import now_ms


class StoreAdapter:
    """RespClient-shaped convenience API over an in-process FakeRedisStore."""

    def __init__(self, store: FakeRedisStore):
        self._store = store
        # pipeline fast path: command name -> bound store method; callers
        # of pipeline_execute always pass str args, so the dispatch
        # coercions are pure overhead for these
        self._fast = {name: getattr(store, name.lower())
                      for name in ("HGET", "HSET", "HINCRBY", "LPUSH",
                                   "SADD", "GET", "SET")}

    def execute(self, *args: Any) -> Any:
        return self._store.dispatch(list(args))

    def pipeline_execute(self, commands: Iterable[tuple]) -> list[Any]:
        # Match RespClient semantics: per-command errors are returned
        # in-list, not raised, and never abort the rest of the batch.
        # Hot commands bypass `dispatch` (its per-arg string coercion +
        # name lookup is ~10x the actual dict operation; the canonical
        # window writeback pushes 10^5+ commands per flush through here).
        fast = self._fast
        out: list[Any] = []
        for c in commands:
            try:
                h = fast.get(c[0])
                out.append(h(*c[1:]) if h is not None
                           else self._store.dispatch(list(c)))
            except RespError as e:
                out.append(e)
        return out

    def close(self) -> None:
        pass

    def __getattr__(self, name: str):
        # ping/get/set/hget/... share names with FakeRedisStore methods.
        attr = getattr(self._store, name)
        if name == "hgetall":
            def hgetall(key: str) -> dict[str, str]:
                flat = attr(key)
                return dict(zip(flat[0::2], flat[1::2]))
            return hgetall
        return attr


RedisLike = RespClient | StoreAdapter


# Fresh opaque keys for window/list structures.  The reference uses
# UUID.randomUUID (AdvertisingSpark.scala:190,196) but the -g reader treats
# them as opaque strings, so a random-prefix counter is schema-equivalent —
# and ~6x cheaper than uuid.uuid4 (os.urandom per call), which matters at
# catchup flush sizes (10^5 new windows per flush).  The prefix is re-drawn
# per pid so forked workers writing one Redis can't collide.
_ID_STATE: dict = {"pid": None}


def _fresh_id() -> str:
    st = _ID_STATE
    if st["pid"] != os.getpid():
        st.update(pid=os.getpid(), prefix=os.urandom(8).hex(),
                  counter=itertools.count())
    return f"{st['prefix']}-{next(st['counter']):010x}"


def as_redis(obj: RespClient | StoreAdapter | FakeRedisStore) -> RedisLike:
    if isinstance(obj, FakeRedisStore):
        return StoreAdapter(obj)
    return obj


# ----------------------------------------------------------------------
# Seeding (generator -n mode / RedisHelper.prepareRedis)
# ----------------------------------------------------------------------

def seed_campaigns(r: RedisLike, campaigns: Iterable[str],
                   flush: bool = True) -> None:
    """``do-new-setup`` (``core.clj:206-213``): FLUSHALL + SADD campaigns."""
    if flush:
        r.execute("FLUSHALL")
    for c in campaigns:
        r.execute("SADD", "campaigns", c)


def seed_ad_mapping(r: RedisLike, ad_to_campaign: Mapping[str, str]) -> None:
    """Join side-table: ``SET <ad_id> <campaign>`` (``RedisHelper.java:73-77``)."""
    r.pipeline_execute([("SET", ad, camp) for ad, camp in ad_to_campaign.items()])


def load_ad_mapping(r: RedisLike, ad_ids: Iterable[str]) -> dict[str, str]:
    """Bulk ``GET`` of the join table (RedisAdCampaignCache warm-up path)."""
    ads = list(ad_ids)
    vals = r.pipeline_execute([("GET", a) for a in ads])
    return {a: v for a, v in zip(ads, vals) if isinstance(v, str)}


# ----------------------------------------------------------------------
# Writeback fence (exactly-once mode, ROBUSTNESS.md "Exactly-once")
# ----------------------------------------------------------------------
# One HASH per (topic, partition) holding the writeback fence:
#   intent -> flush_seq of the LAST ATTEMPTED flush (written FIRST)
#   epoch  -> writer epoch that attempted it
#   seq    -> flush_seq of the last FULLY LANDED flush (written LAST)
# A flush pipeline is [intent/epoch HSET] + window rows + [seq HSET], so
# any partial application leaves intent > seq — the signature resume
# detection keys on.  The key never enters the ``campaigns`` SET, so the
# canonical schema walk (walk_windows) and every stats reader skip it.

def fence_key(topic: str = "", partition: int = 0) -> str:
    return f"__streambench:fence:{topic}:{int(partition)}"


def read_fence(r: RedisLike, key: str) -> tuple[int, int, int]:
    """``(epoch, seq, intent)`` from the sink, zeros where absent.
    One pipeline round trip (one fault decision under chaos wrappers);
    non-string replies (missing field, WRONGTYPE error) read as 0."""
    vals = r.pipeline_execute([("HGET", key, "epoch"),
                               ("HGET", key, "seq"),
                               ("HGET", key, "intent")])
    out = []
    for v in vals:
        try:
            out.append(int(v) if isinstance(v, str) else 0)
        except ValueError:
            out.append(0)
    return out[0], out[1], out[2]


def claim_epoch(r: RedisLike, key: str, epoch: int) -> None:
    """Advertise a new writer epoch (zombie guard: older epochs abort
    their flushes once they observe it).  Leaves seq/intent untouched —
    seq continuity across epochs is what resume detection compares."""
    r.execute("HSET", key, "epoch", str(int(epoch)))


# ----------------------------------------------------------------------
# Canonical window writeback (AdvertisingSpark.scala:184-208)
# ----------------------------------------------------------------------

def write_window(r: RedisLike, campaign: str, window_ts: int | str,
                 seen_count: int, time_updated: int | None = None) -> None:
    """One window's writeback, exactly the Spark ``writeWindow`` algorithm.

    HINCRBY on ``seen_count`` (not SET) so partial flushes of a still-open
    window accumulate, matching the reference semantics.
    """
    wts = str(window_ts)
    window_uuid = r.execute("HGET", campaign, wts)
    if window_uuid is None:
        window_uuid = _fresh_id()
        r.execute("HSET", campaign, wts, window_uuid)
        window_list_uuid = r.execute("HGET", campaign, "windows")
        if window_list_uuid is None:
            window_list_uuid = _fresh_id()
            r.execute("HSET", campaign, "windows", window_list_uuid)
        r.execute("LPUSH", window_list_uuid, wts)
    r.execute("HINCRBY", window_uuid, "seen_count", int(seen_count))
    r.execute("HSET", window_uuid, "time_updated",
              str(now_ms() if time_updated is None else int(time_updated)))


def write_windows_pipelined(r: RedisLike,
                            entries: Iterable[tuple[str, int, int]],
                            time_updated: int | None = None,
                            absolute: bool = False,
                            cache: dict | None = None,
                            fence: tuple[str, int, int] | None = None) -> int:
    """Flush many ``(campaign, window_ts, count)`` rows efficiently.

    Same observable schema as ``write_window``, but the existence probes for
    all rows ride one pipeline and the mutations another — two round trips
    per flush instead of the reference's 5+ per window
    (``AdvertisingSpark.scala:189-205``).  Returns the number of rows written.

    ``absolute=True`` HSETs ``seen_count`` instead of HINCRBY — for
    aggregators whose flushed value is an absolute snapshot rather than a
    delta (HLL distinct estimates: re-flushing a still-open window must
    replace, not accumulate).

    ``cache`` (caller-owned, initially ``{}``) memoizes window/list UUIDs
    across flushes.  Sound whenever the caller is the only writer of these
    campaigns — the reference makes the same assumption (each campaign's
    windows are written by exactly one keyed CampaignProcessor instance,
    ``AdvertisingTopology.java:232-233``).  Cuts the two existence probes
    per already-seen row, which at catchup flush sizes (10^5 rows) is most
    of the Redis round-trip volume.

    ``fence=(key, epoch, seq)`` brackets the mutation batch with the
    exactly-once fence records: ``HSET key intent seq / epoch epoch`` as
    the FIRST command and ``HSET key seq seq`` as the LAST, so the sink
    states a pipeline can be left in are exactly {nothing, intent-only,
    intent+prefix, fully-landed} — the signature
    ``engine/pipeline._RedisWriter`` and resume detection key on.
    """
    rows = [(c, str(w), int(n)) for c, w, n in entries]
    if not rows and fence is None:
        return 0
    stamp = str(now_ms() if time_updated is None else int(time_updated))

    win_cache = cache.setdefault("win", {}) if cache is not None else {}
    list_cache = cache.setdefault("list", {}) if cache is not None else {}
    if isinstance(r, StoreAdapter):
        store = r._store
        if hasattr(store, "write_windows_bulk"):
            # Native store: the whole probe/create/LPUSH/HINCRBY sequence
            # runs in C (~100 ns/row); it maintains its own existence
            # view, so no client-side id cache is involved.  In-process
            # there is no partial-apply failure mode, so the fence rides
            # as one HSET after the bulk write.
            store.write_windows_bulk(rows, stamp, absolute)
            if fence is not None:
                key, epoch, seq = fence
                r.execute("HSET", key, "intent", str(seq),
                          "epoch", str(epoch), "seq", str(seq))
            return len(rows)
        # In-process Python store: one lock hold, no command tuples — the
        # embedded-state-store fast path (the RESP/TCP path below stays
        # byte-identical for real Redis).
        _bulk_write_windows(store, rows, stamp, absolute,
                            win_cache, list_cache, fence=fence)
        return len(rows)
    # Probe only rows the cache can't resolve.
    need = [i for i, (c, w, _) in enumerate(rows)
            if (c, w) not in win_cache]
    if need:
        probes = r.pipeline_execute(
            [("HGET", rows[i][0], rows[i][1]) for i in need]
            + [("HGET", rows[i][0], "windows") for i in need]
        )
        for j, i in enumerate(need):
            c, w, _ = rows[i]
            # Probe replies can be RespError (e.g. WRONGTYPE on a mistyped
            # campaign key) which is truthy; caching one would permanently
            # aim every later flush at a key derived from str(error).
            if isinstance(probes[j], str):
                win_cache[(c, w)] = probes[j]
            lp = probes[len(need) + j]
            if isinstance(lp, str) and c not in list_cache:
                list_cache[c] = lp

    # Assign UUIDs for missing structures; campaigns and even whole rows may
    # repeat within one flush, so the cache doubles as the local view of
    # what this call just created.
    # Stage this call's new ids locally and commit them to the caller's
    # cache only after the pipeline lands: caching an id whose HSET/LPUSH
    # registration then failed would make every retry write to an orphan
    # hash the campaign never references (permanently missing windows).
    new_win: dict[tuple[str, str], str] = {}
    new_list: dict[str, str] = {}
    # mut index of the HSET that registers each fresh id: an id whose
    # registration errored (e.g. WRONGTYPE campaign key) must NOT enter the
    # cache, else every later flush would cache-hit an orphan hash the
    # campaign never references.
    win_reg: dict[tuple[str, str], int] = {}
    list_reg: dict[str, int] = {}
    muts: list[tuple] = []
    for campaign, wts, count in rows:
        wuuid = win_cache.get((campaign, wts)) or new_win.get(
            (campaign, wts))
        if wuuid is None:
            wuuid = _fresh_id()
            new_win[(campaign, wts)] = wuuid
            luuid = list_cache.get(campaign) or new_list.get(campaign)
            if luuid is None:
                luuid = _fresh_id()
                new_list[campaign] = luuid
                list_reg[campaign] = len(muts)
                muts.append(("HSET", campaign, "windows", luuid))
            # Registration order matters under the partial-apply fault
            # (exactly-once chaos): the ``wts -> wuuid`` HSET is the
            # COMMIT of the window's creation and must come LAST of the
            # trio.  Any torn prefix then leaves either no registration
            # (retry recreates everything) or a list entry without the
            # hash mapping (harmless: the walk skips it, the retry
            # re-registers).  The old order could land the hash mapping
            # WITHOUT the list entry — the retry would cache-hit the
            # uuid and never repair the list, leaving a window invisible
            # to every canonical reader.
            muts.append(("LPUSH", luuid, wts))
            win_reg[(campaign, wts)] = len(muts)
            muts.append(("HSET", campaign, wts, wuuid))
        if absolute:
            muts.append(("HSET", wuuid, "seen_count", str(count),
                         "time_updated", stamp))
        else:
            muts.append(("HINCRBY", wuuid, "seen_count", str(count)))
            muts.append(("HSET", wuuid, "time_updated", stamp))
    off = 0
    if fence is not None:
        fkey, epoch, seq = fence
        # intent+epoch FIRST, commit seq LAST: any partial application
        # leaves intent > seq on the sink
        muts = ([("HSET", fkey, "intent", str(seq), "epoch", str(epoch))]
                + muts + [("HSET", fkey, "seq", str(seq))])
        off = 1
    res = r.pipeline_execute(muts)
    for key, i in win_reg.items():
        if isinstance(res[i + off], RespError):
            del new_win[key]
    for campaign, i in list_reg.items():
        if isinstance(res[i + off], RespError):
            del new_list[campaign]
    win_cache.update(new_win)
    list_cache.update(new_list)
    return len(rows)


def _bulk_write_windows(store: FakeRedisStore, rows, stamp: str,
                        absolute: bool, win_cache: dict,
                        list_cache: dict, fence=None) -> None:
    """Canonical-schema writeback directly against the in-process store's
    dicts, one lock hold for the whole flush.  Observable state is
    IDENTICAL to the pipelined path (same keys, same hash fields, same
    list contents) — asserted by the schema round-trip tests.  A fence
    lands under the same lock hold: rows + fence are truly atomic here
    (the partial-apply failure mode only exists on the command path)."""
    with store._lock:
        hashes = store._hashes
        lists = store._lists
        holders = (store._strings, store._hashes, store._sets, store._lists)

        def clashes(key: str, owner: dict) -> bool:
            return any(key in d for d in holders if d is not owner)

        for campaign, wts, count in rows:
            wuuid = win_cache.get((campaign, wts))
            fresh_wuuid = False
            if wuuid is None:
                probe = hashes.get(campaign)
                if probe is None and clashes(campaign, hashes):
                    # Mirror the per-command pipeline: that path would
                    # WRONGTYPE every command of this row in-list and
                    # carry on with the rest of the batch — so skip the
                    # row, never shadow the key and never raise (a raise
                    # here would double-apply rows 0..k-1 when the
                    # flusher retries the retained batch).
                    continue
                wuuid = probe.get(wts) if probe else None
                if wuuid is None:
                    wuuid = _fresh_id()
                    fresh_wuuid = True
                    ch = hashes.setdefault(campaign, {})
                    ch[wts] = wuuid
                    luuid = list_cache.get(campaign) or ch.get("windows")
                    if luuid is None:
                        luuid = _fresh_id()
                        ch["windows"] = luuid
                        list_cache[campaign] = luuid
                        lists.setdefault(luuid, []).insert(0, wts)
                    elif luuid in lists or not clashes(luuid, lists):
                        list_cache[campaign] = luuid
                        lists.setdefault(luuid, []).insert(0, wts)
                    # else: stored list id points at a non-list key — the
                    # per-command LPUSH would error in-list while the
                    # window hash still gets bumped; mirror that.
                win_cache[(campaign, wts)] = wuuid
            if not fresh_wuuid and wuuid not in hashes \
                    and clashes(wuuid, hashes):
                continue  # cached id now a non-hash key: per-command
                # HINCRBY/HSET would error in-list; skip the row
            wh = hashes.setdefault(wuuid, {})
            if absolute:
                wh["seen_count"] = str(count)
            else:
                wh["seen_count"] = str(int(wh.get("seen_count", "0"))
                                       + count)
            wh["time_updated"] = stamp
        if fence is not None:
            fkey, epoch, seq = fence
            fh = hashes.setdefault(fkey, {})
            fh["intent"] = str(seq)
            fh["epoch"] = str(epoch)
            fh["seq"] = str(seq)


# ----------------------------------------------------------------------
# Stats reader (core.clj:130-149 `get-stats`)
# ----------------------------------------------------------------------

def walk_windows(r: RedisLike):
    """The canonical schema walk (``get-stats``, ``core.clj:130-149``):
    campaigns set → per-campaign "windows" list → per-window UUID hash.
    Yields ``(campaign, window_ts_str, window_key)`` — the single source
    of truth every reader builds on."""
    for campaign in r.execute("SMEMBERS", "campaigns"):
        windows_key = r.execute("HGET", campaign, "windows")
        if windows_key is None:
            continue
        n = r.execute("LLEN", windows_key)
        for window_ts in r.execute("LRANGE", windows_key, 0, n):
            window_key = r.execute("HGET", campaign, window_ts)
            if window_key is not None:
                yield campaign, window_ts, window_key


def read_stats(r: RedisLike) -> list[tuple[int, int]]:
    """All ``(seen_count, latency_ms)`` pairs, latency = time_updated −
    window_ts, one row per (campaign, window) — ``get-stats``'s view."""
    out: list[tuple[int, int]] = []
    for _, window_ts, window_key in walk_windows(r):
        seen = r.execute("HGET", window_key, "seen_count")
        updated = r.execute("HGET", window_key, "time_updated")
        if seen is None or updated is None:
            continue
        out.append((int(seen), int(updated) - int(window_ts)))
    return out


def read_window_latencies(r: RedisLike) -> dict[int, int]:
    """Per UNIQUE window: ``window_ts -> final writeback latency`` (ms).

    The canonical walk yields one row per (campaign, window); percentile
    reports over those rows overweight windows with many campaigns and
    collapse to a handful of distinct values (every campaign in a window
    shares its stamps).  The honest latency distribution — what
    ``README.markdown:36-37`` defines — has one sample per window: the
    LAST ``time_updated`` that touched it, minus the window timestamp.
    """
    out: dict[int, int] = {}
    for _, window_ts, window_key in walk_windows(r):
        updated = r.execute("HGET", window_key, "time_updated")
        if updated is None:
            continue
        ts = int(window_ts)
        lat = int(updated) - ts
        if ts not in out or lat > out[ts]:
            out[ts] = lat
    return out


def read_seen_counts(r: RedisLike) -> dict[str, dict[int, int]]:
    """campaign -> {window_ts -> seen_count}; the oracle's comparison view
    (``check-correct``, ``core.clj:215-237``)."""
    out: dict[str, dict[int, int]] = {}
    for campaign in r.execute("SMEMBERS", "campaigns"):
        out.setdefault(campaign, {})
    for campaign, window_ts, window_key in walk_windows(r):
        seen = r.execute("HGET", window_key, "seen_count")
        if seen is not None:
            out[campaign][int(window_ts)] = int(seen)
    return out


# ----------------------------------------------------------------------
# Fork latency hash (AdvertisingTopologyNative.java:521-532)
# ----------------------------------------------------------------------

def dump_latency_hash(r: RedisLike, hashtable: str,
                      latencies: Mapping[int, int], running_time_ms: int) -> int:
    """Per-worker latency dump; returns this worker's 1-based index."""
    idx = r.execute("HINCRBY", hashtable, "thread_idx", 1)
    cmds: list[tuple] = [("HSET", hashtable, f"running_time:{idx}",
                          str(int(running_time_ms)))]
    cmds += [("HSET", hashtable, f"{ts}:{idx}", str(int(lat)))
             for ts, lat in latencies.items()]
    r.pipeline_execute(cmds)
    return idx


def read_latency_hash(r: RedisLike, hashtable: str
                      ) -> tuple[dict[int, int], dict[int, dict[int, int]]]:
    """Inverse of ``dump_latency_hash``.

    Returns ``(running_time_by_idx, {idx: {event_ts: latency_ms}})``.
    """
    flat = r.hgetall(hashtable) if hasattr(r, "hgetall") else {}
    running: dict[int, int] = {}
    per_idx: dict[int, dict[int, int]] = {}
    for field, value in flat.items():
        if field == "thread_idx":
            continue
        name, _, idx_s = field.rpartition(":")
        idx = int(idx_s)
        if name == "running_time":
            running[idx] = int(value)
        else:
            per_idx.setdefault(idx, {})[int(name)] = int(value)
    return running, per_idx
