"""Hermetic in-process Redis: a data store plus a real RESP socket server.

The reference needs a live ``redis-server`` for every run
(``stream-bench.sh:180-187`` downloads and compiles one).  For hermetic tests
and single-process benchmark runs we provide the same command surface two
ways:

- ``FakeRedisStore`` — the data structures + command dispatch, callable
  in-process (zero-copy path used by the engine when configured with
  ``redis.host: ":inprocess:"``);
- ``FakeRedisServer`` — a threaded TCP server speaking RESP2 on a real
  socket, so ``RespClient`` and the wire protocol are exercised for real in
  tests (the same embedded-cluster trick the reference uses with Apex
  ``LocalMode``, ``ApplicationWithDCWithoutDeserializerTest.java:19-45``).

Only the commands the benchmark uses are implemented; unknown commands
return a RESP error, like real Redis.
"""

from __future__ import annotations

import ctypes
import socketserver
import threading
from typing import Any

from streambench_tpu.io.resp import _Reader, RespError


def _s(v: Any) -> str:
    return v.decode("utf-8") if isinstance(v, bytes) else str(v)


class FakeRedisStore:
    """Dict-backed implementation of the YSB Redis command surface."""

    def __init__(self) -> None:
        self._strings: dict[str, str] = {}
        self._hashes: dict[str, dict[str, str]] = {}
        self._sets: dict[str, set[str]] = {}
        self._lists: dict[str, list[str]] = {}
        self._lock = threading.RLock()

    # ---- command handlers (names match Redis commands) ----
    def ping(self) -> str:
        return "PONG"

    def flushall(self) -> str:
        with self._lock:
            self._strings.clear()
            self._hashes.clear()
            self._sets.clear()
            self._lists.clear()
        return "OK"

    def set(self, key: str, value: str) -> str:
        with self._lock:
            self._check_type(key, self._strings)
            self._strings[key] = value
        return "OK"

    def get(self, key: str) -> str | None:
        with self._lock:
            self._check_type(key, self._strings)
            return self._strings.get(key)

    def sadd(self, key: str, *members: str) -> int:
        with self._lock:
            self._check_type(key, self._sets)
            s = self._sets.setdefault(key, set())
            n = len(s)
            s.update(members)
            return len(s) - n

    def smembers(self, key: str) -> list[str]:
        with self._lock:
            self._check_type(key, self._sets)
            return sorted(self._sets.get(key, set()))

    def hset(self, key: str, field: str, value: str, *more: str) -> int:
        """HSET with the (Redis >= 4.0) multi-field form: additional
        field/value pairs in ``more``."""
        if len(more) % 2:
            raise RespError("ERR wrong number of arguments for 'hset'")
        with self._lock:
            self._check_type(key, self._hashes)
            h = self._hashes.setdefault(key, {})
            new = 0 if field in h else 1
            h[field] = value
            for i in range(0, len(more), 2):
                if more[i] not in h:
                    new += 1
                h[more[i]] = more[i + 1]
            return new

    def hget(self, key: str, field: str) -> str | None:
        with self._lock:
            self._check_type(key, self._hashes)
            return self._hashes.get(key, {}).get(field)

    def hdel(self, key: str, *fields: str) -> int:
        with self._lock:
            self._check_type(key, self._hashes)
            h = self._hashes.get(key, {})
            removed = 0
            for f in fields:
                if f in h:
                    del h[f]
                    removed += 1
            if not h and key in self._hashes:
                del self._hashes[key]
            return removed

    def hgetall(self, key: str) -> list[str]:
        with self._lock:
            self._check_type(key, self._hashes)
            out: list[str] = []
            for k, v in self._hashes.get(key, {}).items():
                out.extend((k, v))
            return out

    def hincrby(self, key: str, field: str, amount: str) -> int:
        with self._lock:
            self._check_type(key, self._hashes)
            h = self._hashes.setdefault(key, {})
            cur = h.get(field, "0")
            try:
                nxt = int(cur) + int(amount)
            except ValueError:
                raise RespError("ERR hash value is not an integer")
            h[field] = str(nxt)
            return nxt

    def lpush(self, key: str, *values: str) -> int:
        with self._lock:
            self._check_type(key, self._lists)
            lst = self._lists.setdefault(key, [])
            for v in values:
                lst.insert(0, v)
            return len(lst)

    def llen(self, key: str) -> int:
        with self._lock:
            self._check_type(key, self._lists)
            return len(self._lists.get(key, []))

    def lrange(self, key: str, start: str, stop: str) -> list[str]:
        with self._lock:
            self._check_type(key, self._lists)
            lst = self._lists.get(key, [])
            i, j = int(start), int(stop)
            n = len(lst)
            if i < 0:
                i += n
            if j < 0:
                j += n
            # Redis LRANGE stop is inclusive; clamp like Redis does.
            i = max(i, 0)
            j = min(j, n - 1)
            if i > j:
                return []
            return lst[i : j + 1]

    # ---- plumbing ----
    def _check_type(self, key: str, owner: dict) -> None:
        holders = (self._strings, self._hashes, self._sets, self._lists)
        for h in holders:
            if h is not owner and key in h:
                raise RespError(
                    "WRONGTYPE Operation against a key holding the wrong "
                    "kind of value"
                )

    def dispatch(self, args: list[Any]) -> Any:
        if not args:
            raise RespError("ERR empty command")
        name = _s(args[0]).lower()
        handler = getattr(self, name, None)
        if handler is None or name.startswith("_"):
            raise RespError(f"ERR unknown command '{_s(args[0])}'")
        try:
            return handler(*[_s(a) for a in args[1:]])
        except TypeError as e:
            raise RespError(f"ERR wrong number of arguments: {e}")


def _parse_resp(buf: bytes, pos: int = 0):
    """Parse ONE RESP2 reply from ``buf[pos:]`` -> (value, next_pos).

    Deliberately NOT ``resp._Reader``: the in-process store needs str
    values (``_Reader`` yields bulk strings as bytes, matching the socket
    client's contract) and errors as VALUES so pipeline callers can keep
    them in-list instead of aborting (``RespClient.pipeline_execute``
    semantics); a byte-for-byte reuse would need a transform layer larger
    than this parser.  Covers the same RESP2 shapes _Reader does,
    including nil bulk ($-1) and null array (*-1).
    """
    kind = buf[pos:pos + 1]
    end = buf.index(b"\r\n", pos)
    head = buf[pos + 1:end]
    pos = end + 2
    if kind == b"+":
        return head.decode(), pos
    if kind == b"-":
        return RespError(head.decode()), pos
    if kind == b":":
        return int(head), pos
    if kind == b"$":
        n = int(head)
        if n < 0:
            return None, pos
        val = buf[pos:pos + n].decode("utf-8")
        return val, pos + n + 2
    if kind == b"*":
        n = int(head)
        if n < 0:
            return None, pos
        out = []
        for _ in range(n):
            v, pos = _parse_resp(buf, pos)
            out.append(v)
        return out, pos
    raise ValueError(f"bad RESP reply at {pos}: {buf[pos:pos+16]!r}")


class NativeRedisStore(FakeRedisStore):
    """The same store, implemented in C (native/store.cpp).

    Same command surface and RESP reply shapes as the Python
    implementation (differential-tested), plus ``write_windows_bulk`` —
    the canonical window writeback executed natively at ~100 ns/row,
    which removes the largest remaining host cost in the catchup
    pipeline.  Subclasses ``FakeRedisStore`` so every isinstance check,
    adapter, and the RESP TCP server work unchanged; the Python dict
    state of the base class is simply never used.
    """

    def __init__(self, lib) -> None:
        # deliberately NOT calling super().__init__: state lives in C
        self._lib = lib
        self._h = lib.sbr_new()
        self._buf = ctypes.create_string_buffer(1 << 16)
        # The reply buffer is shared across calls; the TCP server runs
        # one handler thread per client, so command execution + reply
        # extraction must be atomic (the C store has its own mutex, but
        # that doesn't protect this Python-side buffer).
        self._cmd_lock = threading.Lock()

    def __del__(self):  # pragma: no cover - teardown order
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.sbr_free(h)
            self._h = None

    def _cmd(self, *args):
        argv = (ctypes.c_char_p * len(args))()
        lens = (ctypes.c_int64 * len(args))()
        keep = []  # keep encoded bytes alive for the call
        for i, a in enumerate(args):
            b = (a if isinstance(a, bytes)
                 else str(a).encode("utf-8"))
            keep.append(b)
            argv[i] = b
            lens[i] = len(b)
        with self._cmd_lock:
            while True:
                n = self._lib.sbr_cmd(self._h, len(args), argv, lens,
                                      self._buf, len(self._buf))
                if n >= 0:
                    break
                # reply larger than the buffer: grow and re-issue (safe:
                # only read-only commands have unbounded replies).  Loop,
                # not a single retry — another thread's write can grow
                # the same structure between the two calls.
                self._buf = ctypes.create_string_buffer(-n + 256)
            reply = self._buf.raw[:n]
        val, _ = _parse_resp(reply)
        if isinstance(val, RespError):
            raise val
        return val

    # ---- command surface (mirrors the Python impl) ----
    def ping(self):
        return self._cmd("PING")

    def flushall(self):
        return self._cmd("FLUSHALL")

    def set(self, key, value):
        return self._cmd("SET", key, value)

    def get(self, key):
        return self._cmd("GET", key)

    def sadd(self, key, *members):
        return self._cmd("SADD", key, *members)

    def smembers(self, key):
        return self._cmd("SMEMBERS", key)

    def hset(self, key, field, value, *more):
        return self._cmd("HSET", key, field, value, *more)

    def hget(self, key, field):
        return self._cmd("HGET", key, field)

    def hdel(self, key, *fields):
        return self._cmd("HDEL", key, *fields)

    def hgetall(self, key):
        return self._cmd("HGETALL", key)

    def hincrby(self, key, field, amount):
        return self._cmd("HINCRBY", key, field, amount)

    def lpush(self, key, *values):
        return self._cmd("LPUSH", key, *values)

    def llen(self, key):
        return self._cmd("LLEN", key)

    def lrange(self, key, start, stop):
        return self._cmd("LRANGE", key, start, stop)

    def dispatch(self, args: list[Any]) -> Any:
        if not args:
            raise RespError("ERR empty command")
        return self._cmd(*args)

    # ---- native bulk writeback (redis_schema.write_windows_pipelined) --
    def write_windows_bulk(self, rows, stamp: str, absolute: bool) -> int:
        """Canonical-schema writeback of ``(campaign, wts, count)`` rows
        in one native call; observable state identical to issuing the
        HGET/HSET/LPUSH/HINCRBY sequence per row."""
        n = len(rows)
        if n == 0:
            return 0
        camp_off = (ctypes.c_int64 * (n + 1))()
        ts_off = (ctypes.c_int64 * (n + 1))()
        counts = (ctypes.c_int64 * n)()
        camps = []
        tss = []
        co = to = 0
        for i, (c, w, cnt) in enumerate(rows):
            cb = c.encode()
            wb = w.encode() if isinstance(w, str) else str(w).encode()
            camps.append(cb)
            tss.append(wb)
            camp_off[i] = co
            ts_off[i] = to
            co += len(cb)
            to += len(wb)
            counts[i] = cnt
        camp_off[n] = co
        ts_off[n] = to
        sb = stamp.encode()
        rc = self._lib.sbr_write_windows(
            self._h, n, b"".join(camps), camp_off, b"".join(tss), ts_off,
            counts, sb, len(sb), 1 if absolute else 0)
        if rc < 0:
            raise RespError("WRONGTYPE Operation against a key holding "
                            "the wrong kind of value")
        return int(rc)

    def write_windows_arrays(self, names_blob: bytes, names_off,
                             ci, ts, counts, stamp: str,
                             absolute: bool) -> int:
        """Index-form bulk writeback: campaign table once (blob +
        int64 offsets, len C+1), rows as numpy int32 ``ci`` / int64
        ``ts``/``counts`` arrays — the engine flush path, zero per-row
        Python work."""
        import ctypes as _c

        import numpy as _np

        n = int(ci.shape[0])
        if n == 0:
            return 0
        ci = _np.ascontiguousarray(ci, _np.int32)
        ts = _np.ascontiguousarray(ts, _np.int64)
        counts = _np.ascontiguousarray(counts, _np.int64)
        sb = stamp.encode()
        rc = self._lib.sbr_write_windows_idx(
            self._h, n, names_blob,
            names_off.ctypes.data_as(_c.POINTER(_c.c_int64)),
            int(names_off.shape[0]) - 1,
            ci.ctypes.data_as(_c.POINTER(_c.c_int32)),
            ts.ctypes.data_as(_c.POINTER(_c.c_int64)),
            counts.ctypes.data_as(_c.POINTER(_c.c_int64)),
            sb, len(sb), 1 if absolute else 0)
        if rc == -2:
            raise ValueError("campaign index out of range")
        if rc < 0:
            raise RespError("WRONGTYPE Operation against a key holding "
                            "the wrong kind of value")
        return int(rc)


def make_store() -> FakeRedisStore:
    """The native C store when the library is available, else the
    pure-Python one — same observable behavior either way."""
    from streambench_tpu import native

    lib = native.load()
    if lib is not None:
        return NativeRedisStore(lib)
    return FakeRedisStore()


def _encode_reply(v: Any) -> bytes:
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, int):
        return b":%d\r\n" % v
    if isinstance(v, str):
        if v in ("OK", "PONG"):
            return b"+%s\r\n" % v.encode()
        b = v.encode("utf-8")
        return b"$%d\r\n%s\r\n" % (len(b), b)
    if isinstance(v, (list, tuple)):
        return b"*%d\r\n" % len(v) + b"".join(_encode_reply(x) for x in v)
    raise TypeError(f"cannot encode reply: {v!r}")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        reader = _Reader(self.request.recv)
        store: FakeRedisStore = self.server.store  # type: ignore[attr-defined]
        while True:
            try:
                cmd = reader.read_reply()
            except (ConnectionError, OSError):
                return
            try:
                reply = _encode_reply(store.dispatch(cmd))
            except RespError as e:
                reply = b"-%s\r\n" % str(e).encode("utf-8")
            try:
                self.request.sendall(reply)
            except OSError:
                return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FakeRedisServer:
    """RESP2 socket server around a ``FakeRedisStore``.

    Use as a context manager; ``port`` is OS-assigned so tests never collide.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: FakeRedisStore | None = None):
        self.store = store if store is not None else make_store()
        self._server = _Server((host, port), _Handler)
        self._server.store = self.store  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name="fake-redis",
        )

    def start(self) -> "FakeRedisServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "FakeRedisServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv: list[str] | None = None) -> int:
    """Run the server as a standalone process — the harness's
    ``redis-server`` stand-in (``start_if_needed redis-server``,
    ``stream-bench.sh:180-187``).  Exits cleanly on SIGTERM/SIGINT."""
    import argparse
    import signal

    p = argparse.ArgumentParser(prog="streambench-redis")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=6379)
    args = p.parse_args(argv)
    srv = FakeRedisServer(args.host, args.port).start()
    print(f"ready {srv.host}:{srv.port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
