"""Pure-Python Redis (RESP2) client.

The reference talks to Redis through Jedis (Java), sedis (Scala) and
redis-clojure; this environment has no Redis client library, so the framework
carries its own minimal RESP2 implementation.  It covers exactly the command
surface the benchmark uses (see the canonical schema users:
``AdvertisingSpark.scala:184-208`` writer, ``data/src/setup/core.clj:130-149``
reader, ``AdvertisingTopologyNative.java:521-532`` latency dump,
``RedisHelper.java:64-78`` seeding) plus pipelining, which is the host-side
throughput lever the JVM engines got from connection pools.

The client is deliberately transport-only: schema knowledge lives in
``streambench_tpu.io.redis_schema``.
"""

from __future__ import annotations

import socket
from typing import Any, Iterable


class RespError(RuntimeError):
    """A Redis server-side error reply (RESP ``-ERR ...``)."""


def encode_command(*args: Any) -> bytes:
    """Encode one command as a RESP array of bulk strings."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, str):
            b = a.encode("utf-8")
        elif isinstance(a, (int, float)):
            b = repr(a).encode("ascii") if isinstance(a, float) else b"%d" % a
        else:
            raise TypeError(f"unsupported RESP argument type: {type(a)!r}")
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class _Reader:
    """Buffered RESP reply parser over a byte stream."""

    def __init__(self, recv):
        self._recv = recv
        self._buf = b""

    def _fill(self) -> None:
        chunk = self._recv(65536)
        if not chunk:
            raise ConnectionError("connection closed by Redis server")
        self._buf += chunk

    def read_line(self) -> bytes:
        while True:
            i = self._buf.find(b"\r\n")
            if i >= 0:
                line, self._buf = self._buf[:i], self._buf[i + 2 :]
                return line
            self._fill()

    def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            self._fill()
        data, self._buf = self._buf[:n], self._buf[n + 2 :]
        return data

    def read_reply(self) -> Any:
        line = self.read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode("utf-8")
        if kind == b"-":
            raise RespError(rest.decode("utf-8"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self.read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self.read_reply() for _ in range(n)]
        raise RespError(f"unknown RESP reply type: {line!r}")


def _text(v: Any) -> Any:
    """Decode bulk-string replies to str (Jedis-like convenience)."""
    if isinstance(v, bytes):
        return v.decode("utf-8")
    if isinstance(v, list):
        return [_text(x) for x in v]
    return v


class RespClient:
    """A blocking RESP2 client with explicit pipelining.

    ``execute`` is one round-trip; ``pipeline`` batches commands and reads
    all replies at once — the flusher uses this so one window flush is one
    round trip no matter how many dirty windows there are (the reference's
    per-window round trips at ``AdvertisingSpark.scala:189-205`` are its
    writeback bottleneck; pipelining is our first free win).
    """

    def __init__(self, host: str = "localhost", port: int = 6379,
                 timeout_s: float | None = 30.0):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = _Reader(self._sock.recv)

    def reconnect(self) -> None:
        """Drop the socket and dial again (sink-outage recovery: after a
        half-open connection or a server restart the old socket can hang
        every command until its timeout; a fresh dial fails fast or
        works).  Any buffered partial reply dies with the old reader —
        reusing it would desynchronize the RESP stream."""
        self.close()
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = _Reader(self._sock.recv)

    # -- single command ------------------------------------------------
    def execute(self, *args: Any) -> Any:
        self._sock.sendall(encode_command(*args))
        return _text(self._reader.read_reply())

    # -- pipelining ----------------------------------------------------
    def pipeline_execute(self, commands: Iterable[tuple]) -> list[Any]:
        cmds = list(commands)
        if not cmds:
            return []
        self._sock.sendall(b"".join(encode_command(*c) for c in cmds))
        replies = []
        for _ in cmds:
            try:
                replies.append(_text(self._reader.read_reply()))
            except RespError as e:
                replies.append(e)
        return replies

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "RespClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- convenience wrappers (the YSB command surface) ---------------
    def ping(self) -> str:
        return self.execute("PING")

    def flushall(self) -> str:
        return self.execute("FLUSHALL")

    def set(self, key: str, value: str) -> str:
        return self.execute("SET", key, value)

    def get(self, key: str) -> str | None:
        return self.execute("GET", key)

    def sadd(self, key: str, *members: str) -> int:
        return self.execute("SADD", key, *members)

    def smembers(self, key: str) -> list[str]:
        return self.execute("SMEMBERS", key)

    def hset(self, key: str, field: str, value: Any) -> int:
        return self.execute("HSET", key, field, value)

    def hget(self, key: str, field: str) -> str | None:
        return self.execute("HGET", key, field)

    def hgetall(self, key: str) -> dict[str, str]:
        flat = self.execute("HGETALL", key)
        return dict(zip(flat[0::2], flat[1::2]))

    def hincrby(self, key: str, field: str, amount: int) -> int:
        return self.execute("HINCRBY", key, field, amount)

    def lpush(self, key: str, *values: str) -> int:
        return self.execute("LPUSH", key, *values)

    def llen(self, key: str) -> int:
        return self.execute("LLEN", key)

    def lrange(self, key: str, start: int, stop: int) -> list[str]:
        return self.execute("LRANGE", key, start, stop)
