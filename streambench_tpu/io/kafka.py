"""Real-Kafka broker adapter (import-guarded; confluent-kafka optional).

The reference's firehose is a live Kafka cluster: the harness creates the
``ad-events`` topic with ``$PARTITIONS`` partitions
(``create_kafka_topic``, ``stream-bench.sh:107-115``) and the generator
produces paced JSON events to it (``core.clj:203``).  This module is the
same firehose behind the exact reader/writer/broker contract the rest of
the framework consumes (``io.journal.FileBroker``), so an engine, the
generator, and the harness can switch between the hermetic file journal
and a real cluster with one constructor swap:

- ``KafkaWriter.append/append_many/flush/close``  == ``JournalWriter``
- ``KafkaReader.poll/seek/offset/close``          == ``JournalReader``
  (offsets are Kafka record offsets, not byte positions — both are
  opaque monotonic ints to checkpoints, which is all ``Snapshot.offset``
  requires)
- ``KafkaBroker.create_topic/partitions/writer/reader/multi_reader/
  read_all``                                      == ``FileBroker``

confluent-kafka is not in this image, so everything is gated: importing
the module is safe anywhere; constructing an adapter without the library
raises ``KafkaUnavailableError`` with install guidance.  The contract
itself is pinned by ``tests/test_kafka_contract.py``, which runs the same
suite against ``FileBroker`` (always) and against ``KafkaBroker`` (only
when the library and a live broker are present).
"""

from __future__ import annotations

import time
from typing import Iterator

try:  # pragma: no cover - exercised only where the library exists
    import confluent_kafka as _ck
    from confluent_kafka.admin import AdminClient as _AdminClient
    from confluent_kafka.admin import NewTopic as _NewTopic
except ImportError:  # the baked image has no confluent-kafka
    _ck = None
    _AdminClient = None
    _NewTopic = None


class KafkaUnavailableError(RuntimeError):
    """confluent-kafka is not installed in this environment."""


def available() -> bool:
    """True when the confluent-kafka client library is importable."""
    return _ck is not None


def _require() -> None:
    if _ck is None:
        raise KafkaUnavailableError(
            "confluent-kafka is not installed; use io.journal.FileBroker "
            "(the hermetic stand-in) or install confluent-kafka to drive "
            "a real cluster")


class KafkaWriter:
    """JournalWriter-contract producer for one (topic, partition)."""

    def __init__(self, brokers: str, topic: str, partition: int = 0,
                 linger_ms: int = 5):
        _require()
        self.topic = topic
        self.partition = partition
        self._producer = _ck.Producer({
            "bootstrap.servers": brokers,
            "linger.ms": linger_ms,
        })

    def append(self, line: str | bytes) -> None:
        data = line.encode("utf-8") if isinstance(line, str) else line
        self._producer.produce(self.topic, value=data.rstrip(b"\n"),
                               partition=self.partition)
        self._producer.poll(0)  # serve delivery callbacks, no blocking

    def append_many(self, lines: list[str] | list[bytes]) -> None:
        for line in lines:
            self.append(line)

    def flush(self) -> None:
        self._producer.flush()

    def close(self) -> None:
        self._producer.flush()

    def __enter__(self) -> "KafkaWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class KafkaReader:
    """JournalReader-contract consumer over one (topic, partition).

    ``offset`` is the next Kafka record offset to consume — the checkpoint
    unit, advanced only over *delivered* records, exactly like the
    journal reader's byte offset (and Kafka's own committed-offset
    semantics, ``setStartFromEarliest``,
    ``AdvertisingTopologyNative.java:92``).
    """

    def __init__(self, brokers: str, topic: str, partition: int = 0,
                 offset: int = 0, group_id: str = "streambench",
                 poll_timeout_s: float = 0.05):
        _require()
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self._poll_timeout = poll_timeout_s
        self._consumer = _ck.Consumer({
            "bootstrap.servers": brokers,
            "group.id": group_id,
            "enable.auto.commit": False,
            "auto.offset.reset": "earliest",
        })
        self._assign()

    def _assign(self) -> None:
        self._consumer.assign(
            [_ck.TopicPartition(self.topic, self.partition, self.offset)])

    def seek(self, offset: int) -> None:
        self.offset = offset
        self._assign()

    def poll(self, max_records: int = 65536) -> list[bytes]:
        msgs = self._consumer.consume(num_messages=max_records,
                                      timeout=self._poll_timeout)
        out: list[bytes] = []
        for m in msgs:
            if m.error() is not None:
                if m.error().code() == _ck.KafkaError._PARTITION_EOF:
                    continue
                raise _ck.KafkaException(m.error())
            out.append(m.value())
            self.offset = m.offset() + 1
        return out

    def poll_blocking(self, max_records: int = 65536,
                      timeout_s: float = 1.0,
                      poll_interval_s: float = 0.001) -> list[bytes]:
        deadline = time.monotonic() + timeout_s
        while True:
            lines = self.poll(max_records)
            if lines or time.monotonic() >= deadline:
                return lines
            time.sleep(poll_interval_s)

    def close(self) -> None:
        self._consumer.close()

    def __enter__(self) -> "KafkaReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class KafkaBroker:
    """FileBroker-contract facade over a real Kafka cluster."""

    def __init__(self, brokers: str, group_id: str = "streambench",
                 create_timeout_s: float = 30.0):
        _require()
        self.brokers = brokers
        self.group_id = group_id
        self._create_timeout = create_timeout_s
        self._admin = _AdminClient({"bootstrap.servers": brokers})

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        futures = self._admin.create_topics(
            [_NewTopic(topic, num_partitions=partitions,
                       replication_factor=1)])
        for fut in futures.values():
            try:
                fut.result(timeout=self._create_timeout)
            except Exception as e:  # TOPIC_ALREADY_EXISTS is fine
                if "TOPIC_ALREADY_EXISTS" not in str(e):
                    raise

    def partitions(self, topic: str) -> list[int]:
        md = self._admin.list_topics(topic, timeout=self._create_timeout)
        t = md.topics.get(topic)
        if t is None or t.error is not None:
            return []
        return sorted(t.partitions)

    def writer(self, topic: str, partition: int = 0,
               append: bool = True) -> KafkaWriter:
        # Kafka topics are always append-only; append=False (truncate)
        # has no cluster analog and is ignored.
        return KafkaWriter(self.brokers, topic, partition)

    def reader(self, topic: str, partition: int = 0,
               offset: int = 0) -> KafkaReader:
        return KafkaReader(self.brokers, topic, partition, offset,
                           group_id=self.group_id)

    def multi_reader(self, topic: str):
        from streambench_tpu.io.journal import MultiReader

        parts = self.partitions(topic) or [0]
        return MultiReader([self.reader(topic, p) for p in parts])

    def read_all(self, topic: str) -> Iterator[bytes]:
        for p in self.partitions(topic):
            with self.reader(topic, p) as r:
                while True:
                    lines = r.poll_blocking(timeout_s=1.0)
                    if not lines:
                        break
                    yield from lines


def make_broker(brokers: str | None, journal_root: str):
    """The one switch point: a real cluster when ``brokers`` names one,
    else the hermetic file journal.

    A named cluster with no client library is an ERROR, not a silent
    fallback — an operator who pointed the harness at Kafka must not get
    a file journal pretending to be one
    (``stream-bench.sh:107-115`` likewise hard-fails without Kafka).
    """
    if brokers:
        if not available():
            raise KafkaUnavailableError(
                f"kafka bootstrap {brokers!r} was configured but "
                "confluent-kafka is not installed; install it or drop "
                "the kafka.bootstrap / KAFKA_BROKERS setting to use the "
                "file-journal broker")
        return KafkaBroker(brokers)
    from streambench_tpu.io.journal import FileBroker

    return FileBroker(journal_root)
