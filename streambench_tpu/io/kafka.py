"""Real-Kafka broker adapter (import-guarded; confluent-kafka optional).

The reference's firehose is a live Kafka cluster: the harness creates the
``ad-events`` topic with ``$PARTITIONS`` partitions
(``create_kafka_topic``, ``stream-bench.sh:107-115``) and the generator
produces paced JSON events to it (``core.clj:203``).  This module is the
same firehose behind the exact reader/writer/broker contract the rest of
the framework consumes (``io.journal.FileBroker``), so an engine, the
generator, and the harness can switch between the hermetic file journal
and a real cluster with one constructor swap:

- ``KafkaWriter.append/append_many/flush/close``  == ``JournalWriter``
- ``KafkaReader.poll/seek/offset/close``          == ``JournalReader``
  (offsets are Kafka record offsets, not byte positions — both are
  opaque monotonic ints to checkpoints, which is all ``Snapshot.offset``
  requires)
- ``KafkaBroker.create_topic/partitions/writer/reader/multi_reader/
  read_all``                                      == ``FileBroker``

Client resolution goes through one module-level seam: every adapter
resolves its client classes from :func:`_clients`, which returns either
the real confluent-kafka surface or whatever bundle :func:`use_clients`
installed (``io.fakekafka.clients()`` — the hermetic broker).  That is
how the broker-contract suite executes this adapter for real in an image
with no confluent-kafka and no cluster, without monkeypatching internals.

confluent-kafka itself stays gated: importing the module is safe
anywhere; constructing an adapter without the library (and without an
installed bundle) raises ``KafkaUnavailableError`` with install
guidance.  The contract is pinned by ``tests/test_kafka_contract.py``,
which runs the same suite against ``FileBroker`` (always), against
``KafkaBroker`` over the fake (always), and against a real cluster when
one exists.

Robustness (the broker edge is a fault surface — ROBUSTNESS.md):

- transient produce/consume errors and broker-down windows are absorbed
  by bounded capped-jitter retry/backoff (the PR 1 backoff shape), with
  every retry and every backoff millisecond counted
  (``kafka_produce_retries`` / ``kafka_consume_retries`` /
  ``kafka_broker_down_ms``);
- a failed delivery report re-produces the record (it never landed);
- a reconnecting consumer REDELIVERS records past the last checkpoint —
  the reader counts them (``kafka_redeliveries``) and filters them, so
  at-least-once at the socket stays exactly-once into the engine;
- a mid-batch consumer error returns the records already accumulated
  before surfacing (the pre-hardening adapter dropped them after the
  offset had advanced: silent data loss on retry);
- ``pause()``/``resume()`` park the consumer broker-side (the admission
  controller's defer actuator), with ``lag()`` measuring the backlog
  left in the broker via watermark offsets.
"""

from __future__ import annotations

import random
import time
from types import SimpleNamespace
from typing import Iterator

from streambench_tpu.metrics import FaultCounters

try:  # pragma: no cover - exercised only where the library exists
    import confluent_kafka as _ck
    from confluent_kafka.admin import AdminClient as _AdminClient
    from confluent_kafka.admin import NewTopic as _NewTopic
except ImportError:  # the baked image has no confluent-kafka
    _ck = None
    _AdminClient = None
    _NewTopic = None

#: retry/backoff defaults (overridable per adapter): bounded, capped,
#: jittered — the PR 1 supervisor shape at producer/consumer scale
RETRY_LIMIT = 16
RETRY_BASE_MS = 25.0
RETRY_CAP_MS = 500.0


class KafkaUnavailableError(RuntimeError):
    """confluent-kafka is not installed in this environment."""


def available() -> bool:
    """True when the confluent-kafka client library is importable."""
    return _ck is not None


def _require() -> None:
    if _ck is None:
        raise KafkaUnavailableError(
            "confluent-kafka is not installed; use io.journal.FileBroker "
            "(the hermetic stand-in) or install confluent-kafka to drive "
            "a real cluster")


#: the injection seam: a client bundle installed by use_clients() wins
#: over the real library (io.fakekafka.clients() is the one installer)
_override = None


def use_clients(bundle) -> None:
    """Install (or with ``None`` remove) an alternate client bundle.

    The bundle must expose ``Producer``/``Consumer``/``AdminClient``/
    ``NewTopic``/``TopicPartition``/``KafkaError``/``KafkaException`` —
    the exact surface this adapter touches.  This is the module-level
    seam the hermetic fake installs through; nothing else in the adapter
    special-cases fakes.
    """
    global _override
    _override = bundle


def _clients():
    """The client bundle every adapter constructor resolves."""
    if _override is not None:
        return _override
    _require()
    return SimpleNamespace(
        Producer=_ck.Producer, Consumer=_ck.Consumer,
        TopicPartition=_ck.TopicPartition, KafkaError=_ck.KafkaError,
        KafkaException=_ck.KafkaException,
        AdminClient=_AdminClient, NewTopic=_NewTopic)


def _retriable(exc) -> bool:
    """Transient per librdkafka's own taxonomy (``KafkaError.retriable``
    plus the local-queue-full BufferError)."""
    if isinstance(exc, BufferError):
        return True
    err = exc.args[0] if getattr(exc, "args", None) else None
    try:
        return bool(err.retriable())
    except Exception:
        return False


class _Backoff:
    """Capped exponential backoff with jitter (PR 1 shape), counted."""

    def __init__(self, base_ms: float, cap_ms: float, limit: int,
                 counters: FaultCounters, rng: "random.Random | None"):
        self.base_ms = max(float(base_ms), 0.0)
        self.cap_ms = max(float(cap_ms), self.base_ms)
        self.limit = max(int(limit), 0)
        self.counters = counters
        self._rng = rng if rng is not None else random.Random()

    def sleep(self, attempt: int) -> None:
        n = min(max(attempt, 1), 16)
        base = min(self.base_ms * (1 << (n - 1)), self.cap_ms)
        ms = base * (0.5 + 0.5 * self._rng.random())
        self.counters.inc("kafka_broker_down_ms", int(ms) or 1)
        time.sleep(ms / 1000.0)


class KafkaWriter:
    """JournalWriter-contract producer for one (topic, partition).

    ``counters`` accounting: ``kafka_produced`` — records acked by the
    broker (the *sent* side of the delivery ledger);
    ``kafka_produce_retries`` — re-produces after a transient error or a
    failed delivery report; ``kafka_broker_down_ms`` — backoff sleep.
    """

    def __init__(self, brokers: str, topic: str, partition: int = 0,
                 linger_ms: int = 5, clients=None,
                 counters: "FaultCounters | None" = None,
                 retry_base_ms: float = RETRY_BASE_MS,
                 retry_cap_ms: float = RETRY_CAP_MS,
                 retry_limit: int = RETRY_LIMIT,
                 rng: "random.Random | None" = None):
        self._c = clients if clients is not None else _clients()
        self.topic = topic
        self.partition = partition
        self.counters = counters if counters is not None else FaultCounters()
        self.retry_limit = max(int(retry_limit), 0)
        self._back = _Backoff(retry_base_ms, retry_cap_ms, retry_limit,
                              self.counters, rng)
        self._redo: list[bytes] = []   # failed delivery reports, re-produced
        self._producer = self._c.Producer({
            "bootstrap.servers": brokers,
            "linger.ms": linger_ms,
        })

    def _on_delivery(self, err, msg) -> None:
        if err is None:
            self.counters.inc("kafka_produced")
            return
        # the record never landed: queue it for re-produce (at-least-once
        # is the writer's job; the reader dedupes the other direction)
        self.counters.inc("kafka_dr_failures")
        self._redo.append(msg.value())

    def _produce(self, data: bytes) -> None:
        attempt = 0
        while True:
            try:
                self._producer.produce(self.topic, value=data,
                                       partition=self.partition,
                                       on_delivery=self._on_delivery)
                self._producer.poll(0)  # serve delivery callbacks
                return
            except Exception as e:
                if not _retriable(e) or attempt >= self.retry_limit:
                    raise
                attempt += 1
                self.counters.inc("kafka_produce_retries")
                self._back.sleep(attempt)

    def _drain_redo(self) -> None:
        rounds = 0
        while self._redo and rounds <= self.retry_limit:
            rounds += 1
            redo, self._redo = self._redo, []
            for data in redo:
                self.counters.inc("kafka_produce_retries")
                self._produce(data)
            self._producer.flush()
        if self._redo:
            raise self._c.KafkaException(self._c.KafkaError(
                self._c.KafkaError._MSG_TIMED_OUT
                if hasattr(self._c.KafkaError, "_MSG_TIMED_OUT") else -192,
                f"{len(self._redo)} records undeliverable after "
                f"{rounds} re-produce rounds"))

    def append(self, line: str | bytes) -> None:
        data = line.encode("utf-8") if isinstance(line, str) else line
        self._produce(data.rstrip(b"\n"))

    def append_many(self, lines: list[str] | list[bytes]) -> None:
        for line in lines:
            self.append(line)

    def flush(self) -> None:
        self._producer.flush()
        self._drain_redo()

    def close(self) -> None:
        self.flush()

    def __enter__(self) -> "KafkaWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class KafkaReader:
    """JournalReader-contract consumer over one (topic, partition).

    ``offset`` is the next Kafka record offset to consume — the checkpoint
    unit, advanced only over *delivered* records, exactly like the
    journal reader's byte offset (and Kafka's own committed-offset
    semantics, ``setStartFromEarliest``,
    ``AdvertisingTopologyNative.java:92``).

    Delivery ledger (``counters``): ``kafka_consumed`` counts every
    record the broker handed up; ``kafka_delivered`` the unique records
    returned to the caller; ``kafka_redeliveries`` the duplicates a
    reconnecting broker re-sent (observed, counted, filtered — never
    double-delivered); ``kafka_consume_retries``/``kafka_broker_down_ms``
    the retry/backoff spent absorbing transient errors.
    """

    def __init__(self, brokers: str, topic: str, partition: int = 0,
                 offset: int = 0, group_id: str = "streambench",
                 poll_timeout_s: float = 0.05, clients=None,
                 counters: "FaultCounters | None" = None,
                 retry_base_ms: float = RETRY_BASE_MS,
                 retry_cap_ms: float = RETRY_CAP_MS,
                 retry_limit: int = RETRY_LIMIT,
                 rng: "random.Random | None" = None):
        self._c = clients if clients is not None else _clients()
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.counters = counters if counters is not None else FaultCounters()
        self.retry_limit = max(int(retry_limit), 0)
        self._back = _Backoff(retry_base_ms, retry_cap_ms, retry_limit,
                              self.counters, rng)
        self._poll_timeout = poll_timeout_s
        self._paused = False
        self._consumer = self._c.Consumer({
            "bootstrap.servers": brokers,
            "group.id": group_id,
            "enable.auto.commit": False,
            "auto.offset.reset": "earliest",
        })
        self._assign()

    def _tp(self):
        return self._c.TopicPartition(self.topic, self.partition,
                                      self.offset)

    def _assign(self) -> None:
        self._consumer.assign([self._tp()])

    def seek(self, offset: int) -> None:
        self.offset = offset
        self._assign()

    # -- admission actuator: park the backlog IN THE BROKER ------------
    def pause(self) -> None:
        """Stop fetching; records queue up broker-side (measured by
        ``lag()``), nothing is dropped.  The admission controller's
        defer actuator."""
        if self._paused:
            return
        self._paused = True
        try:
            self._consumer.pause([self._tp()])
        except Exception:
            pass  # pause is an optimization; the poll() gate is the law

    def resume(self) -> None:
        if not self._paused:
            return
        self._paused = False
        try:
            self._consumer.resume([self._tp()])
        except Exception:
            pass

    @property
    def paused(self) -> bool:
        return self._paused

    def lag(self) -> int:
        """Records sitting in the broker past this reader's offset (the
        consumer-lag gauge unit)."""
        try:
            _lo, hi = self._consumer.get_watermark_offsets(
                self._tp(), timeout=0.1)
        except Exception:
            return 0
        return max(int(hi) - int(self.offset), 0)

    # -- consume -------------------------------------------------------
    def _consume_into(self, out: list, max_records: int):
        """One consume pass.  Appends delivered values to ``out`` and
        advances ``offset``; returns the first non-EOF error (records
        accumulated BEFORE it stay in ``out``), or None."""
        try:
            msgs = self._consumer.consume(
                num_messages=max(max_records - len(out), 1),
                timeout=self._poll_timeout)
        except Exception as e:
            if _retriable(e):
                return e.args[0] if getattr(e, "args", None) else e
            raise
        for m in msgs:
            err = m.error()
            if err is not None:
                if err.code() == self._c.KafkaError._PARTITION_EOF:
                    continue
                return err
            off = m.offset()
            self.counters.inc("kafka_consumed")
            if off is not None and off < self.offset:
                # a reconnecting broker re-sent records we already
                # delivered: count the redelivery, never double-deliver
                self.counters.inc("kafka_redeliveries")
                continue
            out.append(m.value())
            self.counters.inc("kafka_delivered")
            if off is not None:
                self.offset = max(self.offset, off + 1)
            else:
                self.offset += 1
        return None

    def _pump(self, out: list, max_records: int):
        """Consume passes until records are delivered, the tail is
        confirmed, or an error surfaces.  A pass that yields nothing but
        filtered redeliveries is PROGRESS, not the tail — returning []
        there would read as caught-up to a catchup loop while undelivered
        records still sit past the rewound batch."""
        while True:
            before = self.counters.get("kafka_consumed")
            err = self._consume_into(out, max_records)
            if err is not None or out:
                return err
            if self.counters.get("kafka_consumed") == before:
                return None   # clean empty fetch: genuinely at the tail

    def poll(self, max_records: int = 65536) -> list[bytes]:
        if self._paused:
            return []
        out: list[bytes] = []
        err = self._pump(out, max_records)
        attempt = 0
        # retry only from empty: once records are in hand they are
        # returned THIS call — the pre-hardening adapter raised here and
        # dropped them after the offset had advanced (data loss)
        while err is not None and not out and attempt < self.retry_limit:
            attempt += 1
            self.counters.inc("kafka_consume_retries")
            self._back.sleep(attempt)
            err = self._pump(out, max_records)
        if err is not None and not out:
            raise self._c.KafkaException(err)
        return out

    def poll_blocking(self, max_records: int = 65536,
                      timeout_s: float = 1.0,
                      poll_interval_s: float = 0.001) -> list[bytes]:
        deadline = time.monotonic() + timeout_s
        while True:
            lines = self.poll(max_records)
            if lines or time.monotonic() >= deadline:
                return lines
            time.sleep(poll_interval_s)

    def close(self) -> None:
        self._consumer.close()

    def __enter__(self) -> "KafkaReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class KafkaBroker:
    """FileBroker-contract facade over a real (or fake) Kafka cluster.

    One ``FaultCounters`` ledger is shared by every writer/reader this
    broker hands out, so a run's delivery accounting
    (``kafka_produced`` == ``kafka_delivered``,
    ``kafka_consumed`` == delivered + redelivered) reads off a single
    snapshot — ``chaos.verify.check_kafka_edge`` consumes it.
    """

    def __init__(self, brokers: str, group_id: str = "streambench",
                 create_timeout_s: float = 30.0, clients=None,
                 counters: "FaultCounters | None" = None):
        self._c = clients if clients is not None else _clients()
        self.brokers = brokers
        self.group_id = group_id
        self.counters = counters if counters is not None else FaultCounters()
        self._create_timeout = create_timeout_s
        self._admin = self._c.AdminClient({"bootstrap.servers": brokers})

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        futures = self._admin.create_topics(
            [self._c.NewTopic(topic, num_partitions=partitions,
                              replication_factor=1)])
        for fut in futures.values():
            try:
                fut.result(timeout=self._create_timeout)
            except Exception as e:  # TOPIC_ALREADY_EXISTS is fine
                if "TOPIC_ALREADY_EXISTS" not in str(e):
                    raise

    def partitions(self, topic: str) -> list[int]:
        md = self._admin.list_topics(topic, timeout=self._create_timeout)
        t = md.topics.get(topic)
        if t is None or t.error is not None:
            return []
        return sorted(t.partitions)

    def writer(self, topic: str, partition: int = 0,
               append: bool = True) -> KafkaWriter:
        # Kafka topics are always append-only; append=False (truncate)
        # has no cluster analog and is ignored.
        return KafkaWriter(self.brokers, topic, partition,
                           clients=self._c, counters=self.counters)

    def reader(self, topic: str, partition: int = 0,
               offset: int = 0) -> KafkaReader:
        return KafkaReader(self.brokers, topic, partition, offset,
                           group_id=self.group_id, clients=self._c,
                           counters=self.counters)

    def multi_reader(self, topic: str):
        from streambench_tpu.io.journal import MultiReader

        parts = self.partitions(topic) or [0]
        return MultiReader([self.reader(topic, p) for p in parts])

    def read_all(self, topic: str) -> Iterator[bytes]:
        for p in self.partitions(topic):
            with self.reader(topic, p) as r:
                while True:
                    lines = r.poll_blocking(timeout_s=1.0)
                    if not lines:
                        break
                    yield from lines


def make_broker(brokers: str | None, journal_root: str, *,
                fake: bool = False):
    """The one switch point: a real cluster when ``brokers`` names one,
    the hermetic fake broker when ``fake`` is set (``kafka.fake``), else
    the file journal.

    A named cluster with no client library is an ERROR, not a silent
    fallback — an operator who pointed the harness at Kafka must not get
    a file journal pretending to be one
    (``stream-bench.sh:107-115`` likewise hard-fails without Kafka).
    """
    if fake:
        from streambench_tpu.io import fakekafka

        # empty bootstrap -> the in-process cluster; host:port -> a
        # FakeKafkaServer process (START_KAFKA)
        return KafkaBroker(brokers or fakekafka.INPROC,
                           clients=fakekafka.clients())
    if brokers:
        if not available():
            raise KafkaUnavailableError(
                f"kafka bootstrap {brokers!r} was configured but "
                "confluent-kafka is not installed; install it or drop "
                "the kafka.bootstrap / KAFKA_BROKERS setting to use the "
                "file-journal broker")
        return KafkaBroker(brokers)
    from streambench_tpu.io.journal import FileBroker

    return FileBroker(journal_root)
