"""Recorded-protocol fake of the confluent-kafka surface our adapter uses.

The reference's firehose is a live Kafka cluster, but this image has no
confluent-kafka and no broker — so ``io/kafka.py`` has never executed in
the suite.  This module is the missing half of the ``io/fakeredis.py``
pattern: a semantics-honest stand-in for exactly the client subset
``KafkaWriter``/``KafkaReader``/``KafkaBroker`` touch, good enough that
the broker-contract suite runs against the *real adapter* unmodified.

Two modes, one cluster model:

- **in-process** — ``clients(cluster)`` returns a client bundle that the
  adapter installs through its module-level seam
  (:func:`streambench_tpu.io.kafka.use_clients`) or receives via the
  ``clients=`` constructor argument.  ``":inprocess:"`` as
  ``bootstrap.servers`` resolves to a process-global cluster (the
  ``redis.host: :inprocess:`` precedent).
- **TCP** — :class:`FakeKafkaServer` serves the same cluster over a
  JSON-lines socket protocol (the :class:`~streambench_tpu.io.fakeredis.
  FakeRedisServer` precedent), so ``stream_bench.py`` can launch a real
  broker *process* (START_KAFKA/STOP_KAFKA) and the engine CLI consumes
  over an actual socket.

Delivery model (the honest parts, pinned by ``tests/test_fakekafka.py``):

- per-partition logs are append-only; record offsets are list indices;
  per-partition order is ALWAYS preserved, faults included;
- producers get delivery callbacks (served by ``poll``/``flush``); a
  delivery-report failure means the record did NOT land and the callback
  says so — the hardened writer re-produces it;
- a consumer that loses its connection rewinds to the start of the last
  batch it *returned* — un-checkpointed records arrive twice (Kafka's
  at-least-once shape); the hardened reader counts and filters the
  redelivery;
- broker faults are drawn from a seeded :class:`~streambench_tpu.chaos.
  plan.FaultPlan` via ``FaultInjector.kafka_fault()`` — same plan, same
  faults, byte for byte, and a rate-0 plan is an exact pass-through.
"""

from __future__ import annotations

import argparse
import base64
import json
import signal
import socket
import socketserver
import threading
import time

from streambench_tpu.metrics import FaultCounters

#: bootstrap.servers sentinel: the process-global in-process cluster
INPROC = ":inprocess:"

# confluent_kafka.KafkaError code values (the ones the adapter and the
# fault model touch); negative codes are librdkafka-internal.
ERR__PARTITION_EOF = -191
ERR__TRANSPORT = -195
ERR__ALL_BROKERS_DOWN = -187
ERR__MSG_TIMED_OUT = -192
ERR_TOPIC_ALREADY_EXISTS = 36
ERR_UNKNOWN_TOPIC_OR_PART = 3

_RETRIABLE = frozenset({ERR__TRANSPORT, ERR__ALL_BROKERS_DOWN,
                        ERR__MSG_TIMED_OUT})


class FakeKafkaError:
    """``confluent_kafka.KafkaError`` lookalike (code + retriable)."""

    _PARTITION_EOF = ERR__PARTITION_EOF
    _TRANSPORT = ERR__TRANSPORT
    _ALL_BROKERS_DOWN = ERR__ALL_BROKERS_DOWN
    _MSG_TIMED_OUT = ERR__MSG_TIMED_OUT
    TOPIC_ALREADY_EXISTS = ERR_TOPIC_ALREADY_EXISTS
    UNKNOWN_TOPIC_OR_PART = ERR_UNKNOWN_TOPIC_OR_PART

    def __init__(self, code: int, reason: str = ""):
        self._code = int(code)
        self._reason = reason or f"fake kafka error code={code}"

    def code(self) -> int:
        return self._code

    def retriable(self) -> bool:
        return self._code in _RETRIABLE

    def str(self) -> str:
        return self._reason

    def __str__(self) -> str:  # KafkaException(err) stringifies the error
        return self._reason

    def __repr__(self) -> str:
        return f"FakeKafkaError({self._code}, {self._reason!r})"


class FakeKafkaException(Exception):
    """``confluent_kafka.KafkaException``: ``args[0]`` is the error."""


class FakeTopicPartition:
    """``confluent_kafka.TopicPartition`` lookalike."""

    def __init__(self, topic: str, partition: int = 0, offset: int = 0):
        self.topic = topic
        self.partition = int(partition)
        self.offset = int(offset)

    def __repr__(self) -> str:
        return (f"FakeTopicPartition({self.topic!r}, {self.partition}, "
                f"{self.offset})")


class FakeMessage:
    """``confluent_kafka.Message`` lookalike (value/offset/error)."""

    __slots__ = ("_topic", "_partition", "_offset", "_value", "_error")

    def __init__(self, topic, partition, offset=None, value=None,
                 error=None):
        self._topic = topic
        self._partition = partition
        self._offset = offset
        self._value = value
        self._error = error

    def topic(self):
        return self._topic

    def partition(self):
        return self._partition

    def offset(self):
        return self._offset

    def value(self):
        return self._value

    def error(self):
        return self._error


class FakeConnectionDropped(ConnectionError):
    """The broker dropped this client's connection (fault-injected)."""


# ---------------------------------------------------------------------------
# the cluster: per-partition append-only logs + seeded broker faults
# ---------------------------------------------------------------------------

class FakeCluster:
    """Broker state shared by every client (in-process or via TCP).

    ``attach_chaos(injector)`` arms seeded broker-surface faults: every
    append/fetch asks ``injector.kafka_fault()`` for this op's fault
    kind (``None`` almost always).  Kinds not applicable to the op are
    ignored — the draw is consumed either way, so fault placement is a
    pure function of the plan and the op sequence.
    """

    def __init__(self, chaos=None):
        self._lock = threading.RLock()
        self._topics: "dict[str, list[list[bytes]]]" = {}
        self._chaos = chaos
        self.counters = FaultCounters()

    def attach_chaos(self, injector) -> None:
        with self._lock:
            self._chaos = injector

    def _fault(self) -> "str | None":
        chaos = self._chaos
        if chaos is None:
            return None
        return chaos.kafka_fault()

    # -- admin ---------------------------------------------------------
    def create_topic(self, topic: str, partitions: int = 1) -> bool:
        """True when created, False when it already existed."""
        with self._lock:
            if topic in self._topics:
                return False
            self._topics[topic] = [[] for _ in range(max(partitions, 1))]
            return True

    def topics_meta(self) -> "dict[str, int]":
        with self._lock:
            return {t: len(parts) for t, parts in self._topics.items()}

    # -- data plane ----------------------------------------------------
    def append(self, topic: str, partition: int, value: bytes):
        """-> ``(offset, fault_kind)``; ``offset`` None when rejected."""
        kind = self._fault()
        if kind in ("down", "produce", "dr_fail"):
            self.counters.inc(f"fake_kafka_{kind}")
            return None, kind
        with self._lock:
            parts = self._topics.setdefault(
                topic, [[] for _ in range(partition + 1)])
            while len(parts) <= partition:
                parts.append([])
            log = parts[partition]
            log.append(bytes(value))
            return len(log) - 1, None

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int):
        """-> ``(records, log_end, fault_kind)``.

        ``records`` is ``[(offset, value), ...]`` starting at ``offset``;
        on a fault the records that WOULD have shipped are withheld
        (nothing was delivered), matching a socket that died mid-fetch.
        """
        kind = self._fault()
        if kind in ("down", "consume", "conn_drop"):
            self.counters.inc(f"fake_kafka_{kind}")
            return [], self.log_end(topic, partition), kind
        with self._lock:
            parts = self._topics.get(topic)
            log = parts[partition] if parts and partition < len(parts) \
                else []
            end = len(log)
            lo = max(int(offset), 0)
            recs = [(i, log[i])
                    for i in range(lo, min(end, lo + max(max_records, 0)))]
            return recs, end, None

    def log_end(self, topic: str, partition: int) -> int:
        with self._lock:
            parts = self._topics.get(topic)
            if not parts or partition >= len(parts):
                return 0
            return len(parts[partition])

    def total_records(self) -> int:
        with self._lock:
            return sum(len(log) for parts in self._topics.values()
                       for log in parts)


_default_cluster: "FakeCluster | None" = None
_default_lock = threading.Lock()


def default_cluster() -> FakeCluster:
    """The process-global cluster behind ``":inprocess:"``."""
    global _default_cluster
    with _default_lock:
        if _default_cluster is None:
            _default_cluster = FakeCluster()
        return _default_cluster


def reset_default_cluster() -> None:
    """Drop the process-global cluster (test isolation)."""
    global _default_cluster
    with _default_lock:
        _default_cluster = None


# ---------------------------------------------------------------------------
# transports: same five verbs in-process or over the JSON-lines socket
# ---------------------------------------------------------------------------

class _InProcTransport:
    def __init__(self, cluster: FakeCluster):
        self._cluster = cluster

    def create(self, topic, partitions):
        return self._cluster.create_topic(topic, partitions)

    def meta(self):
        return self._cluster.topics_meta()

    def append(self, topic, partition, value):
        off, kind = self._cluster.append(topic, partition, value)
        if kind == "conn_drop":  # not applicable to appends, but honest
            raise FakeConnectionDropped("broker dropped the connection")
        return off, kind

    def fetch(self, topic, partition, offset, max_records):
        recs, end, kind = self._cluster.fetch(topic, partition, offset,
                                              max_records)
        if kind == "conn_drop":
            raise FakeConnectionDropped("broker dropped the connection")
        return recs, end, kind

    def log_end(self, topic, partition):
        return self._cluster.log_end(topic, partition)

    def close(self):
        pass


class _TcpTransport:
    """One JSON-lines connection to a :class:`FakeKafkaServer`.

    A request is one JSON object + ``\\n``; the response likewise.  A
    fault-injected connection drop closes the socket server-side — the
    next request here raises, and the caller reconnects lazily.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._addr = (host, int(port))
        self._timeout = timeout_s
        self._sock: "socket.socket | None" = None
        self._buf = b""
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self._addr, timeout=self._timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            self._buf = b""
        return self._sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._buf = b""

    def _rpc(self, req: dict) -> dict:
        with self._lock:
            try:
                s = self._connect()
                s.sendall(json.dumps(req).encode("utf-8") + b"\n")
                while b"\n" not in self._buf:
                    chunk = s.recv(65536)
                    if not chunk:
                        raise FakeConnectionDropped(
                            "broker dropped the connection")
                    self._buf += chunk
                line, self._buf = self._buf.split(b"\n", 1)
            except (OSError, FakeConnectionDropped):
                self._drop()
                raise FakeConnectionDropped(
                    "broker dropped the connection") from None
            return json.loads(line)

    def create(self, topic, partitions):
        return self._rpc({"op": "create", "topic": topic,
                          "parts": partitions})["created"]

    def meta(self):
        return {t: int(n)
                for t, n in self._rpc({"op": "meta"})["topics"].items()}

    def append(self, topic, partition, value):
        r = self._rpc({"op": "append", "topic": topic, "part": partition,
                       "value": base64.b64encode(value).decode("ascii")})
        return r.get("offset"), r.get("fault")

    def fetch(self, topic, partition, offset, max_records):
        r = self._rpc({"op": "fetch", "topic": topic, "part": partition,
                       "offset": offset, "max": max_records})
        recs = [(int(o), base64.b64decode(v)) for o, v in r["records"]]
        return recs, int(r["end"]), r.get("fault")

    def log_end(self, topic, partition):
        return int(self._rpc({"op": "end", "topic": topic,
                              "part": partition})["end"])

    def close(self):
        with self._lock:
            self._drop()


def _transport(conf: dict, cluster: "FakeCluster | None"):
    if cluster is not None:
        return _InProcTransport(cluster)
    servers = str(conf.get("bootstrap.servers", "") or "")
    if servers in ("", INPROC):
        return _InProcTransport(default_cluster())
    first = servers.split(",")[0].strip()
    host, _, port = first.rpartition(":")
    return _TcpTransport(host or "127.0.0.1", int(port))


# ---------------------------------------------------------------------------
# the client surface the adapter touches
# ---------------------------------------------------------------------------

class FakeProducer:
    """``confluent_kafka.Producer`` subset: produce/poll/flush.

    Delivery reports are queued at produce time and served from
    ``poll``/``flush`` exactly like librdkafka's callback pump.  A
    fault-injected produce error raises (retriable); a delivery-report
    failure lands the error in the callback instead — the record did
    NOT reach the log either way.
    """

    def __init__(self, conf: dict, *, cluster: "FakeCluster | None" = None):
        self._conf = dict(conf or {})
        self._t = _transport(self._conf, cluster)
        self._pending: "list[tuple]" = []   # (callback, err, msg)
        self._lock = threading.Lock()

    def produce(self, topic, value=None, partition=0, on_delivery=None,
                callback=None, **_kw):
        cb = on_delivery or callback
        data = value if isinstance(value, bytes) else \
            str(value or "").encode("utf-8")
        try:
            off, kind = self._t.append(topic, int(partition), data)
        except FakeConnectionDropped:
            raise FakeKafkaException(FakeKafkaError(
                ERR__TRANSPORT, "produce failed: connection dropped"))
        if kind == "down":
            raise FakeKafkaException(FakeKafkaError(
                ERR__ALL_BROKERS_DOWN, "produce failed: broker down"))
        if kind == "produce":
            raise FakeKafkaException(FakeKafkaError(
                ERR__TRANSPORT, "produce failed: transient broker error"))
        if kind == "dr_fail":
            msg = FakeMessage(topic, int(partition), None, data,
                              FakeKafkaError(ERR__MSG_TIMED_OUT,
                                             "delivery report: timed out"))
            with self._lock:
                self._pending.append(
                    (cb, msg.error(), msg))
            return
        msg = FakeMessage(topic, int(partition), off, data, None)
        with self._lock:
            self._pending.append((cb, None, msg))

    def poll(self, timeout=0):
        with self._lock:
            pending, self._pending = self._pending, []
        for cb, err, msg in pending:
            if cb is not None:
                cb(err, msg)
        return len(pending)

    def flush(self, timeout=None):
        self.poll(0)
        return 0

    def __len__(self):
        with self._lock:
            return len(self._pending)


class FakeConsumer:
    """``confluent_kafka.Consumer`` subset: assign/seek/consume/pause.

    Fetch positions live client-side (like librdkafka's fetch state).
    On a dropped connection the consumer reconnects and resumes from the
    start of the last batch it *returned* — anything newer it had
    fetched but not surfaced is refetched, and anything the caller has
    not checkpointed arrives again.  That redelivery is the honest
    at-least-once shape the hardened reader must absorb.
    """

    def __init__(self, conf: dict, *, cluster: "FakeCluster | None" = None):
        self._conf = dict(conf or {})
        self._t = _transport(self._conf, cluster)
        self._pos: "dict[tuple, int]" = {}
        self._batch_start: "dict[tuple, int]" = {}
        self._order: "list[tuple]" = []
        self._paused: "set[tuple]" = set()
        self._closed = False

    @staticmethod
    def _key(tp) -> tuple:
        return (tp.topic, int(tp.partition))

    def assign(self, tps) -> None:
        self._order = []
        for tp in tps:
            k = self._key(tp)
            off = int(getattr(tp, "offset", 0))
            if off < 0:   # OFFSET_BEGINNING (-2) / OFFSET_END (-1)
                off = self._t.log_end(*k) if off == -1 else 0
            self._order.append(k)
            self._pos[k] = off
            self._batch_start[k] = off

    def seek(self, tp) -> None:
        k = self._key(tp)
        self._pos[k] = int(tp.offset)
        self._batch_start[k] = int(tp.offset)

    def pause(self, tps) -> None:
        self._paused.update(self._key(tp) for tp in tps)

    def resume(self, tps) -> None:
        self._paused.difference_update(self._key(tp) for tp in tps)

    def get_watermark_offsets(self, tp, timeout=None, cached=False):
        return 0, self._t.log_end(*self._key(tp))

    def _dropped(self) -> "list[FakeMessage]":
        # reconnect-and-rewind: resume from the last RETURNED batch
        for k in self._order:
            self._pos[k] = self._batch_start.get(k, self._pos.get(k, 0))
        return [FakeMessage(None, None, None, None,
                            FakeKafkaError(ERR__TRANSPORT,
                                           "connection dropped; "
                                           "reconnected"))]

    def consume(self, num_messages=1, timeout=None):
        if self._closed:
            raise FakeKafkaException(FakeKafkaError(
                ERR__TRANSPORT, "consumer is closed"))
        out: "list[FakeMessage]" = []
        for k in self._order:
            if k in self._paused or len(out) >= num_messages:
                continue
            topic, part = k
            pos = self._pos[k]
            try:
                recs, end, kind = self._t.fetch(
                    topic, part, pos, num_messages - len(out))
            except FakeConnectionDropped:
                out.extend(self._dropped())
                continue
            if kind == "down":
                out.append(FakeMessage(topic, part, None, None,
                                       FakeKafkaError(
                                           ERR__ALL_BROKERS_DOWN,
                                           "broker down")))
                continue
            if recs:
                self._batch_start[k] = pos
                for off, val in recs:
                    out.append(FakeMessage(topic, part, off, val, None))
                self._pos[k] = recs[-1][0] + 1
            elif kind is None and pos >= end:
                # a clean empty fetch confirms the position: a later
                # drop rewinds at most one batch, never the whole log
                self._batch_start[k] = pos
                out.append(FakeMessage(topic, part, end, None,
                                       FakeKafkaError(ERR__PARTITION_EOF,
                                                      "partition EOF")))
            if kind == "consume":
                out.append(FakeMessage(topic, part, None, None,
                                       FakeKafkaError(
                                           ERR__TRANSPORT,
                                           "transient consume error")))
        return out

    def close(self) -> None:
        self._closed = True
        self._t.close()


class _FakeFuture:
    def __init__(self, exc=None):
        self._exc = exc

    def result(self, timeout=None):
        if self._exc is not None:
            raise self._exc
        return None


class _TopicMetadata:
    def __init__(self, topic: str, partitions: int):
        self.topic = topic
        self.error = None
        self.partitions = {i: None for i in range(partitions)}


class _ClusterMetadata:
    def __init__(self, topics: "dict[str, int]"):
        self.topics = {t: _TopicMetadata(t, n) for t, n in topics.items()}


class FakeAdminClient:
    """``confluent_kafka.admin.AdminClient`` subset."""

    def __init__(self, conf: dict, *, cluster: "FakeCluster | None" = None):
        self._conf = dict(conf or {})
        self._t = _transport(self._conf, cluster)

    def create_topics(self, new_topics):
        futures = {}
        for nt in new_topics:
            created = self._t.create(nt.topic,
                                     int(getattr(nt, "num_partitions", 1)))
            exc = None if created else FakeKafkaException(FakeKafkaError(
                ERR_TOPIC_ALREADY_EXISTS,
                f"TOPIC_ALREADY_EXISTS: {nt.topic!r}"))
            futures[nt.topic] = _FakeFuture(exc)
        return futures

    def list_topics(self, topic=None, timeout=None) -> _ClusterMetadata:
        meta = self._t.meta()
        if topic is not None:
            meta = {t: n for t, n in meta.items() if t == topic}
        return _ClusterMetadata(meta)


class FakeNewTopic:
    """``confluent_kafka.admin.NewTopic`` lookalike."""

    def __init__(self, topic: str, num_partitions: int = 1,
                 replication_factor: int = 1):
        self.topic = topic
        self.num_partitions = int(num_partitions)
        self.replication_factor = int(replication_factor)


class FakeClients:
    """The client bundle ``io.kafka`` resolves through its seam.

    Mirrors the attribute surface the adapter needs: ``Producer``,
    ``Consumer``, ``AdminClient``, ``NewTopic``, ``TopicPartition``,
    ``KafkaError``, ``KafkaException``.  When ``cluster`` is given every
    client binds to it; otherwise each client resolves its own transport
    from ``bootstrap.servers`` (``":inprocess:"`` or ``host:port``).
    """

    name = "fakekafka"

    def __init__(self, cluster: "FakeCluster | None" = None):
        self.cluster = cluster
        self.NewTopic = FakeNewTopic
        self.TopicPartition = FakeTopicPartition
        self.KafkaError = FakeKafkaError
        self.KafkaException = FakeKafkaException

    def Producer(self, conf):
        return FakeProducer(conf, cluster=self.cluster)

    def Consumer(self, conf):
        return FakeConsumer(conf, cluster=self.cluster)

    def AdminClient(self, conf):
        return FakeAdminClient(conf, cluster=self.cluster)


def clients(cluster: "FakeCluster | None" = None) -> FakeClients:
    """A client bundle for :func:`streambench_tpu.io.kafka.use_clients`
    or the ``clients=`` constructor seam."""
    return FakeClients(cluster)


# ---------------------------------------------------------------------------
# the standalone broker process (FakeRedisServer precedent)
# ---------------------------------------------------------------------------

class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        cluster: FakeCluster = self.server.cluster  # type: ignore
        buf = b""
        while True:
            try:
                chunk = self.request.recv(65536)
            except (ConnectionError, OSError):
                return
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    req = json.loads(line)
                    resp, drop = self._dispatch(cluster, req)
                except Exception as e:  # malformed request: answer, keep going
                    resp, drop = {"ok": False, "err": str(e)}, False
                if drop:
                    # fault-injected connection drop: no response, close —
                    # the client sees a dead socket mid-fetch
                    return
                try:
                    self.request.sendall(
                        json.dumps(resp).encode("utf-8") + b"\n")
                except (ConnectionError, OSError):
                    return

    @staticmethod
    def _dispatch(cluster: FakeCluster, req: dict):
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True}, False
        if op == "create":
            created = cluster.create_topic(req["topic"],
                                           int(req.get("parts", 1)))
            return {"ok": True, "created": created}, False
        if op == "meta":
            return {"ok": True, "topics": cluster.topics_meta()}, False
        if op == "end":
            return {"ok": True,
                    "end": cluster.log_end(req["topic"],
                                           int(req["part"]))}, False
        if op == "append":
            off, kind = cluster.append(req["topic"], int(req["part"]),
                                       base64.b64decode(req["value"]))
            return {"ok": off is not None, "offset": off,
                    "fault": kind}, False
        if op == "fetch":
            recs, end, kind = cluster.fetch(
                req["topic"], int(req["part"]), int(req["offset"]),
                int(req.get("max", 65536)))
            if kind == "conn_drop":
                return {}, True
            return {"ok": True,
                    "records": [[o, base64.b64encode(v).decode("ascii")]
                                for o, v in recs],
                    "end": end, "fault": kind}, False
        if op == "counters":
            return {"ok": True,
                    "counters": cluster.counters.snapshot(),
                    "records": cluster.total_records()}, False
        return {"ok": False, "err": f"unknown op {op!r}"}, False


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FakeKafkaServer:
    """The fake cluster behind a real socket, as its own lifecycle unit."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 cluster: "FakeCluster | None" = None):
        self.cluster = cluster if cluster is not None else FakeCluster()
        self._server = _Server((host, port), _Handler)
        self._server.cluster = self.cluster  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread: "threading.Thread | None" = None

    def start(self) -> "FakeKafkaServer":
        t = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            name="fakekafka-server", daemon=True)
        t.start()
        self._thread = t
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "FakeKafkaServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def ping(host: str, port: int, timeout_s: float = 1.0) -> bool:
    """True when a FakeKafkaServer answers at host:port (liveness probe,
    the ``_redis_alive`` analog for START_KAFKA adoption)."""
    try:
        t = _TcpTransport(host, port, timeout_s=timeout_s)
        try:
            ok = bool(t._rpc({"op": "ping"}).get("pong"))
        finally:
            t.close()
        return ok
    except (OSError, ValueError, FakeConnectionDropped):
        return False


def _build_chaos(ns):
    """Seeded broker faults for a server process (CLI knobs -> plan)."""
    if not (ns.fault_produce_rate or ns.fault_consume_rate
            or ns.fault_conn_drop_rate or ns.fault_dr_fail_rate
            or ns.fault_down):
        return None
    from streambench_tpu.chaos import FaultInjector, FaultPlan

    down = ()
    if ns.fault_down:
        lo, _, hi = ns.fault_down.partition(":")
        down = ((int(lo), int(hi)),)
    plan = FaultPlan.generate(
        ns.fault_seed,
        kafka_produce_rate=ns.fault_produce_rate,
        kafka_consume_rate=ns.fault_consume_rate,
        kafka_conn_drop_rate=ns.fault_conn_drop_rate,
        kafka_dr_fail_rate=ns.fault_dr_fail_rate,
        kafka_ops=ns.fault_ops, kafka_down=down)
    return FaultInjector(plan)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="standalone fake Kafka broker (JSON-lines protocol)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--fault-seed", type=int, default=0)
    ap.add_argument("--fault-produce-rate", type=float, default=0.0)
    ap.add_argument("--fault-consume-rate", type=float, default=0.0)
    ap.add_argument("--fault-conn-drop-rate", type=float, default=0.0)
    ap.add_argument("--fault-dr-fail-rate", type=float, default=0.0)
    ap.add_argument("--fault-ops", type=int, default=0)
    ap.add_argument("--fault-down", default="",
                    help="broker-down op window as LO:HI")
    ns = ap.parse_args(argv)

    srv = FakeKafkaServer(ns.host, ns.port)
    chaos = _build_chaos(ns)
    if chaos is not None:
        srv.cluster.attach_chaos(chaos)
        print(f"chaos armed: seed={ns.fault_seed} "
              f"plan={'zero' if chaos.plan.is_zero else 'nonzero'}",
              flush=True)
    srv.start()
    print(f"ready {srv.host}:{srv.port}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    snap = srv.cluster.counters.snapshot()
    print(f"stopping: records={srv.cluster.total_records()} "
          f"faults={json.dumps(snap, sort_keys=True)}", flush=True)
    srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
